"""Correctness-tooling suite: uigcsan, the race detector, uigc-lint.

Mutation-style acceptance (ISSUE 2): each test seeds a deliberate
invariant break — double-release, dropped recv fact, reordered undo
fold, duplicate frame tally, premature terminate — and asserts uigcsan
flags it, under both the in-process Fabric and the socket NodeFabric.
Clean-run baselines guard against false positives: the sanitizer must
stay silent on a correct system doing the same churn.
"""

import importlib.util
import os
import sys
import threading
import time
from contextlib import contextmanager

import pytest

from uigc_tpu import AbstractBehavior, Behaviors, Message, NoRefs
from uigc_tpu.analysis import RaceDetector, Sanitizer, VectorClock
from uigc_tpu.engines.crgc.state import CrgcContext, CrgcState
from uigc_tpu.engines.engine import TerminationDecision
from uigc_tpu.runtime.fabric import Fabric
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.runtime.system import ActorSystem
from uigc_tpu.runtime.testkit import ActorTestKit
from uigc_tpu.utils import events
from uigc_tpu.utils.validation import (
    CapacityError,
    GraphMismatchError,
    InvariantViolation,
    WireFormatError,
    require,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.analysis.sanitizer": True,
}

FABRIC_KINDS = ["fabric", "node"]


# ------------------------------------------------------------------- #
# Shared actors
# ------------------------------------------------------------------- #


class Ping(NoRefs):
    pass


class Drop(NoRefs):
    pass


class DoubleDrop(NoRefs):
    pass


class Share(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,) if self.ref is not None else ()


class Worker(AbstractBehavior):
    def on_message(self, msg):
        return self


class Owner(AbstractBehavior):
    """Root owning a worker: pings it locally, shares it to a peer
    root, releases it — once or (seeded mutation) twice."""

    def __init__(self, context, peer_root=None):
        super().__init__(context)
        self.worker = context.spawn(Behaviors.setup(lambda c: Worker(c)), "worker")
        self.peer_root = peer_root

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Ping) and self.worker is not None:
            self.worker.tell(Ping(), ctx)
        elif isinstance(msg, Share) and self.peer_root is not None:
            self.peer_root.tell(
                Share(ctx.create_ref(self.worker, self.peer_root)), ctx
            )
        elif isinstance(msg, Drop) and self.worker is not None:
            ctx.release(self.worker)
            self.worker = None
        elif isinstance(msg, DoubleDrop) and self.worker is not None:
            ctx.release(self.worker)
            ctx.release(self.worker)  # the seeded double release
            self.worker = None
        return self


class Holder(AbstractBehavior):
    """Peer root: receives a shared ref, pings through it, releases."""

    def __init__(self, context):
        super().__init__(context)
        self.held = None

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Share) and msg.ref is not None:
            self.held = msg.ref
        elif isinstance(msg, Ping) and self.held is not None:
            self.held.tell(Ping(), ctx)
        elif isinstance(msg, Drop) and self.held is not None:
            ctx.release(self.held)
            self.held = None
        return self


# ------------------------------------------------------------------- #
# Two-node cluster helper, parametrized over the fabric kind
# ------------------------------------------------------------------- #


class Cluster:
    def __init__(self, kind, names, overrides=None):
        config = dict(BASE)
        config["uigc.crgc.num-nodes"] = len(names)
        if overrides:
            config.update(overrides)
        self.kind = kind
        if kind == "fabric":
            fabric = Fabric()
            self.fabrics = [fabric] * len(names)
            self.systems = [
                ActorSystem(None, name=n, config=config, fabric=fabric)
                for n in names
            ]
        else:
            self.fabrics = [NodeFabric() for _ in names]
            self.systems = [
                ActorSystem(None, name=n, config=config, fabric=f)
                for n, f in zip(names, self.fabrics)
            ]
            ports = [f.listen() for f in self.fabrics]
            for i, fa in enumerate(self.fabrics):
                for j in range(i + 1, len(ports)):
                    fa.connect("127.0.0.1", ports[j])

    def sanitizer(self, idx) -> Sanitizer:
        return self.systems[idx].sanitizer

    def root_ref(self, from_idx, target_idx, raw_ref):
        """A refob usable on system ``from_idx`` naming a root actor on
        system ``target_idx`` (proxy under the node transport)."""
        src = self.systems[from_idx]
        if self.kind == "node":
            cell = self.fabrics[from_idx]._proxy(
                self.systems[target_idx].address, raw_ref.cell.uid
            )
        else:
            cell = raw_ref.cell
        return src.engine.to_root_refob(cell)

    def settle(self, predicate, timeout_s=15.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.05)
        return predicate()

    def terminate(self):
        for system in self.systems:
            try:
                system.terminate(timeout_s=5.0)
            except Exception:
                pass


@contextmanager
def cluster(kind, tag, overrides=None, n=2):
    names = [f"an{tag}{kind[0]}{i}" for i in range(n)]
    c = Cluster(kind, names, overrides)
    try:
        yield c
    finally:
        c.terminate()


def no_nonzero_recv(system):
    graph = system.engine.bookkeeper.shadow_graph
    return graph.investigate_live_set()["nonzero_recv"] == 0


# ------------------------------------------------------------------- #
# Structured validation errors (the de-asserted invariants)
# ------------------------------------------------------------------- #


class _StubSystem:
    address = "uigc://stub"


class _StubCell:
    _uid = 0

    def __init__(self):
        _StubCell._uid += 1
        self.uid = _StubCell._uid
        self.path = f"/stub/{self.uid}"
        self.system = _StubSystem()


def test_capacity_errors_survive_dash_O_and_carry_payload():
    from uigc_tpu.engines.crgc.refob import CrgcRefob

    context = CrgcContext(delta_graph_size=8, entry_field_size=1)
    cell = _StubCell()
    ref = CrgcRefob(cell)
    state = CrgcState(ref, context)
    state.record_new_refob(ref, ref)
    with pytest.raises(CapacityError) as exc:
        state.record_new_refob(ref, ref)
    assert exc.value.rule == "state.capacity"
    assert exc.value.payload["field"] == "created"
    assert exc.value.payload["capacity"] == 1


def test_delta_serialize_desync_is_structured():
    from uigc_tpu.engines.crgc.delta import DeltaGraph

    graph = DeltaGraph("uigc://stub", CrgcContext(8, 2))
    graph._encode(_StubCell())
    graph.compression_table[_StubCell()] = 7  # desync on purpose
    with pytest.raises(WireFormatError) as exc:
        graph.serialize(lambda cell: b"x")
    assert exc.value.rule == "delta.table_desync"
    assert exc.value.payload["table_size"] == 2
    assert exc.value.payload["shadow_count"] == 1


def test_shadow_assert_equals_reports_mismatching_entries():
    from uigc_tpu.engines.crgc.refob import CrgcRefob
    from uigc_tpu.engines.crgc.shadow import ShadowGraph
    from uigc_tpu.engines.crgc.state import Entry

    context = CrgcContext(8, 2)
    cell = _StubCell()
    entry = Entry(context)
    entry.self_ref = CrgcRefob(cell)
    entry.recv_count = 3
    a, b = ShadowGraph(context, "uigc://a"), ShadowGraph(context, "uigc://b")
    a.merge_entry(entry)
    entry.recv_count = 5
    b.merge_entry(entry)
    with pytest.raises(GraphMismatchError) as exc:
        a.assert_equals(b)
    assert exc.value.rule == "graph.mismatch"
    mismatch = exc.value.payload["mismatches"][0]
    assert mismatch["fields"]["recv_count"] == (3, 5)


def test_require_helper():
    require(True, "x.y", "fine")
    with pytest.raises(InvariantViolation) as exc:
        require(False, "x.y", "broken", a=1)
    assert exc.value.payload == {"a": 1}


# ------------------------------------------------------------------- #
# EventRecorder: exception isolation, thread safety, seq stamping
# ------------------------------------------------------------------- #


def test_event_listener_exceptions_are_isolated(capsys):
    rec = events.EventRecorder()
    rec.enable()
    seen = []

    def bad(name, fields):
        raise RuntimeError("listener boom")

    rec.add_listener(bad)
    rec.add_listener(lambda name, fields: seen.append((name, fields)))
    rec.commit("x.y", value=1)  # must not raise
    # The surviving listener saw the original event plus the structured
    # telemetry.listener_error the broken listener produced.
    names = [name for name, _ in seen]
    assert names.count("x.y") == 1
    assert names.count(events.LISTENER_ERROR) == 1
    snap = rec.snapshot()
    assert snap["counts"]["x.y"] == 1
    # Two errors were really raised: one on "x.y" and one on the error
    # event itself (the reentrancy guard counts the second silently
    # instead of recursing).
    assert snap["counts"][events.LISTENER_ERROR] == 2
    assert "listener boom" in capsys.readouterr().err


def test_event_commit_stamps_monotone_seq():
    rec = events.EventRecorder()
    rec.enable()
    seqs = []
    rec.add_listener(lambda name, fields: seqs.append(fields["seq"]))
    for _ in range(5):
        rec.commit("x.y")
    assert seqs == sorted(seqs) and len(set(seqs)) == 5


def test_event_listener_mutation_during_concurrent_commits():
    rec = events.EventRecorder()
    rec.enable()
    stop = threading.Event()
    errors = []

    def committer():
        while not stop.is_set():
            rec.commit("x.y")

    threads = [threading.Thread(target=committer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            fn = lambda name, fields: None  # noqa: E731
            rec.add_listener(fn)
            rec.remove_listener(fn)
    except Exception as exc:  # pragma: no cover
        errors.append(exc)
    finally:
        stop.set()
        for t in threads:
            t.join(5)
    assert not errors


# ------------------------------------------------------------------- #
# Vector clocks and the race detector
# ------------------------------------------------------------------- #


def test_vector_clock_ordering():
    a, b = VectorClock(), VectorClock()
    a.tick("t1")
    b.join(a)
    b.tick("t2")
    assert a.happened_before(b)
    assert not b.happened_before(a)
    c = VectorClock()
    c.tick("t3")
    assert a.concurrent_with(c)
    assert not a.concurrent_with(b)


def _ev(seq, name, **fields):
    fields["seq"] = seq
    return name, fields


def test_race_detector_flags_overlapping_batches():
    stream = [
        _ev(1, events.SCHED_BATCH_START, cell=1, path="/a", thread="t1"),
        _ev(2, events.SCHED_BATCH_START, cell=1, path="/a", thread="t2"),
        _ev(3, events.SCHED_BATCH_END, cell=1, path="/a", thread="t1"),
        _ev(4, events.SCHED_BATCH_END, cell=1, path="/a", thread="t2"),
    ]
    violations = RaceDetector().feed(stream).analyze()
    assert [v.rule for v in violations] == ["sched.overlap"]
    assert violations[0].payload["vc_concurrent"] is True


def test_race_detector_flags_app_before_pending_sys():
    stream = [
        _ev(1, events.SCHED_ENQUEUE, cell=1, path="/a", kind="sys", thread="t9"),
        _ev(2, events.SCHED_ENQUEUE, cell=1, path="/a", kind="app", thread="t9"),
        _ev(3, events.SCHED_BATCH_START, cell=1, path="/a", thread="t1"),
        # Mutated scheduler: app invoked while the earlier sys pends.
        _ev(4, events.SCHED_INVOKE, cell=1, path="/a", kind="app", thread="t1"),
        _ev(5, events.SCHED_INVOKE, cell=1, path="/a", kind="sys", thread="t1"),
        _ev(6, events.SCHED_BATCH_END, cell=1, path="/a", thread="t1"),
    ]
    violations = RaceDetector().feed(stream).analyze()
    assert [v.rule for v in violations] == ["sched.sys_after_app"]
    assert violations[0].payload["pending_sys_seqs"] == [1]


def test_race_detector_accepts_correct_sys_first_order():
    stream = [
        _ev(1, events.SCHED_ENQUEUE, cell=1, path="/a", kind="sys", thread="t9"),
        _ev(2, events.SCHED_ENQUEUE, cell=1, path="/a", kind="app", thread="t9"),
        _ev(3, events.SCHED_BATCH_START, cell=1, path="/a", thread="t1"),
        _ev(4, events.SCHED_INVOKE, cell=1, path="/a", kind="sys", thread="t1"),
        _ev(5, events.SCHED_INVOKE, cell=1, path="/a", kind="app", thread="t1"),
        _ev(6, events.SCHED_BATCH_END, cell=1, path="/a", thread="t1"),
        # A sys message landing mid-batch is NOT a violation.
        _ev(7, events.SCHED_BATCH_START, cell=1, path="/a", thread="t2"),
        _ev(8, events.SCHED_ENQUEUE, cell=1, path="/a", kind="sys", thread="t9"),
        _ev(9, events.SCHED_INVOKE, cell=1, path="/a", kind="app", thread="t2"),
        _ev(10, events.SCHED_BATCH_END, cell=1, path="/a", thread="t2"),
    ]
    assert RaceDetector().feed(stream).analyze() == []


def test_race_detector_flags_poststop_before_children():
    stream = [
        _ev(1, events.SCHED_SPAWN, cell=2, path="/a/kid", parent=1, thread="t1"),
        _ev(2, events.SCHED_POSTSTOP, cell=1, path="/a", thread="t1"),
        _ev(3, events.SCHED_TERMINATED, cell=2, path="/a/kid", thread="t1"),
    ]
    violations = RaceDetector().feed(stream).analyze()
    assert [v.rule for v in violations] == ["sched.poststop_before_children"]
    assert violations[0].payload["live_children"] == ["/a/kid"]


def test_race_detector_clean_on_real_run():
    """A live system with scheduling taps on: the detector must find no
    violations (the false-positive guard for the event instrumentation)."""
    events.recorder.enable()
    detector = RaceDetector().attach()
    try:
        kit = ActorTestKit(
            {
                "uigc.crgc.wakeup-interval": 10,
                "uigc.analysis.sched-events": True,
            }
        )
        try:
            owner = kit.spawn(
                Behaviors.setup_root(lambda c: Owner(c)), "owner"
            )
            for _ in range(30):
                owner.tell(Ping())
            time.sleep(0.3)
            owner.tell(Drop())
            time.sleep(0.5)
        finally:
            kit.shutdown()
    finally:
        detector.detach()
        events.recorder.disable()
        events.recorder.reset()
    assert detector.event_count() > 50
    violations = detector.analyze()
    assert violations == [], [str(v) for v in violations]


# ------------------------------------------------------------------- #
# uigcsan: clean baselines (false-positive guards)
# ------------------------------------------------------------------- #


def test_sanitizer_clean_single_system():
    kit = ActorTestKit(dict(BASE))
    san = kit.system.sanitizer
    try:
        owner = kit.spawn(Behaviors.setup_root(lambda c: Owner(c)), "owner")
        for _ in range(20):
            owner.tell(Ping())
        time.sleep(0.3)
        owner.tell(Drop())
        time.sleep(0.5)
        assert san.checks > 0
        assert san.violations == [], san.report()
        assert san.check_quiescent() == [], san.report()
    finally:
        kit.shutdown()


def test_sanitizer_tap_only_for_mac():
    kit = ActorTestKit(
        {"uigc.engine": "mac", "uigc.analysis.sanitizer": True}
    )
    san = kit.system.sanitizer
    try:
        assert san is not None and san.oracle is None
        owner = kit.spawn(Behaviors.setup_root(lambda c: Owner(c)), "owner")
        for _ in range(10):
            owner.tell(Ping())
        time.sleep(0.3)
        assert san.violations == [], san.report()
        assert san.check_quiescent() == []
        assert san.report()["tap"]["sends"] >= 10
    finally:
        kit.shutdown()


@pytest.mark.parametrize("kind", FABRIC_KINDS)
def test_sanitizer_clean_two_nodes(kind):
    with cluster(kind, "cl") as c:
        a, b = c.systems
        holder = a.spawn_root(Behaviors.setup_root(lambda ctx: Holder(ctx)), "holder")
        owner = b.spawn_root(
            Behaviors.setup_root(
                lambda ctx: Owner(ctx, peer_root=c.root_ref(1, 0, holder))
            ),
            "owner",
        )
        owner.tell(Share(None))
        time.sleep(0.3)
        for _ in range(15):
            holder.tell(Ping())
            time.sleep(0.005)
        holder.tell(Drop())
        owner.tell(Drop())
        assert c.settle(
            lambda: no_nonzero_recv(a) and no_nonzero_recv(b)
        ), "balances never converged — workload itself is broken"
        for i in (0, 1):
            san = c.sanitizer(i)
            assert san.checks > 0
            assert san.violations == [], san.report()
            assert san.check_quiescent() == [], san.report()


# ------------------------------------------------------------------- #
# uigcsan: the five seeded invariant mutations, on both fabrics
# ------------------------------------------------------------------- #


@pytest.mark.parametrize("kind", FABRIC_KINDS)
def test_mutation_double_release(kind):
    """Seeded break #1: a refob released twice in one batch."""
    with cluster(kind, "dr") as c:
        b = c.systems[1]
        owner = b.spawn_root(Behaviors.setup_root(lambda ctx: Owner(ctx)), "owner")
        owner.tell(Ping())
        time.sleep(0.2)
        owner.tell(DoubleDrop())
        assert c.settle(lambda: c.sanitizer(1).by_rule("release.double"))
        violation = c.sanitizer(1).by_rule("release.double")[0]
        assert violation.payload["target"].endswith("/worker")


@pytest.mark.parametrize("kind", FABRIC_KINDS)
def test_mutation_dropped_recv_fact(kind):
    """Seeded break #2: one receive fact silently lost at the worker —
    the folded balance can never return to zero, and the tap ground
    truth proves the facts (not the traffic) are wrong."""
    orig = CrgcState.record_message_received
    dropped = {"done": False}

    def mutated(self):
        if not dropped["done"] and self.self_ref.target.path.endswith("/worker"):
            dropped["done"] = True
            return
        orig(self)

    CrgcState.record_message_received = mutated
    try:
        with cluster(kind, "dv") as c:
            b = c.systems[1]
            owner = b.spawn_root(
                Behaviors.setup_root(lambda ctx: Owner(ctx)), "owner"
            )
            for _ in range(10):
                owner.tell(Ping())
                time.sleep(0.005)
            time.sleep(0.5)
            san = c.sanitizer(1)
            assert c.settle(
                lambda: bool(san.check_quiescent()), timeout_s=5.0
            )
            violation = san.by_rule("balance.nonzero_recv")[0]
            assert violation.payload["balance"] == -1
            assert violation.payload["tap_recvs"] == violation.payload["tap_sends"]
    finally:
        CrgcState.record_message_received = orig


@pytest.mark.parametrize("kind", FABRIC_KINDS)
def test_mutation_reordered_undo_fold(kind):
    """Seeded break #3: the collector folds a peer's undo log on every
    ingress entry — before the finalization quorum, and repeatedly."""
    with cluster(kind, "uf") as c:
        a, b = c.systems
        bookkeeper = b.engine.bookkeeper
        orig_merge = bookkeeper.merge_ingress_entry

        def mutated(entry):
            orig_merge(entry)
            log = bookkeeper.undo_logs.get(entry.egress_address)
            if log is not None:
                bookkeeper.shadow_graph.merge_undo_log(log)

        bookkeeper.merge_ingress_entry = mutated
        holder = a.spawn_root(Behaviors.setup_root(lambda ctx: Holder(ctx)), "holder")
        owner = b.spawn_root(
            Behaviors.setup_root(
                lambda ctx: Owner(ctx, peer_root=c.root_ref(1, 0, holder))
            ),
            "owner",
        )
        owner.tell(Share(None))
        for _ in range(10):
            holder.tell(Ping())
            time.sleep(0.005)
        san = c.sanitizer(1)
        assert c.settle(lambda: san.by_rule("undo.premature_fold"))
        assert c.settle(lambda: san.by_rule("undo.double_fold"))
        violation = san.by_rule("undo.premature_fold")[0]
        assert b.address in violation.payload["missing"]


@pytest.mark.parametrize("kind", FABRIC_KINDS)
def test_mutation_duplicate_frame_tally(kind):
    """Seeded break #4: one inbound app frame is tallied and delivered
    twice (a broken dedup layer) — the receiver's balance stays one
    receive ahead of the sender's claims forever."""
    with cluster(kind, "df") as c:
        a, b = c.systems
        state = {"duplicated": False}
        if kind == "fabric":
            fabric = c.fabrics[0]
            orig_deliver = fabric._deliver_now

            def mutated(link, target, payload):
                orig_deliver(link, target, payload)
                if not state["duplicated"] and link.dst is b:
                    state["duplicated"] = True
                    orig_deliver(link, target, payload)

            fabric._deliver_now = mutated
        else:
            # App frames are delivered in per-cell runs since the
            # batched transport (runtime/node.py _deliver_app_run) —
            # inject the duplicate tally at that seam.
            node_fabric = c.fabrics[1]
            orig_run = node_fabric._deliver_app_run

            def mutated(from_address, uid, frames):
                orig_run(from_address, uid, frames)
                if not state["duplicated"] and frames:
                    state["duplicated"] = True
                    orig_run(from_address, uid, frames)

            node_fabric._deliver_app_run = mutated

        holder = a.spawn_root(Behaviors.setup_root(lambda ctx: Holder(ctx)), "holder")
        owner = b.spawn_root(
            Behaviors.setup_root(
                lambda ctx: Owner(ctx, peer_root=c.root_ref(1, 0, holder))
            ),
            "owner",
        )
        owner.tell(Share(None))
        time.sleep(0.3)
        for _ in range(10):
            holder.tell(Ping())
            time.sleep(0.005)
        time.sleep(0.6)
        assert state["duplicated"], "mutation never fired"
        san = c.sanitizer(1)
        assert c.settle(lambda: bool(san.check_quiescent()), timeout_s=5.0)
        assert san.by_rule("balance.nonzero_recv"), san.report()


@pytest.mark.parametrize("kind", FABRIC_KINDS)
def test_mutation_premature_terminate(kind):
    """Seeded break #5: the engine decides a live, referenced worker
    SHOULD_STOP — the oracle still proves it reachable."""
    with cluster(kind, "pt") as c:
        b = c.systems[1]
        owner = b.spawn_root(Behaviors.setup_root(lambda ctx: Owner(ctx)), "owner")
        for _ in range(5):
            owner.tell(Ping())
        time.sleep(0.3)  # the worker is interned and provably live now

        from uigc_tpu.engines.crgc.messages import AppMsg

        engine = b.engine
        orig_on_idle = engine.on_idle

        def mutated(msg, state, ctx):
            if isinstance(msg, AppMsg) and ctx.cell.path.endswith("/worker"):
                return TerminationDecision.SHOULD_STOP
            return orig_on_idle(msg, state, ctx)

        engine.on_idle = mutated
        owner.tell(Ping())
        san = c.sanitizer(1)
        assert c.settle(lambda: san.by_rule("terminate.premature"))
        violation = san.by_rule("terminate.premature")[0]
        assert violation.payload["actor"].endswith("/worker")


# ------------------------------------------------------------------- #
# uigc-lint
# ------------------------------------------------------------------- #


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "uigc_lint", os.path.join(ROOT, "tools", "uigc_lint.py")
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.fixture(scope="module")
def lint():
    return _load_lint()


BAD_ACTOR_SRC = '''
import time
from uigc_tpu import AbstractBehavior, Behaviors, Message, NoRefs


class CarriesRef(NoRefs):
    def __init__(self, worker_ref):
        self.worker_ref = worker_ref


class HidesRef(Message):
    def __init__(self, worker_ref):
        self.worker_ref = worker_ref

    @property
    def refs(self):
        return ()


class Sloppy(AbstractBehavior):
    def __init__(self, context, friend_ref):
        super().__init__(context)
        self.friend_ref = friend_ref

    def on_message(self, msg):
        time.sleep(1.0)
        child = self.context.spawn(
            Behaviors.setup(lambda ctx: Sloppy(ctx, self.friend_ref)), "kid"
        )
        assert child is not None
        return self
'''

LOCK_ORDER_A = """
import threading

class A:
    def __init__(self):
        self.send_lock = threading.Lock()
        self.recv_lock = threading.Lock()

    def forward(self):
        with self.send_lock:
            with self.recv_lock:
                pass
"""

LOCK_ORDER_B = """
import threading

class B:
    def __init__(self):
        self.send_lock = threading.Lock()
        self.recv_lock = threading.Lock()

    def backward(self):
        with self.recv_lock:
            with self.send_lock:
                pass
"""


def test_lint_catches_each_rule(lint, tmp_path):
    bad = tmp_path / "bad_actor.py"
    bad.write_text(BAD_ACTOR_SRC)
    (tmp_path / "lock_a.py").write_text(LOCK_ORDER_A)
    (tmp_path / "lock_b.py").write_text(LOCK_ORDER_B)
    violations = lint.lint_paths([str(tmp_path)])
    rules = {v.rule for v in violations}
    assert {"UL001", "UL002", "UL003", "UL004", "UL005"} <= rules, sorted(
        v.render() for v in violations
    )
    # UL002 fires for both the NoRefs-with-ref and the empty-refs shapes.
    ul2 = [v for v in violations if v.rule == "UL002"]
    assert len(ul2) >= 2


def test_lint_suppression_comment(lint, tmp_path):
    src = (
        "class W:\n"
        "    def on_message(self, msg):\n"
        "        import time\n"
        "        time.sleep(1)  # uigc-lint: disable=UL003\n"
        "        assert msg  # uigc-lint: disable=all\n"
        "        return self\n"
    )
    f = tmp_path / "suppressed.py"
    f.write_text(src)
    violations = lint.lint_paths([str(f)])
    assert violations == [], [v.render() for v in violations]


def test_lint_allowlist_budget(lint, tmp_path):
    f = tmp_path / "legacy.py"
    f.write_text("def run(x):\n    assert x\n    assert x\n")
    violations = lint.lint_paths([str(f)])
    assert len(violations) == 2
    key = str(f).replace(os.sep, "/")
    grandfathered, fresh = lint.apply_allowlist(violations, {(key, "UL004"): 1})
    assert len(grandfathered) == 1 and len(fresh) == 1


def test_lint_ignores_test_trees_for_asserts(lint, tmp_path):
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "test_thing.py").write_text("def test_x():\n    assert 1\n")
    assert lint.lint_paths([str(tests_dir)]) == []


def test_lint_strict_clean_on_repo(lint):
    """The verify-path gate: the repo's own package must lint clean
    under --strict (grandfathered budget allowed)."""
    rc = lint.main(["--strict", os.path.join(ROOT, "uigc_tpu")])
    assert rc == 0
