"""Liveness inspector suite (uigc_tpu/telemetry/inspect.py).

Layers, bottom up:

- kernel parity: the parents-capturing mark fixpoints (numpy + XLA)
  agree with the plain trace bit-for-bit and produce a valid marking
  forest (every non-seed marked node has a marked parent reachable over
  a real positive edge / supervisor pointer);
- gating: plain wakes never run the capture kernels
  (stats-variant discipline); capture-enabled systems store a
  verdict-exact parent array that the inspector resolves;
- why-live path parity against the uigcsan pointer oracle under random
  churn — every hop of every live actor's retaining path must exist in
  the sanitizer's independent oracle;
- snapshot-under-concurrent-fold safety, flight-recorder diffing, leak
  watchdog true/false-positive behavior;
- exporters: JSONL rotation with ordered replay, /healthz and
  wake-phase histograms, /snapshot + /inspect HTTP endpoints;
- cross-node: "snap" codec round-trips, 2-node merged snapshot, and a
  seeded dropped "snap" frame degrading to a partial merge;
- UL008: the read-only lint contract holds for the real inspect.py and
  catches a mutating one.
"""

import json
import os
import random
import sys
import threading
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from uigc_tpu import (
    AbstractBehavior,
    ActorTestKit,
    Behaviors,
    Message,
    NoRefs,
)
from uigc_tpu.ops import trace as trace_ops
from uigc_tpu.runtime.faults import FaultPlan
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.runtime.system import ActorSystem
from uigc_tpu.runtime import wire
from uigc_tpu.telemetry.exporter import JsonlEventSink, replay_jsonl
from uigc_tpu.telemetry.inspect import (
    FlightRecorder,
    LeakWatchdog,
    diff_snapshots,
    merge_snapshots,
    snapshot_graph,
    validate_why_live,
    why_live,
)
from uigc_tpu.utils import events


@pytest.fixture(autouse=True)
def clean_recorder():
    yield
    events.recorder.disable()
    events.recorder.reset()
    with events.recorder._lock:
        events.recorder._listeners.clear()


# ------------------------------------------------------------------- #
# Workload pieces
# ------------------------------------------------------------------- #


class _Ping(NoRefs):
    pass


class _Give(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,) if self.ref is not None else ()


class _Worker(AbstractBehavior):
    def on_message(self, msg):
        return self


class _Keeper(AbstractBehavior):
    def __init__(self, context):
        super().__init__(context)
        self.held = []

    def on_message(self, msg):
        if isinstance(msg, _Give) and msg.ref is not None:
            self.held.append(msg.ref)
        return self


class _ChainRoot(AbstractBehavior):
    """root -> keeper -> kept: after the hand-off the kept actor is
    retained only through the keeper (a 2-hop why-live chain), plus a
    leaked worker pinned by the root with zero traffic."""

    def __init__(self, context):
        super().__init__(context)
        self.keeper = context.spawn(Behaviors.setup(_Keeper), "keeper")
        self.kept = context.spawn(Behaviors.setup(_Worker), "kept")
        self.leaked = context.spawn(Behaviors.setup(_Worker), "leaked")
        self.workers = [
            context.spawn(Behaviors.setup(_Worker), f"w{i}") for i in range(3)
        ]

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, _Give):
            self.keeper.tell(
                _Give(ctx.create_ref(self.kept, self.keeper)), ctx
            )
            ctx.release(self.kept)
            self.kept = None
        elif isinstance(msg, _Ping):
            for worker in self.workers:
                worker.tell(_Ping(), ctx)
        return self


def _chain_kit(extra=None, name="inspectkit"):
    config = {
        "uigc.crgc.wakeup-interval": 10,
        "uigc.telemetry.inspect": True,
        "uigc.telemetry.snapshot-every": 1,
    }
    if extra:
        config.update(extra)
    kit = ActorTestKit(config=config, name=name)
    root = kit.spawn(Behaviors.setup_root(_ChainRoot), "root")
    root.tell(_Give(None))  # trigger the kept hand-off
    time.sleep(0.15)
    return kit, root


def _key_of(snapshot, name_suffix):
    for key, rec in snapshot["actors"].items():
        if rec.get("name", "").endswith(name_suffix):
            return key
    return None


# ------------------------------------------------------------------- #
# Kernel parity
# ------------------------------------------------------------------- #


def _random_graph(rng, n):
    flags = np.where(
        rng.random(n) < 0.85,
        rng.integers(0, 64, n) | trace_ops.FLAG_IN_USE,
        0,
    ).astype(np.uint8)
    recv = rng.integers(-2, 3, n).astype(np.int64)
    sup = np.where(
        rng.random(n) < 0.4, rng.integers(0, n, n), -1
    ).astype(np.int32)
    m = int(rng.integers(1, 4 * n))
    esrc = rng.integers(0, n, m).astype(np.int32)
    edst = rng.integers(0, n, m).astype(np.int32)
    ew = rng.integers(-1, 3, m).astype(np.int64)
    return flags, recv, sup, esrc, edst, ew


def _assert_valid_parents(flags, recv, sup, esrc, edst, ew, mark, parent):
    seeds = trace_ops.pseudoroots_np(flags, recv)
    for i in range(len(flags)):
        p = int(parent[i])
        if mark[i] and not seeds[i]:
            assert p >= 0 and mark[p]
        if p >= 0:
            has_edge = bool(np.any((esrc == p) & (edst == i) & (ew > 0)))
            assert has_edge or sup[p] == i
            # the marker must propagate: in-use, not halted
            assert flags[p] & trace_ops.FLAG_IN_USE
            assert not (flags[p] & trace_ops.FLAG_HALTED)


def test_parents_kernels_match_plain_trace_and_each_other():
    rng = np.random.default_rng(7)
    from uigc_tpu.ops import pallas_trace as pt

    for trial in range(25):
        n = int(rng.integers(4, 100))
        flags, recv, sup, esrc, edst, ew = _random_graph(rng, n)
        base = trace_ops.trace_marks_np(flags, recv, sup, esrc, edst, ew)
        mark, parent = trace_ops.trace_marks_np_parents(
            flags, recv, sup, esrc, edst, ew
        )
        assert np.array_equal(base, mark)
        _assert_valid_parents(flags, recv, sup, esrc, edst, ew, mark, parent)
        if trial < 6:  # device variant: fewer trials, compile cost
            dmark, dparent = pt.marking_parents_jax(
                flags, recv, sup, esrc, edst, ew
            )
            assert np.array_equal(mark, dmark)
            assert np.array_equal(parent, dparent.astype(np.int64))


# ------------------------------------------------------------------- #
# Live-system why-live + gating
# ------------------------------------------------------------------- #


def test_why_live_chain_and_capture_gating():
    kit, root = _chain_kit(
        extra={"uigc.telemetry.why-live-capture": True}
    )
    try:
        graph = kit.system.engine.bookkeeper.shadow_graph
        insp = kit.system.telemetry.inspector
        assert insp is not None and insp.parent_capture
        deadline = time.monotonic() + 10.0
        result = {}
        while time.monotonic() < deadline:
            result = insp.why_live("kept")
            if result.get("verdict") == "live" and len(result["path"]) >= 2:
                break
            time.sleep(0.05)
        assert result.get("verdict") == "live", result
        # verdict-exact capture was used, not an on-demand derivation
        assert result.get("parents") == "captured"
        names = [hop["from_name"] for hop in result["path"]]
        assert any("keeper" in (n or "") for n in names), result
        assert result["root_reasons"], result
        snap = insp.snapshot()
        assert validate_why_live(snap, result) == []
        assert graph.last_parents is not None
    finally:
        kit.shutdown()


def test_parent_capture_gated_off_by_default(monkeypatch):
    """Plain wakes must never touch the parents kernels — the
    stats-variant gating parity bar (off-path overhead is zero)."""
    called = []
    real = trace_ops.trace_marks_np_parents
    monkeypatch.setattr(
        trace_ops,
        "trace_marks_np_parents",
        lambda *a, **k: called.append(1) or real(*a, **k),
    )
    kit, root = _chain_kit(name="gatingkit")
    try:
        graph = kit.system.engine.bookkeeper.shadow_graph
        for _ in range(5):
            root.tell(_Ping())
            time.sleep(0.03)
        assert graph.capture_parents is False
        assert graph.last_parents is None
        assert called == []
        # on-demand why-live derives parents without flipping the gate
        result = kit.system.telemetry.inspector.why_live("kept")
        assert result["verdict"] in ("live", "collectable")
        assert graph.capture_parents is False
    finally:
        kit.shutdown()


class _ChurnRoot(AbstractBehavior):
    def __init__(self, context, rng, population):
        super().__init__(context)
        self.rng = rng
        self.acq = []
        self.population = population

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, _Ping):
            for _ in range(4):
                p = self.rng.random()
                if p < 0.45 or not self.acq:
                    if len(self.acq) < self.population:
                        self.acq.append(
                            ctx.spawn_anonymous(Behaviors.setup(_Keeper))
                        )
                elif p < 0.7 and len(self.acq) >= 2:
                    a, b = self.rng.sample(self.acq, 2)
                    a.tell(_Give(ctx.create_ref(b, a)), ctx)
                elif p < 0.85:
                    victim = self.acq.pop(self.rng.randrange(len(self.acq)))
                    ctx.release(victim)
                else:
                    self.rng.choice(self.acq).tell(_Ping(), ctx)
        return self


def test_why_live_parity_with_sanitizer_oracle_under_churn():
    """Acceptance: every live actor's retaining path validates against
    the uigcsan pointer oracle — each created hop is a positive-count
    edge in the oracle, each supervisor hop matches, and the chain head
    is an oracle pseudoroot."""
    kit = ActorTestKit(
        config={
            "uigc.crgc.wakeup-interval": 10,
            "uigc.telemetry.inspect": True,
            "uigc.analysis.sanitizer": True,
        },
        name="paritykit",
    )
    rng = random.Random(20260803)
    try:
        root = kit.spawn(
            Behaviors.setup_root(lambda ctx: _ChurnRoot(ctx, rng, 40)),
            "root",
        )
        for _ in range(12):
            root.tell(_Ping())
            time.sleep(0.03)
        time.sleep(0.3)  # settle: no in-flight churn during the check
        san = kit.system.sanitizer
        insp = kit.system.telemetry.inspector
        snap = insp.snapshot()
        checked = 0
        with san._lock:
            oracle = san.oracle
            by_key = {
                f"{cell.system.address}#{cell.uid}": shadow
                for cell, shadow in oracle.shadow_map.items()
            }
            for key, rec in snap["actors"].items():
                result = why_live(snap, key)
                assert validate_why_live(snap, result) == [], (key, result)
                if result["verdict"] != "live":
                    continue
                checked += 1
                head = by_key.get(result["chain"][0])
                assert head is not None, result
                assert oracle.is_pseudo_root(head), result
                for hop in result["path"]:
                    src = by_key.get(hop["from"])
                    dst = by_key.get(hop["to"])
                    assert src is not None and dst is not None, hop
                    if hop["kind"] == "created":
                        assert src.outgoing.get(dst, 0) > 0, hop
                    else:
                        assert src.supervisor is dst, hop
        assert checked >= 5, f"churn left too few live actors ({checked})"
        assert kit.system.sanitizer.violations == []
    finally:
        kit.shutdown()


class _FakeSystem:
    def __init__(self, address):
        self.address = address


class _FakeCell:
    def __init__(self, system, uid, path):
        self.system = system
        self.uid = uid
        self.path = path


def test_stale_captured_parents_fall_back_to_fresh_derivation():
    """A capture describes the LAST wake: an actor interned after it
    must not inherit a stale 'collectable' verdict from the old mark
    array — the resolver re-derives instead (review hardening)."""
    from uigc_tpu.engines.crgc.arrays import ArrayShadowGraph
    from uigc_tpu.engines.crgc.state import CrgcContext
    from uigc_tpu.telemetry.inspect import (
        snapshot_graph,
        why_live_from_parents,
    )

    context = CrgcContext(delta_graph_size=64, entry_field_size=4)
    system = _FakeSystem("uigc://fake")
    graph = ArrayShadowGraph(context, system.address)
    F = trace_ops
    a = _FakeCell(system, 1, "/user/a")
    b = _FakeCell(system, 2, "/user/b")
    sa, sb = graph.slot_for(a), graph.slot_for(b)
    graph.flags[sa] |= F.FLAG_ROOT | F.FLAG_INTERNED | F.FLAG_LOCAL
    graph.flags[sb] |= F.FLAG_INTERNED | F.FLAG_LOCAL
    graph._update_edge(sa, sb, 1)
    graph.capture_parents = True
    graph.trace(should_kill=False)
    assert graph.last_parents is not None

    # c interns AFTER the capture, retained by a fresh edge from a.
    c = _FakeCell(system, 3, "/user/c")
    sc = graph.slot_for(c)
    graph.flags[sc] |= F.FLAG_INTERNED | F.FLAG_LOCAL
    graph._update_edge(sa, sc, 1)

    snap = snapshot_graph(graph, node=system.address)
    result = why_live_from_parents(graph, snap, "/user/c")
    assert result is not None
    assert result["verdict"] == "live", result
    assert result["parents"] == "derived", result  # not the stale capture
    assert validate_why_live(snap, result) == []
    # the untouched actor still resolves through the capture
    kept = why_live_from_parents(graph, snap, "/user/b")
    assert kept["verdict"] == "live" and kept["parents"] == "captured"


# ------------------------------------------------------------------- #
# Snapshot safety + flight recorder + watchdog
# ------------------------------------------------------------------- #


def test_snapshot_under_concurrent_fold_is_safe():
    kit = ActorTestKit(
        config={
            "uigc.crgc.wakeup-interval": 5,
            "uigc.telemetry.inspect": True,
        },
        name="folderkit",
    )
    rng = random.Random(4)
    errors = []
    try:
        root = kit.spawn(
            Behaviors.setup_root(lambda ctx: _ChurnRoot(ctx, rng, 60)),
            "root",
        )
        insp = kit.system.telemetry.inspector
        stop = threading.Event()

        def churn():
            while not stop.is_set():
                root.tell(_Ping())
                time.sleep(0.002)

        thread = threading.Thread(target=churn)
        thread.start()
        try:
            for _ in range(60):
                try:
                    snap = insp.snapshot()
                    assert isinstance(snap["actors"], dict)
                    assert isinstance(snap["edges"], list)
                    # a why-live mid-churn must not raise either
                    insp.why_live("root")
                except Exception as exc:  # pragma: no cover
                    errors.append(exc)
                time.sleep(0.004)
        finally:
            stop.set()
            thread.join()
        assert errors == []
    finally:
        kit.shutdown()


def test_flight_recorder_ring_and_diff():
    recorder = FlightRecorder(keep=3)
    mk = lambda actors, wave: {
        "actors": {
            k: {"recv_count": 0, "busy": False, "root": False,
                "pseudoroot": False, "halted": False}
            for k in actors
        },
        "edges": [],
        "wave": wave,
    }
    recorder.record(mk(["a", "b"], 1))
    recorder.record(mk(["b", "c"], 2))
    diffs = recorder.diffs()
    assert diffs[-1]["added"] == ["c"]
    assert diffs[-1]["removed"] == ["a"]
    assert diffs[-1]["retained"] == 1
    for wave in range(3, 8):
        recorder.record(mk(["x"], wave))
    assert len(recorder.snapshots()) == 3  # ring bound
    doc = recorder.to_json()
    assert doc["versions"] == 7


def test_leak_watchdog_flags_planted_leak_without_false_positives():
    kit, root = _chain_kit(
        extra={"uigc.telemetry.leak-waves": 3}, name="leakkit"
    )
    try:
        insp = kit.system.telemetry.inspector
        # Phase 1: let the system sit quiet until the planted leak is
        # flagged (>= leak-waves zero-traffic waves).
        deadline = time.monotonic() + 15.0
        flagged = []
        while time.monotonic() < deadline:
            time.sleep(0.03)
            snap = insp.snapshot()
            flagged = [
                snap["actors"].get(key, {}).get("name", key)
                for key in insp.watchdog.suspects()
            ]
            if any(name.endswith("leaked") for name in flagged):
                break
        assert any(name.endswith("leaked") for name in flagged), flagged
        # Phase 2: traffic re-arms the watchdog — while the workers are
        # continuously messaged they must drop OUT of the suspect set
        # (the zero-false-positive bar for active actors), while the
        # zero-traffic leak stays flagged.
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            root.tell(_Ping())
            time.sleep(0.008)
            snap = insp.snapshot()
            flagged = [
                snap["actors"].get(key, {}).get("name", key)
                for key in insp.watchdog.suspects()
            ]
            if not any("/w" in name for name in flagged) and any(
                name.endswith("leaked") for name in flagged
            ):
                break
        assert any(name.endswith("leaked") for name in flagged), flagged
        assert not any("/w" in name for name in flagged), flagged
        assert insp.leak_suspects_total >= 1
    finally:
        kit.shutdown()


def test_leak_suspect_event_and_metric():
    kit, root = _chain_kit(
        extra={
            "uigc.telemetry.leak-waves": 2,
            "uigc.telemetry.metrics": True,
        },
        name="leakmetrics",
    )
    try:
        registry = kit.system.telemetry.registry
        deadline = time.monotonic() + 10.0
        total = 0.0
        while time.monotonic() < deadline and total == 0.0:
            time.sleep(0.05)
            total = registry.counter("uigc_leak_suspects_total").value()
        assert total >= 1.0
    finally:
        kit.shutdown()


# ------------------------------------------------------------------- #
# Exporter satellites
# ------------------------------------------------------------------- #


def test_jsonl_rotation_keeps_bounded_set_and_replays_in_order(tmp_path):
    path = str(tmp_path / "events.jsonl")
    sink = JsonlEventSink(path, max_bytes=2000, keep=2)
    for i in range(400):
        sink("test.event", {"i": i})
    sink.close()
    files = sorted(os.listdir(tmp_path))
    assert "events.jsonl" in files
    assert "events.jsonl.1" in files and "events.jsonl.2" in files
    assert "events.jsonl.3" not in files  # oldest dropped
    for name in files:
        assert os.path.getsize(tmp_path / name) <= 2100
    seq = [fields["i"] for name, fields in replay_jsonl(path)
           if name == "test.event"]
    # ordered stream across the rotated set, ending at the newest event
    assert seq == sorted(seq)
    assert seq[-1] == 399
    assert len(seq) >= 3


def test_jsonl_rotation_off_by_default(tmp_path):
    path = str(tmp_path / "plain.jsonl")
    sink = JsonlEventSink(path)
    for i in range(100):
        sink("test.event", {"i": i})
    sink.close()
    assert sorted(os.listdir(tmp_path)) == ["plain.jsonl"]
    assert len(list(replay_jsonl(path))) == 100


def test_healthz_wake_phase_histograms_and_inspect_endpoints():
    kit, root = _chain_kit(
        extra={
            "uigc.telemetry.metrics": True,
            "uigc.telemetry.wake-profile": True,
            "uigc.telemetry.http-port": 0,
        },
        name="httpkit",
    )
    try:
        port = kit.system.telemetry.http.port
        base = f"http://127.0.0.1:{port}"
        for _ in range(5):
            root.tell(_Ping())
            time.sleep(0.03)
        health = json.loads(
            urllib.request.urlopen(base + "/healthz", timeout=5).read()
        )
        assert health["status"] == "ok"
        assert health["node"] == kit.system.address
        deadline = time.monotonic() + 10.0
        text = ""
        while time.monotonic() < deadline:
            text = urllib.request.urlopen(
                base + "/metrics", timeout=5
            ).read().decode()
            if 'uigc_wake_phase_seconds_bucket{' in text:
                break
            time.sleep(0.05)
        assert 'phase="trace"' in text
        assert 'phase="ingest"' in text
        snap = json.loads(
            urllib.request.urlopen(base + "/snapshot", timeout=5).read()
        )
        assert snap["actors"]
        kept = _key_of(snap, "kept")
        assert kept is not None
        result = json.loads(
            urllib.request.urlopen(
                base + "/inspect?actor=" + urllib.parse.quote(kept),
                timeout=5,
            ).read()
        )
        assert result["verdict"] in ("live", "collectable")
        if result["verdict"] == "live":
            assert validate_why_live(snap, result) == []
    finally:
        kit.shutdown()


# ------------------------------------------------------------------- #
# Cross-node: codec + merged snapshot + dropped frame
# ------------------------------------------------------------------- #


def test_snap_frame_codec_roundtrip_and_malformed():
    req = wire.encode_snap_request(7, "nodeA")
    assert wire.decode_snap_frame(req) == ("req", 7, "nodeA", None)
    rsp = wire.encode_snap_response(7, "nodeB", b'{"actors": {}}')
    assert wire.decode_snap_frame(rsp) == ("rsp", 7, "nodeB", b'{"actors": {}}')
    # trailing-element tolerance
    assert wire.decode_snap_frame(req + ("future",))[0] == "req"
    # malformed shapes decode to None, never raise
    assert wire.decode_snap_frame(("snap",)) is None
    assert wire.decode_snap_frame(("snap", "rsp", 1, "x", "notbytes")) is None
    assert wire.decode_snap_frame(("snap", "bogus", 1)) is None


def _spawn_node(name, num_nodes, fault_plan=None, overrides=None):
    config = {
        "uigc.crgc.wakeup-interval": 10,
        "uigc.crgc.egress-finalize-interval": 5,
        "uigc.crgc.num-nodes": num_nodes,
        "uigc.telemetry.inspect": True,
    }
    if overrides:
        config.update(overrides)
    fabric = NodeFabric(fault_plan=fault_plan)
    system = ActorSystem(None, name=name, config=config, fabric=fabric)
    port = fabric.listen()
    return fabric, system, port


def _terminate_all(*systems):
    for system in systems:
        try:
            system.terminate(timeout_s=5.0)
        except Exception:
            pass


def test_two_node_merged_snapshot_and_seeded_snap_drop():
    fa, sa, _pa = _spawn_node("snapa", 2)
    fb, sb, pb = _spawn_node("snapb", 2)
    try:
        fa.connect("127.0.0.1", pb)
        root_b = sb.spawn_root(Behaviors.setup_root(_ChainRoot), "root")
        root_a = sa.spawn_root(Behaviors.setup_root(_ChainRoot), "root")
        root_b.tell(_Give(None))
        root_a.tell(_Give(None))
        time.sleep(0.4)
        insp_a = sa.telemetry.inspector
        deadline = time.monotonic() + 15.0
        merged = {}
        while time.monotonic() < deadline:
            merged = insp_a.merged_snapshot(timeout_s=3.0)
            locations = {
                rec.get("location")
                for rec in merged["actors"].values()
            }
            if sa.address in locations and sb.address in locations and (
                not merged["missing_nodes"]
            ):
                break
            time.sleep(0.1)
        assert not merged["missing_nodes"], merged["missing_nodes"]
        locations = {rec.get("location") for rec in merged["actors"].values()}
        assert sa.address in locations and sb.address in locations
        # B's kept actor is explainable from A's merged view
        kept_b = None
        for key, rec in merged["actors"].items():
            if rec.get("name", "").endswith("kept") and (
                rec.get("location") == sb.address
            ):
                kept_b = key
        assert kept_b is not None
        result = why_live(merged, kept_b)
        assert result["verdict"] == "live", result
        assert validate_why_live(merged, result) == []

        # Seeded drop: every further "snap" frame from A's peer dies on
        # the wire — the merge degrades to a partial graph that NAMES
        # the missing node instead of hanging or raising.
        fa.fault_plan = FaultPlan(seed=1).drop(kind="snap", prob=1.0)
        fb.fault_plan = FaultPlan(seed=1).drop(kind="snap", prob=1.0)
        partial = insp_a.merged_snapshot(timeout_s=1.0)
        assert partial["missing_nodes"] == [sb.address]
        locations = {
            rec.get("location") for rec in partial["actors"].values()
        }
        assert sa.address in locations
    finally:
        _terminate_all(sa, sb)


def test_merge_snapshots_prefers_home_records():
    a = {
        "node": "A",
        "actors": {
            "A#1": {"name": "x", "local": True, "pseudoroot": True,
                    "halted": False, "recv_count": 0, "busy": False,
                    "root": True, "interned": True, "location": "A"},
            "B#2": {"name": "y", "local": False, "pseudoroot": False,
                    "halted": False, "recv_count": 0, "busy": False,
                    "root": False, "interned": False, "location": "B"},
        },
        "edges": [["A#1", "B#2", 1]],
        "supervisors": [],
        "send_matrix": [["A#1", "B#2", 5]],
    }
    b = {
        "node": "B",
        "actors": {
            "B#2": {"name": "y", "local": True, "pseudoroot": False,
                    "halted": False, "recv_count": 0, "busy": False,
                    "root": False, "interned": True, "location": "B"},
        },
        "edges": [],
        "supervisors": [],
        "send_matrix": [],
    }
    merged = merge_snapshots([a, b], missing=["C"])
    assert merged["actors"]["B#2"]["local"]  # home record won
    assert merged["actors"]["B#2"]["reported_by"] == "B"
    assert merged["missing_nodes"] == ["C"]
    assert merged["send_matrix"] == [["A#1", "B#2", 5]]
    result = why_live(merged, "B#2")
    assert result["verdict"] == "live"
    assert [h["kind"] for h in result["path"]] == ["created"]


# ------------------------------------------------------------------- #
# UL008 lint contract
# ------------------------------------------------------------------- #


def _lint(paths):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import uigc_lint

    return uigc_lint.lint_paths(paths, lint_asserts=False)


def test_ul008_real_inspect_module_is_clean():
    repo = os.path.join(os.path.dirname(__file__), "..")
    target = os.path.join(repo, "uigc_tpu", "telemetry", "inspect.py")
    violations = [v for v in _lint([target]) if v.rule == "UL008"]
    assert violations == [], [v.render() for v in violations]


def test_ul008_flags_mutating_inspect_code(tmp_path):
    bad_dir = tmp_path / "telemetry"
    bad_dir.mkdir()
    bad = bad_dir / "inspect.py"
    bad.write_text(
        "from ..engines.crgc import arrays\n"
        "def poke(graph, cell):\n"
        "    graph.flags[0] = 0\n"
        "    graph.capture_parents = True\n"
        "    graph.trace(should_kill=True)\n"
        "    cell.tell(object())\n"
        "def fine(self_like):\n"
        "    out = {}\n"
        "    out['x'] = 1\n"
        "    return out\n"
    )
    violations = [v for v in _lint([str(bad)]) if v.rule == "UL008"]
    lines = {v.line for v in violations}
    assert 1 in lines  # runtime engines import
    assert 3 in lines  # graph.flags[0] = 0
    assert 4 in lines  # graph.capture_parents = ...
    assert 5 in lines  # .trace(...)
    assert 6 in lines  # .tell(...)
    assert all(v.line != 9 for v in violations)  # local dict store is fine
