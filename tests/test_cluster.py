"""Cluster sharding: placement, passivation, live migration, chaos.

Covers the uigc_tpu/cluster subsystem end to end:

- unit layer: stable key->shard hashing, rendezvous assignment (spread
  + minimal churn on membership change), shard-table version ordering;
- wire layer: round-trip property test for the shard/entity/migration
  frame kinds plus the app-frame trace header, and the old-peer
  tolerance contract (a node that does not know a frame kind neither
  crashes nor desyncs sequence numbers);
- name registry satellite: duplicate ``register_name`` raises a
  structured error, a missed ``lookup`` emits ``fabric.lookup_miss``;
- integration: single-node passivation with state resurrection,
  two-node join rebalance with live state migration, EntityRefs
  crossing the wire inside messages, shard metrics via Prometheus;
- acceptance: a 3-node chaos run — >= 200 keyed entities, one node
  killed mid-traffic under a seeded FaultPlan that drops migration
  frames, every entity rehomed and answering a post-rebalance probe,
  with the uigcsan sanitizer attached and clean on the survivors.
"""

import threading
import time

import pytest

from uigc_tpu import ActorSystem, ClusterSharding, Entity
from uigc_tpu.cluster.sharding import ShardTable, rendezvous_assign, shard_of
from uigc_tpu.runtime import wire
from uigc_tpu.runtime.behaviors import RawBehavior
from uigc_tpu.runtime.faults import FaultPlan
from uigc_tpu.runtime.node import DuplicateNameError, NameLookupError, NodeFabric
from uigc_tpu.utils import events

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.crgc.shadow-graph": "array",
    "uigc.cluster.tick-interval": 40,
    "uigc.cluster.handoff-retry": 120,
}


def settle(predicate, timeout_s=20.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


class EventLog:
    def __init__(self):
        self.entries = []
        self._lock = threading.Lock()

    def __call__(self, name, fields):
        with self._lock:
            self.entries.append((name, fields))

    def of(self, name):
        with self._lock:
            return [f for n, f in self.entries if n == name]


@pytest.fixture
def event_log():
    log = EventLog()
    events.recorder.enable()
    events.recorder.add_listener(log)
    yield log
    events.recorder.disable()
    events.recorder.remove_listener(log)
    events.recorder.reset()


# ------------------------------------------------------------------- #
# Entity used throughout: a counter that can be probed and can hold a
# forwarding target (exercises refs/EntityRefs inside state/messages).
# ------------------------------------------------------------------- #


class Counter(Entity):
    def __init__(self, ctx, key, state):
        super().__init__(ctx, key)
        state = state or {}
        self.count = state.get("count", 0)
        self.peer = state.get("peer")

    def receive(self, msg):
        kind = msg[0]
        if kind == "incr":
            self.count += 1
        elif kind == "probe":
            msg[1].tell(("probed", self.key, self.count))
        elif kind == "adopt":  # remember an EntityRef that crossed a link
            self.peer = msg[1]
        elif kind == "poke-peer" and self.peer is not None:
            self.peer.tell(("incr",))
        return self

    def snapshot_state(self):
        return {"count": self.count, "peer": self.peer}


def counter_factory(ctx, key, state):
    return Counter(ctx, key, state)


class Collector(RawBehavior):
    """Raw reply sink: collects ("probed", key, count) tuples."""

    def __init__(self):
        self.got = {}
        self._lock = threading.Lock()

    def on_message(self, msg):
        if isinstance(msg, tuple) and msg and msg[0] == "probed":
            with self._lock:
                self.got[msg[1]] = msg[2]
        return None

    def snapshot(self):
        with self._lock:
            return dict(self.got)


class Node:
    __slots__ = ("fabric", "system", "cluster", "region", "port", "address")

    def __init__(self, name, config, plan=None, passivate_after_s=None):
        self.fabric = NodeFabric(fault_plan=plan)
        self.system = ActorSystem(None, name=name, config=config, fabric=self.fabric)
        self.port = self.fabric.listen()
        self.address = self.system.address
        self.cluster = ClusterSharding.attach(self.system)
        self.region = self.cluster.start(
            "counter", counter_factory, passivate_after_s=passivate_after_s
        )


def build_cluster(names, plan=None, overrides=None, passivate_after_s=None):
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = len(names)
    if overrides:
        config.update(overrides)
    return [Node(n, config, plan, passivate_after_s) for n in names]


def connect_mesh(nodes):
    for i, a in enumerate(nodes):
        for b in nodes[i + 1 :]:
            a.fabric.connect("127.0.0.1", b.port)


def terminate_all(nodes):
    for n in nodes:
        try:
            n.system.terminate(timeout_s=5.0)
        except Exception:
            pass


# ------------------------------------------------------------------- #
# Unit layer: placement
# ------------------------------------------------------------------- #


def test_shard_of_is_stable_and_spread():
    assert shard_of("user-42", 32) == shard_of("user-42", 32)
    hits = {shard_of(f"user-{i}", 32) for i in range(500)}
    assert len(hits) == 32  # 500 keys cover all 32 shards


def test_rendezvous_spread_and_minimal_churn():
    members = ["uigc://a", "uigc://b", "uigc://c"]
    table3 = rendezvous_assign(members, 64)
    per = {m: sum(1 for v in table3.values() if v == m) for m in members}
    assert all(8 <= n <= 40 for n in per.values()), per  # no starved member
    # c leaves: ONLY c's shards move.
    table2 = rendezvous_assign(members[:2], 64)
    for shard, owner in table3.items():
        if owner != "uigc://c":
            assert table2[shard] == owner
    # assignment is order-insensitive in the member list
    assert rendezvous_assign(list(reversed(members)), 64) == table3


def test_shard_table_version_ordering():
    t1 = ShardTable(1, "uigc://a", {0: "uigc://a"})
    t2 = ShardTable(2, "uigc://b", {0: "uigc://b"})
    assert t2.supersedes(t1) and not t1.supersedes(t2)
    # equal versions, equal content: no churn
    assert not ShardTable(2, "uigc://a", {0: "uigc://b"}).supersedes(t2) or True
    same_v = ShardTable(2, "uigc://a", {0: "uigc://a"})
    # deterministic tiebreak on origin for divergent same-version tables
    assert same_v.supersedes(t2) != t2.supersedes(same_v)


# ------------------------------------------------------------------- #
# Wire layer: frame round-trips + tolerance
# ------------------------------------------------------------------- #


def test_cluster_frame_round_trip_property():
    """Round-trip every cluster frame kind (plus the app-frame trace
    header) through the transport's actual byte framing, including the
    version-tolerance clause: decoders accept frames with extra
    trailing elements and reject malformed ones with None, never an
    exception."""
    import random

    from uigc_tpu.runtime.node import _frame_bytes
    import pickle
    import struct

    def round_trip(frame):
        buf = _frame_bytes(("f", 7, frame))
        (n,) = struct.unpack(">I", buf[:4])
        assert n == len(buf) - 4
        kind, seq, inner = pickle.loads(buf[4:])
        assert (kind, seq) == ("f", 7)
        return inner

    rng = random.Random(42)
    for trial in range(50):
        version = rng.randrange(1, 1000)
        assignments = {
            s: f"uigc://n{rng.randrange(4)}" for s in range(rng.randrange(1, 32))
        }
        fence = rng.randrange(4)
        shard = wire.encode_shard_frame(version, "uigc://n0", assignments, fence)
        assert wire.decode_shard_frame(round_trip(shard)) == (
            version,
            "uigc://n0",
            assignments,
            fence,
        )
        # A pre-fencing peer's 4-element frame decodes with fence 0.
        assert wire.decode_shard_frame(
            ("shard", version, "uigc://n0", assignments)
        ) == (version, "uigc://n0", assignments, 0)
        payload = bytes(rng.randrange(256) for _ in range(rng.randrange(64)))
        ent = wire.encode_entity_frame(
            "counter", f"k{trial}", trial % 9, payload, fence
        )
        assert wire.decode_entity_frame(round_trip(ent)) == (
            "counter",
            f"k{trial}",
            trial % 9,
            payload,
            fence,
        )
        assert wire.decode_entity_frame(
            ("ent", "counter", f"k{trial}", trial % 9, payload)
        )[4] == 0
        mig_id = (f"uigc://n{trial % 3}", trial)
        mig = wire.encode_migration_frame(
            "counter", f"k{trial}", mig_id, payload, fence, trial * 7
        )
        assert wire.decode_migration_frame(round_trip(mig)) == (
            "counter",
            f"k{trial}",
            mig_id,
            payload,
            fence,
            trial * 7,
        )
        # A PR-14 frame (no trailing epoch element) scans as epoch 0.
        legacy_mig = ("mig", "counter", f"k{trial}", mig_id, payload, fence)
        assert wire.decode_migration_frame(legacy_mig)[5] == 0
        ack = wire.encode_migration_ack("counter", f"k{trial}", mig_id)
        assert wire.decode_migration_ack(round_trip(ack)) == (
            "counter",
            f"k{trial}",
            mig_id,
        )
        # Tolerance: a NEWER peer appended fields — decode still works.
        assert wire.decode_shard_frame(shard + ("future",))[0] == version
        assert wire.decode_entity_frame(ent + ("future",))[3] == payload
        assert wire.decode_migration_frame(mig + ("future",))[2] == mig_id
        assert wire.decode_migration_ack(ack + ("future",))[2] == mig_id
    # Malformed frames decode to None, never raise.
    assert wire.decode_shard_frame(("shard",)) is None
    assert wire.decode_shard_frame(("shard", "x", "o", [])) is None
    assert wire.decode_entity_frame(("ent", "t", "k", 0, "not-bytes")) is None
    assert wire.decode_migration_frame(("mig", "t", "k", "not-tuple", b"")) is None
    assert wire.decode_migration_ack(("miga", "t")) is None
    # App-frame trace headers survive encode/decode alongside.
    class _Msg:
        trace_ctx = (123, 456)

    header = wire.encode_trace_header(_Msg())
    assert header == (123, 456)


def test_trace_header_and_shard_leave_decoders_tolerate_malformed_input():
    """Pin the malformed-input (-> None) tolerance contract of the two
    decoders the round-trip property test does not reach: a received
    trace header and the voluntary-departure frame.  Surfaced by
    uigc-check (UC105): both decoders promise None-never-raise but had
    no test reference pinning it."""
    # decode_trace_header: anything that is not a (trace_id, span_id)
    # pair of non-negative ints is absent, never an error.
    assert wire.decode_trace_header(None) is None
    assert wire.decode_trace_header((123, 456)) == (123, 456)
    for junk in (
        "not-a-header",
        (1,),
        (1, 2, 3),
        (-1, 2),
        (1, -2),
        ("1", 2),
        (1.0, 2),
        [1, 2],
        {"trace": 1},
        b"\x00\x01",
    ):
        assert wire.decode_trace_header(junk) is None
    # decode_shard_leave: origin round-trips; a frame whose origin slot
    # is missing or not a string decodes to None.
    assert wire.decode_shard_leave(wire.encode_shard_leave("uigc://a")) == (
        "uigc://a"
    )
    # Trailing elements from a newer peer are tolerated.
    assert wire.decode_shard_leave(("sleave", "uigc://a", "extra")) == "uigc://a"
    for junk in (("sleave",), ("sleave", 7), ("sleave", None), ("sleave", b"a")):
        assert wire.decode_shard_leave(junk) is None


def test_unknown_frame_kind_neither_crashes_nor_desyncs(event_log):
    """An old-version peer receiving an unknown frame kind must ignore
    it AND keep its sequence numbers in step: the frames after it are
    neither gap-flagged nor dropped."""
    nodes = build_cluster(["tolera", "tolerb"])
    a, b = nodes
    try:
        connect_mesh(nodes)
        # A speaks a frame kind from the future, mid-stream.
        assert a.fabric.send_frame(b.address, ("frame-from-the-future", 1, 2, 3))

        # Then normal entity traffic keyed to land on B.  B only homes
        # keys once A's shard table has adopted it as a member, so wait
        # for the membership gossip rather than racing it.
        def keys_on_b():
            return [
                f"k{i}"
                for i in range(200)
                if a.cluster.home_of(f"k{i}") == b.address
            ][:10]

        assert settle(lambda: bool(keys_on_b())), "no key homed on B?"
        b_keys = keys_on_b()
        for k in b_keys:
            a.cluster.entity_ref("counter", k).tell(("incr",))
        assert settle(lambda: b.region.active_count() >= len(b_keys))
        st = b.fabric._peer_state(a.address)
        assert st.gaps == 0, "unknown frame kind desynced the seq layer"
        assert not event_log.of(events.FRAME_GAP)
        assert not event_log.of(events.NODE_DOWN)
    finally:
        terminate_all(nodes)


# ------------------------------------------------------------------- #
# Name registry satellite
# ------------------------------------------------------------------- #


def test_register_name_duplicate_raises_and_lookup_miss_emits(event_log):
    nodes = build_cluster(["namesa", "namesb"])
    a, b = nodes
    try:
        connect_mesh(nodes)
        cell1 = a.system.spawn_system_raw(Collector(), "svc-one")
        cell2 = a.system.spawn_system_raw(Collector(), "svc-two")
        a.fabric.register_name("svc", cell1)
        a.fabric.register_name("svc", cell1)  # same cell: idempotent
        with pytest.raises(DuplicateNameError) as exc:
            a.fabric.register_name("svc", cell2)
        assert exc.value.rule == "fabric.name_duplicate"
        assert exc.value.payload["name"] == "svc"
        # Lookup of a name the peer never advertised: structured error
        # (still a KeyError for legacy retry loops) + lookup_miss event.
        with pytest.raises(NameLookupError):
            b.fabric.lookup(a.address, "no-such-name")
        with pytest.raises(KeyError):
            b.fabric.lookup(a.address, "no-such-name")
        misses = event_log.of(events.LOOKUP_MISS)
        assert len(misses) >= 2 and misses[0]["lookup"] == "no-such-name"
    finally:
        terminate_all(nodes)


# ------------------------------------------------------------------- #
# Integration: passivation and migration
# ------------------------------------------------------------------- #


def test_single_node_passivation_resurrects_state(event_log):
    config = dict(BASE, **{"uigc.crgc.num-nodes": 1})
    system = ActorSystem(None, name="passv", config=config)
    try:
        cluster = ClusterSharding.attach(system)
        region = cluster.start("counter", counter_factory, passivate_after_s=0.15)
        for i in range(8):
            ref = region.entity_ref(f"k{i}")
            for _ in range(i + 1):
                ref.tell(("incr",))
        assert settle(lambda: region.active_count() == 8, timeout_s=5.0)
        live_before = system.live_actor_count
        # Idle out: every entity spills and stops.
        assert settle(lambda: region.passive_count() == 8), (
            region.active_count(),
            region.passive_count(),
        )
        assert region.active_count() == 0
        assert settle(lambda: system.live_actor_count <= live_before - 8)
        assert len(event_log.of(events.SHARD_ENTITY_PASSIVATED)) >= 8
        # Next send resurrects with state intact.
        coll = Collector()
        coll_cell = system.spawn_system_raw(coll, "coll")
        for i in range(8):
            region.entity_ref(f"k{i}").tell(("probe", coll_cell))
        assert settle(lambda: len(coll.snapshot()) == 8)
        assert coll.snapshot() == {f"k{i}": i + 1 for i in range(8)}
        resumed = [
            f
            for f in event_log.of(events.SHARD_ENTITY_ACTIVATED)
            if f.get("resumed")
        ]
        assert len(resumed) >= 8
    finally:
        system.terminate()


def test_two_node_join_migrates_live_state(event_log):
    """Entities spawn on a lone node; a second node joins; the shard
    table rebalances and the moved entities carry their state across
    the wire, answering probes from either side afterwards."""
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = 2
    a = Node("joina", config)
    b = None
    try:
        keys = [f"k{i}" for i in range(40)]
        for k in keys:
            ref = a.region.entity_ref(k)
            ref.tell(("incr",))
            ref.tell(("incr",))
        assert settle(lambda: a.region.active_count() == 40, timeout_s=10.0)

        b = Node("joinb", config)
        a.fabric.connect("127.0.0.1", b.port)
        assert settle(
            lambda: a.cluster.migrations.pending_count() == 0
            and a.region.active_count() + b.region.active_count() == 40
            and b.region.active_count() > 0,
            timeout_s=15.0,
        ), (a.region.active_count(), b.region.active_count())
        assert a.cluster.migrations.completed == b.region.active_count()
        migrations = event_log.of(events.SHARD_MIGRATION)
        assert len(migrations) == b.region.active_count()
        assert all(f["duration_s"] > 0 for f in migrations)

        # Both nodes agree on the table and answer probes for ALL keys.
        assert a.cluster.table_snapshot().version == b.cluster.table_snapshot().version
        coll = Collector()
        coll_cell = b.system.spawn_system_raw(coll, "coll")
        for k in keys:
            b.cluster.entity_ref("counter", k).tell(("probe", coll_cell))
        assert settle(lambda: len(coll.snapshot()) == 40, timeout_s=15.0)
        assert all(v == 2 for v in coll.snapshot().values()), coll.snapshot()
    finally:
        terminate_all([n for n in (a, b) if n is not None])


def test_deliver_local_rechecks_ownership_before_blank_spawn(event_log):
    """The rebalance-under-traffic lost-incr race, pinned: a sender
    thread that resolved the key's home BEFORE a handoff completed must
    not blank-spawn the key at the OLD owner — deliver_local rechecks
    the table at the spawn boundary and re-routes instead."""
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = 2
    a = Node("recheck-a", config)
    b = None
    try:
        b = Node("recheck-b", config)
        a.fabric.connect("127.0.0.1", b.port)
        assert settle(
            lambda: len(a.cluster.members()) == 2
            and len(b.cluster.members()) == 2
            and a.cluster.table_snapshot().version
            == b.cluster.table_snapshot().version,
            timeout_s=15.0,
        )
        key = next(
            k
            for k in (f"k{i}" for i in range(400))
            if a.cluster.home_of(k) == b.address
        )
        # Simulate the stale race deterministically: the caller's
        # home_of read happened "before" the rebalance — deliver
        # straight into A's region although the table names B.
        a.region.deliver_local(key, ("incr",))
        assert key not in a.region.record_keys()
        forwarded = [
            f
            for f in event_log.of(events.SHARD_FORWARDED)
            if f.get("site") == "spawn_recheck"
        ]
        assert forwarded and forwarded[0]["key"] == key
        # The message re-routed to the real owner — nothing lost.
        assert settle(lambda: b.region.active_count() == 1, timeout_s=15.0)
        coll = Collector()
        coll_cell = a.system.spawn_system_raw(coll, "coll")
        a.cluster.entity_ref("counter", key).tell(("probe", coll_cell))
        assert settle(lambda: coll.snapshot().get(key) == 1, timeout_s=15.0)
    finally:
        terminate_all([n for n in (a, b) if n is not None])


def test_rebalance_under_traffic_loses_no_state(event_log):
    """The shard-grant protocol: a node join mid-traffic must not let
    an on-demand spawn at the new owner race (and discard) the in-flight
    migration snapshot.  Every incr sent is reflected in the final
    counts — no state conflict, no loss."""
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = 2
    # A loaded CI host can stretch the 60-key handoff past the default
    # 3s hold-timeout, and an expired hold reopens the blank-spawn-vs-
    # in-flight-snapshot race at the NEW owner (the old-owner side is
    # closed by deliver_local's ownership recheck).  The timeout is a
    # wedge safety valve, not a pacing device — give it slack, as the
    # rolling-restart scenario already does.
    config["uigc.cluster.hold-timeout"] = 15000
    a = Node("granta", config)
    b = None
    try:
        keys = [f"k{i}" for i in range(60)]
        sent = {k: 0 for k in keys}
        for k in keys:
            a.region.entity_ref(k).tell(("incr",))
            sent[k] += 1
        assert settle(lambda: a.region.active_count() == 60)

        # Join B while hammering the keyspace from A's side.
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                k = keys[i % len(keys)]
                a.cluster.entity_ref("counter", k).tell(("incr",))
                sent[k] += 1
                i += 1
                time.sleep(0.001)

        churner = threading.Thread(target=churn, daemon=True)
        churner.start()
        b = Node("grantb", config)
        a.fabric.connect("127.0.0.1", b.port)
        assert settle(
            lambda: a.cluster.migrations.pending_count() == 0
            and b.region.active_count() > 0,
            timeout_s=15.0,
        )
        stop.set()
        churner.join(timeout=5)

        coll = Collector()
        coll_cell = a.system.spawn_system_raw(coll, "coll")
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            for k in keys:
                if coll.snapshot().get(k) != sent[k]:
                    a.cluster.entity_ref("counter", k).tell(("probe", coll_cell))
            if all(coll.snapshot().get(k) == sent[k] for k in keys):
                break
            time.sleep(0.3)
        got = coll.snapshot()
        lost = {k: (got.get(k), sent[k]) for k in keys if got.get(k) != sent[k]}
        assert not lost, f"state lost across rebalance: {lost}"
        assert not event_log.of(events.SHARD_STATE_CONFLICT)
    finally:
        terminate_all([n for n in (a, b) if n is not None])


def test_passivated_state_ships_on_rebalance(event_log):
    """A PASSIVATED entity's spilled snapshot must follow its key to
    the new owner on rebalance — otherwise the store copy strands on
    the old node and the new owner recreates the entity blank."""
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = 2
    a = Node("spassa", config, passivate_after_s=0.12)
    b = None
    try:
        keys = [f"k{i}" for i in range(20)]
        for i, k in enumerate(keys):
            ref = a.region.entity_ref(k)
            for _ in range(i + 1):
                ref.tell(("incr",))
        # Idle out: everything spills to A's store.
        assert settle(lambda: a.region.passive_count() == 20, timeout_s=10.0)

        b = Node("spassb", config, passivate_after_s=None)
        a.fabric.connect("127.0.0.1", b.port)
        # B's share of the keyspace must arrive as shipped snapshots
        # (applied straight into active cells), not blank respawns.
        assert settle(
            lambda: a.cluster.migrations.pending_count() == 0
            and len(b.cluster.members()) == 2
            and b.region.active_count() + b.region.passive_count() > 0,
            timeout_s=15.0,
        )
        coll = Collector()
        coll_cell = b.system.spawn_system_raw(coll, "coll")
        for k in keys:
            b.cluster.entity_ref("counter", k).tell(("probe", coll_cell))
        assert settle(lambda: len(coll.snapshot()) == 20, timeout_s=15.0)
        assert coll.snapshot() == {f"k{i}": i + 1 for i in range(20)}, (
            coll.snapshot()
        )
    finally:
        terminate_all([n for n in (a, b) if n is not None])


def test_entity_ref_crosses_the_wire_inside_a_message():
    """An EntityRef shipped inside a message re-binds to the receiving
    node's region (wire token ("entity", type, key)) and keeps routing
    location-transparently."""
    nodes = build_cluster(["xrefa", "xrefb"])
    a, b = nodes
    try:
        connect_mesh(nodes)
        assert settle(lambda: len(a.cluster.members()) == 2)
        keys = [f"k{i}" for i in range(100)]
        on_a = next(k for k in keys if a.cluster.home_of(k) == a.address)
        on_b = next(k for k in keys if a.cluster.home_of(k) == b.address)
        # Seed the A-homed counter, then teach the B-homed one to poke it.
        a.cluster.entity_ref("counter", on_a).tell(("incr",))
        peer_ref = a.cluster.entity_ref("counter", on_a)
        a.cluster.entity_ref("counter", on_b).tell(("adopt", peer_ref))
        a.cluster.entity_ref("counter", on_b).tell(("poke-peer",))
        coll = Collector()
        coll_cell = a.system.spawn_system_raw(coll, "coll")
        assert settle(
            lambda: (
                a.cluster.entity_ref("counter", on_a).tell(("probe", coll_cell))
                or coll.snapshot().get(on_a) == 2
            ),
            timeout_s=15.0,
        ), coll.snapshot()
    finally:
        terminate_all(nodes)


def test_shard_metrics_exported(event_log):
    """The metrics satellite: shard-table size, entity counts, and the
    migration latency histogram all land in the Prometheus text."""
    from uigc_tpu.telemetry import prometheus_text

    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = 2
    config["uigc.telemetry.metrics"] = True
    a = Node("meta", config)
    b = None
    try:
        for i in range(20):
            a.region.entity_ref(f"k{i}").tell(("incr",))
        assert settle(lambda: a.region.active_count() == 20)
        b = Node("metb", config)
        a.fabric.connect("127.0.0.1", b.port)
        assert settle(
            lambda: a.cluster.migrations.pending_count() == 0
            and b.region.active_count() > 0
            and a.cluster.migrations.completed > 0,
            timeout_s=15.0,
        )
        text = prometheus_text(a.system.telemetry.registry)
        assert "uigc_shard_table_size" in text
        assert "uigc_shard_entities_active" in text
        assert "uigc_shard_migrations_total" in text
        assert "uigc_shard_migration_seconds_count" in text
        reg = a.system.telemetry.registry
        assert reg.counter("uigc_shard_migrations_total").value() > 0
        hist = reg.histogram("uigc_shard_migration_seconds")
        # completed increments under the manager lock a hair BEFORE the
        # SHARD_MIGRATION event commits (migration.py), so the histogram
        # can trail by one for a moment — settle, don't race it.
        assert settle(
            lambda: hist.snapshot()["n"] == a.cluster.migrations.completed,
            timeout_s=5.0,
        )
        assert (
            reg.gauge("uigc_shard_table_size").samples()[0][2] == 32.0
        )
    finally:
        terminate_all([n for n in (a, b) if n is not None])


# ------------------------------------------------------------------- #
# Acceptance: 3-node chaos rebalance
# ------------------------------------------------------------------- #


def test_chaos_node_kill_rehomes_every_entity(event_log):
    """The acceptance scenario: >= 200 keyed entities across 3 nodes
    with traffic in flight; migration frames on the surviving pair are
    seeded to drop (the retry/dedup protocol must neither lose nor
    duplicate state); node C is killed mid-traffic; the heartbeat
    declares it dead, the shard table rebalances, and EVERY entity
    answers a post-rebalance probe — with the uigcsan sanitizer
    attached and reporting zero violations on the survivors."""
    plan = FaultPlan(1234)
    nodes = build_cluster(
        ["chshard-a", "chshard-b", "chshard-c"],
        plan=plan,
        overrides={
            "uigc.node.heartbeat-interval": 40,
            "uigc.node.phi-threshold": 6.0,
            "uigc.node.heartbeat-pause": 400,
            "uigc.analysis.sanitizer": True,
        },
    )
    a, b, c = nodes
    try:
        connect_mesh(nodes)
        assert settle(
            lambda: all(len(n.cluster.members()) == 3 for n in nodes),
            timeout_s=10.0,
        )
        # Seeded drops on the surviving pair's migration/ack frames:
        # handoffs triggered by the rebalance MUST survive frame loss.
        plan.drop(src=a.address, dst=b.address, kind=("mig", "miga"), prob=0.4, count=30)
        plan.drop(src=b.address, dst=a.address, kind=("mig", "miga"), prob=0.4, count=30)

        n_entities = 220
        keys = [f"user-{i}" for i in range(n_entities)]
        for i, key in enumerate(keys):
            nodes[i % 3].cluster.entity_ref("counter", key).tell(("incr",))
        assert settle(
            lambda: sum(n.region.active_count() for n in nodes) == n_entities,
            timeout_s=30.0,
        ), [n.region.active_count() for n in nodes]
        dead_keys = {k for k in keys if a.cluster.home_of(k) == c.address}
        assert dead_keys, "no entity homed on the doomed node?"

        # Kill C mid-traffic: links dark, engine stopped, sockets open —
        # only the heartbeat can see it (the PR 1 failure detector).
        churn_stop = threading.Event()

        def churn():
            i = 0
            while not churn_stop.is_set():
                a.cluster.entity_ref("counter", keys[i % n_entities]).tell(("incr",))
                i += 1
                time.sleep(0.002)

        churner = threading.Thread(target=churn, daemon=True)
        churner.start()
        time.sleep(0.2)
        plan.isolate(c.address)
        c.system.engine.on_crash()

        assert settle(
            lambda: c.address not in a.cluster.members()
            and c.address not in b.cluster.members(),
            timeout_s=30.0,
        ), "heartbeat never declared C dead"
        churn_stop.set()
        churner.join(timeout=5)

        # Rebalance settles: survivors agree on a table without C, and
        # no handoff is stuck (the dropped mig frames were re-shipped).
        assert settle(
            lambda: a.cluster.migrations.pending_count() == 0
            and b.cluster.migrations.pending_count() == 0
            and a.cluster.table_snapshot().assignments
            == b.cluster.table_snapshot().assignments,
            timeout_s=30.0,
        )
        assert all(
            owner != c.address
            for owner in a.cluster.table_snapshot().assignments.values()
        )

        # EVERY entity answers a post-rebalance probe — C's entities
        # recreate on demand at their new home.
        coll = Collector()
        coll_cell = a.system.spawn_system_raw(coll, "coll")
        deadline = time.monotonic() + 45.0
        while time.monotonic() < deadline:
            missing = [k for k in keys if k not in coll.snapshot()]
            if not missing:
                break
            for k in missing:
                a.cluster.entity_ref("counter", k).tell(("probe", coll_cell))
            time.sleep(0.4)
        missing = [k for k in keys if k not in coll.snapshot()]
        assert not missing, f"{len(missing)} entities never answered: {missing[:5]}"

        # Nothing dropped silently: entities homed on the SURVIVORS
        # kept their state through the churn and the rebalance's live
        # migrations; entities homed on C lost exactly the in-memory
        # state that died with the node — and the messages that went
        # dark with it are the ones PR 1's accounting tallied (fault
        # plan drops on the isolated links + dead letters), visible in
        # the event stream rather than silently gone.
        counts = coll.snapshot()
        survivor_losses = [
            k for k in keys if k not in dead_keys and counts[k] < 1
        ]
        assert not survivor_losses, survivor_losses
        from uigc_tpu.runtime.faults import DROP

        tallied_drops = sum(
            n for (action, src, _dst), n in plan.stats.items()
            if action == DROP
        )
        assert tallied_drops > 0 or event_log.of(events.FRAME_DROPPED)

        # GC soundness held throughout: the sanitizer saw no premature
        # terminate, no verdict mismatch — across live migrations, a
        # node death, and the rebalance.
        for node in (a, b):
            violations = node.system.sanitizer.violations
            assert not violations, [str(v) for v in violations]
    finally:
        terminate_all(nodes)
