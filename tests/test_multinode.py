"""Multi-node CRGC: delta replication, remote collection, crash recovery.

The in-repo multi-node harness the reference lacks (SURVEY §4).  Covers:
- membership gating (num-nodes),
- remote spawn + cross-node release collected via delta broadcast,
- node crash with undo-log recovery (BASELINE config 4), including with
  injected message drops on the dead link.
"""

import time

import pytest

from uigc_tpu import AbstractBehavior, Behaviors, Message, NoRefs, PostStop
from uigc_tpu.runtime.fabric import Fabric
from uigc_tpu.runtime.remote import RemoteSpawner
from uigc_tpu.runtime.system import ActorSystem
from uigc_tpu.runtime.testkit import TestProbe as Probe

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
}


from conftest import NATIVE_BACKEND

BACKENDS = ["array", "mesh", NATIVE_BACKEND]

#: link modes: "direct" = in-process objects, synchronous lockstep links;
#: "wire" = every message serialized to bytes (object identity destroyed)
#: over async FIFO links with window-id-matched ingress finalization.
WIRE_MODES = ["direct", "wire"]


def make_fabric(wire_mode):
    return Fabric(serialize=wire_mode == "wire", async_links=wire_mode == "wire")


def make_system(name, fabric, num_nodes, backend="array"):
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = num_nodes
    config["uigc.crgc.shadow-graph"] = backend
    return ActorSystem(None, name=name, config=config, fabric=fabric)


class Ping(NoRefs):
    pass


class Drop(NoRefs):
    pass


class Share(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,) if self.ref is not None else ()


class Spawned(NoRefs):
    def __init__(self, name):
        self.name = name


class Stopped(NoRefs):
    def __init__(self, name):
        self.name = name


class Worker(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.pings = 0
        self.peer = None
        probe.ref.tell(Spawned(context.name))

    def on_message(self, msg):
        if isinstance(msg, Ping):
            self.pings += 1
        elif isinstance(msg, Share):
            self.peer = msg.ref
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Stopped(self.context.name))
        return None


def worker_factory(probe):
    return Behaviors.setup(lambda ctx: Worker(ctx, probe))


class Root(AbstractBehavior):
    """Root on node A; spawns a worker remotely on node B."""

    def __init__(self, context, probe, spawner_cell):
        super().__init__(context)
        self.probe = probe
        self.spawner_cell = spawner_cell
        self.remote_worker = None

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Spawned):  # used as "go" trigger
            self.remote_worker = ctx.spawn_remote("worker", self.spawner_cell)
            for _ in range(5):
                self.remote_worker.tell(Ping(), ctx)
        elif isinstance(msg, Drop):
            ctx.release(self.remote_worker)
        return self


@pytest.mark.parametrize("wire_mode", WIRE_MODES)
@pytest.mark.parametrize("backend", BACKENDS)
def test_two_node_remote_spawn_and_collect(backend, wire_mode):
    fabric = make_fabric(wire_mode)
    sys_a = make_system("nodeA", fabric, 2, backend)
    sys_b = make_system("nodeB", fabric, 2, backend)
    try:
        probe = Probe(default_timeout_s=15.0)
        spawner = RemoteSpawner.spawn_service(
            sys_b, {"worker": worker_factory(probe)}
        )
        root = sys_a.spawn_root(
            Behaviors.setup_root(lambda ctx: Root(ctx, probe, spawner)), "root"
        )
        root.tell(Spawned("go"))
        spawned = probe.expect_message_type(Spawned)
        assert "nodeB" not in spawned.name  # path is on B's hierarchy
        # The worker lives on B, referenced only from A. Releasing on A
        # must propagate via delta broadcast and kill it on B.
        time.sleep(0.3)
        root.tell(Drop())
        stopped = probe.expect_message_type(Stopped)
        assert stopped.name == spawned.name
    finally:
        sys_a.terminate()
        sys_b.terminate()


class Holder(AbstractBehavior):
    """Root on a doomed node, holding a ref to a remote worker."""

    def __init__(self, context, probe):
        super().__init__(context)
        self.held = None

    def on_message(self, msg):
        if isinstance(msg, Share):
            self.held = msg.ref
            # Keep the worker busy-ish across the link.
            self.held.tell(Ping(), self.context)
        return self


class Owner(AbstractBehavior):
    """Root on node B owning the worker; hands a ref to the doomed node's
    holder, then releases its own."""

    def __init__(self, context, probe, holder_refs):
        super().__init__(context)
        self.probe = probe
        self.worker = context.spawn(worker_factory(probe), "worker")
        self.holder_refs = holder_refs

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Share):
            for holder in self.holder_refs:
                holder.tell(Share(ctx.create_ref(self.worker, holder)), ctx)
        elif isinstance(msg, Drop):
            ctx.release(self.worker)
        return self


@pytest.mark.parametrize("wire_mode", WIRE_MODES)
@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("with_drops", [False, True], ids=["clean", "drops"])
def test_three_node_crash_recovery(with_drops, backend, wire_mode):
    """A worker on B is kept alive solely by a ref held on C.  C crashes;
    the undo-log quorum reverts C's claims and the worker is collected.
    With drops injected on the C->B link, admitted counts diverge from
    claims — exactly what the ingress-entry machinery reconciles."""
    fabric = make_fabric(wire_mode)
    sys_a = make_system("cnodeA", fabric, 3, backend)
    sys_b = make_system("cnodeB", fabric, 3, backend)
    sys_c = make_system("cnodeC", fabric, 3, backend)
    try:
        # 20s doubles as the regression guard for the idle-wake trace
        # convoy (collector._graph_dirty): post-fix recovery runs in
        # 0.6-2.4s; the convoy regime was 18-60s.
        probe = Probe(default_timeout_s=20.0)

        holder = sys_c.spawn_root(
            Behaviors.setup_root(lambda ctx: Holder(ctx, probe)), "holder"
        )
        # Give Owner a managed route to the holder on C via its root refob.
        owner = sys_b.spawn_root(
            Behaviors.setup_root(
                lambda ctx: Owner(
                    ctx, probe, [ctx.engine.to_root_refob(holder.cell)]
                )
            ),
            "owner",
        )
        probe.expect_message_type(Spawned)

        if with_drops:
            # Drop every ping on the C->B link (but not ref-carrying
            # shares, which travel B->C).
            fabric.set_drop_filter(
                sys_c, sys_b, lambda m: isinstance(getattr(m, "payload", None), Ping)
            )

        owner.tell(Share(None))  # hand the ref to C's holder
        time.sleep(0.4)
        owner.tell(Drop())  # B releases; only C's ref keeps the worker
        probe.expect_no_message(0.5)

        # C crashes. Survivors finalize the dead links, reach quorum,
        # fold the undo log, and the worker finally collapses.
        fabric.crash(sys_c)
        stopped = probe.expect_message_type(Stopped)
        assert stopped.name.endswith("/worker")
    finally:
        sys_a.terminate()
        sys_b.terminate()
        sys_c.terminate()


@pytest.mark.parametrize("wire_mode", WIRE_MODES)
@pytest.mark.parametrize("backend", BACKENDS)
def test_double_crash_quorum_recheck(backend, wire_mode):
    """If a second node dies before delivering its final ingress entry
    for the first dead node, the shrunken quorum must be re-evaluated on
    membership change — otherwise the first node's undo log never folds
    and its actors leak as eternal pseudoroots."""
    fabric = make_fabric(wire_mode)
    sys_a = make_system("dcA", fabric, 3, backend)
    sys_b = make_system("dcB", fabric, 3, backend)
    sys_c = make_system("dcC", fabric, 3, backend)
    try:
        # 20s doubles as the regression guard for the idle-wake trace
        # convoy (collector._graph_dirty): post-fix recovery runs in
        # 0.6-2.4s; the convoy regime was 18-60s.
        probe = Probe(default_timeout_s=20.0)
        holder = sys_c.spawn_root(
            Behaviors.setup_root(lambda ctx: Holder(ctx, probe)), "holder"
        )
        owner = sys_b.spawn_root(
            Behaviors.setup_root(
                lambda ctx: Owner(ctx, probe, [ctx.engine.to_root_refob(holder.cell)])
            ),
            "owner",
        )
        probe.expect_message_type(Spawned)
        owner.tell(Share(None))
        time.sleep(0.4)
        owner.tell(Drop())
        probe.expect_no_message(0.3)

        # Crash C, then immediately crash A — before A's final entry for
        # the C links could possibly be required: B's quorum for log[C]
        # initially includes A, and must shrink when A is removed.
        fabric.crash(sys_c)
        fabric.crash(sys_a)
        stopped = probe.expect_message_type(Stopped)
        assert stopped.name.endswith("/worker")
    finally:
        sys_a.terminate()
        sys_b.terminate()
        sys_c.terminate()
