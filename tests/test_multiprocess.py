"""Multi-process cluster: collectors in separate OS processes over real
TCP sockets (runtime/node.py), with ``kill -9`` as the crash injection.

The cross-process port of ``test_three_node_crash_recovery``: a worker
on node B (child process) is kept alive solely by a ref held on node C
(another child process).  C is SIGKILLed; the survivors see the socket
die, finalize the dead links, reach the undo-log quorum over the
network, and the worker is collected — observed by the driver process
(node A) through its probe.  This is the failure mode the in-process
fabric cannot produce: a peer that vanishes mid-protocol with no
opportunity to flush anything beyond what the kernel already accepted.

Reference: reference.conf:2-10 (real Artery transport),
LocalGC.scala:201 (cross-network collector gossip), 228-243 (member
removal recovery).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path
from queue import Empty, Queue

import pytest

from nodeproc_common import BASE, ProbeForwarder, Spawned, Stopped

from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.runtime.system import ActorSystem
from uigc_tpu.runtime.testkit import TestProbe

CHILD = Path(__file__).resolve().parent / "nodeproc_child.py"


class Child:
    """A node child process with line-based stdin/stdout control."""

    def __init__(self, spec: dict):
        self.proc = subprocess.Popen(
            [sys.executable, str(CHILD), json.dumps(spec)],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            bufsize=1,
        )
        self._lines: Queue = Queue()
        threading.Thread(target=self._pump, daemon=True).start()
        self.port = int(self.expect("READY").split()[1])

    def _pump(self):
        for line in self.proc.stdout:
            self._lines.put(line.strip())

    def send(self, cmd: str) -> None:
        self.proc.stdin.write(cmd + "\n")
        self.proc.stdin.flush()

    def expect(self, prefix: str, timeout: float = 30.0) -> str:
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise AssertionError(
                    f"child did not print {prefix!r} in time; stderr:\n"
                    + (self.proc.stderr.read() if self.proc.poll() is not None else "")
                )
            try:
                line = self._lines.get(timeout=remaining)
            except Empty:
                continue
            if line.startswith(prefix):
                return line
            if line.startswith("ERROR"):
                raise AssertionError(f"child error: {line}")

    def kill9(self) -> None:
        os.kill(self.proc.pid, signal.SIGKILL)
        self.proc.wait(timeout=10)

    def shutdown(self) -> None:
        if self.proc.poll() is None:
            try:
                self.send("exit")
                self.proc.wait(timeout=5)
            except Exception:
                self.proc.kill()
                self.proc.wait(timeout=5)


@pytest.mark.parametrize("with_drops", [False, True], ids=["clean", "drops"])
def test_multiprocess_three_node_crash_recovery(with_drops):
    config = dict(BASE)
    config["uigc.crgc.shadow-graph"] = "array"

    fabric = NodeFabric()
    system = ActorSystem(None, name="procA", config=config, fabric=fabric)
    child_b = child_c = None
    try:
        probe = TestProbe(default_timeout_s=30.0)
        probe_cell = system.spawn_system_raw(ProbeForwarder(probe), "probe-fwd")
        fabric.register_name("probe", probe_cell)
        fabric.listen()

        child_c = Child({"role": "holder", "address": "procC"})
        child_b = Child(
            {"role": "owner", "address": "procB", "with_drops": with_drops}
        )

        # full mesh: A dials both children; B dials C
        fabric.connect("127.0.0.1", child_b.port)
        fabric.connect("127.0.0.1", child_c.port)
        child_b.send(f"connect 127.0.0.1:{child_c.port}")
        child_b.expect("CONNECTED")

        child_b.send("spawn_owner procC procA")
        child_b.expect("OWNER_SPAWNED")
        spawned = probe.expect_message_type(Spawned)

        child_b.send("share")  # hand the only surviving ref to C's holder
        child_b.expect("SHARED")
        time.sleep(0.5)
        child_b.send("drop")  # B releases; only C's ref keeps the worker
        child_b.expect("DROPPED")
        probe.expect_no_message(0.5)

        # C vanishes mid-protocol.  Survivors detect the dead socket,
        # finalize the dead links, reach quorum, fold the undo log, and
        # the worker on B finally collapses.
        child_c.kill9()
        stopped = probe.expect_message_type(Stopped)
        assert stopped.name == spawned.name
    finally:
        if child_b is not None:
            child_b.shutdown()
        if child_c is not None:
            child_c.shutdown()
        system.terminate()


class SpawningRoot:
    """Managed root on the driver: spawns a worker in the child process
    through its RemoteSpawner, pings it, releases on command."""

    def __new__(cls, context, spawner_proxy):
        from uigc_tpu.runtime.behaviors import AbstractBehavior

        from nodeproc_common import DropCmd, Ping

        class _Root(AbstractBehavior):
            def __init__(self, ctx):
                super().__init__(ctx)
                self.remote_worker = None

            def on_message(self, msg):
                ctx = self.context
                if isinstance(msg, Ping):  # "go" trigger
                    self.remote_worker = ctx.spawn_remote(
                        "worker", spawner_proxy
                    )
                    for _ in range(3):
                        self.remote_worker.tell(Ping(), ctx)
                elif isinstance(msg, DropCmd):
                    ctx.release(self.remote_worker)
                return self

        return _Root(context)


def test_multiprocess_remote_spawn_and_collect():
    """Cross-process remote spawn: the blocking ask crosses the socket
    as a wire frame (runtime/remote.py _SpawnWire) and the reply
    returns the spawned cell's token; releasing the only ref on the
    driver then collects the worker in the child process via delta
    gossip (the two-node remote-spawn test of test_multinode.py, with a
    real process boundary)."""
    from uigc_tpu.runtime.behaviors import Behaviors

    from nodeproc_common import DropCmd, Ping, Spawned, Stopped

    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = 2
    config["uigc.crgc.shadow-graph"] = "array"

    fabric = NodeFabric()
    system = ActorSystem(None, name="procA", config=config, fabric=fabric)
    child_b = None
    try:
        probe = TestProbe(default_timeout_s=30.0)
        probe_cell = system.spawn_system_raw(ProbeForwarder(probe), "probe-fwd")
        fabric.register_name("probe", probe_cell)
        fabric.listen()

        child_b = Child(
            {"role": "spawner", "address": "procB", "num_nodes": 2}
        )
        fabric.connect("127.0.0.1", child_b.port)

        spawner = fabric.lookup("uigc://procB", "spawner")
        root = system.spawn_root(
            Behaviors.setup_root(lambda ctx: SpawningRoot(ctx, spawner)),
            "root",
        )
        root.tell(Ping())  # go
        spawned = probe.expect_message_type(Spawned)
        assert spawned.name.startswith("/system/RemoteSpawner/remote-")

        time.sleep(0.4)
        root.tell(DropCmd())  # driver releases the only ref
        stopped = probe.expect_message_type(Stopped)
        assert stopped.name == spawned.name
    finally:
        if child_b is not None:
            child_b.shutdown()
        system.terminate()
