"""Device-plane observatory suite (uigc_tpu/telemetry/device.py).

Layers, bottom up:

- attribution math: per-sweep device-time split reconciles with the
  wake's measured device seconds by construction, dirty-chunk weights;
- ledger walk: duck-typed family tallies over host and device arrays,
  map-entry estimates, torn-read tolerance;
- donation audit: true positive on a forced copy (an un-donatable host
  buffer handed to a donating call), negative on a real donation;
- event folding: compile hit/miss streams, transfer phase attribution,
  origin scoping, registry counter names;
- live planes (decremental CPU backend under seeded churn): the
  memory ledger returns to baseline after sweeps free slots (no ledger
  leak), compile counters are exactly 1-miss-then-hits per geometry,
  the transfer accounter stays silent across transfer-free idle wakes,
  per-sweep attribution reconciles with the profiler's device phase
  within 10%, and ``/device`` serves a schema-valid document;
- the acceptance scenario: a deliberately planted regression — a
  per-wake recompile storm AND an un-donated buffer copy — fires
  ``recompile_storm`` and ``donation_copy_detected`` with the correct
  tag/site labels, and ``device_report`` attributes both to the
  correct plane;
- tools: bench_check's DEVICE family SKIPs honestly on the committed
  (CPU-only) trajectory and FAILs on a doctored regressed round;
  uigc_top's device panel degrades to dashes on nodes without the
  observatory; uigc-lint UL011 flags unannotated host transfers and
  honors the ``# readback:`` annotation.
"""

import json
import sys
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import bench_check  # noqa: E402
import device_report  # noqa: E402
import uigc_lint  # noqa: E402
import uigc_top  # noqa: E402

from uigc_tpu import (  # noqa: E402
    AbstractBehavior,
    ActorTestKit,
    Behaviors,
    NoRefs,
)
from uigc_tpu.engines.crgc.arrays import audit_donation  # noqa: E402
from uigc_tpu.telemetry.device import (  # noqa: E402
    DeviceObservatory,
    ledger_families,
    sweep_attribution,
    validate_device_doc,
)
from uigc_tpu.telemetry.metrics import MetricsRegistry  # noqa: E402
from uigc_tpu.utils import events  # noqa: E402

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def clean_recorder():
    """Telemetry enables the process-global recorder; leave no residue
    for the rest of the suite."""
    yield
    events.recorder.disable()
    events.recorder.reset()
    with events.recorder._lock:
        events.recorder._listeners.clear()


# ------------------------------------------------------------------- #
# Attribution math
# ------------------------------------------------------------------- #


def test_sweep_attribution_reconciles_by_construction():
    ms, bytes_est = sweep_attribution(0.012, 3, [100, 50, 1])
    assert len(ms) == len(bytes_est) == 3
    assert abs(sum(ms) - 12.0) < 1e-9
    # dirty-chunk weighting: the 100-chunk sweep gets 100/151 of it
    assert ms[0] > ms[1] > ms[2]
    assert abs(ms[0] - 12.0 * 100 / 151) < 1e-9
    assert bytes_est[0] == 100 * 12288  # CHUNK_BYTES_EST


def test_sweep_attribution_degrades_without_stats():
    ms, _ = sweep_attribution(0.010, 4, None)
    assert len(ms) == 4
    assert all(abs(x - 2.5) < 1e-9 for x in ms)
    assert sweep_attribution(0.010, 0, None) == ([], [])
    # short stats vector: missing entries weight 1, never raises
    ms, _ = sweep_attribution(0.010, 3, [7])
    assert abs(sum(ms) - 10.0) < 1e-9


# ------------------------------------------------------------------- #
# Ledger walk
# ------------------------------------------------------------------- #


class _FakeGraph:
    def __init__(self):
        self.flags = np.zeros(1024, np.uint8)
        self.recv_count = np.zeros(1024, np.int64)
        self.edge_src = np.zeros(64, np.int32)
        self.edge_dst = np.zeros(64, np.int32)
        self.edge_weight = np.zeros(64, np.int64)
        self.slot_of = {object(): i for i in range(10)}
        self.send_matrix = {1: 2, 3: 4}
        self._pair_log = [(True, 1, 2, 0)] * 5


def test_ledger_families_duck_typed():
    fams = ledger_families(_FakeGraph())
    assert fams["node_features"]["host"] == 1024 * (1 + 8)
    assert fams["edges"]["host"] == 64 * (4 + 4 + 8)
    # maps are entry-count estimates: 10 slots + 2 matrix + 5 log rows
    assert fams["maps"]["host"] == (10 + 2) * 96 + 5 * 72
    assert fams["node_features"]["device"] == 0
    # an alien object contributes nothing and never raises
    assert isinstance(ledger_families(object()), dict)


def test_ledger_families_sees_device_arrays():
    import jax

    class G:
        _dev_flags = jax.device_put(np.zeros(256, np.uint8))
        _dev_stacked = {"row_pos": jax.device_put(np.zeros((4, 8), np.int32))}

    fams = ledger_families(G())
    assert fams["device_nodes"]["device"] == 256
    assert fams["device_layout"]["device"] == 4 * 8 * 4


# ------------------------------------------------------------------- #
# Donation audit
# ------------------------------------------------------------------- #


def test_donation_audit_true_positive_on_forced_copy():
    """A host (numpy) buffer handed to a 'donating' call can never be
    aliased — XLA copies.  The audit must flag it with the site label."""
    events.recorder.enable()
    obs = DeviceObservatory(node="")
    events.recorder.add_listener(obs)
    try:
        audit_donation("planted.copy", np.zeros(1024, np.int32))
        assert obs.donations == {"planted.copy": 1}
    finally:
        events.recorder.remove_listener(obs)
        obs.close()


def test_donation_audit_negative_on_real_donation():
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def bump(x):
        return x.at[0].add(1)

    donated = jax.device_put(np.zeros(512, np.int32))
    out = bump(donated)
    out.block_until_ready()
    events.recorder.enable()
    obs = DeviceObservatory(node="")
    events.recorder.add_listener(obs)
    try:
        audit_donation("real.donation", donated)
        assert obs.donations == {}
    finally:
        events.recorder.remove_listener(obs)
        obs.close()


# ------------------------------------------------------------------- #
# Event folding + registry metrics
# ------------------------------------------------------------------- #


def test_observatory_folds_events_and_registers_metrics():
    events.recorder.enable()
    registry = MetricsRegistry()
    obs = DeviceObservatory(node="uigc://me", registry=registry)
    try:
        obs(events.COMPILE, {"tag": "t", "geom": "g1", "hit": False,
                             "duration_s": 0.5})
        obs(events.COMPILE, {"tag": "t", "geom": "g1", "hit": True})
        obs(events.COMPILE, {"tag": "t", "geom": "g1", "hit": True})
        obs(events.HOST_TRANSFER, {"site": "s", "bytes": 100, "phase": "trace"})
        obs(events.DONATION_COPY, {"site": "d"})
        # origin scoping: a peer system's event is ignored
        obs(events.COMPILE, {"tag": "peer", "hit": False,
                             "origin": "uigc://other"})
        doc = obs.to_doc()
        assert doc["compile"]["entries"] == [
            {"tag": "t", "geom": "g1", "hits": 2, "misses": 1,
             "compile_s": 0.5}
        ]
        assert doc["transfers"]["total_bytes"] == 100
        assert doc["donation"]["copies_total"] == 1
        snap = registry.snapshot()
        assert snap["uigc_compile_misses_total"]["samples"][0]["value"] == 1
        assert snap["uigc_compile_hits_total"]["samples"][0]["value"] == 2
        assert snap["uigc_host_transfers_total"]["samples"][0]["labels"] == {
            "phase": "trace", "site": "s",
        }
        assert validate_device_doc(doc) == []
    finally:
        obs.close()


def test_compile_streams_bounded_during_storm():
    """A shape-key storm mints a fresh geometry per wake; the
    observatory's per-tag streams must stay bounded (overflow fold, the
    registry's max-labelsets discipline) while the miss count — the
    alert input — keeps growing."""
    obs = DeviceObservatory(node="")
    try:
        for i in range(obs.MAX_GEOMS_PER_TAG + 500):
            obs(events.COMPILE, {"tag": "storm", "geom": f"g{i}", "hit": False})
        entries = obs.to_doc()["compile"]["entries"]
        assert len(entries) == obs.MAX_GEOMS_PER_TAG + 1
        overflow = [e for e in entries if e["geom"] == "overflow"]
        assert overflow and overflow[0]["misses"] == 500
        assert sum(e["misses"] for e in entries) == obs.MAX_GEOMS_PER_TAG + 500
    finally:
        obs.close()


def test_validate_device_doc_rejects_malformed():
    assert validate_device_doc([]) == ["document is not an object"]
    assert any("wakes" in p for p in validate_device_doc({"version": 1}))
    good = DeviceObservatory(node="x")
    try:
        doc = good.to_doc()
        assert validate_device_doc(doc) == []
        doc["recent_wakes"] = [{"n_sweeps": 2, "sweep_device_ms": [1.0]}]
        assert any("sweep_device_ms" in p for p in validate_device_doc(doc))
    finally:
        good.close()


def test_findings_attribute_planted_planes():
    """The report's explainer names the planted tag/site, worst first."""
    doc = {
        "compile": {"entries": [
            # shape-key churn: one miss per FRESH geometry, same tag
            {"tag": "dec_wake", "geom": f"g{i}", "hits": 0, "misses": 1}
            for i in range(5)
        ]},
        "donation": {"sites": {"mesh.fold": 2}},
        "transfers": {"sites": [
            {"site": "stray", "phase": "fold", "count": 3, "bytes": 999},
            {"site": "marks.decremental", "phase": "trace", "count": 9,
             "bytes": 100},
        ]},
        "ledger": {"families": {}, "peaks": {}},
        "recent_wakes": [],
    }
    flist = device_report.findings(doc)
    assert flist[0]["plane"] == "compile"
    assert flist[0]["label"] == "dec_wake"
    assert flist[0]["severity"] == "critical"
    planes = {f["plane"]: f for f in flist}
    assert planes["donation"]["label"] == "mesh.fold"
    assert planes["transfer"]["label"] == "stray@fold"
    # the accounted trace-phase readback is NOT a finding
    assert not any("marks.decremental" in f["label"] for f in flist)


# ------------------------------------------------------------------- #
# Live planes (decremental CPU backend under churn)
# ------------------------------------------------------------------- #


class _Spawn(NoRefs):
    pass


class _Drop(NoRefs):
    pass


class _Worker(AbstractBehavior):
    def on_message(self, msg):
        return self


def _churn_root(counter):
    class Root(AbstractBehavior):
        def __init__(self, context):
            super().__init__(context)
            self.held = []

        def on_message(self, msg):
            ctx = self.context
            if isinstance(msg, _Spawn):
                base = counter[0]
                counter[0] += 16
                self.held.extend(
                    ctx.spawn(Behaviors.setup(_Worker), f"w{base + i}")
                    for i in range(16)
                )
            elif isinstance(msg, _Drop) and self.held:
                ctx.release(*self.held)
                self.held = []
            return self

    return Root


def _device_kit(extra=None):
    config = {
        "uigc.crgc.wakeup-interval": 10,
        "uigc.crgc.shadow-graph": "decremental",
        "uigc.telemetry.device": True,
        "uigc.telemetry.timeseries": True,
        "uigc.telemetry.ts-sample-interval": 100,
    }
    config.update(extra or {})
    return ActorTestKit(config=config, name="devtest")


def _wait(predicate, timeout_s=30.0, poll_s=0.1):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(poll_s)
    return predicate()


def test_device_observatory_live_planes():
    """One live churn run asserting every plane: ledger no-leak,
    compile 1-miss-then-hits, transfer-free idle wakes, attribution
    reconciliation, and the /device schema."""
    from uigc_tpu.ops import pallas_decremental

    pallas_decremental._fn_cache.clear()  # deterministic miss counts
    counter = [0]
    kit = _device_kit({"uigc.telemetry.http-port": 0})
    try:
        root = kit.spawn(Behaviors.setup_root(_churn_root(counter)), "root")
        obs = kit.system.telemetry.observatory
        assert _wait(lambda: obs.wakes > 0)  # first (cold) wake landed

        def cycle():
            root.tell(_Spawn())
            time.sleep(0.15)
            root.tell(_Drop())
            time.sleep(0.15)

        cycle()
        assert _wait(
            lambda: len(kit.system.engine.bookkeeper.shadow_graph.slot_of)
            <= 6
        )  # churn swept
        baseline = ledger_families(
            kit.system.engine.bookkeeper.shadow_graph
        )
        base_maps = baseline["maps"]["host"]
        base_nodes = baseline["node_features"]["host"]
        for _ in range(4):
            cycle()
        assert _wait(
            lambda: len(kit.system.engine.bookkeeper.shadow_graph.slot_of)
            <= 6
        )
        final = ledger_families(kit.system.engine.bookkeeper.shadow_graph)
        # -- memory ledger: live bytes return to baseline after sweeps
        # free slots; repeated cycles must not trend upward (no leak).
        assert final["maps"]["host"] <= base_maps + 2 * 96
        assert final["node_features"]["host"] == base_nodes  # no capacity growth
        # the peak watermark recorded the churn high-water mark
        doc = obs.to_doc()
        assert doc["ledger"]["peaks"]["maps"] > final["maps"]["host"]

        # -- compile plane: exactly 1 miss then hits per geometry.
        dec_streams = {
            (e["geom"]): e
            for e in doc["compile"]["entries"]
            if e["tag"] == "dec_wake"
        }
        assert dec_streams, doc["compile"]["entries"]
        for geom, entry in dec_streams.items():
            assert entry["misses"] <= 1, (geom, entry)
        assert sum(e["hits"] for e in dec_streams.values()) >= 3

        # -- sweep plane: attribution reconciles with the profiler's
        # device phase (record["device_s"]) within 10% per wake.
        def has_stats_wake():
            return any(
                r.get("n_sweeps") for r in obs.to_doc()["recent_wakes"]
            )

        if not _wait(has_stats_wake, timeout_s=10.0):
            cycle()  # one more repair round if the first ones were trivial
        assert _wait(has_stats_wake, timeout_s=10.0)
        doc = obs.to_doc()
        stats_wakes = [r for r in doc["recent_wakes"] if r.get("n_sweeps")]
        assert stats_wakes
        for rec in stats_wakes:
            ms = rec["sweep_device_ms"]
            assert len(ms) == int(rec["n_sweeps"])
            device_ms = rec["device_s"] * 1000.0
            assert abs(sum(ms) - device_ms) <= 0.10 * device_ms

        # -- transfer plane negative case: idle (transfer-free) wakes
        # commit nothing — the graph-dirty gate skips the trace, so the
        # accounter must stay flat while wakes keep happening.
        time.sleep(0.3)  # drain any in-flight cascade
        before = obs.to_doc()
        before_wakes = before["wakes"]
        time.sleep(0.6)
        after = obs.to_doc()
        assert after["wakes"] > before_wakes  # collector kept waking
        assert (
            after["transfers"]["total_count"]
            == before["transfers"]["total_count"]
        )

        # -- /device serves the same schema-valid document.
        port = kit.system.telemetry.http.port
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/device", timeout=10
        ) as rsp:
            served = json.loads(rsp.read())
        assert validate_device_doc(served) == []
        assert served["node"] == kit.system.address

        # the time plane carries the decomposition series
        store = kit.system.telemetry.store
        assert store.range("uigc_device_sweeps", window_s=300)["buckets"]
    finally:
        kit.shutdown()


def test_planted_regression_fires_alerts_with_labels():
    """Acceptance: a forced per-wake recompile (fresh geometry every
    beat, one tag) and an un-donated buffer copy, both injected, must
    fire ``recompile_storm`` and the donation audit with the planted
    tag/site labels — and device_report must attribute both planes."""
    kit = _device_kit()
    try:
        telemetry = kit.system.telemetry
        obs = telemetry.observatory
        engine = telemetry.alerts
        assert engine is not None
        t0 = time.time()
        beats = 0
        while time.time() - t0 < 3.5:
            events.recorder.commit(
                events.COMPILE, tag="planted_storm", geom=f"g{beats}",
                hit=False,
            )
            audit_donation(
                "planted.copy", np.zeros(256, np.int32)
            )
            beats += 1
            time.sleep(0.1)

        def fired():
            active = {
                (a["rule"], tuple(sorted(a["labels"].items())))
                for a in engine.active()
            }
            return (
                ("recompile_storm", (("tag", "planted_storm"),)) in active
                and (
                    "donation_copy_detected",
                    (("site", "planted.copy"),),
                ) in active
            )

        assert _wait(fired, timeout_s=15.0), engine.active()

        doc = obs.to_doc()
        flist = device_report.findings(doc)
        compile_findings = [f for f in flist if f["plane"] == "compile"]
        assert any(f["label"] == "planted_storm" for f in compile_findings)
        donation_findings = [f for f in flist if f["plane"] == "donation"]
        assert any(f["label"] == "planted.copy" for f in donation_findings)
        # the planes carried the planted labels all the way through
        assert doc["donation"]["sites"]["planted.copy"] == beats
        storm = [
            e for e in doc["compile"]["entries"]
            if e["tag"] == "planted_storm"
        ]
        assert len(storm) == beats  # one fresh geometry per beat
    finally:
        kit.shutdown()


# ------------------------------------------------------------------- #
# Tools
# ------------------------------------------------------------------- #


def test_bench_check_device_family_skips_honestly():
    """No committed TPU round carries device_per_wake_ms yet: every
    DEVICE metric must SKIP (visible), never PASS silently."""
    rows = bench_check.check_family(str(REPO), "DEVICE")
    assert rows
    assert all(row["status"] == "SKIP" for row in rows)


def test_bench_check_device_family_gates_regression(tmp_path):
    prior = {"device_per_wake_ms": 10.0, "sweeps_mean": 5.0}
    newer = {"device_per_wake_ms": 30.0, "sweeps_mean": 5.0}
    (tmp_path / "BENCH_TPU_SESSION_r01.json").write_text(json.dumps(prior))
    (tmp_path / "BENCH_TPU_SESSION_r02.json").write_text(json.dumps(newer))
    rows = bench_check.check_family(str(tmp_path), "DEVICE")
    by_metric = {r["metric"]: r for r in rows}
    assert by_metric["device_per_wake_ms"]["status"] == "FAIL"
    assert by_metric["sweeps_mean"]["status"] == "PASS"


def test_uigc_top_device_panel_degrades():
    assert "device: -" in uigc_top.render_device_panel(None)[0]
    assert "device: -" in uigc_top.render_device_panel("not a doc")[0]
    obs = DeviceObservatory(node="x")
    try:
        lines = uigc_top.render_device_panel(obs.to_doc())
    finally:
        obs.close()
    assert lines[0].startswith("device: ledger")


def test_committed_device_figures_absent_on_cpu_trajectory(tmp_path):
    # the real repo: TPU sessions predate wake_chain device figures
    assert device_report.committed_device_figures(str(REPO)) is None
    doc = {"device_per_wake_ms": 2.5, "sweeps_mean": 4.0}
    (tmp_path / "BENCH_WAKE_r01.json").write_text(json.dumps(doc))
    got = device_report.committed_device_figures(str(tmp_path))
    assert got["device_per_wake_ms"] == 2.5
    assert got["source"] == "BENCH_WAKE_r01.json"
    # families number rounds independently: a higher-numbered TPU
    # session must NOT outrank the canonical WAKE artifact
    (tmp_path / "BENCH_TPU_SESSION_r05.json").write_text(
        json.dumps({"device_per_wake_ms": 99.0})
    )
    got = device_report.committed_device_figures(str(tmp_path))
    assert got["source"] == "BENCH_WAKE_r01.json"


def test_replay_device_accepts_origin_tagged_events(tmp_path):
    """A real node's JSONL sink stamps every line with the node's
    origin; offline replay must fold them, not scope them away."""
    sink = tmp_path / "events.jsonl"
    lines = [
        {"event": events.COMPILE, "tag": "dec_wake", "geom": "g1",
         "hit": False, "origin": "uigc://node-a"},
        {"event": events.COMPILE, "tag": "dec_wake", "geom": "g1",
         "hit": True, "origin": "uigc://node-a"},
        {"event": events.HOST_TRANSFER, "site": "marks.decremental",
         "bytes": 512, "phase": "trace", "origin": "uigc://node-a"},
        {"event": events.DONATION_COPY, "site": "mesh.fold",
         "origin": "uigc://node-a"},
    ]
    sink.write_text("".join(json.dumps(line) + "\n" for line in lines))
    doc = uigc_top.replay_device(str(sink))
    assert doc is not None
    assert doc["compile"]["entries"] == [
        {"tag": "dec_wake", "geom": "g1", "hits": 1, "misses": 1,
         "compile_s": 0.0}
    ]
    assert doc["transfers"]["total_bytes"] == 512
    assert doc["donation"]["sites"] == {"mesh.fold": 1}
    assert doc["node"].startswith("replay:")


def test_ul011_flags_and_annotation(tmp_path):
    target = tmp_path / "engines" / "hot.py"
    target.parent.mkdir()
    target.write_text(
        "import numpy as np\n"
        "import jax\n"
        "def bad(self, x, y, z):\n"
        "    a = np.asarray(x)\n"                       # flagged
        "    b = jax.device_get(y)\n"                    # flagged
        "    c = z.item()\n"                             # flagged
        "    d = self._dev_flags.item()\n"               # flagged (attr recv)
        "    ok1 = np.asarray(x)  # readback: tested\n"  # annotated
        "    ok2 = np.asarray(x, dtype=np.int64)\n"      # dtype: host idiom
        "    return a, b, c, d, ok1, ok2\n"
    )
    violations = [
        v for v in uigc_lint.lint_paths([str(tmp_path)]) if v.rule == "UL011"
    ]
    assert len(violations) == 4
    lines = sorted(v.line for v in violations)
    assert lines == [4, 5, 6, 7]
    # outside engines/ops/parallel the rule never applies
    other = tmp_path / "models" / "cold.py"
    other.parent.mkdir()
    other.write_text("import numpy as np\nx = np.asarray([1])\n")
    assert not [
        v
        for v in uigc_lint.lint_paths([str(other)])
        if v.rule == "UL011"
    ]


def test_repo_is_ul011_strict_clean():
    violations = [
        v
        for v in uigc_lint.lint_paths([str(REPO / "uigc_tpu")])
        if v.rule == "UL011"
    ]
    budget = uigc_lint._load_allowlist(
        str(REPO / "tools" / "uigc_lint_allow.txt")
    )
    _grandfathered, fresh = uigc_lint.apply_allowlist(violations, budget)
    assert fresh == [], [v.render() for v in fresh]
