"""Thin child-process runner: keeps role code importable as
``nodeproc_common`` in every process (see that module's note)."""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

import nodeproc_common

if __name__ == "__main__":
    nodeproc_common.run_child(json.loads(sys.argv[1]))
