"""Model-based test of the packed refob info word.

Analogue of the reference's ScalaCheck property suite (reference:
src/test/scala/edu/illinois/osl/uigc/engines/crgc/RefobInfoSpec.scala:8-61):
random inc/reset/deactivate executions compared against a trivial model.
"""

import random

from uigc_tpu.engines.crgc import refob as refob_info


def check(model, info):
    active, count = model
    assert refob_info.is_active(info) == active
    assert refob_info.count(info) == count


def test_refob_info_model():
    rng = random.Random(12345)
    for _ in range(200):
        ops = ["inc"] * rng.randint(0, 1000) + ["reset"] * rng.randint(0, 1000)
        rng.shuffle(ops)
        ops.append("deactivate")

        model = (True, 0)
        info = refob_info.ACTIVE_REFOB
        check(model, info)
        for op in ops:
            if op == "inc":
                model = (model[0], model[1] + 1)
                info = refob_info.inc_send_count(info)
            elif op == "reset":
                model = (model[0], 0)
                info = refob_info.reset_count(info)
            else:
                model = (False, model[1])
                info = refob_info.deactivate(info)
            check(model, info)


def test_saturation_guard():
    info = refob_info.ACTIVE_REFOB
    while refob_info.can_increment(info):
        info = refob_info.inc_send_count(info)
    # Saturated: count fits in 15 bits, stays active.
    assert refob_info.count(info) == refob_info.SHORT_MAX >> 1
    assert refob_info.is_active(info)
    info = refob_info.deactivate(info)
    assert not refob_info.is_active(info)
    assert refob_info.count(info) == refob_info.SHORT_MAX >> 1
