"""Differential test: the packed entry plane (packed.py) against the
object Entry plane over identical operation scripts.

Two independent worlds run the same random script of CRGC mutator
operations (create ref / spawn / receive / send+update / release /
flush), one flushing object Entries folded by ``merge_entries``, the
other flushing packed rows folded by ``merge_packed``.  After every
drain — and after a kill sweep that frees slots and forces uid
re-interning — the graphs must agree exactly (flags, receive counts,
supervisors, edge weights), keyed by actor uid since slot numbering
legitimately differs between planes.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from uigc_tpu.engines.crgc.arrays import ArrayShadowGraph
from uigc_tpu.engines.crgc.packed import PackedPlane, PackedRing
from uigc_tpu.engines.crgc.refob import CrgcRefob
from uigc_tpu.engines.crgc.state import CrgcContext, CrgcState, Entry
from uigc_tpu.ops import trace as trace_ops

_F = trace_ops


class FakeSystem:
    def __init__(self, address="uigc://packedtest"):
        self.address = address


class FakeCell:
    __slots__ = ("uid", "system")

    def __init__(self, uid, system):
        self.uid = uid
        self.system = system

    def tell(self, msg):
        pass


class World:
    """One plane's half of the differential: its own cells (same uids),
    states, refobs, graph, and flush route."""

    def __init__(self, n, packed: bool):
        self.packed = packed
        self.ctx = CrgcContext(delta_graph_size=64, entry_field_size=4)
        system = FakeSystem()
        self.cells = [FakeCell(uid, system) for uid in range(1, n + 1)]
        self.states = [
            CrgcState(CrgcRefob(c), self.ctx) for c in self.cells
        ]
        self.graph = ArrayShadowGraph(self.ctx, system.address)
        self.refobs = {}  # (owner idx, target idx) -> live refob
        self.entries = []
        if packed:
            self.plane = PackedPlane(self.ctx.entry_field_size)
            by_uid = {c.uid: c for c in self.cells}
            self.graph.attach_packed_plane(self.plane, by_uid.get)

    def flush(self, a, busy):
        if self.packed:
            self.states[a].flush_to_ring(busy, self.plane)
        else:
            e = Entry(self.ctx)
            self.states[a].flush_to_entry(busy, e)
            self.entries.append(e)

    def drain(self):
        if self.packed:
            rows = self.plane.drain()
            if rows is not None:
                self.graph.merge_packed(rows)
        else:
            if self.entries:
                self.graph.merge_entries(self.entries)
                self.entries = []

    def snapshot(self):
        """uid-keyed graph state (slot numbering is plane-specific)."""
        g = self.graph
        slot_uid = {}
        for cell, slot in g.slot_of.items():
            slot_uid[slot] = cell.uid
        nodes = {
            uid: (
                int(g.flags[slot]),
                int(g.recv_count[slot]),
                slot_uid.get(int(g.supervisor[slot]), -1),
            )
            for slot, uid in slot_uid.items()
        }
        edges = {}
        for key, eid in g.edge_of.items():
            w = int(g.edge_weight[eid])
            if w != 0:
                edges[(slot_uid[key >> 32], slot_uid[key & 0xFFFFFFFF])] = w
        return nodes, edges


def _run_script(rng, worlds, n, ops_per_round):
    """One round of identical random mutator ops on every world."""
    for _ in range(ops_per_round):
        a = int(rng.integers(0, n))
        r = rng.random()
        if r < 0.3:  # create a ref owner -> target
            o = int(rng.integers(0, n))
            t = int(rng.integers(0, n))
            for w in worlds:
                st = w.states[a]
                if not st.can_record_new_refob():
                    w.flush(a, True)
                st.record_new_refob(
                    CrgcRefob(w.cells[o]), CrgcRefob(w.cells[t])
                )
        elif r < 0.45:  # spawn child
            c = int(rng.integers(0, n))
            for w in worlds:
                st = w.states[a]
                if not st.can_record_new_actor():
                    w.flush(a, True)
                st.record_new_actor(CrgcRefob(w.cells[c]))
        elif r < 0.6:  # receive a message
            for w in worlds:
                st = w.states[a]
                if not st.can_record_message_received():
                    w.flush(a, True)
                st.record_message_received()
        elif r < 0.85:  # send along a (possibly new) refob
            t = int(rng.integers(0, n))
            for w in worlds:
                st = w.states[a]
                ref = w.refobs.get((a, t))
                if ref is None:
                    ref = CrgcRefob(w.cells[t])
                    w.refobs[(a, t)] = ref
                if not ref.can_inc_send_count() or not st.can_record_updated_refob(ref):
                    w.flush(a, True)
                ref.inc_send_count()
                st.record_updated_refob(ref)
        else:  # release the refob if one is live
            t = int(rng.integers(0, n))
            for w in worlds:
                st = w.states[a]
                ref = w.refobs.pop((a, t), None)
                if ref is None:
                    continue
                if not st.can_record_updated_refob(ref):
                    w.flush(a, True)
                ref.deactivate()
                st.record_updated_refob(ref)
    # end-of-round: every actor flushes (idle), half busy
    for a in range(n):
        busy = bool(a & 1)
        for w in worlds:
            w.flush(a, busy)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_packed_plane_matches_object_plane(seed):
    rng = np.random.default_rng(seed)
    n = 64
    obj = World(n, packed=False)
    pk = World(n, packed=True)
    worlds = [obj, pk]
    # mark some roots (mirrored)
    for a in range(0, n, 16):
        obj.states[a].mark_as_root()
        pk.states[a].mark_as_root()

    for round_ in range(6):
        _run_script(rng, worlds, n, ops_per_round=200)
        for w in worlds:
            w.drain()
        no, eo = obj.snapshot()
        np_, ep = pk.snapshot()
        assert no == np_, f"seed {seed} round {round_}: node state diverged"
        assert eo == ep, f"seed {seed} round {round_}: edge state diverged"

    # Kill sweep: frees slots, must invalidate uid mappings in the
    # packed graph; the next rounds re-intern freed uids.
    for w in worlds:
        w.graph.trace(should_kill=True)
    no, eo = obj.snapshot()
    np_, ep = pk.snapshot()
    assert no == np_ and eo == ep, f"seed {seed}: post-sweep state diverged"

    for round_ in range(3):
        _run_script(rng, worlds, n, ops_per_round=150)
        for w in worlds:
            w.drain()
        for w in worlds:
            w.graph.trace(should_kill=True)
        no, eo = obj.snapshot()
        np_, ep = pk.snapshot()
        assert no == np_, f"seed {seed} churn round {round_}: nodes diverged"
        assert eo == ep, f"seed {seed} churn round {round_}: edges diverged"


def test_out_of_order_batches_respect_flush_stamps():
    """Per-thread rings drain independently, so a LATER batch can carry
    an EARLIER flush of the same actor (the actor migrated workers
    between flushes).  Stale busy/root and supervisor writes must lose
    to the stamps already applied; commutative facts (recv) still
    sum."""
    from uigc_tpu.engines.crgc.packed import row_width
    from uigc_tpu.ops import trace as F

    ctx = CrgcContext(delta_graph_size=64, entry_field_size=4)
    system = FakeSystem()
    cells = [FakeCell(uid, system) for uid in range(1, 6)]
    graph = ArrayShadowGraph(ctx, system.address)
    plane = PackedPlane(4)
    by_uid = {c.uid: c for c in cells}
    graph.attach_packed_plane(plane, by_uid.get)
    W = row_width(4)

    def row(seq, uid, busy, root, recv=0, spawned=(), sup_parent=None):
        r = np.full(W, -1, dtype=np.int64)
        r[0] = seq
        r[1] = uid
        r[2] = (1 if busy else 0) | (2 if root else 0)
        r[3] = recv
        for i, s in enumerate(spawned):
            r[4 + 8 + i] = s
        return r

    # seq 10: actor 1 busy, root, supervisor(child 2 -> parent 1)
    newer = row(10, 1, busy=True, root=True, recv=3, spawned=(2,))
    # seq 5: the STALE flush — not busy, not root, child 2's parent = 3
    stale_parent = np.full(W, -1, dtype=np.int64)
    stale_parent[0] = 5
    stale_parent[1] = 3
    stale_parent[2] = 0
    stale_parent[3] = 1
    stale_parent[4 + 8] = 2  # actor 3 claims child 2
    stale_self = row(4, 1, busy=False, root=False, recv=2)

    graph.merge_packed(np.stack([newer]))
    s1 = graph.slot_of[cells[0]]
    s2 = graph.slot_of[cells[1]]
    assert graph.flags[s1] & F.FLAG_BUSY and graph.flags[s1] & F.FLAG_ROOT
    assert graph.supervisor[s2] == s1

    # the stale batch arrives afterwards
    graph.merge_packed(np.stack([stale_self, stale_parent]))
    assert graph.flags[s1] & F.FLAG_BUSY, "stale busy=0 must not regress"
    assert graph.flags[s1] & F.FLAG_ROOT, "stale root=0 must not regress"
    assert graph.supervisor[s2] == s1, "stale supervisor must not regress"
    # commutative recv still summed from both batches
    assert graph.recv_count[s1] == 5

    # a genuinely newer flush still wins
    graph.merge_packed(np.stack([row(20, 1, busy=False, root=False)]))
    assert not (graph.flags[s1] & F.FLAG_BUSY)
    assert not (graph.flags[s1] & F.FLAG_ROOT)


def test_proven_garbage_uid_fields_dropped():
    """A row naming a uid that was swept AND whose cell is gone must
    fold without error, its fields dropped (garbage is monotone)."""
    ctx = CrgcContext(delta_graph_size=64, entry_field_size=4)
    system = FakeSystem()
    registry = {}
    graph = ArrayShadowGraph(ctx, system.address)
    plane = PackedPlane(4)
    graph.attach_packed_plane(plane, registry.get)
    from uigc_tpu.engines.crgc.packed import row_width

    W = row_width(4)
    live = FakeCell(1, system)
    registry[1] = live
    r = np.full(W, -1, dtype=np.int64)
    r[0] = 0
    r[1] = 1
    r[2] = 1
    r[3] = 0
    # created pair: owner 1 -> target 99 (uid 99 resolves nowhere)
    r[4] = 1
    r[5] = 99
    graph.merge_packed(np.stack([r]))
    s1 = graph.slot_of[live]
    assert graph.flags[s1]  # row itself folded
    assert len(graph.edge_of) == 0  # dead-uid edge dropped
    assert 99 not in [c.uid for c in graph.slot_of]


def test_sweep_unpins_uid_strong():
    """The sweep must drop the plane's strong pins for freed uids or
    every actor ever spawned stays pinned forever."""
    import time

    from uigc_tpu.interfaces import Message
    from uigc_tpu.runtime.behaviors import AbstractBehavior, Behaviors
    from uigc_tpu.runtime.testkit import ActorTestKit

    class Release(Message):
        @property
        def refs(self):
            return []

    class Kid(AbstractBehavior):
        def on_message(self, msg):
            return self

    kit = ActorTestKit({"uigc.crgc.wakeup-interval": 10})
    try:
        eng = kit.system.engine
        state = {}

        def root_setup(ctx):
            state["kids"] = [
                ctx.spawn(Behaviors.setup(lambda c: Kid(c)), f"k{i}")
                for i in range(10)
            ]

            class Root(AbstractBehavior):
                def on_message(self, msg):
                    if isinstance(msg, Release):
                        ctx.release(state["kids"])
                    return self

            return Root(ctx)

        root = kit.spawn(Behaviors.setup_root(root_setup), "root")
        time.sleep(0.3)
        kid_uids = {k.target.uid for k in state["kids"]}
        root.tell(Release())
        deadline = time.time() + 20
        leaked = kid_uids
        while time.time() < deadline:
            leaked = kid_uids & set(eng.packed_plane.uid_strong)
            if not leaked:
                break
            time.sleep(0.1)
        assert not leaked, f"uid pins leaked for dead actors: {leaked}"
    finally:
        kit.shutdown()


def test_ring_wraps_and_grows():
    ring = PackedRing(width=4, cap=8)
    out = []
    for i in range(5):
        v = ring.begin()
        v[:] = i
        ring.commit()
    got = ring.drain()
    out.append(got)
    assert got.shape == (5, 4) and got[:, 0].tolist() == [0, 1, 2, 3, 4]
    # wrap across the boundary
    for i in range(5, 11):
        v = ring.begin()
        v[:] = i
        ring.commit()
    got = ring.drain()
    assert got[:, 0].tolist() == [5, 6, 7, 8, 9, 10]
    # overflow without a drain: grows, order preserved
    for i in range(20):
        v = ring.begin()
        v[:] = 100 + i
        ring.commit()
    got = ring.drain()
    assert got[:, 0].tolist() == [100 + i for i in range(20)]
    assert ring.cap >= 16
    assert ring.drain() is None


def test_ring_concurrent_writer_reader():
    """Smoke the SPSC contract: one writer thread, one reader thread,
    every committed row arrives exactly once in order."""
    import time

    ring = PackedRing(width=2, cap=16)
    total = 20_000
    seen = []
    stop = threading.Event()

    def reader():
        while True:
            got = ring.drain()
            if got is not None:
                seen.append(got[:, 0].copy())
            elif stop.is_set():
                # one final drain AFTER observing stop: the writer may
                # have committed between our empty drain and the flag
                got = ring.drain()
                if got is not None:
                    seen.append(got[:, 0].copy())
                break
            else:
                # yield instead of busy-spinning: under a loaded
                # machine a spinning reader can starve the writer (and
                # this test's join) for tens of seconds
                time.sleep(0.0005)

    t = threading.Thread(target=reader)
    t.start()
    for i in range(total):
        v = ring.begin()
        v[0] = i
        v[1] = -i
        ring.commit()
    stop.set()
    t.join(timeout=120)
    assert not t.is_alive()
    flat = np.concatenate(seen) if seen else np.empty(0)
    assert flat.shape[0] == total
    assert flat.tolist() == list(range(total))


def test_packed_plane_default_on_single_node():
    """Engine wiring: single-node array backend gets the plane; the
    oracle backend (no array fold) does not."""
    from uigc_tpu.runtime.testkit import ActorTestKit

    kit = ActorTestKit({"uigc.crgc.wakeup-interval": 10})
    try:
        assert kit.system.engine.packed_plane is not None
    finally:
        kit.shutdown()
    kit = ActorTestKit(
        {"uigc.crgc.wakeup-interval": 10, "uigc.crgc.shadow-graph": "oracle"}
    )
    try:
        assert kit.system.engine.packed_plane is None
    finally:
        kit.shutdown()
