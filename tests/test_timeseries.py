"""Telemetry time-plane suite (uigc_tpu/telemetry/timeseries + alerts).

Layers, bottom up:

- store math: ring bound over >=10k samples, bucket aggregates,
  tier/window selection, labelset-cardinality overflow (store AND
  registry sides of the satellite);
- sampler: registry counters/gauges/histograms fold into series
  (histograms as ``_count``/``_sum``);
- alert engine: threshold/rate/EWMA rules fire with the series'
  labelset, resolve on recovery, and count into
  ``uigc_alerts_total{rule,severity}`` through the event bridge;
- ``tsq``/``tsr`` wire codecs: round-trip, trailing-element and
  malformed-frame tolerance;
- HTTP faces: ``/timeseries`` and ``/alerts``, plus the concurrent
  scrape-safety satellite (hammered from threads under live churn, no
  torn exposition, monotone counters);
- tools: ``uigc_top`` renders live (2-node) and offline from a rotated
  JSONL set; ``bench_check`` passes on the committed trajectory and
  fails on a synthetically regressed copy;
- the acceptance scenario: a 3-node chaos run (seeded frame drops +
  one node kill) where the wake-latency and frame-gap rules fire with
  correct labels, the ``tsq`` merge returns every survivor's series
  and names the dead peer in ``missing_nodes``, and the store's ring
  bound holds over >=10k samples.
"""

import json
import re
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
import bench_check  # noqa: E402
import uigc_top  # noqa: E402

from uigc_tpu import (  # noqa: E402
    AbstractBehavior,
    ActorTestKit,
    Behaviors,
    NoRefs,
)
from uigc_tpu.config import Config  # noqa: E402
from uigc_tpu.runtime import wire  # noqa: E402
from uigc_tpu.runtime.faults import FaultPlan  # noqa: E402
from uigc_tpu.runtime.node import NodeFabric  # noqa: E402
from uigc_tpu.runtime.system import ActorSystem  # noqa: E402
from uigc_tpu.telemetry.alerts import AlertEngine, AlertRule  # noqa: E402
from uigc_tpu.telemetry.metrics import (  # noqa: E402
    EventMetricsBridge,
    MetricsRegistry,
)
from uigc_tpu.telemetry.timeseries import (  # noqa: E402
    DEFAULT_TIERS,
    MetricsSampler,
    TimeSeriesStore,
    merge_series_docs,
    parse_tiers,
)
from uigc_tpu.utils import events  # noqa: E402

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def clean_recorder():
    """Telemetry enables the process-global recorder; leave no residue
    for the rest of the suite."""
    yield
    events.recorder.disable()
    events.recorder.reset()
    with events.recorder._lock:
        events.recorder._listeners.clear()


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ------------------------------------------------------------------- #
# Store math
# ------------------------------------------------------------------- #


def test_ring_bound_over_10k_samples():
    """The O(1)-memory claim: >=10k samples across several labelsets
    allocate no more buckets than the fixed rings hold."""
    clock = _FakeClock()
    store = TimeSeriesStore(
        node="n1", tiers=((1.0, 16), (8.0, 8)), clock=clock
    )
    n = 12_000
    for i in range(n):
        clock.t += 0.05
        store.record("uigc_x_total", float(i), src=f"peer{i % 3}")
    stats = store.stats()
    assert stats["series"] == 3
    assert stats["buckets_allocated"] <= 3 * (16 + 8)
    assert stats["buckets_allocated"] <= stats["buckets_capacity"]
    # the ring still answers with the *newest* window, fully aggregated
    out = store.range("uigc_x_total", {"src": "peer0"}, window_s=10.0)
    assert out["buckets"]
    assert out["buckets"][-1]["max"] >= n - 3


def test_bucket_aggregates_and_resolution_selection():
    clock = _FakeClock(0.0)
    store = TimeSeriesStore(tiers=((1.0, 32), (8.0, 16)), clock=clock)
    for t, v in ((100.0, 5.0), (100.4, 1.0), (101.2, 7.0)):
        store.record("m", v, t=t)
    clock.t = 101.5
    fine = store.range("m", window_s=5.0, resolution=1.0)
    assert fine["resolution"] == 1.0
    by_t = {b["t"]: b for b in fine["buckets"]}
    assert by_t[100.0]["count"] == 2
    assert by_t[100.0]["sum"] == pytest.approx(6.0)
    assert by_t[100.0]["min"] == 1.0 and by_t[100.0]["max"] == 5.0
    assert by_t[100.0]["last"] == 1.0
    assert by_t[101.0]["mean"] == pytest.approx(7.0)
    # a coarser requested resolution climbs to the 8s tier, where all
    # three samples share one bucket
    coarse = store.range("m", window_s=16.0, resolution=8.0)
    assert coarse["resolution"] == 8.0
    assert coarse["buckets"][-1]["count"] == 3
    # no resolution asked + a window wider than the fine ring covers ->
    # the coarse tier answers
    clock.t = 101.5
    auto = store.range("m", window_s=200.0)
    assert auto["resolution"] == 8.0


def test_stale_sample_never_resurrects_an_evicted_bucket():
    store = TimeSeriesStore(tiers=((1.0, 4),))
    store.record("m", 1.0, t=10.0)
    store.record("m", 2.0, t=50.0)  # evicts the t=10 bucket's slot...
    store.record("m", 9.0, t=10.0)  # ...which a straggler must not reclaim
    out = store.range("m", window_s=100.0, now=50.0)
    assert [b["last"] for b in out["buckets"]] == [2.0]


def test_parse_tiers():
    assert parse_tiers("1x120,10x180,60x240") == DEFAULT_TIERS
    assert parse_tiers("0.5x8") == ((0.5, 8),)
    assert parse_tiers("garbage") == DEFAULT_TIERS
    assert parse_tiers("") == DEFAULT_TIERS
    assert parse_tiers("-1x5") == DEFAULT_TIERS


def test_store_labelset_overflow_bounds_cardinality():
    events.recorder.enable()
    seen = []
    events.recorder.add_listener(lambda n, f: seen.append((n, f)))
    store = TimeSeriesStore(node="n1", max_labelsets=3)
    for i in range(50):
        store.record("uigc_dyn", float(i), key=f"k{i}")
    sets = store.label_sets("uigc_dyn")
    assert len(sets) <= 4  # 3 + the overflow labelset
    assert (("overflow", "true"),) in sets
    assert store.stats()["dropped_labelsets"] == 47
    overflows = [f for n, f in seen if n == events.LABELSET_OVERFLOW]
    assert len(overflows) == 1  # once per metric, not per sample
    assert overflows[0]["metric"] == "uigc_dyn"
    assert overflows[0]["scope"] == "timeseries"


def test_registry_labelset_overflow_bounds_every_metric_kind():
    """The satellite proper: Counter/Gauge/Histogram dicts stop growing
    at uigc.telemetry.max-labelsets; the overflow labelset absorbs the
    tail and the structured event fires once per metric."""
    events.recorder.enable()
    seen = []
    events.recorder.add_listener(lambda n, f: seen.append((n, f)))
    registry = MetricsRegistry(max_labelsets=4)
    counter = registry.counter("uigc_c_total")
    gauge = registry.gauge("uigc_g")
    hist = registry.histogram("uigc_h_seconds")
    for i in range(100):
        counter.inc(peer=f"p{i}")
        gauge.set(i, peer=f"p{i}")
        hist.observe(0.001 * i, peer=f"p{i}")
    assert len(counter._values) <= 5
    assert len(gauge._values) <= 5
    assert len(hist._data) <= 5
    assert counter.value(overflow="true") == 96.0
    overflows = [f for n, f in seen if n == events.LABELSET_OVERFLOW]
    assert sorted(f["metric"] for f in overflows) == [
        "uigc_c_total", "uigc_g", "uigc_h_seconds",
    ]
    assert all(f["scope"] == "registry" for f in overflows)
    # existing labelsets keep updating normally after the fold
    counter.inc(peer="p0")
    assert counter.value(peer="p0") == 2.0


# ------------------------------------------------------------------- #
# Sampler
# ------------------------------------------------------------------- #


def test_sampler_folds_registry_into_series():
    clock = _FakeClock()
    registry = MetricsRegistry()
    counter = registry.counter("uigc_events_total")
    gauge = registry.gauge("uigc_depth")
    hist = registry.histogram("uigc_lat_seconds", buckets=(0.1, 1.0))
    store = TimeSeriesStore(clock=clock)
    sampler = MetricsSampler(store, registry=registry, clock=clock)
    counter.inc(5, src="a")
    gauge.set(3.0)
    hist.observe(0.05)
    hist.observe(0.5)
    sampler.sample_once()
    clock.t += 1.0
    counter.inc(2, src="a")
    sampler.sample_once()
    out = store.range("uigc_events_total", {"src": "a"}, window_s=10.0)
    assert [b["last"] for b in out["buckets"]] == [5.0, 7.0]
    assert store.range("uigc_depth", window_s=10.0)["buckets"][-1]["last"] == 3.0
    # histograms fold as _count/_sum series, never their bucket vectors
    assert (
        store.range("uigc_lat_seconds_count", window_s=10.0)["buckets"][-1]["last"]
        == 2.0
    )
    assert store.range("uigc_lat_seconds_sum", window_s=10.0)["buckets"][-1][
        "last"
    ] == pytest.approx(0.55)
    assert "uigc_lat_seconds" not in store.names()


# ------------------------------------------------------------------- #
# Alert engine
# ------------------------------------------------------------------- #


def _engine(clock, rules):
    store = TimeSeriesStore(node="uigc://n1", clock=clock)
    engine = AlertEngine(store, node="uigc://n1")
    for rule in rules:
        engine.add_rule(rule)
    return store, engine


def test_threshold_rule_fires_with_labels_and_resolves():
    events.recorder.enable()
    seen = []
    events.recorder.add_listener(
        lambda n, f: seen.append(f) if n == events.ALERT else None
    )
    clock = _FakeClock()
    store, engine = _engine(
        clock,
        [
            AlertRule(
                "queue_sat", "uigc_writer_queue_depth", "threshold",
                severity="critical", op=">=", value=100.0, agg="max",
            )
        ],
    )
    store.record("uigc_writer_queue_depth", 150.0, peer="uigc://b")
    fired = engine.evaluate()
    assert len(fired) == 1
    alert = fired[0]
    assert alert["rule"] == "queue_sat"
    assert alert["labels"] == {"peer": "uigc://b"}
    assert alert["value"] == 150.0
    assert engine.active() and engine.active()[0]["rule"] == "queue_sat"
    # still firing: no duplicate event
    engine.evaluate()
    assert len(seen) == 1 and seen[0]["state"] == "firing"
    # recovery in a later bucket resolves it
    clock.t += 2.0
    store.record("uigc_writer_queue_depth", 3.0, peer="uigc://b")
    engine.evaluate()
    assert engine.active() == []
    assert [f["state"] for f in seen] == ["firing", "resolved"]
    # the bridge counts firing transitions only
    registry = MetricsRegistry()
    bridge = EventMetricsBridge(registry)
    for fields in seen:
        bridge(events.ALERT, fields)
    assert registry.counter("uigc_alerts_total").value(
        rule="queue_sat", severity="critical"
    ) == 1.0


def test_rate_rule_differentiates_counter_series():
    clock = _FakeClock()
    store, engine = _engine(
        clock,
        [
            AlertRule(
                "gap_spike", "uigc_frame_gaps_total", "rate",
                op=">", value=1.0, window_s=30.0,
            )
        ],
    )
    store.record("uigc_frame_gaps_total", 0.0, src="uigc://a")
    assert engine.evaluate() == []  # one bucket: no slope yet
    clock.t += 1.0
    store.record("uigc_frame_gaps_total", 5.0, src="uigc://a")
    fired = engine.evaluate()
    assert len(fired) == 1
    assert fired[0]["labels"] == {"src": "uigc://a"}
    assert fired[0]["value"] == pytest.approx(5.0)  # (5-0)/1s
    clock.t += 1.0
    store.record("uigc_frame_gaps_total", 12.0, src="uigc://a")
    engine.evaluate()  # still firing: value refreshes, no re-fire
    active = engine.active()
    assert len(active) == 1
    assert active[0]["value"] == pytest.approx(6.0)  # (12-0)/2s
    # a flat counter decays the rate below the bound -> resolves
    for _ in range(40):
        clock.t += 1.0
        store.record("uigc_frame_gaps_total", 12.0, src="uigc://a")
    engine.evaluate()
    assert engine.active() == []


def test_ewma_rule_learns_baseline_then_flags_regression():
    clock = _FakeClock()
    store, engine = _engine(
        clock,
        [
            AlertRule(
                "wake_reg", "uigc_wake_wall_seconds", "ewma",
                sigma=3.0, min_points=6, window_s=60.0,
            )
        ],
    )
    for i in range(10):
        store.record("uigc_wake_wall_seconds", 0.010 + 0.0002 * (i % 3))
        assert engine.evaluate() == []  # learning the baseline
        clock.t += 1.0
    store.record("uigc_wake_wall_seconds", 0.200)  # 20x regression
    fired = engine.evaluate()
    assert len(fired) == 1
    assert fired[0]["rule"] == "wake_reg"
    assert fired[0]["value"] == pytest.approx(0.200)
    assert fired[0]["baseline"] == pytest.approx(0.010, rel=0.2)


def test_ewma_absolute_floor_fires_without_baseline():
    clock = _FakeClock()
    store, engine = _engine(
        clock,
        [
            AlertRule(
                "wake_floor", "uigc_wake_wall_seconds", "ewma",
                value=0.05, min_points=50,
            )
        ],
    )
    store.record("uigc_wake_wall_seconds", 0.5)
    fired = engine.evaluate()
    assert len(fired) == 1 and fired[0]["threshold"] == 0.05


# ------------------------------------------------------------------- #
# tsq/tsr codecs + merge math
# ------------------------------------------------------------------- #


def test_tsq_tsr_codec_round_trip_and_tolerance():
    frame = wire.encode_ts_query(7, "uigc://a", {"name": "m", "window": 60})
    assert frame[0] == wire.TSQ_FRAME_KIND
    req_id, origin, query = wire.decode_ts_query(frame)
    assert (req_id, origin) == (7, "uigc://a")
    assert query == {"name": "m", "window": 60}
    # trailing elements from a newer peer are accepted
    assert wire.decode_ts_query(frame + ("future",)) is not None
    # unreadable query body degrades to {} (answer with everything)
    assert wire.decode_ts_query(("tsq", 1, "o", b"\xff{not json"))[2] == {}
    # malformed shapes -> None, never a raise
    assert wire.decode_ts_query(("tsq", 1, "o", "not-bytes")) is None
    assert wire.decode_ts_query(("tsq",)) is None
    rsp = wire.encode_ts_response(7, "uigc://b", b'{"series": []}')
    assert wire.decode_ts_response(rsp) == (7, "uigc://b", b'{"series": []}')
    assert wire.decode_ts_response(rsp + ("x",)) is not None
    assert wire.decode_ts_response(("tsr", 1, "o", None)) is None


def test_merge_series_docs_aligns_buckets_and_names_missing():
    def doc(node, name, last):
        return {
            "node": node,
            "series": [
                {
                    "name": name,
                    "labels": {},
                    "tiers": [
                        {"res": 1.0, "buckets": [[100, 2, 10.0, 4.0, 6.0, last]]}
                    ],
                }
            ],
        }

    # counter-style (_total): per-node tallies are additive
    merged = merge_series_docs(
        [
            doc("uigc://a", "uigc_x_total", 6.0),
            doc("uigc://b", "uigc_x_total", 4.0),
        ],
        missing=["uigc://c"],
    )
    assert sorted(merged["nodes"]) == ["uigc://a", "uigc://b"]
    assert merged["missing_nodes"] == ["uigc://c"]
    entry = merged["cluster"][0]
    idx, count, total, vmin, vmax, last = entry["buckets"][0]
    assert (idx, count, total) == (100, 4, 20.0)
    assert (vmin, vmax) == (4.0, 6.0)
    assert last == 10.0  # cluster-wide sum of per-node tallies
    # gauge-style (no unit suffix): a level like phi folds by max —
    # summing would fabricate a value no node ever reported
    merged = merge_series_docs(
        [
            doc("uigc://a", "uigc_link_phi", 0.8),
            doc("uigc://b", "uigc_link_phi", 0.6),
        ]
    )
    assert merged["cluster"][0]["buckets"][0][5] == 0.8


def test_merged_does_not_wait_out_timeout_for_refused_sends():
    """A peer whose tsq send the fabric refuses (link died between the
    liveness check and the send) must fold into the completion check
    immediately — one dead link must not make every merge sit out the
    full timeout after all reachable peers answered."""
    clock = _FakeClock()
    store = TimeSeriesStore(node="uigc://a", clock=clock)
    store.record("uigc_x_total", 1.0)

    def send_query(peer, rid, q):
        if peer == "uigc://dead":
            return False  # send_frame: no live link
        store.on_response_frame(
            rid, peer, json.dumps({"node": peer, "series": []}).encode()
        )
        return True

    store.bind_fabric(
        known_peers_fn=lambda: ["uigc://b", "uigc://dead"],
        live_peers_fn=lambda: ["uigc://b", "uigc://dead"],
        send_query=send_query,
        send_response=lambda *a: None,
    )
    t0 = time.monotonic()
    merged = store.merged(timeout_s=10.0)
    assert time.monotonic() - t0 < 2.0, "merge waited out the timeout"
    assert "uigc://b" in merged["nodes"]
    assert merged["missing_nodes"] == ["uigc://dead"]


# ------------------------------------------------------------------- #
# HTTP faces + concurrent scrape safety
# ------------------------------------------------------------------- #


class _Ping(NoRefs):
    pass


class _Release(NoRefs):
    pass


class _Worker(AbstractBehavior):
    def on_message(self, msg):
        return self


class _Root(AbstractBehavior):
    def __init__(self, context):
        super().__init__(context)
        self.workers = [
            context.spawn(Behaviors.setup(_Worker), f"w{i}") for i in range(4)
        ]

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, _Ping):
            for worker in self.workers:
                worker.tell(_Ping(), ctx)
        elif self.workers:
            ctx.release(*self.workers)
            self.workers = []
        return self


_TS_CONFIG = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.telemetry.timeseries": True,
    "uigc.telemetry.ts-sample-interval": 50,
    "uigc.telemetry.http-port": 0,
}


def test_http_serves_timeseries_and_alerts():
    kit = ActorTestKit(config=dict(_TS_CONFIG), name="tshttp")
    try:
        root = kit.spawn(Behaviors.setup_root(_Root), "root")
        for _ in range(20):
            root.tell(_Ping())
        time.sleep(0.6)
        base = f"http://127.0.0.1:{kit.system.telemetry.http.port}"
        doc = json.loads(
            urllib.request.urlopen(base + "/timeseries", timeout=5).read()
        )
        names = {s["name"] for s in doc["series"]}
        assert "uigc_live_actors" in names
        assert doc["node"] == kit.system.address
        one = json.loads(
            urllib.request.urlopen(
                base + "/timeseries?name=uigc_live_actors&window=60", timeout=5
            ).read()
        )
        assert {s["name"] for s in one["series"]} == {"uigc_live_actors"}
        assert one["series"][0]["tiers"][0]["buckets"]
        alerts = json.loads(
            urllib.request.urlopen(base + "/alerts", timeout=5).read()
        )
        rule_names = {r["name"] for r in alerts["rules"]}
        assert {
            "wake_latency_regression", "frame_gap_spike",
            "writer_queue_saturation", "leak_suspect_growth",
            "heartbeat_phi_climb",
        } <= rule_names
        assert isinstance(alerts["firing"], list)
    finally:
        kit.shutdown()


_SAMPLE_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE.+-]+(inf)?$"
)


def test_concurrent_scrape_safety_under_churn():
    """The satellite: /metrics, /metrics.json and /timeseries hammered
    from threads while collector wakes and folds mutate the registry —
    no exceptions, no torn exposition, counters monotone."""
    kit = ActorTestKit(config=dict(_TS_CONFIG), name="tshammer")
    errors = []
    prom_bodies = []
    stop = threading.Event()
    try:
        base = f"http://127.0.0.1:{kit.system.telemetry.http.port}"

        def hammer(path, sink):
            while not stop.is_set():
                try:
                    body = urllib.request.urlopen(base + path, timeout=5).read()
                    sink(body)
                except Exception as exc:  # noqa: BLE001 - the assertion
                    errors.append((path, repr(exc)))
                    return

        threads = [
            threading.Thread(
                target=hammer,
                args=("/metrics", lambda b: prom_bodies.append(b.decode())),
            ),
            threading.Thread(
                target=hammer, args=("/metrics.json", lambda b: json.loads(b))
            ),
            threading.Thread(
                target=hammer, args=("/timeseries", lambda b: json.loads(b))
            ),
        ]
        for t in threads:
            t.start()
        root = kit.spawn(Behaviors.setup_root(_Root), "root")
        deadline = time.monotonic() + 1.5
        while time.monotonic() < deadline:
            root.tell(_Ping())
            time.sleep(0.002)
        root.tell(_Release())  # fold churn: a kill wave mid-hammer
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors
        assert len(prom_bodies) > 5
        entries_seen = []
        for body in prom_bodies:
            for line in body.splitlines():
                if line and not line.startswith("#"):
                    assert _SAMPLE_LINE.match(line), f"torn line: {line!r}"
                    if line.startswith("uigc_entries_flushed_total"):
                        entries_seen.append(float(line.rsplit(" ", 1)[1]))
        # scrape order == append order per thread, so the counter must
        # be non-decreasing across successive scrapes
        assert entries_seen == sorted(entries_seen)
        assert entries_seen[-1] > 0
    finally:
        stop.set()
        kit.shutdown()


# ------------------------------------------------------------------- #
# Tools: uigc_top (live + offline) and bench_check
# ------------------------------------------------------------------- #


def _spawn_ts_node(name, num_nodes, overrides=None):
    config = {
        "uigc.crgc.wakeup-interval": 20,
        "uigc.crgc.egress-finalize-interval": 5,
        "uigc.crgc.num-nodes": num_nodes,
        "uigc.telemetry.timeseries": True,
        "uigc.telemetry.ts-sample-interval": 50,
        "uigc.telemetry.ts-tiers": "1x120,10x60",
    }
    if overrides:
        config.update(overrides)
    fabric = NodeFabric()
    system = ActorSystem(None, name=name, config=config, fabric=fabric)
    port = fabric.listen()
    return fabric, system, port


def _terminate_all(*systems):
    for system in systems:
        try:
            system.terminate(timeout_s=5.0)
        except Exception:
            pass


def test_uigc_top_renders_live_2node_system(capsys):
    fa, sa, _pa = _spawn_ts_node("topa", 2, {"uigc.telemetry.http-port": 0})
    fb, sb, pb = _spawn_ts_node("topb", 2)
    try:
        fa.connect("127.0.0.1", pb)
        root = sa.spawn_root(Behaviors.setup_root(_Root), "root")
        for _ in range(30):
            root.tell(_Ping())
            time.sleep(0.005)
        time.sleep(0.8)
        base = f"http://127.0.0.1:{sa.telemetry.http.port}"
        assert uigc_top.main(["--url", base, "--once", "--plain"]) == 0
        out = capsys.readouterr().out
        assert "uigc-top" in out and sa.address in out
        assert "live actors" in out
        assert "alerts:" in out or "ALERTS" in out
        # the merged (cluster) view pulls B's series over tsq/tsr
        assert uigc_top.main(["--url", base, "--once", "--merged"]) == 0
        merged_out = capsys.readouterr().out
        assert "cluster: 2 node(s) merged" in merged_out
        # and the /timeseries?merged=1 doc names both nodes
        doc = json.loads(
            urllib.request.urlopen(base + "/timeseries?merged=1", timeout=5).read()
        )
        assert set(doc["nodes"]) == {sa.address, sb.address}
        assert doc["missing_nodes"] == []
    finally:
        _terminate_all(sa, sb)


def test_uigc_top_and_series_render_offline_from_rotated_jsonl(
    tmp_path, capsys
):
    path = str(tmp_path / "events.jsonl")
    kit = ActorTestKit(
        config={
            "uigc.crgc.wakeup-interval": 10,
            "uigc.telemetry.metrics": True,
            "uigc.telemetry.jsonl-path": path,
            "uigc.telemetry.jsonl-max-bytes": 4096,  # force a rotated set
            "uigc.telemetry.jsonl-keep": 3,
        },
        name="topjsonl",
    )
    try:
        root = kit.spawn(Behaviors.setup_root(_Root), "root")
        for _ in range(60):
            root.tell(_Ping())
            time.sleep(0.002)
        time.sleep(0.4)
    finally:
        kit.shutdown()
    assert (tmp_path / "events.jsonl.1").exists()  # rotation really happened
    assert uigc_top.main(["--from-jsonl", path]) == 0
    out = capsys.readouterr().out
    assert "uigc-top" in out and "replay:events.jsonl" in out
    assert "gc live actors" in out  # bridge-fed series (TRACING events)
    assert "entries/s" in out
    # telemetry_dump --series shares the same renderers and source
    # (driven in-process: a subprocess would pay the full JAX import)
    import telemetry_dump

    assert (
        telemetry_dump.main(
            ["--series", "uigc_gc_wave_seconds_count", "--from-jsonl", path]
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "uigc_gc_wave_seconds_count" in out
    assert "labelset" in out


def test_sparkline_and_points_renderers():
    assert uigc_top.sparkline([]) == "····"
    line = uigc_top.sparkline([0.0, 1.0, 2.0, 3.0], width=4)
    assert len(line) == 4 and line[0] == "▁" and line[-1] == "█"
    assert " " in uigc_top.sparkline([1.0, None, 2.0])
    series = {
        "name": "uigc_x_total",
        "labels": {},
        "tiers": [
            {"res": 2.0, "buckets": [[5, 1, 10.0, 10.0, 10.0, 10.0],
                                     [6, 1, 14.0, 14.0, 14.0, 14.0]]}
        ],
    }
    rates = uigc_top.series_points(series, "rate")
    assert rates == [(12.0, pytest.approx(2.0))]
    means = uigc_top.series_points(series, "mean")
    assert means == [(10.0, 10.0), (12.0, 14.0)]


def test_bench_check_passes_on_committed_trajectory(capsys):
    assert bench_check.main(["--repo", str(REPO)]) == 0
    out = capsys.readouterr().out
    assert "SHARD" in out and "status" in out


def test_bench_check_fails_on_synthetically_regressed_copy(tmp_path, capsys):
    doc = json.loads((REPO / "BENCH_SHARD_r02.json").read_text())
    doc["steady"]["messages_per_sec"] = 1.0
    doc["post_rebalance_probe"]["undercounted_entities"] = 7
    bad = tmp_path / "BENCH_SHARD_r99.json"
    bad.write_text(json.dumps(doc))
    assert (
        bench_check.main(
            ["--repo", str(REPO), "--check-regression", str(bad)]
        )
        == 1
    )
    out = capsys.readouterr().out
    assert "FAIL" in out
    assert "undercounted" in out


# ------------------------------------------------------------------- #
# Acceptance: 3-node chaos — alerts fire, tsq merge degrades, ring holds
# ------------------------------------------------------------------- #


def test_chaos_alerts_fire_and_merge_names_dead_peer():
    """ISSUE 8 acceptance: seeded FaultPlan (heartbeat-frame drops on
    the a->b link + node c killed mid-run).  The wake-latency rule
    (absolute floor configured) and the frame-gap rule must fire with
    correct labels; a ``tsq`` merge from a must return both survivors'
    series and name the dead peer in ``missing_nodes``; the store's
    ring bound must hold over >=10k samples."""
    plan = FaultPlan(42)
    overrides = {
        "uigc.node.heartbeat-interval": 50,
        # High threshold on purpose: the seeded hb drops (plus CPU
        # contention from a loaded test host) must not let phi declare
        # the a-link dead before the gap-rate rule has fired — the kill
        # in this scenario is the explicit fc.die(), nothing else.
        "uigc.node.phi-threshold": 16.0,
        # the wake rule's absolute floor: any completed wake fires it
        "uigc.telemetry.alert-wake-threshold": 1e-9,
        "uigc.telemetry.alert-gap-rate": 0.2,
    }
    fa, sa, pa = _spawn_ts_node("chaosta", 3, overrides)
    fb, sb, pb = _spawn_ts_node("chaostb", 3, overrides)
    fc, sc, pc = _spawn_ts_node("chaostc", 3, overrides)
    systems = (sa, sb, sc)
    try:
        for fabric in (fa, fb, fc):
            fabric.set_fault_plan(plan)
        # Sustained hb-frame drops a->b: phi absorbs them, b's seq layer
        # reports a steady gap stream — the frame_gap_spike input.
        plan.drop(src=sa.address, dst=sb.address, kind="hb", prob=0.35, count=100000)
        fa.connect("127.0.0.1", pb)
        fa.connect("127.0.0.1", pc)
        fb.connect("127.0.0.1", pc)

        root = sa.spawn_root(Behaviors.setup_root(_Root), "root")
        deadline = time.monotonic() + 40.0
        gap_alert = wake_alert = None
        while time.monotonic() < deadline and not (gap_alert and wake_alert):
            root.tell(_Ping())  # keep folds (and therefore wakes) coming
            time.sleep(0.02)
            for alert in sb.telemetry.alerts.active():
                if alert["rule"] == "frame_gap_spike":
                    gap_alert = alert
            for alert in sa.telemetry.alerts.active():
                if alert["rule"] == "wake_latency_regression":
                    wake_alert = alert
        assert gap_alert is not None, "frame_gap_spike never fired on b"
        assert gap_alert["labels"] == {"src": sa.address}
        assert gap_alert["severity"] == "warning"
        assert gap_alert["node"] == sb.address
        assert wake_alert is not None, "wake_latency_regression never fired"
        assert wake_alert["node"] == sa.address
        assert wake_alert["series"] == "uigc_wake_wall_seconds"

        # -- node kill: the merge must degrade, not block or forget --- #
        fc.die()
        time.sleep(0.2)
        merged = sa.telemetry.store.merged(timeout_s=3.0)
        survivors = set(merged["nodes"])
        assert {sa.address, sb.address} <= survivors
        assert sc.address not in survivors
        assert sc.address in merged["missing_nodes"]
        # both survivors' series really arrived (not just names)
        for node in (sa.address, sb.address):
            names = {s["name"] for s in merged["nodes"][node]}
            assert "uigc_live_actors" in names
        # b's gap counter is visible from a through the merge
        gap_rollup = [
            e for e in merged["cluster"]
            if e["name"] == "uigc_frame_gaps_total"
            and e["labels"].get("src") == sa.address
        ]
        assert gap_rollup and gap_rollup[0]["buckets"]

        # -- ring bound over >=10k samples on the live store ---------- #
        # The sampler is still feeding this store, so assert on the
        # probe series' own rings (exact) and the global capacity bound
        # — concurrently materializing labelsets must not flake this.
        store = sa.telemetry.store
        before = store.stats()
        t0 = time.time()
        for i in range(10_000):
            store.record("uigc_chaos_probe", float(i), t=t0 + i * 0.01)
        after = store.stats()
        assert after["buckets_allocated"] <= after["buckets_capacity"]
        assert after["series"] >= before["series"] + 1
        probe = store._series[("uigc_chaos_probe", ())]
        assert sum(tier.allocated() for tier in probe.tiers) <= 120 + 60
    finally:
        _terminate_all(*systems)
