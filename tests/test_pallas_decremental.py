"""Differential test: decremental wakes vs the from-scratch numpy oracle.

Every wake applies a random batch of pair insertions/removals and flag
mutations (busy/root toggles, recv drains, halts — the events a live
collector produces), runs the closure+repair wake from the previous
fixpoint, and compares the marks against trace_marks_np re-run from
scratch on the current graph (the reference semantics of
ShadowGraph.java:205-289).  Covers exactly the non-monotone cases the
full re-trace never exercises: deletion cascades, released cycles,
de-seeded hubs, crash-style halts.
"""

import numpy as np
import pytest

from uigc_tpu.ops import pallas_decremental as pd
from uigc_tpu.ops import trace as trace_ops
from uigc_tpu.ops.pallas_incremental import EDGE, SUP

F = trace_ops


class OracleGraph:
    """Host-side mutable truth the tracer's wakes are diffed against."""

    def __init__(self, rng, n, n_edges):
        self.n = n
        self.flags = np.zeros(n, dtype=np.uint8)
        in_use = rng.random(n) < 0.9
        self.flags[in_use] |= F.FLAG_IN_USE
        self.flags[rng.random(n) < 0.85] |= F.FLAG_INTERNED
        self.flags[rng.random(n) < 0.1] |= F.FLAG_BUSY
        self.flags[rng.random(n) < 0.05] |= F.FLAG_ROOT
        self.flags[rng.random(n) < 0.05] |= F.FLAG_HALTED
        self.recv = np.zeros(n, dtype=np.int64)
        self.recv[rng.random(n) < 0.1] = rng.integers(1, 5)
        # pair set: (src, dst, kind) -> None, kind EDGE only for edges
        # plus per-node supervisor pointers as SUP pairs
        self.pairs = {}
        src = rng.integers(0, n, n_edges)
        dst = rng.integers(0, n, n_edges)
        for s, d in zip(src.tolist(), dst.tolist()):
            self.pairs[(s, d, EDGE)] = None
        sup_child = np.nonzero(rng.random(n) < 0.3)[0]
        for c in sup_child.tolist():
            self.pairs[(c, int(rng.integers(0, n)), SUP)] = None

    def arrays(self):
        """(edge_src, edge_dst, weight, supervisor): EDGE pairs as the
        edge arrays, SUP pairs as the supervisor vector — the tracer's
        rebuild must see the kinds it will later get removals for."""
        ek = [k for k in self.pairs if k[2] == EDGE]
        src = np.array([k[0] for k in ek] or [0], dtype=np.int32)
        dst = np.array([k[1] for k in ek] or [0], dtype=np.int32)
        w = np.ones(len(ek) or 1, dtype=np.int64)
        if not ek:
            w[0] = 0
        sup = np.full(self.n, -1, np.int32)
        for k in self.pairs:
            if k[2] == SUP:
                sup[k[0]] = k[1]
        return src, dst, w, sup

    def oracle_marks(self):
        src, dst, w, sup = self.arrays()
        return trace_ops.trace_marks_np(
            self.flags, self.recv, sup, src, dst, w
        )


def _rand_schedule(rng, g, tracer, k):
    """One wake's worth of random churn, applied to both sides."""
    log = []
    keys = list(g.pairs)
    # removals
    for _ in range(min(k, len(keys))):
        key = keys[rng.integers(0, len(keys))]
        if key in g.pairs:
            del g.pairs[key]
            log.append((False, key[0], key[1], key[2]))
    # insertions
    for _ in range(k):
        key = (int(rng.integers(0, g.n)), int(rng.integers(0, g.n)), EDGE)
        if key not in g.pairs:
            g.pairs[key] = None
            log.append((True, key[0], key[1], key[2]))
    tracer.apply_log(log)
    # flag churn: seeds appear and disappear, nodes halt, slots free
    # and get reused — both additive (iu & ~prev_iu supertile gate)
    # and subtractive (~iu & prev_mark freed-slot suspects) in_use
    # transitions must hit the wake's suspect paths.
    for _ in range(k // 2):
        i = int(rng.integers(0, g.n))
        r = rng.random()
        if r < 0.25:
            g.flags[i] ^= F.FLAG_BUSY
        elif r < 0.4:
            g.flags[i] ^= F.FLAG_ROOT
        elif r < 0.55:
            g.recv[i] = 0 if g.recv[i] else 3
        elif r < 0.7:
            g.flags[i] |= F.FLAG_HALTED
        elif r < 0.85:
            g.flags[i] |= F.FLAG_IN_USE | F.FLAG_INTERNED
        else:
            # free the slot; a later iteration's IN_USE set is a reuse
            g.flags[i] &= ~(F.FLAG_IN_USE | F.FLAG_HALTED)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_decremental_wakes_match_oracle(seed):
    rng = np.random.default_rng(seed)
    n = 1 << 11
    g = OracleGraph(rng, n, n_edges=4 * n)
    tracer = pd.DecrementalTracer(n, freeze_threshold=64, max_frozen=2)
    _drive_random_wakes(rng, g, tracer, seed, wakes=8)


@pytest.mark.parametrize("mode", ["push", "pull", "jump"])
def test_decremental_modes_match_oracle(mode):
    """Every repair-fixpoint propagation strategy under the same random
    churn schedule (released cycles, halt cascades, de-seeded hubs,
    freed/reused slots) stays oracle-identical.  Auto is the default
    and covered by the seed-sweep test above plus the backends suite;
    here the pure strategies are pinned explicitly."""
    rng = np.random.default_rng(7)
    n = 1 << 10
    g = OracleGraph(rng, n, n_edges=4 * n)
    tracer = pd.DecrementalTracer(
        n, freeze_threshold=64, max_frozen=2, mode=mode
    )
    _drive_random_wakes(rng, g, tracer, 7, wakes=4)


def _drive_random_wakes(rng, g, tracer, seed, wakes):
    src, dst, w, sup = g.arrays()
    tracer.rebuild(src, dst, w, sup)

    # cold-start wake = full derivation
    got = tracer.marks(g.flags, g.recv)
    assert np.array_equal(got, g.oracle_marks())

    for wake in range(wakes):
        _rand_schedule(rng, g, tracer, k=40)
        got = tracer.marks(g.flags, g.recv)
        expected = g.oracle_marks()
        assert np.array_equal(got, expected), (
            f"seed {seed} wake {wake}: "
            f"{int((got != expected).sum())} mismatched marks"
        )
    # SUP removals must have matched their packed kind (a key-kind
    # mismatch shows up as a silently-dropped anomaly)
    assert tracer.layout.stats["anomalies"] == 0


def test_released_cycle_dies():
    """The canonical non-monotone case: a marked cycle loses its last
    external support and must be fully unmarked by one wake."""
    n = 256
    flags = np.full(n, F.FLAG_IN_USE | F.FLAG_INTERNED, dtype=np.uint8)
    flags[0] |= F.FLAG_ROOT
    recv = np.zeros(n, dtype=np.int64)
    # root -> 10, cycle 10 -> 11 -> ... -> 19 -> 10
    pairs = [(0, 10, EDGE)] + [
        (10 + i, 10 + ((i + 1) % 10), EDGE) for i in range(10)
    ]
    src = np.array([p[0] for p in pairs], np.int32)
    dst = np.array([p[1] for p in pairs], np.int32)
    w = np.ones(len(pairs), np.int64)
    tracer = pd.DecrementalTracer(n)
    tracer.rebuild(src, dst, w, np.full(n, -1, np.int32))
    got = tracer.marks(flags, recv)
    assert got[0] and got[10:20].all()

    # cut the root's edge: the whole cycle is suspect and dies
    tracer.apply_log([(False, 0, 10, EDGE)])
    got = tracer.marks(flags, recv)
    assert got[0] and not got[10:20].any()


def test_halt_cascade():
    """Crash-style wake: halting a relay node kills everything only it
    kept alive, while a second support path survives."""
    n = 128
    flags = np.full(n, F.FLAG_IN_USE | F.FLAG_INTERNED, dtype=np.uint8)
    flags[0] |= F.FLAG_ROOT
    recv = np.zeros(n, dtype=np.int64)
    # 0 -> 1 -> 2 -> 3 (chain through relay 1); 0 -> 4 -> 3 (second path
    # to 3 only)
    pairs = [(0, 1), (1, 2), (2, 3), (0, 4), (4, 3)]
    src = np.array([p[0] for p in pairs], np.int32)
    dst = np.array([p[1] for p in pairs], np.int32)
    w = np.ones(len(pairs), np.int64)
    tracer = pd.DecrementalTracer(n)
    tracer.rebuild(src, dst, w, np.full(n, -1, np.int32))
    got = tracer.marks(flags, recv)
    assert got[[0, 1, 2, 3, 4]].all()

    flags = flags.copy()
    flags[1] |= F.FLAG_HALTED
    got = tracer.marks(flags, recv)
    # 1 stays marked (reachable), 2 dies (only via halted 1), 3 survives
    # via 4
    assert got[0] and got[1] and not got[2] and got[3] and got[4]


def test_additive_only_wakes():
    """Pure insertions never enter the closure path; marks only grow."""
    n = 512
    flags = np.full(n, F.FLAG_IN_USE | F.FLAG_INTERNED, dtype=np.uint8)
    flags[0] |= F.FLAG_ROOT
    recv = np.zeros(n, dtype=np.int64)
    tracer = pd.DecrementalTracer(n)
    src = np.array([0], np.int32)
    dst = np.array([1], np.int32)
    tracer.rebuild(src, dst, np.ones(1, np.int64), np.full(n, -1, np.int32))
    got = tracer.marks(flags, recv)
    assert got[0] and got[1] and not got[2]

    tracer.apply_log([(True, 1, 2, EDGE), (True, 2, 3, EDGE)])
    got = tracer.marks(flags, recv)
    assert got[[0, 1, 2, 3]].all()


@pytest.mark.parametrize("seed", [0, 1])
def test_decremental_wide_geometry(seed):
    """The TPU walk geometry through the closure+repair wake, in
    interpret mode (the compiled tier re-checks on hardware)."""
    rng = np.random.default_rng(seed)
    n = 1 << 11
    g = OracleGraph(rng, n, n_edges=4 * n)
    tracer = pd.DecrementalTracer(
        n, freeze_threshold=64, max_frozen=2, sub=4, group=8
    )
    src, dst, w, sup = g.arrays()
    tracer.rebuild(src, dst, w, sup)
    got = tracer.marks(g.flags, g.recv)
    assert np.array_equal(got, g.oracle_marks())
    for wake in range(4):
        _rand_schedule(rng, g, tracer, k=40)
        got = tracer.marks(g.flags, g.recv)
        expected = g.oracle_marks()
        assert np.array_equal(got, expected), f"seed {seed} wake {wake}"
    assert tracer.layout.stats["anomalies"] == 0


def test_freed_relay_unmarks_downstream():
    """Clearing FLAG_IN_USE on a previously-marked relay must unmark it
    AND everything only it supported (the oracle gates marks on in_use)."""
    n = 128
    flags = np.full(n, F.FLAG_IN_USE | F.FLAG_INTERNED, np.uint8)
    flags[0] |= F.FLAG_ROOT
    recv = np.zeros(n, np.int64)
    pairs = [(0, 1), (1, 2)]
    src = np.array([p[0] for p in pairs], np.int32)
    dst = np.array([p[1] for p in pairs], np.int32)
    tracer = pd.DecrementalTracer(n)
    tracer.rebuild(src, dst, np.ones(2, np.int64), np.full(n, -1, np.int32))
    got = tracer.marks(flags, recv)
    assert got[[0, 1, 2]].all()

    flags = flags.copy()
    flags[1] = 0  # freed
    got = tracer.marks(flags, recv)
    assert got[0] and not got[1] and not got[2]


def test_rebuild_invalidates_previous_fixpoint():
    """A second rebuild() that drops pairs outside the removal log must
    not leave stale marks from the first fixpoint."""
    n = 128
    flags = np.full(n, F.FLAG_IN_USE | F.FLAG_INTERNED, np.uint8)
    flags[0] |= F.FLAG_ROOT
    recv = np.zeros(n, np.int64)
    tracer = pd.DecrementalTracer(n)
    src = np.array([0, 1], np.int32)
    dst = np.array([1, 2], np.int32)
    tracer.rebuild(src, dst, np.ones(2, np.int64), np.full(n, -1, np.int32))
    got = tracer.marks(flags, recv)
    assert got[[0, 1, 2]].all()

    tracer.rebuild(
        np.array([0], np.int32),
        np.array([1], np.int32),
        np.ones(1, np.int64),
        np.full(n, -1, np.int32),
    )
    got = tracer.marks(flags, recv)
    assert got[0] and got[1] and not got[2]


def test_newly_in_use_node_gets_marked():
    """Gaining FLAG_IN_USE (slot reuse) is an additive event with no
    word change anywhere; the wake must still pick the mark up."""
    n = 128
    flags = np.full(n, F.FLAG_IN_USE | F.FLAG_INTERNED, np.uint8)
    flags[0] |= F.FLAG_ROOT
    flags[2] = 0  # not yet in use
    recv = np.zeros(n, np.int64)
    src = np.array([0, 1], np.int32)
    dst = np.array([1, 2], np.int32)
    tracer = pd.DecrementalTracer(n)
    tracer.rebuild(src, dst, np.ones(2, np.int64), np.full(n, -1, np.int32))
    got = tracer.marks(flags, recv)
    assert got[0] and got[1] and not got[2]

    flags = flags.copy()
    flags[2] = F.FLAG_IN_USE | F.FLAG_INTERNED  # slot comes alive
    got = tracer.marks(flags, recv)
    assert got[[0, 1, 2]].all()


@pytest.mark.parametrize(
    # One seed guards the property in tier-1 (~100s of interpret-mode
    # kernel eval per seed); the second rides in the slow tier.
    "seed", [0, pytest.param(1, marks=pytest.mark.slow)]
)
def test_selective_gating_at_scale(seed):
    """Many supertiles, little churn: the suspect/fresh gates cover only
    a small fraction of the graph, so an under-approximated suspect set
    cannot hide behind whole-graph re-derivation (s_rows=1 gives
    128-node supertiles -> 256 supertiles at n=2^15, ~6% gated)."""
    rng = np.random.default_rng(seed)
    n = 1 << 15
    g = OracleGraph(rng, n, n_edges=2 * n)
    tracer = pd.DecrementalTracer(
        n, s_rows=1, freeze_threshold=64, max_frozen=2
    )
    src, dst, w, sup = g.arrays()
    tracer.rebuild(src, dst, w, sup)
    assert np.array_equal(tracer.marks(g.flags, g.recv), g.oracle_marks())
    for wake in range(4):
        _rand_schedule(rng, g, tracer, k=8)
        got = tracer.marks(g.flags, g.recv)
        expected = g.oracle_marks()
        assert np.array_equal(got, expected), (
            f"seed {seed} wake {wake}: "
            f"{int((got != expected).sum())} mismatched marks"
        )
    assert tracer.layout.stats["anomalies"] == 0
