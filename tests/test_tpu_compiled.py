"""Compiled-on-TPU parity tier (``UIGC_TEST_TPU=1 python -m pytest tests/``).

Every test here runs the Pallas trace kernel with ``interpret=False`` on a
real chip and checks byte-identical marks against the numpy oracle
(reference semantics: ShadowGraph.java:205-289).  The default CPU tier runs
the same kernels in interpret mode only, which cannot catch Mosaic lowering
failures — a kernel can trace fine interpreted and still be uncompilable on
hardware (that exact failure hid the flagship kernel for three rounds).  A
deliberate kernel break must turn THIS file red on a TPU host.
"""

import numpy as np
import pytest

from uigc_tpu.ops import pallas_trace, trace as trace_ops
from test_pallas_incremental import run_history
from test_pallas_trace import random_graph

pytestmark = pytest.mark.tpu

F = trace_ops


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("n,n_edges", [(1000, 4000), (20000, 80000)])
def test_compiled_matches_oracle(seed, n, n_edges):
    rng = np.random.default_rng(seed)
    g = random_graph(rng, n, n_edges)
    expected = trace_ops.trace_marks_np(*g)
    flags, recv, supervisor, src, dst, w = g
    prep = pallas_trace.prepare_chunks(src, dst, w, supervisor, n)
    got = pallas_trace.trace_marks_layouts(flags, recv, [prep], interpret=False)
    assert np.array_equal(got, expected)


def test_compiled_million_actor_parity():
    """One >=1M-actor case on hardware: the geometry (312k+ word table
    rows, thousands of grid steps) is nothing like the small cases'."""
    n, m = 1_000_000, 4_000_000
    rng = np.random.default_rng(42)
    flags = np.full(n, F.FLAG_IN_USE | F.FLAG_INTERNED, np.uint8)
    flags[rng.choice(n, n // 100, replace=False)] |= F.FLAG_ROOT
    flags[rng.choice(n, n // 50, replace=False)] |= F.FLAG_HALTED
    recv = np.zeros(n, np.int64)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    w = np.ones(m, np.int64)
    sup = np.full(n, -1, np.int32)
    expected = trace_ops.trace_marks_np(flags, recv, sup, src, dst, w)
    prep = pallas_trace.prepare_chunks(src, dst, w, sup, n)
    got = pallas_trace.trace_marks_layouts(flags, recv, [prep], interpret=False)
    assert np.array_equal(got, expected)


def test_compiled_incremental_mutation_sequence():
    """The full tier lifecycle — base pack, delta freeze, consolidation,
    in-place base masking, XLA live tier — compiled at every checkpoint."""
    layout = run_history(
        0,
        n=2500,
        steps=300,
        check_every=60,
        interpret=False,
        freeze_threshold=24,
        max_frozen=2,
    )
    assert layout.stats["rebuilds"] == 1
    assert layout.stats["freezes"] >= 1


def test_compiled_decremental_wakes():
    """The closure+repair wake (dst-gated kernel variant) compiled on
    hardware, diffed against the from-scratch oracle across churn wakes
    incl. a released cycle and a halt cascade."""
    from test_pallas_decremental import OracleGraph, _rand_schedule
    from uigc_tpu.ops import pallas_decremental as pd

    rng = np.random.default_rng(7)
    n = 1 << 12
    g = OracleGraph(rng, n, n_edges=4 * n)
    tracer = pd.DecrementalTracer(
        n, interpret=False, freeze_threshold=64, max_frozen=2
    )
    src, dst, w, sup = g.arrays()
    tracer.rebuild(src, dst, w, sup)
    assert np.array_equal(tracer.marks(g.flags, g.recv), g.oracle_marks())
    for wake in range(4):
        _rand_schedule(rng, g, tracer, k=60)
        got = tracer.marks(g.flags, g.recv)
        assert np.array_equal(got, g.oracle_marks()), f"wake {wake}"
