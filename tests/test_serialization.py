"""Wire-format round trips for cross-node batches.

Analogue of the reference's SerializationSpec (reference:
src/test/scala/.../crgc/SerializationSpec.scala:10-126): DeltaShadow,
DeltaGraph (built through a real State -> Entry -> DeltaGraph pipeline),
and IngressEntry round-trip through their binary encodings.
"""

import pytest

from uigc_tpu.engines.crgc.delta import DeltaGraph, DeltaShadow
from uigc_tpu.engines.crgc.gateways import IngressEntry
from uigc_tpu.engines.crgc.refob import CrgcRefob
from uigc_tpu.engines.crgc.state import CrgcContext, CrgcState, Entry


class FakeSystem:
    address = "uigc://ser"


class FakeCell:
    _count = 0

    def __init__(self):
        FakeCell._count += 1
        self.uid = FakeCell._count
        self.path = f"/ser/{self.uid}"
        self.system = FakeSystem()


class Registry:
    """Cell <-> bytes codec standing in for actor-ref serialization."""

    def __init__(self):
        self.by_id = {}

    def encode(self, cell):
        self.by_id[cell.uid] = cell
        return str(cell.uid).encode()

    def decode(self, data):
        return self.by_id[int(data.decode())]


def test_delta_shadow_roundtrip():
    shadow = DeltaShadow()
    shadow.recv_count = -7
    shadow.supervisor = 3
    shadow.interned = True
    shadow.is_root = False
    shadow.is_busy = True
    shadow.outgoing = {0: 2, 5: -1}
    data = shadow.serialize()
    back, offset = DeltaShadow.deserialize(data, 0)
    assert offset == len(data)
    assert back == shadow

    # Empty shadow, like the reference's 13-byte case.
    empty = DeltaShadow()
    data = empty.serialize()
    back, offset = DeltaShadow.deserialize(data, 0)
    assert offset == len(data)
    assert back == empty


def test_delta_graph_roundtrip_via_state_pipeline():
    """Build entries through the real State machinery, fold into a
    DeltaGraph, round-trip it (reference: SerializationSpec.scala:85-97)."""
    context = CrgcContext(delta_graph_size=64, entry_field_size=4)
    registry = Registry()

    a, b, c = FakeCell(), FakeCell(), FakeCell()
    ref_a, ref_b, ref_c = CrgcRefob(a), CrgcRefob(b), CrgcRefob(c)

    state = CrgcState(ref_a, context)
    state.record_new_refob(ref_a, ref_a)
    state.record_new_refob(ref_a, ref_b)
    state.record_new_actor(ref_c)
    ref_b.inc_send_count()
    state.record_updated_refob(ref_b)
    state.record_message_received()

    entry = Entry(context)
    state.flush_to_entry(is_busy=True, entry=entry)

    graph = DeltaGraph(FakeSystem.address, context)
    graph.merge_entry(entry)
    assert graph.non_empty()

    data = graph.serialize(registry.encode)
    back = DeltaGraph.deserialize(data, context, registry.decode)
    assert back == graph
    assert back.decoder() == graph.decoder()


def test_delta_graph_fills_and_reports():
    context = CrgcContext(delta_graph_size=16, entry_field_size=2)
    graph = DeltaGraph("x", context)
    cells = [FakeCell() for _ in range(12)]
    for cell in cells:
        entry = Entry(context)
        entry.self_ref = CrgcRefob(cell)
        entry.recv_count = 1
        graph.merge_entry(entry)
        if graph.is_full():
            break
    assert graph.is_full()


def test_ingress_entry_roundtrip():
    registry = Registry()
    entry = IngressEntry()
    entry.id = 42
    entry.is_final = True
    entry.egress_address = "uigc://a"
    entry.ingress_address = "uigc://b"
    x, y, z = FakeCell(), FakeCell(), FakeCell()
    entry.on_message(x, [CrgcRefob(y), CrgcRefob(z), CrgcRefob(y)])
    entry.on_message(x, [])
    entry.on_message(z, [CrgcRefob(x)])

    data = entry.serialize(registry.encode)
    back = IngressEntry.deserialize(data, registry.decode)
    assert back == entry
    assert back.admitted[x].message_count == 2
    assert back.admitted[x].created_refs[y] == 2
