"""An actor with only self-references and in-flight self-messages must not
terminate until its queue drains.

Analogue of the reference's SelfMessagingSpec (reference:
src/test/scala/edu/illinois/osl/uigc/SelfMessagingSpec.scala:22-34).
"""

from uigc_tpu import AbstractBehavior, ActorTestKit, Behaviors, NoRefs, PostStop

CONFIG = {"uigc.crgc.wakeup-interval": 10}


class SelfRefTestInit(NoRefs):
    def __init__(self, n):
        self.n = n


class Countdown(NoRefs):
    def __init__(self, n):
        self.n = n


class SelfRefTerminated(NoRefs):
    def __init__(self, n):
        self.n = n

    def __eq__(self, other):
        return isinstance(other, SelfRefTerminated) and other.n == self.n

    def __hash__(self):
        return hash(("SelfRefTerminated", self.n))


class ActorB(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.count = 0

    def on_message(self, msg):
        if isinstance(msg, Countdown) and msg.n > 0:
            self.context.self.tell(Countdown(msg.n - 1), self.context)
            self.count += 1
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(SelfRefTerminated(self.count))
        return None


class ActorA(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.actor_b = context.spawn(
            Behaviors.setup(lambda ctx: ActorB(ctx, probe)), "actorB"
        )

    def on_message(self, msg):
        if isinstance(msg, SelfRefTestInit):
            self.actor_b.tell(Countdown(msg.n), self.context)
            self.context.release(self.actor_b)
        return self


def test_no_premature_termination_with_self_messages():
    kit = ActorTestKit(CONFIG)
    try:
        probe = kit.create_test_probe(timeout_s=30.0)
        actor_a = kit.spawn(
            Behaviors.setup_root(lambda ctx: ActorA(ctx, probe)), "actorA"
        )
        n = 10000
        actor_a.tell(SelfRefTestInit(n))
        # B must process all n countdowns before being collected.
        probe.expect_message(SelfRefTerminated(n))
    finally:
        kit.shutdown()
