"""Shared roles for the multi-process cluster tests.

Imported under the SAME module name by the pytest driver process and by
every child process (via nodeproc_child.py), so pickled application
messages resolve to identical classes on both sides of the socket."""

from __future__ import annotations

import json
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from uigc_tpu import AbstractBehavior, Behaviors, Message, NoRefs, PostStop
from uigc_tpu.runtime.behaviors import RawBehavior
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.runtime.system import ActorSystem

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.crgc.num-nodes": 3,
}


class Ping(NoRefs):
    pass


class Share(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,) if self.ref is not None else ()


class DropCmd(NoRefs):
    pass


class Spawned(NoRefs):
    def __init__(self, name):
        self.name = name


class Stopped(NoRefs):
    def __init__(self, name):
        self.name = name


class RemoteProbe:
    """Probe facade whose .ref is a ProxyCell of the driver's probe
    forwarder cell."""

    def __init__(self, cell):
        self.ref = cell


class Worker(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        probe.ref.tell(Spawned(context.name))

    def on_message(self, msg):
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Stopped(self.context.name))
        return None


class Holder(AbstractBehavior):
    """Root on the doomed node, holding the only ref to a remote
    worker."""

    def __init__(self, context):
        super().__init__(context)
        self.held = None

    def on_message(self, msg):
        if isinstance(msg, Share):
            self.held = msg.ref
            self.held.tell(Ping(), self.context)
        return self


class Owner(AbstractBehavior):
    """Root on node B owning the worker; hands a ref to the doomed
    node's holder, then releases its own."""

    def __init__(self, context, probe, holder_ref):
        super().__init__(context)
        self.worker = context.spawn(
            Behaviors.setup(lambda ctx: Worker(ctx, probe)), "worker"
        )
        self.holder_ref = holder_ref

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Share):
            self.holder_ref.tell(
                Share(ctx.create_ref(self.worker, self.holder_ref)), ctx
            )
        elif isinstance(msg, DropCmd):
            ctx.release(self.worker)
        return self


class ProbeForwarder(RawBehavior):
    """Unmanaged cell on the driver node that funnels raw cross-process
    messages into the in-process TestProbe."""

    def __init__(self, probe):
        self.probe = probe

    def on_message(self, msg):
        self.probe._offer(msg)
        return None


def _say(line: str) -> None:
    sys.stdout.write(line + "\n")
    sys.stdout.flush()


def run_child(spec: dict) -> None:
    """Child process main: build the node, listen, then follow stdin
    commands from the driver."""
    role = spec["role"]
    address = spec["address"]
    with_drops = spec.get("with_drops", False)
    backend = spec.get("backend", "array")

    config = dict(BASE)
    config["uigc.crgc.shadow-graph"] = backend
    if "num_nodes" in spec:
        config["uigc.crgc.num-nodes"] = spec["num_nodes"]

    fabric = NodeFabric()
    system = ActorSystem(None, name=address, config=config, fabric=fabric)

    holder_handle = None
    owner_handle = None
    if role == "holder":
        holder_handle = system.spawn_root(
            Behaviors.setup_root(lambda ctx: Holder(ctx)), "holder"
        )
        fabric.register_name("holder", holder_handle.cell)
    elif role == "spawner":
        from uigc_tpu.runtime.remote import RemoteSpawner

        probe_addr = f"uigc://{spec.get('probe_node', 'procA')}"

        def worker_setup(ctx):
            # probe looked up lazily at spawn time (the driver's hello,
            # carrying the name, has arrived by then)
            return Worker(ctx, RemoteProbe(fabric.lookup(probe_addr, "probe")))

        spawner_cell = RemoteSpawner.spawn_service(
            system, {"worker": Behaviors.setup(worker_setup)}
        )
        fabric.register_name("spawner", spawner_cell)

    port = fabric.listen()
    _say(f"READY {port}")

    for raw in sys.stdin:
        parts = raw.strip().split()
        if not parts:
            continue
        cmd = parts[0]
        if cmd == "connect":
            host, p = parts[1].rsplit(":", 1)
            peer = fabric.connect(host, int(p))
            _say(f"CONNECTED {peer}")
        elif cmd == "spawn_owner":
            holder_addr = f"uigc://{parts[1]}"
            probe_addr = f"uigc://{parts[2]}"
            # wait for both peers' hellos (names arrive with them)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                try:
                    holder = fabric.lookup(holder_addr, "holder")
                    probe_cell = fabric.lookup(probe_addr, "probe")
                    break
                except KeyError:
                    time.sleep(0.05)
            else:
                _say("ERROR lookup timed out")
                continue
            if with_drops:
                fabric.set_inbound_drop_filter(
                    holder_addr,
                    lambda m: isinstance(getattr(m, "payload", None), Ping),
                )
            probe = RemoteProbe(probe_cell)

            def make_owner(ctx):
                return Owner(ctx, probe, ctx.engine.to_root_refob(holder))

            owner_handle = system.spawn_root(
                Behaviors.setup_root(make_owner), "owner"
            )
            _say("OWNER_SPAWNED")
        elif cmd == "share":
            owner_handle.tell(Share(None))
            _say("SHARED")
        elif cmd == "drop":
            owner_handle.tell(DropCmd())
            _say("DROPPED")
        elif cmd == "dump":
            bk = system.engine.bookkeeper
            state = {
                "members": fabric.members(),
                "crashed": sorted(fabric.crashed),
                "remote_gcs": sorted(bk.remote_gcs),
                "downed": sorted(bk.downed_gcs),
                "undone": sorted(bk.undone_gcs),
                "finalized_by": {
                    a: sorted(l.finalized_by) for a, l in bk.undo_logs.items()
                },
                "in_use": getattr(bk.shadow_graph, "num_in_use", -1),
            }
            _say("DUMP " + json.dumps(state))
        elif cmd == "exit":
            break
    import os

    os._exit(0)

# NOTE: no __main__ entry here on purpose — children must run via
# nodeproc_child.py so this module keeps the name "nodeproc_common" in
# every process (pickled message classes must resolve identically).
