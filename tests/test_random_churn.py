"""Soundness/completeness stress: random spawn/link/release/ping churn.

Analogue of the reference's RandomSpec (reference:
src/test/scala/edu/illinois/osl/uigc/RandomSpec.scala:14-125): spawn
MAX_ACTORS actors in a random topology (including cycles), then wait for
the GC to collect every one of them.  Unsound GC kills live actors (dead
letters / lost countdowns); incomplete GC times out.
"""

import os
import random
import threading
import time

from uigc_tpu import AbstractBehavior, ActorTestKit, Behaviors, Message, NoRefs, PostStop

MAX_ACTORS = int(os.environ.get("UIGC_RANDOM_SPEC_ACTORS", "10000"))
CONFIG = {"uigc.crgc.wakeup-interval": 20}


class Link(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,)


class Ping(NoRefs):
    pass


class Latch:
    """CountDownLatch analogue."""

    def __init__(self, count):
        self._count = count
        self._cond = threading.Condition()

    def count_down(self):
        with self._cond:
            self._count -= 1
            if self._count <= 0:
                self._cond.notify_all()

    def await_zero(self, timeout_s):
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._count > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self._count
                self._cond.wait(remaining)
            return 0


class Shared:
    def __init__(self):
        self.spawn_counter = 0
        self.lock = threading.Lock()
        self.latch = Latch(MAX_ACTORS)
        self.rng = random.Random(20260729)

    def try_reserve_spawn(self):
        with self.lock:
            self.spawn_counter += 1
            return self.spawn_counter <= MAX_ACTORS

    def reached_max(self):
        with self.lock:
            return self.spawn_counter >= MAX_ACTORS

    def rand(self):
        with self.lock:
            return self.rng.random()

    def randint(self, n):
        with self.lock:
            return self.rng.randrange(n)


class RandomActor(AbstractBehavior):
    def __init__(self, context, shared, timers):
        super().__init__(context)
        self.shared = shared
        self.timers = timers
        self.acquaintances = []

    def on_message(self, msg):
        if isinstance(msg, Link):
            self.acquaintances.append(msg.ref)
            self.do_some_actions()
        elif isinstance(msg, Ping):
            self.do_some_actions()
        return self

    def do_some_actions(self):
        if self.shared.reached_max():
            if self.timers is not None:
                # Root: stop the churn and release everything so the whole
                # population becomes garbage.
                self.timers.cancel_all()
                if self.acquaintances:
                    self.context.release(self.acquaintances)
                    self.acquaintances = []
            return
        self.do_something()
        self.do_something()

    def do_something(self):
        ctx = self.context
        shared = self.shared
        p = shared.rand()
        if p < 0.2:
            if shared.try_reserve_spawn():
                self.acquaintances.append(
                    ctx.spawn_anonymous(random_actor_factory(shared))
                )
        elif p < 0.4 and self.acquaintances:
            owner = self.acquaintances[shared.randint(len(self.acquaintances))]
            target = self.acquaintances[shared.randint(len(self.acquaintances))]
            owner.tell(Link(ctx.create_ref(target, owner)), ctx)
        elif p < 0.6 and self.acquaintances:
            i = shared.randint(len(self.acquaintances))
            actor = self.acquaintances.pop(i)
            ctx.release(actor)
        elif p < 0.8 and self.acquaintances:
            self.acquaintances[shared.randint(len(self.acquaintances))].tell(
                Ping(), ctx
            )

    def on_signal(self, signal):
        if signal is PostStop:
            if self.timers is None:  # root doesn't count
                self.shared.latch.count_down()
        return None


def random_actor_factory(shared):
    return Behaviors.setup(lambda ctx: RandomActor(ctx, shared, None))


import pytest


@pytest.mark.parametrize(
    "backend,pipelined",
    [
        ("array", False),
        ("decremental", False),
        ("decremental", True),
        ("mesh-decremental", True),
    ],
    ids=[
        "array",
        "decremental",
        "decremental-pipelined",
        "mesh-decremental-pipelined",
    ],
)
def test_random_churn_fully_collected(backend, pipelined):
    """Unsound GC kills live actors; incomplete GC times out.  The
    decremental variant must detect every released subgraph (incl.
    cycles) by regional repair, never by luck of a full re-trace; the
    pipelined variant additionally sweeps snapshot verdicts while the
    next wake runs."""
    shared = Shared()
    kit = ActorTestKit(
        dict(
            CONFIG,
            **{
                "uigc.crgc.shadow-graph": backend,
                "uigc.crgc.pipelined": pipelined,
            },
        )
    )
    try:
        def make_root(timers):
            def setup(ctx):
                timers.start_timer_at_fixed_rate("ping", Ping(), 0.001)
                return RandomActor(ctx, shared, timers)

            return Behaviors.setup_root(setup)

        kit.spawn(Behaviors.with_timers(make_root), "root")
        remaining = shared.latch.await_zero(timeout_s=300.0)
        assert remaining == 0, (
            f"{remaining} of {MAX_ACTORS} actors were never collected "
            "(GC incomplete)"
        )
    finally:
        kit.shutdown()

