"""MAC engine: weighted-reference-counting collection + cycle detection.

Covers BASELINE config 2 (MAC acyclic garbage, single node) and the
completed cycle detector (the reference's is a stub — reference.conf:48).
"""

import time

import pytest

from uigc_tpu import AbstractBehavior, ActorTestKit, Behaviors, Message, NoRefs, PostStop


class Share(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,) if self.ref is not None else ()


class Drop(NoRefs):
    pass


class Ping(NoRefs):
    pass


class CountdownInit(NoRefs):
    def __init__(self, n):
        self.n = n


class Countdown(NoRefs):
    def __init__(self, n):
        self.n = n


class Stopped(NoRefs):
    def __init__(self, name):
        self.name = name


class Worker(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.peer = None
        self.count = 0

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Share):
            self.peer = msg.ref
        elif isinstance(msg, Countdown):
            self.count += 1
            if msg.n > 0:
                ctx.self.tell(Countdown(msg.n - 1), ctx)
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Stopped(self.context.name))
        return None


def worker_factory(probe):
    return Behaviors.setup(lambda ctx: Worker(ctx, probe))


class Root(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.a = context.spawn(worker_factory(probe), "a")
        self.b = context.spawn(worker_factory(probe), "b")

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Drop):
            ctx.release(self.a, self.b)
        elif isinstance(msg, Share):
            # Build the cycle a <-> b, then drop our refs.
            self.a.tell(Share(ctx.create_ref(self.b, self.a)), ctx)
            self.b.tell(Share(ctx.create_ref(self.a, self.b)), ctx)
        elif isinstance(msg, CountdownInit):
            self.a.tell(Countdown(msg.n), ctx)
            ctx.release(self.a)
        return self


def test_mac_acyclic_collection():
    """Releasing the only refs collects both workers via DecMsg/rc=0."""
    kit = ActorTestKit({"uigc.engine": "mac"})
    try:
        probe = kit.create_test_probe()
        root = kit.spawn(Behaviors.setup_root(lambda c: Root(c, probe)), "root")
        probe.expect_no_message(0.2)
        root.tell(Drop())
        probe.expect_message_type(Stopped)
        probe.expect_message_type(Stopped)
    finally:
        kit.shutdown()


def test_mac_pending_self_messages_block_termination():
    """An actor with in-flight self-messages must not terminate until they
    drain (reference: MAC.scala:237-246 pendingSelfMessages)."""
    kit = ActorTestKit({"uigc.engine": "mac"})
    try:
        probe = kit.create_test_probe(timeout_s=30.0)
        root = kit.spawn(Behaviors.setup_root(lambda c: Root(c, probe)), "root")
        root.tell(CountdownInit(5000))
        stopped = probe.expect_message_type(Stopped)
        assert stopped.name.endswith("/a")
    finally:
        kit.shutdown()


def test_mac_cycle_not_collected_without_detection():
    """With cycle-detection off (the reference default), a released cycle
    leaks — WRC alone cannot collect it."""
    kit = ActorTestKit({"uigc.engine": "mac", "uigc.mac.cycle-detection": False})
    try:
        probe = kit.create_test_probe()
        root = kit.spawn(Behaviors.setup_root(lambda c: Root(c, probe)), "root")
        root.tell(Share(None))  # builds the cycle
        time.sleep(0.2)
        root.tell(Drop())
        probe.expect_no_message(0.5)
    finally:
        kit.shutdown()


def test_mac_cycle_collected_with_detection():
    """The completed SCC detector finds the closed a<->b cycle, confirms
    via CNF/ACK, and kills it."""
    kit = ActorTestKit(
        {
            "uigc.engine": "mac",
            "uigc.mac.cycle-detection": True,
            "uigc.mac.wakeup-interval": 10,
        }
    )
    try:
        probe = kit.create_test_probe(timeout_s=15.0)
        root = kit.spawn(Behaviors.setup_root(lambda c: Root(c, probe)), "root")
        root.tell(Share(None))
        time.sleep(0.2)
        root.tell(Drop())
        probe.expect_message_type(Stopped)
        probe.expect_message_type(Stopped)
        detector = kit.system.engine.detector
        assert detector.total_cycles_collected >= 1
    finally:
        kit.shutdown()


def test_mac_live_cycle_not_collected():
    """A cycle still owned by the root must survive — closedness fails
    because the root's weight shows up in members' rc."""
    kit = ActorTestKit(
        {
            "uigc.engine": "mac",
            "uigc.mac.cycle-detection": True,
            "uigc.mac.wakeup-interval": 10,
        }
    )
    try:
        probe = kit.create_test_probe()
        root = kit.spawn(Behaviors.setup_root(lambda c: Root(c, probe)), "root")
        root.tell(Share(None))  # cycle built, root still owns both
        probe.expect_no_message(0.5)
    finally:
        kit.shutdown()
