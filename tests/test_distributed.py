"""Distributed collector: the partitioned shadow graph and its wave
protocol (engines/crgc/distributed.py + parallel/partition.py).

Layers:

- unit: partition map churn/alignment, reduction-tree shape, the
  dmark-family frame codecs (tolerance contract), fence-keyed ingress
  windows and the undo log's straggler filter (the gateways satellite),
  and the fold-locality audit (the UL014 runtime twin);
- cluster: 3-node in-process fabric — a garbage cycle spanning all
  three nodes collects with NO node ever holding the full graph,
  verdicts identical to the single-host collector on the same
  workload, merged-oracle uigcsan clean;
- chaos: 3-node NodeFabric over real sockets — seeded dmark drops
  (cumulative re-send until ack heals them) and a silent node kill
  mid-collection (heartbeat verdict -> fence bump -> partition
  ownership transfer -> journal re-fold), survivors sanitizer-clean.
"""

import time
import types

import pytest

from uigc_tpu import AbstractBehavior, Behaviors, Message, NoRefs, PostStop
from uigc_tpu.analysis.sanitizer import cross_check_distributed, merged_oracle
from uigc_tpu.engines.crgc.delta import DeltaGraph
from uigc_tpu.engines.crgc.distributed import PartitionedShadowGraph
from uigc_tpu.engines.crgc.gateways import IngressEntry
from uigc_tpu.engines.crgc.state import CrgcContext
from uigc_tpu.engines.crgc.undo import UndoLog
from uigc_tpu.parallel.partition import PartitionMap, ReductionTree, cell_key
from uigc_tpu.runtime import wire
from uigc_tpu.runtime.behaviors import RawBehavior
from uigc_tpu.runtime.fabric import Fabric
from uigc_tpu.runtime.faults import FaultPlan
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.runtime.remote import RemoteSpawner
from uigc_tpu.runtime.system import ActorSystem
from uigc_tpu.runtime.testkit import TestProbe
from uigc_tpu.utils import events

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.crgc.num-nodes": 3,
    "uigc.crgc.distributed": True,
    "uigc.analysis.sanitizer": True,
}


# ------------------------------------------------------------------- #
# Workload actors (module-level: they cross pickling fabrics)
# ------------------------------------------------------------------- #


class Hold(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,) if self.ref is not None else ()


class Go(NoRefs):
    def __init__(self, rings, kept=0):
        self.rings = rings
        self.kept = kept


class Drop(NoRefs):
    pass


class Spawned(NoRefs):
    pass


class Stopped(NoRefs):
    pass


class ProbeForwarder(RawBehavior):
    def __init__(self, probe):
        self.probe = probe

    def on_message(self, msg):
        self.probe._offer(msg)
        return None


class Worker(AbstractBehavior):
    def __init__(self, context, probe_ref):
        super().__init__(context)
        self.probe_ref = probe_ref
        self.held = []
        probe_ref.tell(Spawned())

    def on_message(self, msg):
        if isinstance(msg, Hold):
            self.held.append(msg.ref)
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe_ref.tell(Stopped())
        return None


class RingMaster(AbstractBehavior):
    """Spawns rings of workers, one per node via the spawner services:
    every ring is a reference cycle spanning the whole cluster.  Kept
    rings stay pinned by the master's own refs (the over-collection
    canary); dropped rings are garbage only the cross-node trace can
    prove dead."""

    def __init__(self, context, spawners):
        super().__init__(context)
        self.spawners = spawners
        self.workers = []
        self.kept = []

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Go):
            for r in range(msg.rings + msg.kept):
                ring = [ctx.spawn_remote("worker", sc) for sc in self.spawners]
                n = len(ring)
                for i, w in enumerate(ring):
                    nxt = ring[(i + 1) % n]
                    w.tell(Hold(ctx.create_ref(nxt, w)), ctx)
                (self.kept if r >= msg.rings else self.workers).extend(ring)
        elif isinstance(msg, Drop):
            for w in self.workers:
                ctx.release(w)
            self.workers = []
        return self


# ------------------------------------------------------------------- #
# Cluster builders
# ------------------------------------------------------------------- #


def build_inproc(probe, overrides=None, nodes=3):
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = nodes
    if overrides:
        config.update(overrides)
    fabric = Fabric()
    systems = [
        ActorSystem(None, name=f"dn{i}", config=config, fabric=fabric)
        for i in range(nodes)
    ]
    spawners = [
        RemoteSpawner.spawn_service(
            s, {"worker": Behaviors.setup(lambda ctx: Worker(ctx, probe.ref))}
        )
        for s in systems
    ]
    master = systems[0].spawn_root(
        Behaviors.setup_root(lambda ctx: RingMaster(ctx, spawners)), "master"
    )
    return systems, master


class _Node:
    __slots__ = ("fabric", "system", "port", "address")

    def __init__(self, name, config, plan):
        self.fabric = NodeFabric(fault_plan=plan)
        self.system = ActorSystem(None, name=name, config=config, fabric=self.fabric)
        self.port = self.fabric.listen()
        self.address = self.system.address


def build_nodefabric(names, probe, plan=None, overrides=None):
    """3 NodeFabrics over localhost sockets; the probe forwarder and
    each node's spawner service are registered as well-known names
    BEFORE the mesh connects (names ride the hello)."""
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = len(names)
    if overrides:
        config.update(overrides)
    nodes = [_Node(n, config, plan) for n in names]
    probe_cell = nodes[0].system.spawn_system_raw(
        ProbeForwarder(probe), "probe-fwd"
    )
    nodes[0].fabric.register_name("probe", probe_cell)
    addr0 = nodes[0].address
    for n in nodes:
        if n is nodes[0]:
            factory = Behaviors.setup(lambda ctx: Worker(ctx, probe_cell))
        else:
            fab = n.fabric

            def factory_for(fab=fab):
                return Behaviors.setup(
                    lambda ctx: Worker(ctx, fab.lookup(addr0, "probe"))
                )

            factory = factory_for()
        sc = RemoteSpawner.spawn_service(n.system, {"worker": factory})
        n.fabric.register_name("spawner", sc)
    for i, a in enumerate(nodes):
        for b in nodes[i + 1 :]:
            a.fabric.connect("127.0.0.1", b.port)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if all(len(n.fabric.members()) == len(names) for n in nodes):
            break
        time.sleep(0.02)
    spawners = [
        nodes[0].fabric.lookup(n.address, "spawner") if n is not nodes[0]
        else n.fabric._names["spawner"]
        for n in nodes
    ]
    master = nodes[0].system.spawn_root(
        Behaviors.setup_root(lambda ctx: RingMaster(ctx, spawners)), "master"
    )
    return nodes, master


def terminate_all(items):
    for it in items:
        system = getattr(it, "system", it)
        try:
            system.terminate(timeout_s=5.0)
        except Exception:
            pass


def collect_stopped(probe, expected, timeout_s=30.0):
    stopped = 0
    deadline = time.monotonic() + timeout_s
    while stopped < expected and time.monotonic() < deadline:
        try:
            probe.expect_message_type(Stopped, timeout_s=2.0)
            stopped += 1
        except AssertionError:
            continue
    return stopped


class EventLog:
    def __init__(self):
        import threading

        self.entries = []
        self._lock = threading.Lock()

    def __call__(self, name, fields):
        with self._lock:
            self.entries.append((name, dict(fields)))

    def names(self):
        with self._lock:
            return [n for n, _ in self.entries]

    def of(self, name):
        with self._lock:
            return [f for n, f in self.entries if n == name]


@pytest.fixture
def event_log():
    log = EventLog()
    events.recorder.enable()
    events.recorder.add_listener(log)
    yield log
    events.recorder.disable()
    events.recorder.remove_listener(log)
    events.recorder.reset()


# ------------------------------------------------------------------- #
# Unit layer
# ------------------------------------------------------------------- #


class _FakeCell:
    """Identity-hashed stand-in exposing the (system.address, uid)
    coordinate every graph-level API reads."""

    __slots__ = ("system", "uid", "path")

    def __init__(self, address, uid):
        self.system = types.SimpleNamespace(address=address)
        self.uid = uid
        self.path = f"{address}/fake-{uid}"


def _fake_cell(address, uid):
    return _FakeCell(address, uid)


def test_partition_map_minimal_churn_and_coverage():
    members = ["uigc://a", "uigc://b", "uigc://c"]
    pmap = PartitionMap(members, 32, fence=0, self_address="uigc://a")
    # Complete coverage, deterministic in the member set.
    owners = pmap.assignments()
    assert sorted(owners) == list(range(32))
    assert set(owners.values()) <= set(members)
    again = PartitionMap(list(reversed(members)), 32, self_address="uigc://a")
    assert again.assignments() == owners
    # A death moves ONLY the dead node's partitions (rendezvous).
    survivors = PartitionMap(
        ["uigc://a", "uigc://b"], 32, fence=1, self_address="uigc://a"
    )
    moved = survivors.moved_partitions(pmap)
    assert moved == pmap.owned_partitions("uigc://c")
    for p in moved:
        assert survivors.owner(p) in ("uigc://a", "uigc://b")
    # Key routing is stable and owner-consistent.
    key = ("uigc://a", 1234)
    assert pmap.partition_of(key) == pmap.partition_of(key)
    assert pmap.owner_of(key) == owners[pmap.partition_of(key)]


def test_reduction_tree_shape_and_reroot():
    members = sorted(f"uigc://n{i}" for i in range(7))
    tree = ReductionTree(members)
    assert tree.root == members[0]
    # parent/children are mutually consistent and cover everyone once.
    seen = []
    for m in members:
        for c in tree.children(m):
            assert tree.parent(c) == m
            seen.append(c)
    assert sorted(seen + [tree.root]) == members
    assert tree.subtree_size(tree.root) == len(members)
    # Root death: the recomputed tree re-roots with no handoff protocol.
    rebuilt = ReductionTree(members[1:])
    assert rebuilt.root == members[1]
    assert rebuilt.subtree_size(rebuilt.root) == len(members) - 1


def test_dist_frame_codecs_round_trip_and_tolerance():
    keys = [("uigc://a", 7), ("uigc://b", 123456789)]
    pairs = [(("uigc://a", 1), ("uigc://b", 2))]
    stats = {"settled": True, "changed": False, "sent": 3, "recv": 3, "nodes": 2}
    cases = [
        (
            wire.encode_dwave(4, 1, "uigc://a"),
            wire.decode_dwave,
            (4, 1, "uigc://a", 0),
        ),
        (
            wire.encode_dwave(4, 1, "uigc://a", round_id=2),
            wire.decode_dwave,
            (4, 1, "uigc://a", 2),
        ),
        (
            wire.encode_dmark(4, 1, "uigc://a", keys),
            wire.decode_dmark,
            (4, 1, "uigc://a", sorted(keys), 0, 0),
        ),
        (
            wire.encode_dmark(4, 1, "uigc://a", keys, start=5, round_id=2),
            wire.decode_dmark,
            (4, 1, "uigc://a", sorted(keys), 5, 2),
        ),
        # The legacy (binary=False) shape a PR-14 peer receives — and
        # the frame it would itself send — keeps JSON list order.
        (
            wire.encode_dmark(4, 1, "uigc://a", keys, binary=False),
            wire.decode_dmark,
            (4, 1, "uigc://a", keys, 0, 0),
        ),
        # The ack/round frames carry a trailing fence: absent (an older
        # peer) decodes as era 0, explicit values round-trip.
        (
            wire.encode_dmack(4, "uigc://a", 9),
            wire.decode_dmack,
            (4, "uigc://a", 9, 0, 0, None),
        ),
        (
            wire.encode_dmack(
                4, "uigc://a", 9, fence=3, round_id=2, report=(1, 0, 3, 3, 1)
            ),
            wire.decode_dmack,
            (4, "uigc://a", 9, 3, 2, (1, 0, 3, 3, 1)),
        ),
        (
            wire.encode_dprobe(4, 2, "uigc://a"),
            wire.decode_dprobe,
            (4, 2, "uigc://a", 0),
        ),
        (
            wire.encode_dstat(4, 2, "uigc://a", stats),
            wire.decode_dstat,
            (4, 2, "uigc://a", stats, 0),
        ),
        (wire.encode_dfin(4, 1, "uigc://a"), wire.decode_dfin, (4, 1, "uigc://a")),
        (
            wire.encode_dgate(4, 1, "uigc://a", pairs),
            wire.decode_dgate,
            (4, 1, "uigc://a", pairs),
        ),
        (
            wire.encode_dgack(4, "uigc://a", 1),
            wire.decode_dgack,
            (4, "uigc://a", 1, 0),
        ),
        (wire.encode_ddirty("uigc://a"), wire.decode_ddirty, "uigc://a"),
        (
            wire.encode_djournal(1, 5, b"graphbytes"),
            wire.decode_djournal,
            (1, 5, b"graphbytes"),
        ),
    ]
    for frame, decode, expected in cases:
        assert frame[0] in wire.DIST_FRAME_KINDS
        assert decode(frame) == expected
        # Trailing elements from a newer peer are tolerated.
        assert decode(frame + ("future", 42)) == expected
        # Truncation is malformed -> None, never a raise.
        assert decode(frame[:1]) is None
    # Corrupt payloads: bad json / bad binary / wrong types -> None.
    assert wire.decode_dmark(("dmark", 1, 1, "a", b"{not json")) is None
    assert wire.decode_dmark(("dmark", 1, 1, "a", "not-bytes")) is None
    assert wire.decode_dmark(("dmark", 1, 1, "a", b"\x01\x02trunc")) is None
    assert wire.decode_dstat(("dstat", 1, 1, "a", b"[1,2]")) is None
    assert wire.decode_dgate(("dgate", 1, 1, "a", b"[[1]]")) is None
    assert wire.decode_djournal(("djnl", 1, 5, 42)) is None
    # A garbled piggyback report degrades to absent, never an error.
    assert wire.decode_dmack(("dmack", 4, "a", 9, 0, 2, "junk")) == (
        4, "a", 9, 0, 2, None,
    )
    # Exact PR-14 frame shapes (no start/round/report elements) decode
    # with the legacy defaults — the mixed-version receive direction.
    import json as _json

    legacy_payload = _json.dumps([["uigc://a", 7]]).encode()
    assert wire.decode_dmark(("dmark", 4, 1, "uigc://a", legacy_payload)) == (
        4, 1, "uigc://a", [("uigc://a", 7)], 0, 0,
    )
    assert wire.decode_dmack(("dmack", 4, "uigc://a", 9, 1)) == (
        4, "uigc://a", 9, 1, 0, None,
    )
    assert wire.decode_dwave(("dwave", 4, 1, "uigc://a")) == (
        4, 1, "uigc://a", 0,
    )


def test_keyset_codec_round_trip_property():
    """Random key sets round-trip the density-switched binary codec
    exactly (as sets), across densities, multi-address mixes, and
    uid magnitudes."""
    import random

    from uigc_tpu.runtime import schema

    rng = random.Random(99)
    addresses = ["uigc://a", "uigc://bb", "uigc://much-longer-name-0"]
    for trial in range(40):
        keys = set()
        for _ in range(rng.randrange(1, 120)):
            addr = rng.choice(addresses)
            if rng.random() < 0.5:
                uid = rng.randrange(0, 200)  # dense regime
            else:
                uid = rng.randrange(0, 1 << rng.randrange(8, 50))
            keys.add((addr, uid))
        payload = schema.encode_keyset(keys)
        assert payload[0] == schema.KEYSET_MAGIC
        back = schema.decode_keyset(payload)
        assert back is not None and set(back) == keys
        # The magic-dispatch decoder accepts both codecs.
        assert set(schema.decode_keyset_any(payload)) == keys
        assert set(
            schema.decode_keyset_any(schema.encode_keyset_json(keys))
        ) == keys
    # Empty set round-trips too (a retransmit window can be empty).
    assert schema.decode_keyset(schema.encode_keyset([])) == []


def test_keyset_codec_density_switch_boundary():
    """The bitmap/varint switch is by encoded size: a contiguous run
    takes the bitmap (1 bit/key), the same count scattered across a
    huge span takes delta-varints — and both round-trip at the exact
    boundary where bitmap bytes == key count."""
    from uigc_tpu.runtime import schema

    dense = [("uigc://a", uid) for uid in range(64)]
    sparse = [("uigc://a", uid * 100000) for uid in range(64)]
    enc_dense = schema.encode_keyset(dense)
    enc_sparse = schema.encode_keyset(sparse)
    assert b"B" in enc_dense[:16]
    assert b"V" in enc_sparse[:16]
    assert len(enc_dense) < len(enc_sparse)
    assert set(schema.decode_keyset(enc_dense)) == set(dense)
    assert set(schema.decode_keyset(enc_sparse)) == set(sparse)
    # Boundary: n keys over span 8n => bitmap bytes == n == varint
    # lower bound; the switch must pick ONE deterministically and
    # round-trip either way.
    n = 16
    edge = [("uigc://a", uid * 8) for uid in range(n)]
    enc_edge = schema.encode_keyset(edge)
    assert set(schema.decode_keyset(enc_edge)) == set(edge)
    # One uid tighter flips to bitmap; one sparser stays varint.
    tight = [("uigc://a", uid * 8) for uid in range(n - 1)] + [
        ("uigc://a", (n - 1) * 8 - 7)
    ]
    assert set(schema.decode_keyset(schema.encode_keyset(tight))) == set(tight)
    # A key set is bytes-cheaper than its JSON shape in both regimes.
    assert len(enc_dense) < len(schema.encode_keyset_json(dense))
    assert len(enc_sparse) < len(schema.encode_keyset_json(sparse))


def test_keyset_schema_negotiated_in_caps():
    """SCHEMA_DIST_KEYS rides the PR 9 schema-codec hello caps: two
    same-build peers negotiate it; a PR-14 peer (no sc cap / older id
    table) yields an id set without it, which is what routes dmark
    payloads back to the legacy JSON shape."""
    from uigc_tpu.runtime import schema

    assert schema.SCHEMA_DIST_KEYS in schema.registry.ids()
    ours = schema.capability()
    assert schema.SCHEMA_DIST_KEYS in schema.peer_schema_ids((ours,))
    legacy = ours.rsplit(":", 1)[0] + ":1,2,3"
    assert schema.SCHEMA_DIST_KEYS not in schema.peer_schema_ids((legacy,))


def test_mirror_decay_evicts_and_revives():
    """Foreign-owned mirrors leave the working set after the decay
    window; fold mentions refresh resident mirrors; a partition remap
    revives everything (gained slices must be visible to the absorb
    reset/re-fold); hygiene unpins evicted shadows once nothing
    references them."""
    context = CrgcContext(delta_graph_size=64, entry_field_size=8)
    g = PartitionedShadowGraph(context, "uigc://a")
    pmap = PartitionMap(
        ["uigc://a", "uigc://b"], 32, fence=0, self_address="uigc://a"
    )
    g.set_partition_map(pmap)
    owned = foreign = None
    for uid in range(200):
        cell = _fake_cell("uigc://a", uid)
        if pmap.owns(cell_key(cell)) and owned is None:
            owned = cell
        elif not pmap.owns(cell_key(cell)) and foreign is None:
            foreign = cell
        if owned is not None and foreign is not None:
            break
    delta = DeltaGraph("uigc://a", context)
    delta.fold_self(owned, 0, False, True)
    delta.fold_created(owned, foreign)
    g.merge_delta(delta)
    g.audit_fold_locality()
    assert g.shadow_for_key(cell_key(foreign)) is not None
    pop0 = len(g.from_set)
    # Under the decay window: still resident.
    assert g.decay_mirrors(3) == 0
    # A fold mention refreshes the clock.
    touch = DeltaGraph("uigc://a", context)
    touch.fold_self(owned, 0, False, True)
    touch.touch(foreign)
    g.merge_delta(touch)
    g.audit_fold_locality()
    assert g.decay_mirrors(3) == 0 and g.decay_mirrors(3) == 0
    # Past the window with no mentions: evicted — out of from_set and
    # key_index, but the OBJECT stays pinned behind the owned edge.
    evicted = 0
    for _ in range(5):
        evicted += g.decay_mirrors(3)
    assert evicted == 1
    assert len(g.from_set) == pop0 - 1
    assert g.shadow_for_key(cell_key(foreign)) is None
    foreign_shadow = g.shadow_map[foreign]
    owned_shadow = g.shadow_map[owned]
    assert owned_shadow.outgoing.get(foreign_shadow) == 1
    # A later -1 fold still cancels against the SAME object (eviction
    # must never fork edge identity).
    release = DeltaGraph("uigc://a", context)
    release.fold_self(owned, 0, False, True)
    release.fold_deactivate(owned, foreign)
    g.merge_delta(release)
    g.audit_fold_locality()
    assert foreign not in [s for s in owned_shadow.outgoing]
    assert owned_shadow.outgoing.get(foreign_shadow) is None
    # Remap revives whatever is still parked.
    g.evicted[foreign] = foreign_shadow  # simulate a still-parked mirror
    g.set_partition_map(
        PartitionMap(["uigc://a"], 32, fence=1, self_address="uigc://a")
    )
    assert g.shadow_for_key(cell_key(foreign)) is not None
    assert not g.evicted


def test_ingress_entry_fence_wire_round_trip():
    entry = IngressEntry()
    entry.id = 3
    entry.fence = 2
    entry.nonce = 0xDEADBEEFCAFE
    entry.egress_address = "uigc://dead"
    entry.ingress_address = "uigc://obs"
    cell = _fake_cell("uigc://obs", 77)
    entry.on_message(cell, [])
    tokens = {}

    def encode_cell(c):
        tokens[b"t"] = c
        return b"t"

    buf = entry.serialize(encode_cell)
    back = IngressEntry.deserialize(buf, lambda b: tokens[b])
    assert back.fence == 2 and back.id == 3
    assert back.nonce == 0xDEADBEEFCAFE
    assert back == entry
    # A fence-only frame (peer predates the nonce) scans nonce 0.
    fence_only = IngressEntry.deserialize(buf[:-8], lambda b: tokens[b])
    assert fence_only.fence == 2 and fence_only.nonce == 0
    # A legacy frame (neither trailing field) scans as era 0, nonce 0.
    legacy = IngressEntry.deserialize(buf[:-12], lambda b: tokens[b])
    assert legacy.fence == 0 and legacy.nonce == 0
    assert legacy.admitted == entry.admitted


def test_undo_log_refuses_pre_death_stragglers():
    log = UndoLog("uigc://dead", fence=1, own_address="uigc://me")

    def entry(ingress, fence, wid=0, final=False):
        e = IngressEntry()
        e.id = wid
        e.fence = fence
        e.egress_address = "uigc://dead"
        e.ingress_address = ingress
        e.is_final = final
        return e

    # Our own pre-rejoin straggler: below the creation floor -> stale.
    assert log.stale_fence(entry("uigc://me", 0)) is True
    assert log.stale_fence(entry("uigc://me", 1)) is False
    # A peer's stream is judged only by its own monotonicity (its
    # era counter is not comparable to ours).
    assert log.stale_fence(entry("uigc://peer", 0)) is False
    assert log.stale_fence(entry("uigc://peer", 1)) is False
    assert log.stale_fence(entry("uigc://peer", 0)) is True
    # Final entries from a stale era must not join the quorum.
    stale_final = entry("uigc://me", 0, final=True)
    assert log.stale_fence(stale_final) is True
    assert "uigc://me" not in log.finalized_by


def _straggler_entry(ingress, fence, recipient, n_msgs, wid=0, final=False):
    e = IngressEntry()
    e.id = wid
    e.fence = fence
    e.egress_address = "uigc://dead"
    e.ingress_address = ingress
    e.is_final = final
    for _ in range(n_msgs):
        e.on_message(recipient, [])
    return e


def test_undo_log_seeded_floors_fence_out_dead_eras():
    """At a rejoin the new log inherits the superseded log's per-peer
    eras as floors: a dead-era rebroadcast arriving FIRST after the
    rejoin is refused even though the new log has never heard from that
    peer."""
    recipient = _fake_cell("uigc://me", 9)
    old = UndoLog("uigc://dead", fence=0, own_address="uigc://me")
    assert old.stale_fence(_straggler_entry("uigc://b", 0, recipient, 1)) is False
    fresh = UndoLog("uigc://dead", fence=1, own_address="uigc://me")
    fresh.seed_floors(old)
    # b reported era 0 toward the dead incarnation -> era 0 is fenced.
    assert fresh.stale_fence(_straggler_entry("uigc://b", 0, recipient, 1)) is True
    assert fresh.stale_fence(_straggler_entry("uigc://b", 1, recipient, 1)) is False
    # A peer the old log never heard from is still judged only by its
    # own stream (late joiners legitimately run era 0).
    assert fresh.stale_fence(_straggler_entry("uigc://c", 0, recipient, 1)) is False
    # Floors survive a second rejoin via the intermediate log.
    third = UndoLog("uigc://dead", fence=2, own_address="uigc://me")
    third.seed_floors(fresh)
    assert third.stale_fence(_straggler_entry("uigc://b", 0, recipient, 1)) is True


def test_undo_log_nonce_refuses_other_incarnation_outright():
    """The quorum-race closer: a straggler about a PREVIOUS incarnation
    of the dead address — even a final, even as the first thing ever
    heard from that observer — is refused by incarnation identity
    before it can tally or satisfy the fold quorum.  No floor, no
    watermark, no supersession wait."""
    recipient = _fake_cell("uigc://me", 9)
    log = UndoLog(
        "uigc://dead", fence=1, own_address="uigc://me",
        expected_nonce=0xA1,
    )
    stale = _straggler_entry("uigc://c", 0, recipient, 3, final=True)
    stale.nonce = 0xA0  # the incarnation that died the time BEFORE
    assert log.stale_fence(stale) is True
    assert "uigc://c" not in log.finalized_by
    genuine = _straggler_entry("uigc://c", 0, recipient, 2, final=True)
    genuine.nonce = 0xA1  # a late joiner's era 0 IS the live stream
    assert log.stale_fence(genuine) is False
    log.merge_ingress_entry(genuine)
    assert "uigc://c" in log.finalized_by
    assert log.admitted[recipient].message_count == -2
    # Nonce-less entries (old peers / in-process fabrics) fall back to
    # the fence-era discipline rather than being refused.
    legacy = _straggler_entry("uigc://d", 0, recipient, 1)
    assert log.stale_fence(legacy) is False


def test_undo_log_supersession_unmerges_stale_first_straggler():
    """No floor on record (the peer's dead-era entries never arrived
    before the rejoin): the stale entry merges, but the peer's first
    live-era entry un-applies its tallies and withdraws its
    finalization before landing."""
    recipient = _fake_cell("uigc://me", 9)
    log = UndoLog("uigc://dead", fence=1, own_address="uigc://me")
    stale = _straggler_entry("uigc://b", 0, recipient, 3, wid=7, final=True)
    assert log.stale_fence(stale) is False
    log.merge_ingress_entry(stale)
    assert "uigc://b" in log.finalized_by
    assert log.admitted[recipient].message_count == -3
    live = _straggler_entry("uigc://b", 1, recipient, 2, wid=0)
    assert log.stale_fence(live) is False
    log.merge_ingress_entry(live)
    # Era-0 tallies and the era-0 final are gone; only era 1 remains.
    assert "uigc://b" not in log.finalized_by
    assert log.admitted[recipient].message_count == -2
    live_final = _straggler_entry("uigc://b", 1, recipient, 1, wid=1, final=True)
    log.merge_ingress_entry(live_final)
    assert "uigc://b" in log.finalized_by
    assert log.admitted[recipient].message_count == -3
    # And the dead era can no longer sneak back in behind the live one.
    assert log.stale_fence(_straggler_entry("uigc://b", 0, recipient, 5)) is True
    # Retention is a bounded per-actor NET, not a window archive: a
    # healthy link's continuous (and often empty) windows must not grow
    # the log.  Empty windows retain nothing at all.
    for wid in range(2, 52):
        log.merge_ingress_entry(_straggler_entry("uigc://b", 1, recipient, 0, wid=wid))
    assert len(log._applied_net.get("uigc://b", {})) <= 1
    assert log._applied_counts.get("uigc://b", 0) == 2  # the two non-empty windows


def test_ingress_windows_key_by_peer_fence():
    """Same window id, different fence era -> different tallies (the
    rejoined incarnation's stream never merges with its pre-death
    windows)."""
    from uigc_tpu.engines.crgc.gateways import Ingress
    from uigc_tpu.engines.crgc.messages import AppMsg

    sent = []

    class FakeEngine:
        def __init__(self):
            self._fence = 0
            self.bookkeeper_cell = types.SimpleNamespace(
                tell=lambda msg: sent.append(msg.entry)
            )

        def link_fence(self, address):
            return self._fence

    link = types.SimpleNamespace(
        src=types.SimpleNamespace(address="uigc://peer"),
        dst=types.SimpleNamespace(address="uigc://me"),
    )
    engine = FakeEngine()
    ingress = Ingress(link, engine)
    recipient = _fake_cell("uigc://me", 5)
    msg = AppMsg(None, (), None)
    msg.window_id = 0
    ingress.on_message(recipient, msg)
    engine._fence = 1  # the peer died and rejoined
    ingress.on_messages(recipient, [msg, msg])
    assert sorted(ingress.open_windows()) == [(0, 0), (1, 0)]
    old = ingress.entries[(0, 0)]
    new = ingress.entries[(1, 0)]
    assert old.fence == 0 and new.fence == 1
    assert old.admitted[recipient].message_count == 1
    assert new.admitted[recipient].message_count == 2
    # Marker for window 0 closes the CURRENT era's window only.
    ingress.finalize_window(0)
    assert ingress.open_windows() == [(0, 0)]
    assert sent[-1].fence == 1
    # Link death flushes the stale era too, final entry in current era.
    ingress.finalize_all(is_final=True)
    assert sent[-1].is_final and sent[-1].fence == 1
    assert ingress.open_windows() == []


def test_fold_locality_audit_flags_foreign_fold():
    """The UL014 runtime twin: a content-bearing fold landing outside
    the owned slice is caught by the per-sweep audit."""
    context = CrgcContext(delta_graph_size=64, entry_field_size=8)
    g = PartitionedShadowGraph(context, "uigc://a")
    pmap = PartitionMap(
        ["uigc://a", "uigc://b"], 32, fence=0, self_address="uigc://a"
    )
    g.set_partition_map(pmap)
    owned = foreign = None
    for uid in range(200):
        cell = _fake_cell("uigc://a", uid)
        if pmap.owns(cell_key(cell)):
            owned = owned or cell
        else:
            foreign = foreign or cell
        if owned is not None and foreign is not None:
            break
    delta = DeltaGraph("uigc://a", context)
    delta.fold_self(owned, 0, False, False)
    delta.fold_self(foreign, 1, False, False)
    g.merge_delta(delta)
    bad = g.audit_fold_locality()
    assert bad == [cell_key(foreign)]
    # The audit window cleared; an owned-only fold stays clean.
    delta2 = DeltaGraph("uigc://a", context)
    delta2.fold_self(owned, 0, True, False)
    g.merge_delta(delta2)
    assert g.audit_fold_locality() == []


def test_ul014_flags_out_of_fold_slot_mutation(tmp_path):
    """Lint rule UL014, both directions: a rogue module mutating shadow
    slots outside the fold plane is flagged; the real fold-plane
    modules stay clean."""
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent / "tools"))
    try:
        from uigc_lint import lint_paths
    finally:
        sys.path.pop(0)

    rogue_dir = tmp_path / "uigc_tpu" / "engines" / "crgc"
    rogue_dir.mkdir(parents=True)
    (rogue_dir / "rogue.py").write_text(
        "def f(shadow, other):\n"
        "    shadow.is_halted = True\n"
        "    shadow.recv_count += 1\n"
        "    shadow.outgoing[other] = 2\n"
    )
    hits = [v for v in lint_paths([str(tmp_path)]) if v.rule == "UL014"]
    assert len(hits) == 3
    repo = __import__("pathlib").Path(__file__).parent.parent
    clean = [
        v
        for v in lint_paths(
            [
                str(repo / "uigc_tpu" / "engines" / "crgc" / "distributed.py"),
                str(repo / "uigc_tpu" / "parallel" / "partition.py"),
            ]
        )
        if v.rule == "UL014"
    ]
    assert clean == []


def test_dmark_retransmit_reorder_cannot_lose_marks():
    """The binary codec re-orders keys inside a frame (address-grouped,
    uid-sorted), so a retransmit spanning differently-bounded original
    flushes carries keys at different positions than first shipped.
    Position coverage must therefore be SPAN-only and every key in a
    frame must seed regardless — otherwise a dropped middle flush plus
    a from-watermark retransmit silently skips a mark and a live actor
    gets swept."""
    from uigc_tpu.engines.crgc.distributed import (
        DistributedBookkeeper,
        DMark,
        _WaveState,
    )
    from uigc_tpu.runtime import schema

    context = CrgcContext(delta_graph_size=64, entry_field_size=8)

    class _StubConfig:
        def get_int(self, key):
            return {
                "uigc.crgc.dist-partitions": 8,
                "uigc.cluster.num-shards": 8,
                "uigc.crgc.mirror-decay-waves": 0,
            }[key]

    class _StubSystem:
        address = "uigc://a"
        fabric = None
        config = _StubConfig()

    class _StubEngine:
        system = _StubSystem()
        crgc_context = context
        num_nodes = 2

        def make_shadow_graph(self):
            from uigc_tpu.engines.crgc.distributed import (
                PartitionedShadowGraph,
            )

            return PartitionedShadowGraph(context, "uigc://a")

    bk = DistributedBookkeeper(_StubEngine())
    # Join race: a dmark arriving BEFORE the partition map exists must
    # be refused harmlessly (no wave entered, no exception — a raising
    # handler would stop the collector cell for good); the sender's
    # retransmits re-deliver once membership completes.
    early = wire.decode_dmark(
        wire.encode_dmark(1, 0, "uigc://b", [("uigc://a", 1)])
    )
    bk._on_dmark(DMark(*early))
    assert bk.ws is None
    members = ["uigc://a", "uigc://b"]
    bk.pmap = PartitionMap(members, 8, fence=0, self_address="uigc://a")
    bk.tree = ReductionTree(members)
    bk.started = True
    g = bk.shadow_graph
    g.set_partition_map(bk.pmap)
    # Three OWNED keys, with sender-side list order != sorted order.
    owned_uids = [
        uid for uid in range(64) if bk.pmap.owns(("uigc://a", uid))
    ][:3]
    assert len(owned_uids) == 3
    sender_list = [
        ("uigc://a", owned_uids[1]),
        ("uigc://a", owned_uids[0]),
        ("uigc://a", owned_uids[2]),
    ]
    cells = {uid: _fake_cell("uigc://a", uid) for _a, uid in sender_list}
    for cell in cells.values():
        g.make_shadow(cell)
    bk.ws = _WaveState(1, 0)
    bk.ws.seeded = True  # isolate the dmark path from local seeding

    def deliver(chunk, start):
        decoded = wire.decode_dmark(
            wire.encode_dmark(1, 0, "uigc://b", chunk, start=start)
        )
        assert decoded is not None
        bk._on_dmark(DMark(*decoded))

    # Flush 1 arrives; flush 2 ([k2, k9] at start=1) is DROPPED; the
    # retransmit re-covers from the acked watermark 1... but since the
    # sorted re-encode of [k2, k9] would reorder a wider span, model
    # the worst case: retransmit of the FULL list from start=0, whose
    # decoded order ([2, 5, 9]) disagrees with list order everywhere.
    deliver([sender_list[0]], 0)
    deliver(sender_list, 0)
    marked_keys = {cell_key(s.self_cell) for s in bk.ws.marked}
    assert set(sender_list) <= marked_keys, marked_keys
    assert bk.ws.recv_upto["uigc://b"] == 3
    assert bk.ws.recv_total() == 3
    # A MISROUTED mark (sender's map disagrees during an adopt window)
    # is forwarded to the owner by OUR map, never consumed through a
    # mirror: the relay guard that keeps divergent views from silently
    # absorbing a live actor's mark.
    foreign_uid = next(
        uid for uid in range(64) if not bk.pmap.owns(("uigc://a", uid))
    )
    deliver([("uigc://a", foreign_uid)], 3)
    assert ("uigc://a", foreign_uid) in bk.ws.out_sets.get("uigc://b", set())
    assert ("uigc://a", foreign_uid) not in {
        cell_key(s.self_cell) for s in bk.ws.marked
    }


def test_ul015_flags_adhoc_dmark_payloads(tmp_path):
    """Lint rule UL015, both directions: ad-hoc dmark/dmack frame
    literals outside wire.py and json payload construction inside
    wire.py's dmark codecs are flagged; the real modules stay clean."""
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent / "tools"))
    try:
        from uigc_lint import lint_paths
    finally:
        sys.path.pop(0)

    rogue_dir = tmp_path / "uigc_tpu" / "engines" / "crgc"
    rogue_dir.mkdir(parents=True)
    (rogue_dir / "rogue_frames.py").write_text(
        "import json\n"
        "def f(keys, wave):\n"
        "    frame = ('dmark', wave, 0, 'me', json.dumps(keys).encode())\n"
        "    ack = ('dmack', wave, 'me', len(keys))\n"
        "    return frame, ack\n"
    )
    wire_dir = tmp_path / "uigc_tpu" / "runtime"
    wire_dir.mkdir(parents=True)
    (wire_dir / "wire.py").write_text(
        "import json\n"
        "def encode_dmark(wave, keys):\n"
        "    return ('x', json.dumps(keys).encode())\n"
        "def decode_dmack(frame):\n"
        "    return json.loads(frame[1])\n"
        "def encode_other(x):\n"
        "    return json.dumps(x)\n"
    )
    hits = [v for v in lint_paths([str(tmp_path)]) if v.rule == "UL015"]
    # two frame literals + two json calls inside dmark/dmack codecs
    # (encode_other is NOT flagged: the rule scopes to the dmark plane)
    assert len(hits) == 4
    repo = __import__("pathlib").Path(__file__).parent.parent
    clean = [
        v
        for v in lint_paths(
            [
                str(repo / "uigc_tpu" / "engines" / "crgc" / "distributed.py"),
                str(repo / "uigc_tpu" / "runtime" / "wire.py"),
                str(repo / "uigc_tpu" / "runtime" / "schema.py"),
            ]
        )
        if v.rule == "UL015"
    ]
    assert clean == []


# ------------------------------------------------------------------- #
# Cluster layer (in-process fabric)
# ------------------------------------------------------------------- #


def test_three_node_cycle_collected_without_full_replica(event_log):
    """The acceptance core: a garbage cycle spanning all three nodes is
    detected while no node ever folds the full graph, every fold stays
    inside the owned slice, and the merged per-node oracles confirm
    every sweep verdict."""
    probe = TestProbe(default_timeout_s=20.0)
    systems, master = build_inproc(probe)
    rings, kept = 5, 2
    total = (rings + kept) * 3
    try:
        master.tell(Go(rings, kept))
        for _ in range(total):
            probe.expect_message_type(Spawned)
        time.sleep(0.4)
        master.tell(Drop())
        stopped = collect_stopped(probe, rings * 3)
        assert stopped == rings * 3
        # Kept rings survive (the over-collection canary).
        probe.expect_no_message(0.5)
        # No node ever held the full graph: the global population is
        # the kept workers + spawners + master + already-swept slop;
        # each node's slice must be strictly smaller than the cluster
        # total of live + kept actors.
        pops = [
            len(s.engine.bookkeeper.shadow_graph.from_set) for s in systems
        ]
        owned = [
            s.engine.bookkeeper.shadow_graph.owned_population()
            for s in systems
        ]
        assert sum(owned) >= kept * 3
        for pop, own in zip(pops, owned):
            assert own <= pop
            assert own < sum(owned)
        # Every node's folds stayed inside its owned slice.
        assert not event_log.of(events.DIST_LOCALITY)
        for s in systems:
            assert s.engine.bookkeeper.shadow_graph.audit_fold_locality() == []
        # The wave protocol actually ran cross-node.
        assert event_log.of(events.DIST_WAVE)
        assert event_log.of(events.DIST_MARKS)
        assert event_log.of(events.DIST_ROUND)
        # Distributed uigcsan: merged oracle agrees with every verdict.
        time.sleep(0.3)
        merged = merged_oracle(systems)
        assert len(merged.garbage) >= rings * 3
        assert cross_check_distributed(systems) == []
        for s in systems:
            assert s.sanitizer.violations == []
            assert s.sanitizer.dist_sweeps > 0
    finally:
        terminate_all(systems)


def test_verdict_parity_with_single_host():
    """The same workload on the partitioned 3-node collector and on a
    single-host collector: every actor gets the identical verdict
    (dropped rings collected, kept rings alive)."""
    rings, kept = 4, 2

    def run(distributed):
        probe = TestProbe(default_timeout_s=20.0)
        if distributed:
            systems, master = build_inproc(probe)
        else:
            config = dict(BASE)
            config["uigc.crgc.num-nodes"] = 1
            config["uigc.crgc.distributed"] = False
            config["uigc.crgc.shadow-graph"] = "oracle"
            system = ActorSystem(None, name="solo", config=config)
            systems = [system]
            spawner = RemoteSpawner.spawn_service(
                system,
                {"worker": Behaviors.setup(lambda ctx: Worker(ctx, probe.ref))},
            )
            master = system.spawn_root(
                Behaviors.setup_root(
                    lambda ctx: RingMaster(ctx, [spawner] * 3)
                ),
                "master",
            )
        try:
            master.tell(Go(rings, kept))
            for _ in range((rings + kept) * 3):
                probe.expect_message_type(Spawned)
            time.sleep(0.4)
            master.tell(Drop())
            stopped = collect_stopped(probe, rings * 3)
            probe.expect_no_message(0.5)
            return stopped
        finally:
            terminate_all(systems)

    assert run(distributed=True) == run(distributed=False) == rings * 3


# ------------------------------------------------------------------- #
# Chaos layer (NodeFabric over real sockets)
# ------------------------------------------------------------------- #


def test_nodefabric_dmark_drops_tolerated(event_log):
    """Seeded drops, duplicates and reorders on the dmark/dmack
    frames: the position-addressed suffix protocol (idempotent set
    union + watermark acks + wake-driven retransmit) converges anyway
    and the verdicts stay sanitizer-clean."""
    plan = FaultPlan(1234)
    names = ["dda", "ddb", "ddc"]
    probe = TestProbe(default_timeout_s=30.0)
    nodes, master = build_nodefabric(names, probe, plan=plan)
    addrs = [n.address for n in nodes]
    for src in addrs:
        for dst in addrs:
            if src != dst:
                plan.drop(src=src, dst=dst, kind="dmark", prob=0.35)
                plan.drop(src=src, dst=dst, kind="dmack", prob=0.35)
                plan.duplicate(src=src, dst=dst, kind="dmark", prob=0.15)
                plan.reorder(src=src, dst=dst, kind="dmark", prob=0.15)
                plan.duplicate(src=src, dst=dst, kind="dmack", prob=0.15)
    rings = 4
    try:
        master.tell(Go(rings))
        for _ in range(rings * 3):
            probe.expect_message_type(Spawned)
        time.sleep(0.5)
        master.tell(Drop())
        stopped = collect_stopped(probe, rings * 3, timeout_s=40.0)
        assert stopped == rings * 3
        dropped = [
            f
            for f in event_log.of(events.FRAME_DROPPED)
            if f.get("kind") in ("dmark", "dmack")
        ]
        assert dropped, "the fault plan never actually dropped a dmark"
        for n in nodes:
            assert n.system.sanitizer.violations == []
        assert cross_check_distributed([n.system for n in nodes]) == []
    finally:
        terminate_all(nodes)


def test_nodefabric_node_death_absorbs_partition(event_log):
    """A node dies silently mid-collection: the heartbeat verdict bumps
    the fence, ownership of its partitions transfers by rendezvous, the
    survivors re-fold their retained journals, and the surviving
    members of every broken ring collect — sanitizer-clean throughout."""
    names = ["nka", "nkb", "nkc"]
    probe = TestProbe(default_timeout_s=30.0)
    nodes, master = build_nodefabric(
        names,
        probe,
        overrides={
            "uigc.node.heartbeat-interval": 40,
            "uigc.node.phi-threshold": 6.0,
            "uigc.node.heartbeat-pause": 400,
        },
    )
    a, b, c = nodes
    rings = 4
    try:
        master.tell(Go(rings))
        for _ in range(rings * 3):
            probe.expect_message_type(Spawned)
        time.sleep(0.5)
        fences_before = [
            n.system.engine.bookkeeper.fence for n in (a, b)
        ]
        master.tell(Drop())
        # Kill c immediately: the drop's collection waves are in flight.
        c.fabric.die()
        # The dead node's workers die with it; the survivors' ring
        # members must still collect once the undo fold reverts c's
        # claims and the absorb re-folds its partitions.
        stopped = collect_stopped(probe, rings * 2, timeout_s=60.0)
        assert stopped >= rings * 2
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if all(
                c.address not in n.fabric.members() for n in (a, b)
            ):
                break
            time.sleep(0.05)
        for i, n in enumerate((a, b)):
            bk = n.system.engine.bookkeeper
            assert c.address not in bk.pmap.members
            assert bk.fence > fences_before[i]
            # Ownership covers the whole space between the survivors.
            owners = set(bk.pmap.assignments().values())
            assert owners <= {a.address, b.address}
        for n in (a, b):
            assert n.system.sanitizer.violations == []
        assert cross_check_distributed([a.system, b.system]) == []
    finally:
        terminate_all(nodes)
