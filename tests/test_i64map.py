"""Differential tests for the vectorized open-addressing map
(ops/i64map.py) against a Python dict, mixing scalar and batch
operations, growth, and tombstone churn."""

from __future__ import annotations

import numpy as np
import pytest

from uigc_tpu.ops.i64map import I64Map, IntStack


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_i64map_matches_dict(seed):
    rng = np.random.default_rng(seed)
    m = I64Map(cap=16)
    d = {}
    key_space = 5000
    for round_ in range(30):
        op = rng.random()
        if op < 0.35:  # batch insert of new unique keys
            cand = rng.integers(0, key_space, size=rng.integers(1, 400))
            new = np.unique(cand)
            new = new[[k not in d for k in new.tolist()]]
            vals = rng.integers(0, 1 << 40, size=new.size)
            m.put_batch_new(new, vals)
            d.update(zip(new.tolist(), vals.tolist()))
        elif op < 0.55:  # batch pop (mix of present and absent)
            cand = np.unique(rng.integers(0, key_space, size=rng.integers(1, 300)))
            got = m.pop_batch(cand)
            for k, v in zip(cand.tolist(), got.tolist()):
                if k in d:
                    assert v == d.pop(k)
                else:
                    assert v == -1
        elif op < 0.75:  # batch get incl. duplicates
            cand = rng.integers(0, key_space, size=rng.integers(1, 500))
            got = m.get_batch(cand)
            for k, v in zip(cand.tolist(), got.tolist()):
                assert v == d.get(k, -1), f"round {round_} key {k}"
        elif op < 0.9:  # scalar upsert
            for _ in range(20):
                k = int(rng.integers(0, key_space))
                v = int(rng.integers(0, 1 << 40))
                m[k] = v
                d[k] = v
        else:  # scalar pop / get / contains
            for _ in range(20):
                k = int(rng.integers(0, key_space))
                assert (k in m) == (k in d)
                assert m.get(k, -1) == d.get(k, -1)
                if rng.random() < 0.5:
                    assert m.pop(k, None) == d.pop(k, None)
        assert len(m) == len(d), f"round {round_}"
    assert dict(m.items()) == d
    assert m.key_set() == set(d)


def test_i64map_build_and_grow():
    keys = np.arange(0, 100_000, dtype=np.int64) * 7 + 3
    vals = np.arange(100_000, dtype=np.int64)
    m = I64Map.build(keys, vals)
    assert len(m) == 100_000
    got = m.get_batch(keys)
    assert np.array_equal(got, vals)
    # absent keys miss
    assert np.all(m.get_batch(keys + 1) == -1)


def test_i64map_tombstone_reuse():
    """Heavy insert/delete cycling over a small key set must not grow
    unboundedly (tombstones are reclaimed on rehash)."""
    m = I64Map(cap=64)
    keys = np.arange(0, 40, dtype=np.int64)
    for i in range(200):
        m.put_batch_new(keys, keys * 2)
        assert np.array_equal(m.pop_batch(keys), keys * 2)
        assert len(m) == 0
    assert m.cap <= 1024


def test_intstack():
    s = IntStack.from_range(0, 8)
    # pop order matches list(range(7, -1, -1)).pop()
    assert s.pop() == 0 and s.pop() == 1
    s.push(99)
    assert s.pop() == 99
    s.push_batch(np.array([5, 6, 7]))
    assert len(s) == 9
    got = s.pop_batch(3)
    assert got.tolist() == [5, 6, 7]
    s.push_range(8, 16)
    assert s.pop() == 8  # lowest-first, like the list idiom
    assert bool(s)
    while s:
        s.pop()
    assert not s
