"""Run the live actor runtime against every shadow-graph backend.

The oracle is the reference-exact pointer graph; "array" folds into dense
numpy arrays; "device" additionally runs the trace through the JAX kernel.
All three must produce identical lifecycle behavior.
"""

import pytest

from uigc_tpu import AbstractBehavior, ActorTestKit, Behaviors, Message, NoRefs, PostStop


class Share(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,)


class Drop(NoRefs):
    pass


class Spawned(NoRefs):
    def __init__(self, name):
        self.name = name


class Stopped(NoRefs):
    def __init__(self, name):
        self.name = name


class Node(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.peer = None
        probe.ref.tell(Spawned(context.name))

    def on_message(self, msg):
        if isinstance(msg, Share):
            self.peer = msg.ref
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Stopped(self.context.name))
        return None


class Root(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        ctx = context
        self.a = ctx.spawn(Behaviors.setup(lambda c: Node(c, probe)), "a")
        self.b = ctx.spawn(Behaviors.setup(lambda c: Node(c, probe)), "b")
        # Mutual cycle a <-> b.
        self.a.tell(Share(ctx.create_ref(self.b, self.a)), ctx)
        self.b.tell(Share(ctx.create_ref(self.a, self.b)), ctx)

    def on_message(self, msg):
        if isinstance(msg, Drop):
            self.context.release(self.a, self.b)
        return self


from conftest import NATIVE_BACKEND


@pytest.mark.parametrize(
    "backend",
    [
        "oracle", "array", "device", "mesh", "decremental",
        "mesh-decremental", NATIVE_BACKEND,
    ],
)
def test_cycle_collection_all_backends(backend):
    kit = ActorTestKit(
        {"uigc.crgc.wakeup-interval": 10, "uigc.crgc.shadow-graph": backend}
    )
    try:
        probe = kit.create_test_probe(timeout_s=30.0)
        root = kit.spawn(Behaviors.setup_root(lambda ctx: Root(ctx, probe)), "root")
        probe.expect_message_type(Spawned)
        probe.expect_message_type(Spawned)
        probe.expect_no_message(0.2)  # cycle alive while root holds refs
        root.tell(Drop())
        probe.expect_message_type(Stopped)
        probe.expect_message_type(Stopped)
    finally:
        kit.shutdown()


def test_cycle_collection_device_pallas(monkeypatch):
    """The device backend's Pallas trace path, forced on CPU (interpret
    mode) by faking the platform check; same lifecycle contract."""
    from uigc_tpu.engines.crgc.arrays import ArrayShadowGraph

    monkeypatch.setattr(ArrayShadowGraph, "_on_tpu", lambda self: True)
    kit = ActorTestKit(
        {"uigc.crgc.wakeup-interval": 10, "uigc.crgc.shadow-graph": "device"}
    )
    try:
        probe = kit.create_test_probe(timeout_s=60.0)
        root = kit.spawn(Behaviors.setup_root(lambda ctx: Root(ctx, probe)), "root")
        probe.expect_message_type(Spawned)
        probe.expect_message_type(Spawned)
        root.tell(Drop())
        probe.expect_message_type(Stopped)
        probe.expect_message_type(Stopped)
    finally:
        kit.shutdown()


class LoneRoot(AbstractBehavior):
    """A root that spawns workers, never releases them, then stops itself."""

    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.kids = [
            context.spawn(Behaviors.setup(lambda c: Node(c, probe)), f"w{i}")
            for i in range(3)
        ]

    def on_message(self, msg):
        if isinstance(msg, Drop):
            return Behaviors.stopped()
        return self


def test_dead_root_does_not_leak_referents():
    """A stopped root must not pin its referents forever: its death flush
    clears root status, so the workers (and the root's zombie shadow)
    collapse on the next trace."""
    kit = ActorTestKit({"uigc.crgc.wakeup-interval": 10})
    try:
        probe = kit.create_test_probe(timeout_s=10.0)
        root = kit.spawn(
            Behaviors.setup_root(lambda ctx: LoneRoot(ctx, probe)), "root"
        )
        probe.expect_message_type(Spawned)
        probe.expect_message_type(Spawned)
        probe.expect_message_type(Spawned)
        root.tell(Drop())
        # Workers are children of the root, so the runtime cascade stops
        # them; the regression here is the SHADOW side: the collector must
        # also conclude they are garbage (root flag cleared), not keep
        # zombie pseudoroots. All three must report stopping.
        probe.expect_message_type(Stopped)
        probe.expect_message_type(Stopped)
        probe.expect_message_type(Stopped)
        import time

        time.sleep(0.2)  # let a few collection rounds run
        graph = kit.system.engine.bookkeeper.shadow_graph
        assert graph.num_in_use <= 1, (
            f"{graph.num_in_use} zombie shadows left after root death"
        )
    finally:
        kit.shutdown()


def test_pipelined_decremental_collection():
    """uigc.crgc.pipelined: the collector sweeps the previous wake's
    verdicts while the next runs; cyclic garbage still collapses (a
    consistent-snapshot verdict is never wrong — CRGC garbage is
    monotone)."""
    kit = ActorTestKit(
        {
            "uigc.crgc.wakeup-interval": 10,
            "uigc.crgc.shadow-graph": "decremental",
            "uigc.crgc.pipelined": True,
        }
    )
    try:
        probe = kit.create_test_probe(timeout_s=30.0)
        root = kit.spawn(Behaviors.setup_root(lambda ctx: Root(ctx, probe)), "root")
        probe.expect_message_type(Spawned)
        probe.expect_message_type(Spawned)
        probe.expect_no_message(0.2)
        root.tell(Drop())
        probe.expect_message_type(Stopped)
        probe.expect_message_type(Stopped)
    finally:
        kit.shutdown()


def test_pipelined_mesh_decremental_collection():
    """uigc.crgc.pipelined + shadow-graph=mesh-decremental: the mesh
    runs its OWN pipelined wake (launch syncs the shard layouts
    mesh-natively, then dispatches the sharded decremental wake
    asynchronously; the harvest sweeps the launch snapshot's verdicts).
    Cyclic garbage still collapses, and the regression this guards: the
    base-class path through the single-device tracer would have
    desynced the shard layouts."""
    kit = ActorTestKit(
        {
            "uigc.crgc.wakeup-interval": 10,
            "uigc.crgc.shadow-graph": "mesh-decremental",
            "uigc.crgc.pipelined": True,
        }
    )
    try:
        graph = kit.system.engine.bookkeeper.shadow_graph
        assert graph.can_pipeline is True
        probe = kit.create_test_probe(timeout_s=60.0)
        root = kit.spawn(Behaviors.setup_root(lambda ctx: Root(ctx, probe)), "root")
        probe.expect_message_type(Spawned)
        probe.expect_message_type(Spawned)
        root.tell(Drop())
        probe.expect_message_type(Stopped)
        probe.expect_message_type(Stopped)
    finally:
        kit.shutdown()


def test_pipelined_stalled_wake_expires():
    """A wake whose device result never lands must expire (tracer
    invalidated, pipeline freed) instead of deadlocking collection."""
    import time

    from uigc_tpu.engines.crgc.arrays import ArrayShadowGraph
    from uigc_tpu.engines.crgc.state import CrgcContext

    graph = ArrayShadowGraph(
        CrgcContext(delta_graph_size=64, entry_field_size=4),
        "uigc://test",
        use_device=True,
        decremental=True,
    )

    class NeverReady:
        def is_ready(self):
            return False

    class FakeDec:
        invalidated = False

        def invalidate(self):
            self.invalidated = True

    dec = FakeDec()
    graph._pending_wake = (dec, NeverReady(), None, None, time.monotonic() - 60)
    assert not graph.harvest_ready()
    assert not graph.expire_stalled_wake(max_age_s=120)  # too young
    assert graph.has_pending_wake
    assert graph.expire_stalled_wake(max_age_s=30)
    assert dec.invalidated and not graph.has_pending_wake
