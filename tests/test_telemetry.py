"""Telemetry subsystem suite (uigc_tpu/telemetry).

Layers, bottom up:

- registry math: counter/gauge/histogram semantics, fixed bucket
  bounds, label handling;
- event recorder satellites: O(buckets) duration memory under a
  1M-timed-event loop, structured listener-error accounting;
- exporters: Prometheus text exposition parses and is internally
  consistent, the localhost HTTP handle serves it, JSONL persistence
  replays into the same metrics and into ``RaceDetector.feed()`` with
  verdicts identical to the live listener;
- causal tracing: trace ids propagate across a real 2-node
  ``NodeFabric`` link (and a peer with tracing OFF ignores the frame
  header without dropping traffic);
- the acceptance scenario: a 3-node chaos run with telemetry on
  exports a Chrome-trace JSON whose causally-linked spans span >= 2
  nodes (send on A -> invoke on B -> GC wave -> terminate) and a wake
  profile attributing >= 4 named phases per wake, with nonzero
  wave/fault metrics.
"""

import json
import re
import threading
import time
import urllib.request

import pytest

from uigc_tpu import (
    AbstractBehavior,
    ActorTestKit,
    Behaviors,
    Message,
    NoRefs,
    PostStop,
)
from uigc_tpu.analysis import RaceDetector
from uigc_tpu.runtime.faults import FaultPlan
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.runtime.system import ActorSystem
from uigc_tpu.runtime.testkit import TestProbe
from uigc_tpu.runtime.behaviors import RawBehavior
from uigc_tpu.telemetry import (
    EventMetricsBridge,
    MetricsRegistry,
    chrome_trace,
    prometheus_text,
    replay_jsonl,
)
from uigc_tpu.telemetry.metrics import COUNT_BUCKETS
from uigc_tpu.utils import events


@pytest.fixture(autouse=True)
def clean_recorder():
    """Telemetry enables the process-global recorder; leave no residue
    for the rest of the suite."""
    yield
    events.recorder.disable()
    events.recorder.reset()
    with events.recorder._lock:
        events.recorder._listeners.clear()


# ------------------------------------------------------------------- #
# Metric registry math
# ------------------------------------------------------------------- #


def test_counter_math_and_labels():
    registry = MetricsRegistry()
    counter = registry.counter("c_total", "help")
    counter.inc()
    counter.inc(2.5)
    counter.inc(src="a")
    counter.inc(3, src="a")
    assert counter.value() == 3.5
    assert counter.value(src="a") == 4.0
    with pytest.raises(Exception):
        counter.inc(-1)
    # idempotent re-registration returns the same object
    assert registry.counter("c_total") is counter


def test_gauge_set_and_callback_fanout():
    registry = MetricsRegistry()
    gauge = registry.gauge("g")
    gauge.set(7)
    gauge.set(9)
    assert gauge.value() == 9.0
    phi = registry.gauge("phi", fn=lambda: {"b": 1.5, "c": 0.25}, label_name="peer")
    samples = {labels: value for _, labels, value in phi.samples()}
    assert samples[(("peer", "b"),)] == 1.5
    assert samples[(("peer", "c"),)] == 0.25
    broken = registry.gauge("broken", fn=lambda: 1 / 0)
    assert broken.samples() == []  # dead callback never breaks a scrape


def test_histogram_bucket_bounds():
    registry = MetricsRegistry()
    hist = registry.histogram("h", buckets=(1.0, 2.0, 4.0))
    for value in (0.5, 1.0, 1.5, 3.0, 100.0):
        hist.observe(value)
    snap = hist.snapshot()
    # non-cumulative internals: (<=1.0): 0.5 and 1.0; (<=2.0): 1.5;
    # (<=4.0): 3.0; overflow: 100.0
    assert snap["counts"] == [2, 1, 1, 1]
    assert snap["n"] == 5
    assert snap["sum"] == pytest.approx(106.0)
    assert snap["min"] == 0.5 and snap["max"] == 100.0
    # exported cumulative series
    by_le = {
        dict(labels)["le"]: value
        for suffix, labels, value in hist.samples()
        if suffix == "_bucket"
    }
    assert by_le["1.0"] == 2 and by_le["2.0"] == 3 and by_le["4.0"] == 4
    assert by_le["+Inf"] == 5


# ------------------------------------------------------------------- #
# Event recorder satellites
# ------------------------------------------------------------------- #


def test_event_recorder_duration_memory_is_bounded():
    """1M timed events must hold O(buckets), not O(events)."""
    recorder = events.EventRecorder()
    recorder.enable()
    n = 1_000_000
    for i in range(n):
        recorder.commit("bench.timed", duration_s=1e-6 * (i % 1000 + 1))
    stat = recorder._durations["bench.timed"]
    # The storage is the fixed bucket array plus four scalars — nothing
    # proportional to the observation count.
    assert isinstance(stat, events.DurationStat)
    assert len(stat.buckets) == len(events.DURATION_BUCKET_BOUNDS_S) + 1
    assert not hasattr(stat, "__dict__")  # slots only: no growable side table
    snap = recorder.snapshot()["durations"]["bench.timed"]
    # backward-compatible shape plus the streaming extras
    assert snap["n"] == n
    assert snap["total_s"] == pytest.approx(sum(1e-6 * (i % 1000 + 1) for i in range(1000)) * (n // 1000), rel=1e-6)
    assert snap["max_s"] == pytest.approx(1e-3)
    assert snap["min_s"] == pytest.approx(1e-6)
    assert sum(snap["buckets"]) == n


def test_listener_error_is_structured_and_counted(capsys):
    recorder = events.EventRecorder()
    recorder.enable()
    seen = []

    def broken(name, fields):
        if name != events.LISTENER_ERROR:
            raise RuntimeError("boom")

    recorder.add_listener(broken)
    recorder.add_listener(lambda name, fields: seen.append((name, fields)))
    recorder.commit("some.event", value=1)
    snap = recorder.snapshot()
    assert snap["counts"][events.LISTENER_ERROR] == 1
    # the surviving listener saw both the original and the error event
    names = [name for name, _ in seen]
    assert "some.event" in names and events.LISTENER_ERROR in names
    error_fields = dict(seen)[events.LISTENER_ERROR]
    assert error_fields["event"] == "some.event"
    assert "RuntimeError" in error_fields["error"]
    assert "boom" in capsys.readouterr().err  # stderr traceback retained


def test_listener_error_recursion_is_bounded():
    recorder = events.EventRecorder()
    recorder.enable()

    def always_broken(name, fields):
        raise RuntimeError("always")

    recorder.add_listener(always_broken)
    recorder.commit("e1")  # must not recurse to death
    snap = recorder.snapshot()
    assert snap["counts"]["e1"] == 1
    assert snap["counts"][events.LISTENER_ERROR] >= 1


# ------------------------------------------------------------------- #
# Prometheus exposition + HTTP handle
# ------------------------------------------------------------------- #

_SAMPLE_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE.+-]+(inf)?$"
)


def test_prometheus_exposition_parses():
    registry = MetricsRegistry(const_labels={"node": "uigc://n1"})
    registry.counter("a_total", "a help").inc(3)
    registry.gauge("b").set(1.25, peer='uigc://x"y\n')
    hist = registry.histogram("c_seconds", buckets=(0.1, 1.0))
    hist.observe(0.05)
    hist.observe(5.0)
    text = prometheus_text(registry)
    sample_lines = [l for l in text.splitlines() if l and not l.startswith("#")]
    assert sample_lines, text
    for line in sample_lines:
        assert _SAMPLE_LINE.match(line), f"unparseable sample line: {line!r}"
    # histogram consistency: +Inf bucket == _count
    inf = next(l for l in sample_lines if 'le="+Inf"' in l)
    count = next(l for l in sample_lines if l.startswith("c_seconds_count"))
    assert inf.rsplit(" ", 1)[1] == count.rsplit(" ", 1)[1] == "2"
    # every sample carries the constant node label
    assert all('node="uigc://n1"' in l for l in sample_lines)


def test_http_handle_serves_metrics():
    kit = ActorTestKit(
        config={
            "uigc.telemetry.metrics": True,
            "uigc.telemetry.http-port": 0,
            "uigc.crgc.wakeup-interval": 10,
        },
        name="telhttp",
    )
    try:
        telemetry = kit.system.telemetry
        assert telemetry is not None and telemetry.http is not None
        time.sleep(0.1)
        base = f"http://127.0.0.1:{telemetry.http.port}"
        text = urllib.request.urlopen(base + "/metrics", timeout=5).read().decode()
        assert "uigc_live_actors" in text
        snap = json.loads(
            urllib.request.urlopen(base + "/metrics.json", timeout=5).read()
        )
        assert snap["uigc_live_actors"]["kind"] == "gauge"
    finally:
        kit.shutdown()


# ------------------------------------------------------------------- #
# JSONL persistence + replay parity
# ------------------------------------------------------------------- #


class _Ping(NoRefs):
    pass


class _Release(NoRefs):
    pass


class _Worker(AbstractBehavior):
    def on_message(self, msg):
        return self


class _Root(AbstractBehavior):
    def __init__(self, context):
        super().__init__(context)
        self.workers = [
            context.spawn(Behaviors.setup(_Worker), f"w{i}") for i in range(4)
        ]

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, _Ping):
            for worker in self.workers:
                worker.tell(_Ping(), ctx)
        elif self.workers:
            ctx.release(*self.workers)
            self.workers = []
        return self


def test_jsonl_replay_matches_live_race_detector(tmp_path):
    path = str(tmp_path / "events.jsonl")
    live = RaceDetector().attach()
    kit = ActorTestKit(
        config={
            "uigc.crgc.wakeup-interval": 10,
            "uigc.analysis.sched-events": True,
            "uigc.telemetry.jsonl-path": path,
        },
        name="teljsonl",
    )
    try:
        root = kit.spawn(Behaviors.setup_root(_Root), "root")
        for _ in range(10):
            root.tell(_Ping())
        time.sleep(0.3)
        root.tell(_Release())
        time.sleep(0.4)
    finally:
        kit.shutdown()
        live.detach()
    assert live.event_count() > 0
    replayed = RaceDetector().feed(replay_jsonl(path))
    assert replayed.event_count() == live.event_count()
    live_verdicts = [(v.rule, v.payload.get("cell")) for v in live.analyze()]
    replay_verdicts = [(v.rule, v.payload.get("cell")) for v in replayed.analyze()]
    assert replay_verdicts == live_verdicts
    # a correct runtime shows no violations in either view
    assert live_verdicts == []


def test_jsonl_replay_rebuilds_metrics(tmp_path):
    path = str(tmp_path / "events.jsonl")
    kit = ActorTestKit(
        config={
            "uigc.crgc.wakeup-interval": 10,
            "uigc.telemetry.metrics": True,
            "uigc.telemetry.jsonl-path": path,
        },
        name="telreplay",
    )
    try:
        root = kit.spawn(Behaviors.setup_root(_Root), "root")
        for _ in range(10):
            root.tell(_Ping())
        time.sleep(0.4)
        registry_live = kit.system.telemetry.registry
    finally:
        # Snapshot AFTER shutdown: listener detach and file close happen
        # with all machinery quiesced, so live and replayed views cover
        # exactly the same event stream.
        kit.shutdown()
    live_count = registry_live.snapshot()["uigc_gc_wave_seconds"]
    registry = MetricsRegistry()
    bridge = EventMetricsBridge(registry)
    for name, fields in replay_jsonl(path):
        bridge(name, fields)
    replayed = registry.snapshot()["uigc_gc_wave_seconds"]

    def count_of(entry):
        return [
            s["value"] for s in entry["samples"] if s["suffix"] == "_count"
        ]

    assert count_of(replayed) == count_of(live_count)
    assert count_of(replayed)[0] > 0


def test_sanitizer_oracle_trace_does_not_double_count_metrics():
    """With uigcsan AND metrics on, the oracle's shadow re-trace must
    not emit a second crgc.tracing/crgc.sweep per wake (suppressed
    commits, utils/events.py) — garbage would count twice otherwise."""
    kit = ActorTestKit(
        config={
            "uigc.crgc.wakeup-interval": 10,
            "uigc.crgc.shadow-graph": "array",
            "uigc.analysis.sanitizer": True,
            "uigc.telemetry.metrics": True,
        },
        name="sanmetrics",
    )
    try:
        root = kit.spawn(Behaviors.setup_root(_Root), "root")
        for _ in range(5):
            root.tell(_Ping())
        time.sleep(0.2)
        root.tell(_Release())
        # Each collected actor contributes exactly TWO shadow frees to
        # the crgc.tracing counts (the kill-wave free, then the free of
        # the shadow its death flush re-interns), so 4 workers -> 8.
        # An unsuppressed oracle re-trace would double that to 16.
        deadline = time.monotonic() + 10.0
        total = 0
        while time.monotonic() < deadline and total < 8:
            text = prometheus_text(kit.system.telemetry.registry)
            got = re.search(r"uigc_gc_garbage_total(\{[^}]*\})? (\d+)", text)
            total = int(got.group(2)) if got else 0
            time.sleep(0.05)
        assert total == 8, f"expected 8 shadow frees for 4 actors, got {total}"
        assert kit.system.sanitizer.violations == []
    finally:
        kit.shutdown()


def test_http_fixed_port_conflict_degrades_to_ephemeral():
    """Two systems sharing a config with a fixed http-port must both
    come up; the second falls back to an ephemeral port."""
    kit_a = ActorTestKit(
        config={"uigc.telemetry.http-port": 0}, name="porta"
    )
    fixed = kit_a.system.telemetry.http.port
    kit_b = ActorTestKit(
        config={"uigc.telemetry.http-port": fixed}, name="portb"
    )
    try:
        port_b = kit_b.system.telemetry.http.port
        assert port_b != fixed
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port_b}/metrics", timeout=5
        ).read().decode()
        assert "uigc_live_actors" in body
    finally:
        kit_a.shutdown()
        kit_b.shutdown()


def test_metrics_are_scoped_per_system_in_one_process():
    """The recorder is a process singleton; two instrumented systems in
    one process must NOT fold each other's GC events into their
    registries (thread-origin scoping, utils/events.py)."""
    config = {
        "uigc.crgc.wakeup-interval": 10,
        "uigc.telemetry.metrics": True,
    }
    kit_a = ActorTestKit(config=config, name="scopea")
    kit_b = ActorTestKit(config=config, name="scopeb")
    try:
        root = kit_a.spawn(Behaviors.setup_root(_Root), "root")
        for _ in range(10):
            root.tell(_Ping())
        time.sleep(0.3)
        root.tell(_Release())  # garbage on A only
        deadline = time.monotonic() + 10.0
        bridge_a = None
        while time.monotonic() < deadline:
            text_a = prometheus_text(kit_a.system.telemetry.registry)
            got = re.search(r"uigc_gc_garbage_total(\{[^}]*\})? (\d+)", text_a)
            if got and int(got.group(2)) > 0:
                break
            time.sleep(0.05)
        assert got and int(got.group(2)) > 0, "A never collected its garbage"
        text_b = prometheus_text(kit_b.system.telemetry.registry)
        got_b = re.search(r"uigc_gc_garbage_total(\{[^}]*\})? (\d+)", text_b)
        assert got_b is None or int(got_b.group(2)) == 0, (
            "B's registry absorbed A's garbage events"
        )
        # B still counts its OWN wakes — scoping filters, not silences.
        waves_b = re.search(r"uigc_gc_wave_seconds_count\{[^}]*\} (\d+)", text_b)
        assert waves_b and int(waves_b.group(1)) > 0
    finally:
        kit_a.shutdown()
        kit_b.shutdown()


# ------------------------------------------------------------------- #
# Cross-node causal tracing
# ------------------------------------------------------------------- #


class _Probe:
    def __init__(self, probe):
        self.ref = probe


class _ProbeForwarder(RawBehavior):
    def __init__(self, probe):
        self.probe = probe

    def on_message(self, msg):
        self.probe._offer(msg)
        return None


class _Spawned(NoRefs):
    def __init__(self, name):
        self.name = name


class _Stopped(NoRefs):
    def __init__(self, name):
        self.name = name


class _ShareMsg(Message):
    def __init__(self, shared):
        self.shared = shared

    @property
    def refs(self):
        return (self.shared,) if self.shared is not None else ()


class _RemoteWorker(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        probe.ref.tell(_Spawned(context.name))

    def on_message(self, msg):
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(_Stopped(self.context.name))
        return None


class _Driver(AbstractBehavior):
    """Root on node A pinging a worker that lives on node B."""

    def __init__(self, context, remote):
        super().__init__(context)
        self.remote = remote

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, _ShareMsg) and msg.shared is not None:
            self.remote = msg.shared
        elif isinstance(msg, _Ping) and self.remote is not None:
            self.remote.tell(_Ping(), ctx)
        elif isinstance(msg, _Release) and self.remote is not None:
            ctx.release(self.remote)
            self.remote = None
        return self


class _Owner(AbstractBehavior):
    """Root on node B owning a managed worker child; shares the ref to
    node A's driver, then releases its own copy on demand — after both
    releases only a GC wave can terminate the worker."""

    def __init__(self, context, probe, driver_ref):
        super().__init__(context)
        self.worker = context.spawn(
            Behaviors.setup(lambda c: _RemoteWorker(c, probe)), "worker"
        )
        self.driver_ref = driver_ref

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, _ShareMsg):
            self.driver_ref.tell(
                _ShareMsg(ctx.create_ref(self.worker, self.driver_ref)), ctx
            )
        elif isinstance(msg, _Release) and self.worker is not None:
            ctx.release(self.worker)
            self.worker = None
        return self


def _spawn_node(name, num_nodes, overrides=None):
    config = {
        "uigc.crgc.wakeup-interval": 10,
        "uigc.crgc.egress-finalize-interval": 5,
        "uigc.crgc.num-nodes": num_nodes,
        "uigc.telemetry.tracing": True,
    }
    if overrides:
        config.update(overrides)
    fabric = NodeFabric()
    system = ActorSystem(None, name=name, config=config, fabric=fabric)
    port = fabric.listen()
    return fabric, system, port


def _terminate_all(*systems):
    for system in systems:
        try:
            system.terminate(timeout_s=5.0)
        except Exception:
            pass


def test_trace_id_propagates_across_node_fabric():
    fa, sa, _pa = _spawn_node("trca", 2)
    fb, sb, pb = _spawn_node("trcb", 2)
    try:
        fa.connect("127.0.0.1", pb)
        probe = TestProbe(default_timeout_s=20.0)
        probe_cell = sb.system_probe = sb.spawn_system_raw(
            _ProbeForwarder(probe), "probe-fwd"
        )
        worker = sb.spawn_root(
            Behaviors.setup_root(lambda ctx: _RemoteWorker(ctx, _Probe(probe_cell))),
            "worker",
        )
        proxy = fa._proxy(sb.address, worker.cell.uid)
        driver = sa.spawn_root(
            Behaviors.setup_root(
                lambda ctx: _Driver(ctx, ctx.engine.to_root_refob(proxy))
            ),
            "driver",
        )
        probe.expect_message_type(_Spawned)
        for _ in range(10):
            driver.tell(_Ping())
        deadline = time.monotonic() + 10.0
        linked = []
        while time.monotonic() < deadline and not linked:
            sends = [s for s in sa.telemetry.tracer.spans() if s["name"] == "send"]
            invokes = [
                s for s in sb.telemetry.tracer.spans() if s["name"] == "invoke"
            ]
            send_ids = {s["span_id"] for s in sends}
            send_traces = {s["trace_id"] for s in sends}
            linked = [
                s
                for s in invokes
                if s["trace_id"] in send_traces and s["parent_id"] in send_ids
            ]
            time.sleep(0.05)
        assert linked, "no invoke span on B causally linked to a send on A"
        assert linked[0]["node"] == sb.address
    finally:
        _terminate_all(sa, sb)


def test_trace_header_ignored_by_peer_with_tracing_off():
    """Version tolerance: A traces, B does not — B must deliver the
    traffic (header silently ignored) and record nothing."""
    fa, sa, _pa = _spawn_node("toffa", 2)
    fb, sb, pb = _spawn_node("toffb", 2, overrides={"uigc.telemetry.tracing": False})
    try:
        fa.connect("127.0.0.1", pb)
        probe = TestProbe(default_timeout_s=20.0)
        probe_cell = sb.spawn_system_raw(_ProbeForwarder(probe), "probe-fwd")

        class _Echo(AbstractBehavior):
            def __init__(self, context):
                super().__init__(context)

            def on_message(self, msg):
                probe_cell.tell(_Ping())
                return self

        worker = sb.spawn_root(Behaviors.setup_root(_Echo), "worker")
        proxy = fa._proxy(sb.address, worker.cell.uid)
        driver = sa.spawn_root(
            Behaviors.setup_root(
                lambda ctx: _Driver(ctx, ctx.engine.to_root_refob(proxy))
            ),
            "driver",
        )
        for _ in range(5):
            driver.tell(_Ping())
        for _ in range(5):
            probe.expect_message_type(_Ping)  # traffic flows end to end
        assert sb.telemetry is None
        sends = [s for s in sa.telemetry.tracer.spans() if s["name"] == "send"]
        assert sends  # A still traced its half
    finally:
        _terminate_all(sa, sb)


# ------------------------------------------------------------------- #
# Acceptance: 3-node chaos run, chrome trace + wake profile + metrics
# ------------------------------------------------------------------- #


def test_chaos_run_exports_causal_timeline_and_wake_profile(tmp_path):
    """The ISSUE's acceptance scenario: three NodeFabrics with tracing,
    metrics and the wake profiler on, seeded faults on the links, a
    remote-held worker released so a GC wave terminates it.  The
    exported Chrome trace must contain causally-linked spans from >= 2
    distinct nodes covering send -> invoke -> gc_wave -> terminate; the
    wake profile must attribute >= 4 named phases per wake; wave and
    fault metrics must be nonzero."""
    plan = FaultPlan(42)
    overrides = {
        "uigc.telemetry.metrics": True,
        "uigc.telemetry.wake-profile": True,
        "uigc.node.heartbeat-interval": 50,
    }
    fa, sa, pa = _spawn_node("chaosa", 3, overrides)
    fb, sb, pb = _spawn_node("chaosb", 3, overrides)
    fc, sc, pc = _spawn_node("chaosc", 3, overrides)
    systems = (sa, sb, sc)
    try:
        for fabric in (fa, fb, fc):
            fabric.set_fault_plan(plan)
        # Bounded chaos the run must absorb WITHOUT skewing GC message
        # balances (a dropped app send on a surviving link leaks its
        # recv count until the link dies, by design): drop heartbeat
        # frames (phi absorbs them; the seq layer reports the gaps) and
        # duplicate app frames (discarded by the seq layer).
        plan.drop(src=sa.address, dst=sb.address, kind="hb", prob=0.3, count=8)
        plan.duplicate(src=sa.address, dst=sb.address, kind="app", prob=0.2, count=6)
        fa.connect("127.0.0.1", pb)
        fa.connect("127.0.0.1", pc)
        fb.connect("127.0.0.1", pc)

        probe = TestProbe(default_timeout_s=30.0)
        probe_cell = sb.spawn_system_raw(_ProbeForwarder(probe), "probe-fwd")
        driver = sa.spawn_root(
            Behaviors.setup_root(lambda ctx: _Driver(ctx, None)), "driver"
        )
        driver_proxy = fb._proxy(sa.address, driver.cell.uid)
        owner = sb.spawn_root(
            Behaviors.setup_root(
                lambda ctx: _Owner(
                    ctx, _Probe(probe_cell), ctx.engine.to_root_refob(driver_proxy)
                )
            ),
            "owner",
        )
        spawned = probe.expect_message_type(_Spawned)
        owner.tell(_ShareMsg(None))  # hand the worker ref to A's driver
        for _ in range(30):
            driver.tell(_Ping())
            time.sleep(0.005)
        driver.tell(_Release())
        owner.tell(_Release())  # both refs gone -> only a GC wave can kill it
        stopped = probe.expect_message_type(_Stopped, timeout_s=30.0)
        assert stopped.name == spawned.name
        time.sleep(0.3)

        # -- chrome trace: causally-linked spans from >= 2 nodes ------- #
        tracers = [s.telemetry.tracer for s in systems]
        doc = chrome_trace(tracers)
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(doc))
        parsed = json.loads(path.read_text())
        spans = [e for e in parsed["traceEvents"] if e["ph"] == "X"]
        by_id = {e["args"]["span_id"]: e for e in spans if "span_id" in e["args"]}
        linked_pids = set()
        chain_names = set()
        for event in spans:
            parent = event["args"].get("parent_id")
            if parent and parent in by_id:
                linked_pids.add(event["pid"])
                linked_pids.add(by_id[parent]["pid"])
                chain_names.add(event["name"])
        assert len(linked_pids) >= 2, "causal links span fewer than 2 nodes"
        names = {e["name"] for e in spans}
        assert {"send", "invoke", "gc_wave", "terminate"} <= names, names
        # the terminate chains to the wave that killed the worker
        wave_ids = {
            e["args"]["span_id"] for e in spans if e["name"] == "gc_wave"
        }
        terminates = [e for e in spans if e["name"] == "terminate"]
        assert any(e["args"].get("parent_id") in wave_ids for e in terminates)
        # cross-node flow arrows made it into the export
        assert any(e.get("ph") == "s" for e in parsed["traceEvents"])

        # -- wake profiler: >= 4 named phases per wake ----------------- #
        profile = sb.telemetry.profiler.dump(str(tmp_path / "wake.json"))
        assert profile["wakes"] > 0
        for wake in profile["recent"]:
            assert len(wake["phases"]) >= 4, wake
            assert {"ingest", "fold", "trace", "sweep"} <= set(wake["phases"])
        assert profile["phases"]["trace"]["total_s"] > 0

        # -- metrics: nonzero wave + fault counters -------------------- #
        text = prometheus_text(sb.telemetry.registry)
        wave_count = re.search(
            r"uigc_gc_wave_seconds_count\{[^}]*\} (\d+)", text
        )
        assert wave_count and int(wave_count.group(1)) > 0
        garbage = re.search(r"uigc_gc_garbage_total(\{[^}]*\})? (\d+)", text)
        assert garbage and int(garbage.group(2)) > 0
        dropped_text = prometheus_text(sa.telemetry.registry)
        dropped = re.search(
            r"uigc_frames_dropped_total(\{[^}]*\})? (\d+)", dropped_text
        )
        assert dropped and int(dropped.group(2)) > 0, "fault metrics empty"
    finally:
        _terminate_all(*systems)
