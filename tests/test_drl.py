"""DRL engine: reference-listing lifecycle.

The reference's DRL engine is dead code (not selectable,
UIGC.scala:14-18); here it is a first-class engine, so it gets the same
lifecycle coverage as the others: spawn / ref sharing / release-with-
created-refs reconciliation / pending self-message detection.
"""

from uigc_tpu import AbstractBehavior, ActorTestKit, Behaviors, Message, NoRefs, PostStop

CONFIG = {"uigc.engine": "drl"}


class GetRef(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,)


class Hello(NoRefs):
    def __eq__(self, other):
        return isinstance(other, Hello)

    def __hash__(self):
        return hash("Hello")


class SendC(NoRefs):
    def __init__(self, msg):
        self.msg = msg


class SendB(NoRefs):
    def __init__(self, msg):
        self.msg = msg


class TellBAboutC(NoRefs):
    pass


class ReleaseC(NoRefs):
    def __eq__(self, other):
        return isinstance(other, ReleaseC)

    def __hash__(self):
        return hash("ReleaseC")


class ReleaseB(NoRefs):
    pass


class Countdown(NoRefs):
    def __init__(self, n):
        self.n = n


class StartCountdown(NoRefs):
    def __init__(self, n):
        self.n = n


class Stopped(NoRefs):
    def __init__(self, name=None):
        self.name = name

    def __eq__(self, other):
        return isinstance(other, Stopped)

    def __hash__(self):
        return hash("Stopped")


class ActorB(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.actor_c = None

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, GetRef):
            self.actor_c = msg.ref
        elif isinstance(msg, SendC):
            self.actor_c.tell(msg.msg, ctx)
        elif isinstance(msg, ReleaseC):
            ctx.release(self.actor_c)
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Stopped())
        return None


class ActorC(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.count = 0

    def on_message(self, msg):
        if isinstance(msg, Hello):
            self.probe.ref.tell(Hello())
        elif isinstance(msg, Countdown):
            self.count += 1
            if msg.n > 0:
                self.context.self.tell(Countdown(msg.n - 1), self.context)
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Stopped())
        return None


class ActorA(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.actor_b = context.spawn(
            Behaviors.setup(lambda c: ActorB(c, probe)), "actorB"
        )
        self.actor_c = context.spawn(
            Behaviors.setup(lambda c: ActorC(c, probe)), "actorC"
        )

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, TellBAboutC):
            self.actor_b.tell(GetRef(ctx.create_ref(self.actor_c, self.actor_b)), ctx)
        elif isinstance(msg, SendB):
            self.actor_b.tell(msg.msg, ctx)
        elif isinstance(msg, SendC):
            self.actor_c.tell(msg.msg, ctx)
        elif isinstance(msg, ReleaseC):
            ctx.release(self.actor_c)
        elif isinstance(msg, ReleaseB):
            ctx.release(self.actor_b)
        elif isinstance(msg, StartCountdown):
            self.actor_c.tell(Countdown(msg.n), ctx)
            ctx.release(self.actor_c)
        return self


def test_drl_shared_ref_lifecycle():
    kit = ActorTestKit(CONFIG)
    try:
        probe = kit.create_test_probe()
        root = kit.spawn(Behaviors.setup_root(lambda c: ActorA(c, probe)), "root")
        root.tell(TellBAboutC())
        root.tell(SendB(SendC(Hello())))
        probe.expect_message(Hello())

        # C has two owners; releasing one must not kill it.
        root.tell(ReleaseC())
        probe.expect_no_message(0.3)
        root.tell(SendB(SendC(Hello())))
        probe.expect_message(Hello())

        # Last owner releases: C terminates.
        root.tell(SendB(ReleaseC()))
        probe.expect_message(Stopped())

        # Releasing B terminates B.
        root.tell(ReleaseB())
        probe.expect_message(Stopped())
    finally:
        kit.shutdown()


def test_drl_pending_self_messages():
    kit = ActorTestKit(CONFIG)
    try:
        probe = kit.create_test_probe(timeout_s=30.0)
        root = kit.spawn(Behaviors.setup_root(lambda c: ActorA(c, probe)), "root")
        root.tell(StartCountdown(2000))
        probe.expect_message(Stopped())  # C, only after the countdown drains
    finally:
        kit.shutdown()
