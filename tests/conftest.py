"""Test environment: two tiers.

Default tier: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding is exercised without TPU hardware; Pallas kernels run in
interpret mode.  Tests marked ``@pytest.mark.tpu`` are *skipped* (visibly)
in this tier.

Compiled tier (``UIGC_TEST_TPU=1 python -m pytest tests/ -q``): the CPU
pin is lifted, only ``tpu``-marked tests run, and they compile the Pallas
kernels for real on the ambient TPU (``tpu`` or this host's ``axon``
tunnel plugin).  This tier exists because interpret mode cannot catch
Mosaic lowering failures — a kernel that traces fine on CPU can still be
uncompilable on hardware (VERDICT r3: the bf16 where-broadcast bug).

Note: on this machine an 'axon' TPU plugin wins platform selection even
when JAX_PLATFORMS=cpu is set in the environment; only
``jax.config.update("jax_platforms", "cpu")`` reliably overrides it, and
XLA_FLAGS must be set before backend initialization.
"""

import os

#: Compiled-on-TPU tier requested?
TPU_MODE = os.environ.get("UIGC_TEST_TPU", "") not in ("", "0")

_flags = os.environ.get("XLA_FLAGS", "")
if not TPU_MODE and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

if not TPU_MODE:
    jax.config.update("jax_platforms", "cpu")


import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: compiled-on-TPU parity tier (run with UIGC_TEST_TPU=1 on a "
        "machine with a real chip; skipped in the default CPU tier)",
    )
    config.addinivalue_line(
        "markers",
        "slow: long randomized runs (chaos long-haul, determinism "
        "replays); excluded from the tier-1 gate via -m 'not slow'",
    )


def pytest_collection_modifyitems(config, items):
    if TPU_MODE:
        from uigc_tpu.utils.platform import is_tpu_platform

        if not is_tpu_platform(jax.devices()[0].platform):
            # An explicit opt-in with no chip must fail, not all-skip to
            # green — the tier's whole purpose is catching compile breaks.
            pytest.exit(
                "UIGC_TEST_TPU=1 but no TPU device is visible "
                f"(platform={jax.devices()[0].platform!r})",
                returncode=2,
            )
        skip_cpu = pytest.mark.skip(
            reason="UIGC_TEST_TPU=1: only the compiled-TPU tier runs"
        )
        for item in items:
            if "tpu" not in item.keywords:
                item.add_marker(skip_cpu)
    else:
        skip_tpu = pytest.mark.skip(
            reason="needs a real TPU: run UIGC_TEST_TPU=1 python -m pytest tests/"
        )
        for item in items:
            if "tpu" in item.keywords:
                item.add_marker(skip_tpu)

def pytest_sessionfinish(session, exitstatus):
    """Compiled-tier ledger: every UIGC_TEST_TPU=1 run appends one line
    to TPU_COMPILED_LEDGER.jsonl, so 'the kernels compile on hardware
    at commit X' is a committed per-commit fact instead of session
    prose (the r1-r3 invisible-Mosaic-regression class)."""
    if not TPU_MODE:
        return
    import datetime
    import json
    import pathlib
    import subprocess

    repo = pathlib.Path(__file__).resolve().parent.parent
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=repo,
            capture_output=True,
            text=True,
            timeout=10,
        ).stdout.strip()
        dirty = bool(
            subprocess.run(
                ["git", "status", "--porcelain"],
                cwd=repo,
                capture_output=True,
                text=True,
                timeout=10,
            ).stdout.strip()
        )
    except Exception:
        commit, dirty = "unknown", True
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    # The reporter can be absent (-p no:terminalreporter, xdist workers)
    # — the ledger line must still be written.
    stats = tr.stats if tr is not None else {}
    counts = {k: len(stats.get(k, [])) for k in ("passed", "failed", "error")}
    record = {
        "commit": commit,
        "dirty": dirty,
        "date": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"
        ),
        "exitstatus": int(exitstatus),
        **counts,
        "platform": jax.devices()[0].platform,
        "int8": os.environ.get("UIGC_KERNEL_INT8", "0"),
        "geometry": {
            k: os.environ[k]
            for k in ("UIGC_KERNEL_SUB", "UIGC_KERNEL_GROUP")
            if k in os.environ
        },
    }
    with open(repo / "TPU_COMPILED_LEDGER.jsonl", "a") as f:
        f.write(json.dumps(record) + "\n")


from uigc_tpu import native as _native  # noqa: E402

#: True when the C++ data plane could be built and loaded.
NATIVE_AVAILABLE = _native.is_available()

#: Shared parametrize value for the native shadow-graph backend: skips
#: (visibly) instead of silently dropping coverage when g++ is missing.
NATIVE_BACKEND = pytest.param(
    "native",
    marks=pytest.mark.skipif(not NATIVE_AVAILABLE, reason="no C++ toolchain"),
)
