"""Test environment: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding is exercised without TPU hardware.

Note: on this machine an 'axon' TPU plugin wins platform selection even
when JAX_PLATFORMS=cpu is set in the environment; only
``jax.config.update("jax_platforms", "cpu")`` reliably overrides it, and
XLA_FLAGS must be set before backend initialization.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import pytest  # noqa: E402

from uigc_tpu import native as _native  # noqa: E402

#: True when the C++ data plane could be built and loaded.
NATIVE_AVAILABLE = _native.is_available()

#: Shared parametrize value for the native shadow-graph backend: skips
#: (visibly) instead of silently dropping coverage when g++ is missing.
NATIVE_BACKEND = pytest.param(
    "native",
    marks=pytest.mark.skipif(not NATIVE_AVAILABLE, reason="no C++ toolchain"),
)
