"""Contract tests for PR 9's mutator-plane fast path: the schema-native
wire codec (runtime/schema.py), the co-located shared-memory ring
transport (runtime/shm_ring.py), and the decode lanes — crossed with
the negotiation, fallback, FaultPlan and recovery semantics the rest of
the suite relies on.

The load-bearing properties:

- mixed-version hello in BOTH directions (schema-capable vs not) keeps
  links byte-compatible — the non-advertising side sees only pickle;
- a message no schema admits falls back to pickle MID-STREAM, in order;
- the shm rings preserve the exact seq/FaultPlan/dead-letter semantics
  of the socket path, survive wraparound and full-ring backpressure,
  and a ring renounced mid-traffic recovers to the socket with zero
  sequence gaps or duplicates.
"""

import collections
import threading
import time

import pytest

from uigc_tpu import ActorSystem
from uigc_tpu.runtime import schema, shm_ring, wire
from uigc_tpu.runtime.behaviors import RawBehavior
from uigc_tpu.runtime.faults import FaultPlan
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.utils import events

#: module-level so pickle (the fallback codec under test) can find it
NT = collections.namedtuple("NT", "lane i")

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.crgc.shadow-graph": "array",
    "uigc.crgc.num-nodes": 2,
}


def cfg(**overrides):
    """BASE + overrides given as underscored kwargs: the first two
    underscores become the dots of the dotted key, the rest dashes
    (``uigc_node_shm_transport`` -> ``uigc.node.shm-transport``)."""
    out = dict(BASE)
    for k, v in overrides.items():
        head, section, rest = k.split("_", 2)
        out[f"{head}.{section}.{rest.replace('_', '-')}"] = v
    return out


class Sink(RawBehavior):
    """Records every payload, per-lane order violations included."""

    def __init__(self):
        self.n = 0
        self.got = []
        self.order_violations = 0
        self._last = {}
        self._lock = threading.Lock()

    def on_message(self, msg):
        with self._lock:
            if isinstance(msg, tuple) and msg and msg[0] == "n":
                lane, i = msg[1], msg[2]
                if i <= self._last.get(lane, -1):
                    self.order_violations += 1
                self._last[lane] = i
            self.got.append(msg)
            self.n += 1
        return None


class EventLog:
    def __init__(self):
        self.entries = []
        self._lock = threading.Lock()

    def __call__(self, name, fields):
        with self._lock:
            self.entries.append((name, dict(fields)))

    def of(self, name):
        with self._lock:
            return [f for n, f in self.entries if n == name]

    def total(self, name, field):
        return sum(f.get(field, 0) for f in self.of(name))


@pytest.fixture
def event_log():
    log = EventLog()
    events.recorder.enable()
    events.recorder.add_listener(log)
    yield log
    events.recorder.disable()
    events.recorder.remove_listener(log)
    events.recorder.reset()


class Pair:
    def __init__(self, name, cfg_a=BASE, cfg_b=BASE, plan=None):
        self.fa = NodeFabric(fault_plan=plan)
        self.fb = NodeFabric(fault_plan=plan)
        self.a = ActorSystem(None, name=f"{name}-a", config=cfg_a, fabric=self.fa)
        self.b = ActorSystem(None, name=f"{name}-b", config=cfg_b, fabric=self.fb)
        self.sink = Sink()
        sink_cell = self.b.spawn_system_raw(self.sink, "sink")
        self.fb.register_name("sink", sink_cell)
        port = self.fb.listen()
        self.addr_b = self.fa.connect("127.0.0.1", port)
        self.proxy = self.fa.lookup(self.addr_b, "sink")

    def wait_shm(self, timeout_s=5.0):
        deadline = time.monotonic() + timeout_s
        while not self.fa.shm_active(self.addr_b) and time.monotonic() < deadline:
            time.sleep(0.005)
        return self.fa.shm_active(self.addr_b)

    def settle(self, expected, timeout_s=20.0):
        deadline = time.monotonic() + timeout_s
        while self.sink.n < expected and time.monotonic() < deadline:
            time.sleep(0.01)
        return self.sink.n

    def recv_state(self):
        return self.fb._peer_state(self.a.address)

    def close(self):
        for system in (self.a, self.b):
            try:
                system.terminate(timeout_s=5.0)
            except Exception:
                pass


# ------------------------------------------------------------------- #
# Schema codec units
# ------------------------------------------------------------------- #


def test_value_safe_exact_types_only():
    assert schema.value_safe(("n", 1, 2.5, b"x", None, True))
    assert schema.value_safe({"k": [1, (2, "three")]})
    NT = collections.namedtuple("NT", "a")
    assert not schema.value_safe(NT(1))  # marshal would flatten it
    assert not schema.value_safe(object())
    assert not schema.value_safe((1, object()))
    assert not schema.value_safe(1 << 80)  # outside int64: pickle path


def test_value_run_roundtrip():
    sch = schema.registry.get(schema.SCHEMA_VAL)
    msgs = [("n", 0, i, b"blob") for i in range(64)]
    body = sch.vec_encode(msgs)
    assert sch.vec_decode(None, body) == msgs


def test_capability_negotiation_rules():
    ours = schema.capability()
    assert schema.peer_schema_ids(("fb", ours)) == frozenset(
        schema.registry.ids()
    )
    # no schema cap at all -> pickle-only link
    assert schema.peer_schema_ids(("fb",)) == frozenset()
    # a different interpreter/table pin -> pickle-only, never a guess
    assert schema.peer_schema_ids(("sc1:9.9.9:1,2,3",)) == frozenset()
    # garbage ids -> pickle-only
    prefix = ours.rpartition(":")[0]
    assert schema.peer_schema_ids((f"{prefix}:zap",)) == frozenset()


def test_encode_message_schema_magic_dispatch():
    ids = frozenset(schema.registry.ids())
    data = wire.encode_message_schema(("hello", 7), ids)
    assert data[:3] == wire.SCHEMA_MAGIC
    assert wire.decode_message(None, data) == ("hello", 7)
    # not negotiated -> pickle bytes, same decoder
    data = wire.encode_message_schema(("hello", 7), frozenset())
    assert data[:3] != wire.SCHEMA_MAGIC
    assert wire.decode_message(None, data) == ("hello", 7)
    # not admissible (a class instance) -> pickle even when negotiated
    data = wire.encode_message_schema(ValueError("boom"), ids)
    assert data[:3] != wire.SCHEMA_MAGIC


def test_run_block_codec_roundtrip_and_corruption():
    sch = schema.registry.get(schema.SCHEMA_VAL)
    body = sch.vec_encode([("n", 0, 0), ("n", 0, 1)])
    block = wire.encode_run_block(9, schema.SCHEMA_VAL, 2, body)
    decoded = wire.decode_block(block)
    assert decoded == ("appr", 9, schema.SCHEMA_VAL, 2, body)
    assert wire.decode_block(block[: len(block) // 2]) is None
    assert wire.decode_block(b"R") is None


# ------------------------------------------------------------------- #
# Schema codec over a live link
# ------------------------------------------------------------------- #


def test_schema_codec_on_by_default_and_counted(event_log):
    pair = Pair("sc-default")
    try:
        assert pair.fa.peer_schema_ids(pair.addr_b) == frozenset(
            schema.registry.ids()
        )
        for i in range(500):
            pair.proxy.tell(("n", 0, i))
        assert pair.settle(500) == 500
        assert pair.sink.order_violations == 0
        assert event_log.total(events.CODEC_FRAMES, "schema") >= 500
    finally:
        pair.close()


def test_unencodable_messages_fall_back_to_pickle_mid_stream(event_log):
    """A stream interleaving schema-admitted tuples with class
    instances and oversized ints delivers everything, in order, with
    both codecs observably in play."""
    pair = Pair("sc-mid")
    try:
        expected = []
        for i in range(300):
            if i % 3 == 2:
                msg = NT(0, i) if i % 2 else ("big", 1 << 90, i)
            else:
                msg = ("n", 0, i)
            expected.append(msg)
            pair.proxy.tell(msg)
        assert pair.settle(300) == 300
        assert pair.sink.got == expected
        # namedtuples survive as namedtuples (the exact-type gate)
        assert any(isinstance(m, NT) for m in pair.sink.got)
        assert event_log.total(events.CODEC_FRAMES, "schema") > 0
        assert event_log.total(events.CODEC_FRAMES, "pickle") > 0
        st = pair.recv_state()
        assert (st.gaps, st.dups) == (0, 0)
    finally:
        pair.close()


@pytest.mark.parametrize("capable_side", ["a", "b"])
def test_mixed_version_hello_both_directions(event_log, capable_side):
    """A schema-capable node and a non-advertising one interoperate in
    both directions; the non-negotiated link carries only pickle."""
    plain = cfg(uigc_node_schema_codec=False)
    cfg_a = BASE if capable_side == "a" else plain
    cfg_b = plain if capable_side == "a" else BASE
    pair = Pair(f"sc-mix-{capable_side}", cfg_a=cfg_a, cfg_b=cfg_b)
    try:
        assert pair.fa.peer_schema_ids(pair.addr_b) == frozenset()
        for i in range(200):
            pair.proxy.tell(("n", 0, i))
        assert pair.settle(200) == 200
        assert pair.sink.order_violations == 0
        assert event_log.total(events.CODEC_FRAMES, "schema") == 0
        st = pair.recv_state()
        assert (st.gaps, st.dups) == (0, 0)
    finally:
        pair.close()


def test_schema_run_respects_fault_plan_drops(event_log):
    """Outbound drop verdicts land on schema-run traffic with the same
    observable accounting as the pickle path: dropped frames consume
    sequence numbers, the receiver reports the gap, everything else
    arrives in order."""
    names = ("uigc://sc-drop-a", "uigc://sc-drop-b")
    plan = FaultPlan(7).drop(src=names[0], dst=names[1], kind="app", count=25)
    pair = Pair("sc-drop", plan=plan)
    try:
        for i in range(200):
            pair.proxy.tell(("n", 0, i))
        assert pair.settle(175) == 175
        assert len(event_log.of(events.FRAME_DROPPED)) == 25
        st = pair.recv_state()
        assert st.gaps == 25  # every drop is a visible gap
        assert st.dups == 0
        assert pair.sink.order_violations == 0
    finally:
        pair.close()


# ------------------------------------------------------------------- #
# Shm ring units
# ------------------------------------------------------------------- #


def test_shm_ring_wraparound_fifo():
    ring = shm_ring.ShmRing.create(4096)
    try:
        peer = shm_ring.ShmRing.attach(ring.name)
        try:
            sent = []
            received = []
            for i in range(500):
                data = bytes([i % 251]) * (17 + i % 211)
                while not ring.write(data):
                    got = peer.read()
                    assert got is not None
                    received.append(got)
                sent.append(data)
            while True:
                got = peer.read()
                if got is None:
                    break
                received.append(got)
            assert received == sent
        finally:
            peer.close()
    finally:
        ring.close()


def test_shm_ring_full_refusal_and_poison():
    ring = shm_ring.ShmRing.create(4096)
    try:
        peer = shm_ring.ShmRing.attach(ring.name)
        try:
            n = 0
            while ring.write(b"z" * 100):
                n += 1
            assert n > 0  # filled up, then refused without corruption
            assert not ring.write(b"z" * 100)
            ring.poison()
            assert peer.poisoned
            # data written before the poison still drains
            for _ in range(n):
                assert peer.read() == b"z" * 100
            assert peer.read() is None
        finally:
            peer.close()
    finally:
        ring.close()


def test_shm_ring_selfcheck():
    assert shm_ring.selfcheck()


# ------------------------------------------------------------------- #
# Shm transport end-to-end
# ------------------------------------------------------------------- #


def test_shm_transport_negotiates_and_delivers(event_log):
    pair = Pair("shm-basic", cfg_a=cfg(uigc_node_shm_transport=True),
                cfg_b=cfg(uigc_node_shm_transport=True))
    try:
        assert pair.wait_shm()
        for i in range(2000):
            pair.proxy.tell(("n", 0, i))
        assert pair.settle(2000) == 2000
        assert pair.sink.order_violations == 0
        st = pair.recv_state()
        assert (st.gaps, st.dups) == (0, 0)
        roles = {f.get("role") for f in event_log.of(events.SHM_ESTABLISHED)}
        assert roles == {"producer", "consumer"}
    finally:
        pair.close()


def test_shm_not_negotiated_when_peer_lacks_cap():
    pair = Pair("shm-mixed", cfg_a=cfg(uigc_node_shm_transport=True), cfg_b=BASE)
    try:
        time.sleep(0.3)
        assert not pair.fa.shm_active(pair.addr_b)
        for i in range(200):
            pair.proxy.tell(("n", 0, i))
        assert pair.settle(200) == 200
    finally:
        pair.close()


def test_shm_full_ring_backpressure(event_log):
    """A tiny ring forces the writer into the full-ring stall; traffic
    still delivers completely and in order, and the stall is counted."""
    small = cfg(uigc_node_shm_transport=True, uigc_node_shm_ring_bytes=8192)
    pair = Pair("shm-full", cfg_a=small, cfg_b=small)
    try:
        assert pair.wait_shm()
        for i in range(4000):
            pair.proxy.tell(("n", 0, i, b"pad" * 40))
        assert pair.settle(4000, timeout_s=40.0) == 4000
        assert pair.sink.order_violations == 0
        st = pair.recv_state()
        assert (st.gaps, st.dups) == (0, 0)
        assert len(event_log.of(events.SHM_RING_FULL)) > 0
    finally:
        pair.close()


def test_shm_fault_plan_verdicts_apply(event_log):
    """FaultPlan verdicts run identically on the shm path (they sit
    above the transport): drops surface as receiver gaps."""
    names = ("uigc://shm-fault-a", "uigc://shm-fault-b")
    plan = FaultPlan(11).drop(src=names[0], dst=names[1], kind="app", count=20)
    shm = cfg(uigc_node_shm_transport=True)
    pair = Pair("shm-fault", cfg_a=shm, cfg_b=shm, plan=plan)
    try:
        assert pair.wait_shm()
        for i in range(200):
            pair.proxy.tell(("n", 0, i))
        assert pair.settle(180) == 180
        assert len(event_log.of(events.FRAME_DROPPED)) == 20
        st = pair.recv_state()
        assert st.gaps == 20
        assert st.dups == 0
        assert pair.sink.order_violations == 0
    finally:
        pair.close()


def test_shm_ring_death_recovers_to_socket_without_desync(event_log):
    """Mid-traffic ring renouncement (the peer-crash model: the ring
    becomes unwritable while the process and socket survive) falls the
    link back to the socket path with ZERO sequence gaps or duplicates
    — the receiver drains the ring before its first socket frame."""
    shm = cfg(uigc_node_shm_transport=True)
    pair = Pair("shm-crash", cfg_a=shm, cfg_b=shm)
    try:
        assert pair.wait_shm()
        for i in range(1000):
            pair.proxy.tell(("n", 0, i))
        assert pair.settle(1000) == 1000
        # poison the producing ring mid-stream: the writer's next flush
        # renounces it and resumes the socket
        st_a = pair.fa._peer_state(pair.addr_b)
        st_a.shm_tx.poison()
        for i in range(1000, 2000):
            pair.proxy.tell(("n", 0, i))
        assert pair.settle(2000) == 2000
        assert pair.sink.order_violations == 0
        assert not pair.fa.shm_active(pair.addr_b)
        st = pair.recv_state()
        assert (st.gaps, st.dups) == (0, 0)
        reasons = {f.get("reason") for f in event_log.of(events.SHM_FALLBACK)}
        assert "poisoned" in reasons
        # and the link still works for a third burst
        for i in range(2000, 2500):
            pair.proxy.tell(("n", 0, i))
        assert pair.settle(2500) == 2500
    finally:
        pair.close()


def test_decode_lanes_degrade_gracefully_under_gil(event_log):
    """``decode-workers: on`` forces per-peer decode lanes even under
    the stock GIL — delivery, ordering and seq accounting must be
    byte-identical to the inline path."""
    lanes = cfg(uigc_node_shm_transport=True, uigc_node_decode_workers="on")
    pair = Pair("lanes", cfg_a=lanes, cfg_b=lanes)
    try:
        assert pair.wait_shm()
        n_senders, per = 4, 500
        threads = [
            threading.Thread(
                target=lambda lane=lane: [
                    pair.proxy.tell(("n", lane, i)) for i in range(per)
                ]
            )
            for lane in range(n_senders)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert pair.settle(n_senders * per) == n_senders * per
        assert pair.sink.order_violations == 0
        st = pair.recv_state()
        assert st.decode_lane is not None
        assert (st.gaps, st.dups) == (0, 0)
    finally:
        pair.close()


# ------------------------------------------------------------------- #
# UL010 lint rule
# ------------------------------------------------------------------- #


def test_ul010_flags_pickle_on_runtime_hot_path(tmp_path):
    import sys

    sys.path.insert(0, str((__import__("pathlib").Path(__file__).parent.parent / "tools")))
    import uigc_lint

    runtime = tmp_path / "runtime"
    runtime.mkdir()
    bad = runtime / "hotpath.py"
    bad.write_text(
        "import pickle\n\ndef enc(x):\n    return pickle.dumps(x)\n"
    )
    violations = uigc_lint.lint_paths([str(bad)])
    assert any(v.rule == "UL010" for v in violations)
    # wire.py is sanctioned
    good = runtime / "wire.py"
    good.write_text(
        "import pickle\n\ndef enc(x):\n    return pickle.dumps(x)\n"
    )
    violations = uigc_lint.lint_paths([str(good)])
    assert not any(v.rule == "UL010" for v in violations)
    # repo itself is strict-clean for UL010 beyond the grandfathered set
    repo_root = __import__("pathlib").Path(__file__).parent.parent
    violations = uigc_lint.lint_paths([str(repo_root / "uigc_tpu")])
    ul010 = [v for v in violations if v.rule == "UL010"]
    budget = uigc_lint._load_allowlist(
        str(repo_root / "tools" / "uigc_lint_allow.txt")
    )
    _grand, fresh = uigc_lint.apply_allowlist(ul010, budget)
    assert fresh == []
