"""Bounds-check the State/Entry/RefobInfo saturation + early-flush paths.

Analogue of the reference's ManyMessagesSpec (reference:
src/test/scala/edu/illinois/osl/uigc/ManyMessagesSpec.scala): A sends
4 * Short.MaxValue messages to B, exercising send-count saturation
(reference: RefobInfo.java:11-13, CRGC.scala:215-216) and recv-count
saturation (State.java:81-88); both actors are then collected.
"""

from uigc_tpu import AbstractBehavior, ActorTestKit, Behaviors, Message, NoRefs, PostStop

NUM_MESSAGES = 4 * 32767
CONFIG = {"uigc.crgc.wakeup-interval": 10}


class Ping(NoRefs):
    pass


class DoneSending(NoRefs):
    def __eq__(self, other):
        return isinstance(other, DoneSending)

    def __hash__(self):
        return hash("DoneSending")


class DoneReceiving(NoRefs):
    def __eq__(self, other):
        return isinstance(other, DoneReceiving)

    def __hash__(self):
        return hash("DoneReceiving")


class Terminated(NoRefs):
    def __eq__(self, other):
        return isinstance(other, Terminated)

    def __hash__(self):
        return hash("Terminated")


class NewAcquaintance(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,)


class ActorA(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe

    def on_message(self, msg):
        if isinstance(msg, NewAcquaintance):
            ctx = self.context
            for _ in range(NUM_MESSAGES):
                msg.ref.tell(Ping(), ctx)
            self.probe.ref.tell(DoneSending())
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Terminated())
        return None


class ActorB(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.count = 0

    def on_message(self, msg):
        if isinstance(msg, Ping):
            self.count += 1
            if self.count == NUM_MESSAGES:
                self.probe.ref.tell(DoneReceiving())
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Terminated())
        return None


class Root(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        ctx = context
        actor_a = ctx.spawn(Behaviors.setup(lambda c: ActorA(c, probe)), "actorA")
        actor_b = ctx.spawn(Behaviors.setup(lambda c: ActorB(c, probe)), "actorB")
        actor_a.tell(NewAcquaintance(ctx.create_ref(actor_b, actor_a)), ctx)
        ctx.release(actor_a, actor_b)

    def on_message(self, msg):
        return self


def test_many_messages_collected():
    kit = ActorTestKit(CONFIG)
    try:
        probe = kit.create_test_probe(timeout_s=60.0)
        kit.spawn(Behaviors.setup_root(lambda ctx: Root(ctx, probe)), "root")
        seen = [probe.expect_message_type(object) for _ in range(4)]
        kinds = sorted(type(m).__name__ for m in seen)
        assert kinds == ["DoneReceiving", "DoneSending", "Terminated", "Terminated"], kinds
    finally:
        kit.shutdown()
