"""Spawn / send / share-ref / release lifecycle.

Analogue of the reference's SimpleActorSpec (reference:
src/test/scala/edu/illinois/osl/uigc/SimpleActorSpec.scala:26-60): actor C
terminates only after *all* owners release their references.
"""

import pytest

from uigc_tpu import AbstractBehavior, ActorTestKit, Behaviors, Message, NoRefs

CONFIG = {"uigc.crgc.wakeup-interval": 10}


class Init(NoRefs):
    pass


class Hello(NoRefs):
    def __eq__(self, other):
        return isinstance(other, Hello)

    def __hash__(self):
        return hash("Hello")


class SendC(NoRefs):
    def __init__(self, msg):
        self.msg = msg


class SendB(NoRefs):
    def __init__(self, msg):
        self.msg = msg


class TellBAboutC(NoRefs):
    pass


class ReleaseC(NoRefs):
    def __eq__(self, other):
        return isinstance(other, ReleaseC)

    def __hash__(self):
        return hash("ReleaseC")


class ReleaseB(NoRefs):
    pass


class Spawned(NoRefs):
    def __init__(self, name):
        self.name = name


class Terminated(NoRefs):
    def __eq__(self, other):
        return isinstance(other, Terminated)

    def __hash__(self):
        return hash("Terminated")


class GetRef(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,)


class ActorA(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.actor_b = None
        self.actor_c = None

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Init):
            self.actor_b = ctx.spawn(actor_b_factory(self.probe), "actorB")
            self.actor_c = ctx.spawn(actor_c_factory(self.probe), "actorC")
        elif isinstance(msg, SendC):
            self.actor_c.tell(msg.msg, ctx)
        elif isinstance(msg, SendB):
            self.actor_b.tell(msg.msg, ctx)
        elif isinstance(msg, TellBAboutC):
            ref = ctx.create_ref(self.actor_c, self.actor_b)
            self.actor_b.tell(GetRef(ref), ctx)
        elif isinstance(msg, ReleaseC):
            ctx.release(self.actor_c)
        elif isinstance(msg, ReleaseB):
            ctx.release(self.actor_b)
        return self


class ActorB(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.actor_c = None
        probe.ref.tell(Spawned(context.name))

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, GetRef):
            self.actor_c = msg.ref
        elif isinstance(msg, SendC):
            self.actor_c.tell(msg.msg, ctx)
        elif isinstance(msg, ReleaseC):
            ctx.release(self.actor_c)
        return self

    def on_signal(self, signal):
        from uigc_tpu import PostStop

        if signal is PostStop:
            self.probe.ref.tell(Terminated())
        return None


class ActorC(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        probe.ref.tell(Spawned(context.name))

    def on_message(self, msg):
        if isinstance(msg, Hello):
            self.probe.ref.tell(Hello())
        return self

    def on_signal(self, signal):
        from uigc_tpu import PostStop

        if signal is PostStop:
            self.probe.ref.tell(Terminated())
        return None


def actor_b_factory(probe):
    return Behaviors.setup(lambda ctx: ActorB(ctx, probe))


def actor_c_factory(probe):
    return Behaviors.setup(lambda ctx: ActorC(ctx, probe))


@pytest.mark.parametrize(
    "style", ["on-block", "on-idle", "wave"]
)
def test_simple_actor_lifecycle(style):
    config = dict(CONFIG)
    config["uigc.crgc.collection-style"] = style
    if style == "wave":
        config["uigc.crgc.wave-frequency"] = 10
    kit = ActorTestKit(config)
    try:
        probe = kit.create_test_probe()
        actor_a = kit.spawn(
            Behaviors.setup_root(lambda ctx: ActorA(ctx, probe)), "actorA"
        )

        # spawn actors
        actor_a.tell(Init())
        probe.expect_message_type(Spawned)
        probe.expect_message_type(Spawned)

        # send messages
        actor_a.tell(SendC(Hello()))
        probe.expect_message(Hello())

        # share references
        actor_a.tell(TellBAboutC())
        actor_a.tell(SendB(SendC(Hello())))
        probe.expect_message(Hello())

        # no termination while some owners still exist
        actor_a.tell(ReleaseC())
        probe.expect_no_message(0.3)

        # still usable through the other owner
        actor_a.tell(SendB(SendC(Hello())))
        probe.expect_message(Hello())

        # terminate after all references released
        actor_a.tell(SendB(ReleaseC()))
        probe.expect_message(Terminated())

        # terminate after the only reference is released
        actor_a.tell(ReleaseB())
        probe.expect_message(Terminated())
    finally:
        kit.shutdown()
