"""Parents must not be collected before the children they supervise.

Analogue of the reference's SupervisionSpec (reference:
src/test/scala/edu/illinois/osl/uigc/SupervisionSpec.scala, GH issue #15):
the trace marks supervisors of live actors so stopping a parent can never
take down a live child (reference: ShadowGraph.java:242-267).
"""

from uigc_tpu import AbstractBehavior, ActorTestKit, Behaviors, Message, NoRefs, PostStop

CONFIG = {"uigc.crgc.wakeup-interval": 10}


class Init(NoRefs):
    pass


class Initialized(NoRefs):
    def __eq__(self, other):
        return isinstance(other, Initialized)

    def __hash__(self):
        return hash("Initialized")


class ReleaseParent(NoRefs):
    pass


class ReleaseChild1(NoRefs):
    pass


class ReleaseChild2(NoRefs):
    pass


class Spawned(NoRefs):
    def __init__(self, name):
        self.name = name


class Terminated(NoRefs):
    def __init__(self, name):
        self.name = name

    def __eq__(self, other):
        return isinstance(other, Terminated) and other.name == self.name

    def __hash__(self):
        return hash(("Terminated", self.name))


class GetRef(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,)


class Child(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        probe.ref.tell(Spawned(context.name))

    def on_message(self, msg):
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Terminated(self.context.name))
        return None


class Parent(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        probe.ref.tell(Spawned(context.name))
        self.child1 = context.spawn(
            Behaviors.setup(lambda ctx: Child(ctx, probe)), "child1"
        )
        self.child2 = context.spawn(
            Behaviors.setup(lambda ctx: Child(ctx, probe)), "child2"
        )

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, GetRef):
            root = msg.ref
            root.tell(GetRef(ctx.create_ref(self.child1, root)), ctx)
            root.tell(GetRef(ctx.create_ref(self.child2, root)), ctx)
            ctx.release(self.child1, self.child2)
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Terminated(self.context.name))
        return None


class RootActor(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.parent = None
        self.child1 = None
        self.child2 = None

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Init):
            self.parent = ctx.spawn(
                Behaviors.setup(lambda c: Parent(c, self.probe)), "parent"
            )
            self.parent.tell(GetRef(ctx.create_ref(ctx.self, self.parent)), ctx)
        elif isinstance(msg, GetRef):
            if self.child1 is None:
                self.child1 = msg.ref
            else:
                self.child2 = msg.ref
                self.probe.ref.tell(Initialized())
        elif isinstance(msg, ReleaseParent):
            ctx.release(self.parent)
        elif isinstance(msg, ReleaseChild1):
            ctx.release(self.child1)
        elif isinstance(msg, ReleaseChild2):
            ctx.release(self.child2)
        return self


def test_supervision_ordering():
    kit = ActorTestKit(CONFIG)
    try:
        probe = kit.create_test_probe()
        root = kit.spawn(
            Behaviors.setup_root(lambda ctx: RootActor(ctx, probe)), "root"
        )
        root.tell(Init())
        parent = probe.expect_message_type(Spawned).name
        child1 = probe.expect_message_type(Spawned).name
        child2 = probe.expect_message_type(Spawned).name
        probe.expect_message(Initialized())

        # Parent is not collected while its children are alive.
        root.tell(ReleaseParent())
        probe.expect_no_message(0.3)

        # Releasing one child collects only that child.
        root.tell(ReleaseChild1())
        probe.expect_message(Terminated(child1))

        # Releasing the last child collects child and then parent.
        root.tell(ReleaseChild2())
        probe.expect_message(Terminated(child2))
        probe.expect_message(Terminated(parent))
    finally:
        kit.shutdown()
