"""uigc-check suite (uigc_tpu/analysis/check + tools/uigc_check.py).

Layers:

- seeded positives: a planted mini-repo triggers each rule family —
  undocumented/typo'd/dead config keys (UC101/UC108/UC102), an orphan
  frame kind (UC104), an untested wire decoder (UC105), a cross-module
  lock inversion with a witness path (UC201) and a blocking call under
  a held lock (UC203), an impure traced function (UC301/UC302), an
  unhashable literal at a jit static position (UC304), and a pickle
  call in gateway code — reachable from a client entry point (UC401)
  or merely present there (UL016), each with a closed-codec clean
  counterpart;
- negatives: the repository itself is strict-clean (the acceptance
  gate), and ``# uigc-lint: disable=`` comments silence surface rules;
- machinery: the refactored ``tools/uigc_lint.py`` wrapper and
  ``uigc_check --rules 'UL*'`` produce identical verdicts over the
  same tree, the registry document's schema is stable, and the
  CONFIG.md round-trip (``--write-config`` then re-check) clears the
  UC106 drift finding;
- regression pins for defects the analyzer surfaced in its first
  whole-repo run: the event->metrics bridge folds the seven
  previously-unbridged events (link_healed, node_draining,
  sbr_quarantine, stale_window, delta/ingress serialization,
  analysis.check) into their metrics.
"""

import importlib.util
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from uigc_tpu.analysis.check import cli
from uigc_tpu.telemetry import EventMetricsBridge, MetricsRegistry
from uigc_tpu.utils import events


# ------------------------------------------------------------------- #
# The planted mini-repo
# ------------------------------------------------------------------- #


def _plant(root, rel, source):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(textwrap.dedent(source))
    return path


def _mini_repo(root):
    """A tree exercising every pass: one defect of each family, plus a
    clean counterpart proving the rule does not overfire."""
    _plant(
        root,
        "uigc_tpu/config.py",
        '''\
        DEFAULTS = {
            # A knob GUIDE.md documents.
            "uigc.good.knob": 1,
            # Read by the engine but absent from GUIDE.md.
            "uigc.planted.undocumented": 2,
            # Defaulted, documented nowhere, read nowhere.
            "uigc.planted.dead": 3,
        }
        ''',
    )
    _plant(
        root,
        "uigc_tpu/engine.py",
        '''\
        def setup(config):
            a = config.get_int("uigc.good.knob")
            b = config.get_int("uigc.planted.undocumented")
            c = config.get("uigc.planted.typo")
            return a, b, c
        ''',
    )
    _plant(
        root,
        "GUIDE.md",
        """\
        # Guide

        | Key | Default | Meaning |
        |---|---|---|
        | `uigc.good.knob` | `1` | the documented knob |
        """,
    )
    _plant(
        root,
        "uigc_tpu/runtime/wire.py",
        '''\
        PING_FRAME_KIND = "ping"
        ORPHAN_FRAME_KIND = "orph"


        def encode_ping(origin):
            return ("ping", origin)


        def encode_orphan(origin):
            return ("orph", origin)


        def decode_ping(frame):
            try:
                return frame[1]
            except IndexError:
                return None
        ''',
    )
    _plant(
        root,
        "uigc_tpu/runtime/node.py",
        """\
        def bind(fabric):
            fabric.register_frame_handler("ping", _on_ping)


        def _on_ping(frame):
            return frame
        """,
    )
    _plant(
        root,
        "uigc_tpu/runtime/locka.py",
        """\
        import threading
        import time


        class Pool:
            def __init__(self):
                self.alpha_lock = threading.Lock()
                self.beta_lock = threading.Lock()

            def forward(self):
                with self.alpha_lock:
                    with self.beta_lock:
                        return 1

            def slow(self):
                with self.alpha_lock:
                    time.sleep(0.1)
        """,
    )
    _plant(
        root,
        "uigc_tpu/runtime/lockb.py",
        """\
        def backward(pool):
            with pool.beta_lock:
                with pool.alpha_lock:
                    return 2
        """,
    )
    _plant(
        root,
        "uigc_tpu/gateway/ingest.py",
        '''\
        import marshal
        import pickle

        from ..runtime import schema


        def client_ingest(buf):
            # Planted: a client-input entry point whose helper pickles.
            return _hydrate(buf)


        def _hydrate(buf):
            return pickle.loads(buf)


        def client_parse_ok(buf):
            # Clean counterpart: the closed client codec fires nothing.
            return schema.decode_client_value(buf)


        def _archive_restore(blob):
            # A gateway-side deserializer no client entry point reaches:
            # UL016 territory, but not UC401.
            return marshal.loads(blob)
        ''',
    )
    _plant(
        root,
        "uigc_tpu/ops/kernel.py",
        """\
        import time

        import jax
        import jax.numpy as jnp

        _CACHE = {}


        def _impure(x):
            _CACHE["last"] = time.time()
            return x + 1


        @jax.jit
        def traced_step(x):
            return _impure(x)


        def _tile(x, shape):
            return jnp.zeros(shape) + x


        tile = jax.jit(_tile, static_argnums=(1,))


        def drive(x):
            return tile(x, [4, 4])
        """,
    )
    return root


@pytest.fixture()
def mini(tmp_path):
    return _mini_repo(str(tmp_path))


def _check(mini, rules):
    return cli.run_check(
        [os.path.join(mini, "uigc_tpu")], rules=rules, repo_root=mini
    )


def _by_rule(result, rule):
    return [d for d in result["fresh"] if d.rule == rule]


# ------------------------------------------------------------------- #
# Seeded positives
# ------------------------------------------------------------------- #


def test_seeded_config_plane_rules(mini):
    result = _check(mini, ["UC101", "UC102", "UC106", "UC108"])
    rendered = "\n".join(d.render() for d in result["fresh"])
    undocumented = _by_rule(result, "UC101")
    assert len(undocumented) == 1
    assert "'uigc.planted.undocumented'" in undocumented[0].message
    assert undocumented[0].path.endswith("config.py")
    typo = _by_rule(result, "UC108")
    assert len(typo) == 1
    assert "'uigc.planted.typo'" in typo[0].message
    assert typo[0].path.endswith("engine.py")  # anchored at the read site
    dead = _by_rule(result, "UC102")
    assert len(dead) == 1
    assert "'uigc.planted.dead'" in dead[0].message
    # The documented + read key fires nothing.
    assert "uigc.good.knob" not in rendered
    # CONFIG.md does not exist yet -> drift.
    assert len(_by_rule(result, "UC106")) == 1


def test_seeded_orphan_frame_kind(mini):
    result = _check(mini, ["UC104"])
    findings = _by_rule(result, "UC104")
    assert len(findings) == 1
    assert "'orph'" in findings[0].message
    assert "no receiver" in findings[0].message
    assert "'ping'" not in findings[0].message


def test_seeded_untested_decoder(mini):
    result = _check(mini, ["UC105"])
    findings = _by_rule(result, "UC105")
    assert len(findings) == 1
    assert "decode_ping()" in findings[0].message


def test_seeded_cross_module_lock_inversion_with_witness(mini):
    result = _check(mini, ["UC201"])
    findings = _by_rule(result, "UC201")
    assert len(findings) == 1
    message = findings[0].message
    assert "alpha_lock" in message and "beta_lock" in message
    # The witness names both acquisition paths, not just the cycle.
    assert " -> " in message and "via" in message


def test_seeded_blocking_under_lock(mini):
    result = _check(mini, ["UC203"])
    findings = _by_rule(result, "UC203")
    assert len(findings) == 1
    assert "time.sleep()" in findings[0].message
    assert "alpha_lock" in findings[0].message


def test_seeded_impure_traced_function(mini):
    result = _check(mini, ["UC301", "UC302"])
    mutation = _by_rule(result, "UC301")
    assert len(mutation) == 1
    assert "_CACHE" in mutation[0].message
    assert "traced via" in mutation[0].message  # witness chain to the entry
    rng = _by_rule(result, "UC302")
    assert len(rng) == 1
    assert "time.time" in rng[0].message


def test_seeded_unhashable_static_arg(mini):
    result = _check(mini, ["UC304"])
    findings = _by_rule(result, "UC304")
    assert len(findings) == 1
    assert "'tile'" in findings[0].message
    assert "list" in findings[0].message
    assert "static position 1" in findings[0].message


def test_seeded_gateway_unsafe_deserializer_reachability(mini):
    result = _check(mini, ["UC401"])
    findings = _by_rule(result, "UC401")
    assert len(findings) == 1
    message = findings[0].message
    assert "pickle.loads" in message
    assert "via _hydrate" in message  # the transitive closure, not the entry
    assert findings[0].path.endswith("gateway/ingest.py")
    # marshal.loads also sits in gateway code but no client entry point
    # reaches it: reachability, not mere presence, drives UC401.
    assert "marshal" not in message


def test_seeded_gateway_pickle_lint_both_directions(mini):
    result = _check(mini, ["UL016"])
    findings = _by_rule(result, "UL016")
    # Presence, not reachability: both deserializer calls fire.
    assert len(findings) == 2
    rendered = "\n".join(d.render() for d in findings)
    assert "pickle.loads()" in rendered
    assert "marshal.loads()" in rendered
    # The closed client codec is the sanctioned path and stays silent:
    # findings anchor only at the two deserializer call sites, not at
    # client_parse_ok's schema.decode_client_value line.
    assert "ingest.py:13" in rendered  # pickle.loads in _hydrate
    assert "ingest.py:24" in rendered  # marshal.loads in _archive_restore
    assert "ingest.py:18" not in rendered  # the clean codec call


def test_suppression_comment_silences_surface_rule(mini):
    config = os.path.join(mini, "uigc_tpu", "config.py")
    with open(config, encoding="utf-8") as fh:
        source = fh.read()
    source = source.replace(
        '"uigc.planted.undocumented": 2,',
        '"uigc.planted.undocumented": 2,  # uigc-lint: disable=UC101',
    )
    with open(config, "w", encoding="utf-8") as fh:
        fh.write(source)
    result = _check(mini, ["UC101"])
    assert _by_rule(result, "UC101") == []


# ------------------------------------------------------------------- #
# The refactored linter
# ------------------------------------------------------------------- #


def _load_standalone_lint():
    spec = importlib.util.spec_from_file_location(
        "uigc_lint_for_check_suite", os.path.join(REPO, "tools", "uigc_lint.py")
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_lint_wrapper_and_check_produce_identical_verdicts(tmp_path):
    """Satellite pin: tools/uigc_lint.py is a thin wrapper over the
    shared framework, so it and ``uigc_check --rules UL*`` must render
    byte-identical findings (same rule id, line, message,
    suppression)."""
    path = _plant(
        str(tmp_path),
        "uigc_tpu/engines/thing.py",
        """\
        def apply(entries, n):
            assert len(entries) == n
            assert n >= 0  # uigc-lint: disable=UL004
            return entries
        """,
    )
    lint = _load_standalone_lint()
    standalone = [v.render() for v in lint.lint_paths([path])]
    via_check = [
        d.render()
        for d in cli.run_check([path], rules=["UL*"], repo_root=str(tmp_path))[
            "fresh"
        ]
    ]
    assert standalone == via_check
    assert len(standalone) == 1 and "UL004" in standalone[0]


# ------------------------------------------------------------------- #
# Registry + CONFIG.md round-trip
# ------------------------------------------------------------------- #


def test_registry_schema_is_stable(mini):
    result = _check(mini, None)
    registry = result["registry"]
    assert registry["version"] == 1
    assert set(registry) == {
        "version",
        "config",
        "events",
        "metrics",
        "frames",
        "decoders",
        "schemas",
        "caps",
        "locks",
        "purity",
    }
    knob = registry["config"]["uigc.good.knob"]
    assert knob["default"] == 1
    assert knob["in_defaults"] and knob["documented_guide"]
    assert knob["readers"] and knob["readers"][0].endswith(
        "uigc_tpu/engine.py:2"
    )
    assert registry["frames"]["ping"]["encoders"]
    assert registry["frames"]["ping"]["handlers"]
    assert registry["decoders"]["decode_ping"]["tested"] is False
    assert registry["locks"]["edges"]
    assert registry["purity"]["entries"]
    # The JSON envelope the --json flag emits is versioned too.
    payload = cli._to_json(result, strict=True)
    assert payload["version"] == 1
    assert set(payload) == {
        "version",
        "strict",
        "files",
        "passes",
        "counts",
        "fresh",
        "grandfathered",
    }


def test_write_config_round_trip_clears_drift(mini):
    assert _by_rule(_check(mini, ["UC106"]), "UC106")
    written = cli.run_check(
        [os.path.join(mini, "uigc_tpu")],
        rules=["UC106"],
        repo_root=mini,
        write_config=True,
    )
    assert _by_rule(written, "UC106") == []
    config_md = os.path.join(mini, "CONFIG.md")
    with open(config_md, encoding="utf-8") as fh:
        text = fh.read()
    assert "GENERATED FILE" in text
    assert "`uigc.planted.undocumented`" in text
    # Regenerated is current: the drift finding stays cleared.
    assert _by_rule(_check(mini, ["UC106"]), "UC106") == []


# ------------------------------------------------------------------- #
# Negatives: the repository itself
# ------------------------------------------------------------------- #


def test_repo_is_strict_clean():
    """The acceptance gate: the analyzer's own tree passes --strict
    (every finding it surfaced in this PR was fixed, not allowlisted
    away — the allowlist only carries the pre-existing lint budgets)."""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "uigc_check.py"),
            "--strict",
            os.path.join(REPO, "uigc_tpu"),
            os.path.join(REPO, "tools"),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # All four passes ran (none degraded to SKIP on the real tree).
    assert "SKIP" not in proc.stderr


# ------------------------------------------------------------------- #
# Regression pins for the defects uigc-check surfaced
# ------------------------------------------------------------------- #


def _hist_count(snapshot, name):
    return sum(
        s["value"] for s in snapshot[name]["samples"] if s["suffix"] == "_count"
    )


def test_event_bridge_covers_previously_unbridged_events():
    """uigc-check's first whole-repo run flagged seven committed events
    (UC103) that no telemetry module bridged and no test asserted —
    observability dead ends.  Pin the bridge arms added for them."""
    registry = MetricsRegistry()
    bridge = EventMetricsBridge(registry)
    bridge(events.LINK_HEALED, {"address": "uigc://b"})
    bridge(events.NODE_DRAINING, {"address": "uigc://a"})
    bridge(events.SBR_QUARANTINE, {"entities": 3, "checkpointed": True})
    bridge(
        events.STALE_WINDOW,
        {"peer": "uigc://a", "ingress": "uigc://b", "fence": 1, "log_fence": 2},
    )
    bridge(
        events.DELTA_GRAPH_SERIALIZATION,
        {"shadow_size": 100, "compression_table_size": 28},
    )
    bridge(events.INGRESS_ENTRY_SERIALIZATION, {"size": 64})
    bridge(
        events.ANALYSIS_CHECK,
        {"node": "uigc://a", "n_garbage": 5, "oracle_garbage": 5},
    )
    bridge(
        events.ANALYSIS_CHECK,
        {"node": "uigc://a", "n_garbage": 5, "oracle_garbage": 4},
    )
    assert registry.counter("uigc_link_heals_total").value() == 1
    assert registry.counter("uigc_node_draining_total").value() == 1
    assert (
        registry.counter("uigc_sbr_quarantine_total").value(checkpointed="true")
        == 1
    )
    assert (
        registry.counter("uigc_stale_windows_total").value(peer="uigc://a") == 1
    )
    assert (
        registry.counter("uigc_sanitizer_checks_total").value(divergent="false")
        == 1
    )
    assert (
        registry.counter("uigc_sanitizer_checks_total").value(divergent="true")
        == 1
    )
    snapshot = registry.snapshot()
    assert _hist_count(snapshot, "uigc_delta_graph_bytes") == 1
    assert _hist_count(snapshot, "uigc_ingress_entry_bytes") == 1
