"""Differential test: Pallas-scatter trace vs the numpy oracle.

Random graphs with all the semantic wrinkles — halted nodes, roots,
negative/zero-weight edges, supervisor pointers, free slots — must produce
identical mark vectors (the reference author's dual-graph technique,
reference: ShadowGraph.java:176-199).  On CPU the kernel runs in Pallas
interpret mode; on TPU it compiles for real.
"""

import numpy as np
import pytest

from uigc_tpu.ops import pallas_trace, trace as trace_ops

F = trace_ops


def random_graph(rng, n, n_edges):
    flags = np.zeros(n, dtype=np.uint8)
    in_use = rng.random(n) < 0.9
    flags[in_use] |= F.FLAG_IN_USE
    flags[rng.random(n) < 0.8] |= F.FLAG_INTERNED
    flags[rng.random(n) < 0.1] |= F.FLAG_BUSY
    flags[rng.random(n) < 0.05] |= F.FLAG_ROOT
    flags[rng.random(n) < 0.1] |= F.FLAG_HALTED
    flags[rng.random(n) < 0.7] |= F.FLAG_LOCAL

    recv = np.zeros(n, dtype=np.int64)
    recv[rng.random(n) < 0.15] = rng.integers(-3, 10)

    supervisor = np.full(n, -1, dtype=np.int32)
    sup_mask = rng.random(n) < 0.4
    supervisor[sup_mask] = rng.integers(0, n, size=int(sup_mask.sum()))

    edge_src = rng.integers(0, n, size=n_edges).astype(np.int32)
    edge_dst = rng.integers(0, n, size=n_edges).astype(np.int32)
    edge_weight = rng.integers(-2, 5, size=n_edges).astype(np.int64)
    return flags, recv, supervisor, edge_src, edge_dst, edge_weight


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("n,n_edges", [(50, 120), (300, 900), (1000, 4000)])
def test_pallas_matches_oracle(seed, n, n_edges):
    rng = np.random.default_rng(seed)
    g = random_graph(rng, n, n_edges)
    expected = trace_ops.trace_marks_np(*g)
    got = pallas_trace.trace_marks_pallas(*g)
    assert np.array_equal(got, expected)


@pytest.mark.parametrize("mode", ["push", "pull", "jump", "auto"])
@pytest.mark.parametrize("seed", [0, 1])
def test_trace_modes_match_oracle(seed, mode):
    """Every propagation strategy (uigc.crgc.trace-mode) must produce
    oracle-identical marks over graphs with all the semantic wrinkles —
    the direction-optimizing gates and the pointer jumps are
    accelerations, never semantics."""
    rng = np.random.default_rng(seed)
    g = random_graph(rng, 1500, 6000)
    expected = trace_ops.trace_marks_np(*g)
    got = pallas_trace.trace_marks_pallas(*g, mode=mode)
    assert np.array_equal(got, expected)


def test_jump_collapses_chain_sweeps():
    """The ISSUE-6 acceptance shape: on a long chain (diameter = n) the
    push fixpoint needs O(n) sweeps while pointer-jumping converges in
    O(log n) — and both agree with the oracle.  Sweep counts come from
    the with_stats fixpoint, which is what the wake profiler reports."""
    n = 200
    flags = np.full(n, F.FLAG_IN_USE | F.FLAG_INTERNED, dtype=np.uint8)
    flags[0] |= F.FLAG_ROOT
    recv = np.zeros(n, dtype=np.int64)
    sup = np.full(n, -1, dtype=np.int32)
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    w = np.ones(n - 1, dtype=np.int64)
    expected = trace_ops.trace_marks_np(flags, recv, sup, src, dst, w)
    prep = pallas_trace.prepare_chunks(src, dst, w, sup, n)
    jp = pallas_trace.jump_parents_from_graph(src, dst, w, sup, n)

    push_marks, push_stats = pallas_trace.trace_marks_layouts(
        flags, recv, [prep], mode="push", with_stats=True
    )
    jump_marks, jump_stats = pallas_trace.trace_marks_layouts(
        flags, recv, [prep], mode="jump", jump_parent=jp, with_stats=True
    )
    assert np.array_equal(push_marks, expected)
    assert np.array_equal(jump_marks, expected)
    push_sweeps = int(push_stats["n_sweeps"])
    jump_sweeps = int(jump_stats["n_sweeps"])
    assert push_sweeps >= n - 1  # O(diameter)
    assert jump_sweeps <= 10  # O(log diameter) at JUMP_STEPS=2
    assert jump_sweeps * 6 < push_sweeps


def test_mode_sweep_counts_at_powerlaw_geometry():
    """At the benchmark graph model (powerlaw, the 10M-actor geometry's
    shape at reduced n — sweep counts are hardware-independent and only
    weakly size-dependent) the jump/auto fixpoint must converge in <=6
    sweeps where push needs more."""
    from uigc_tpu.models.graphgen import powerlaw_actor_graph

    n = 1 << 14
    g = powerlaw_actor_graph(n, seed=0, garbage_fraction=0.5)
    prep = pallas_trace.prepare_chunks(
        g["edge_src"].astype(np.int32),
        g["edge_dst"].astype(np.int32),
        g["edge_weight"],
        g["supervisor"],
        n,
    )
    jp = pallas_trace.jump_parents_from_graph(
        g["edge_src"], g["edge_dst"], g["edge_weight"], g["supervisor"], n
    )
    expected = trace_ops.trace_marks_np(
        g["flags"], g["recv_count"], g["supervisor"],
        g["edge_src"], g["edge_dst"], g["edge_weight"],
    )
    sweeps = {}
    for mode in ("push", "auto"):
        marks, stats = pallas_trace.trace_marks_layouts(
            g["flags"], g["recv_count"], [prep], mode=mode,
            jump_parent=jp if mode == "auto" else None, with_stats=True,
        )
        assert np.array_equal(marks, expected), mode
        sweeps[mode] = int(stats["n_sweeps"])
    assert sweeps["auto"] <= 6
    assert sweeps["auto"] < sweeps["push"]


def test_no_edges():
    n = 40
    flags = np.full(n, F.FLAG_IN_USE | F.FLAG_INTERNED, dtype=np.uint8)
    flags[0] |= F.FLAG_ROOT
    recv = np.zeros(n, dtype=np.int64)
    sup = np.full(n, -1, dtype=np.int32)
    e = np.zeros(0, dtype=np.int32)
    w = np.zeros(0, dtype=np.int64)
    expected = trace_ops.trace_marks_np(flags, recv, sup, e, e, w)
    got = pallas_trace.trace_marks_pallas(flags, recv, sup, e, e, w)
    assert np.array_equal(got, expected)


def test_long_chain():
    # A chain forces many fixpoint iterations (diameter = n).
    n = 300
    flags = np.full(n, F.FLAG_IN_USE | F.FLAG_INTERNED, dtype=np.uint8)
    flags[0] |= F.FLAG_ROOT
    recv = np.zeros(n, dtype=np.int64)
    sup = np.full(n, -1, dtype=np.int32)
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    w = np.ones(n - 1, dtype=np.int64)
    expected = trace_ops.trace_marks_np(flags, recv, sup, src, dst, w)
    assert expected.all()
    got = pallas_trace.trace_marks_pallas(flags, recv, sup, src, dst, w)
    assert np.array_equal(got, expected)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("sub,group", [(4, 8), (2, 2), (4, 1), (1, 8)])
def test_wide_geometry_matches_oracle(seed, sub, group):
    """The TPU walk geometry (sub-blocks per grid step, chunks per walk
    iteration) packs and propagates identically to the minimal interpret
    geometry — covered here in interpret mode so a packer/kernel
    geometry bug is caught off-chip too (the compiled tier re-checks the
    wide pair on hardware)."""
    rng = np.random.default_rng(seed)
    flags, recv, supervisor, edge_src, edge_dst, edge_weight = random_graph(
        rng, 2000, 8000
    )
    expected = trace_ops.trace_marks_np(
        flags, recv, supervisor, edge_src, edge_dst, edge_weight
    )
    prep = pallas_trace.prepare_chunks(
        edge_src, edge_dst, edge_weight, supervisor, flags.shape[0],
        s_rows=8, sub=sub, group=group,
    )
    got = pallas_trace.trace_marks_prepared(flags, recv, prep)
    assert np.array_equal(got, expected)


def test_int8_mxu_flag_parity():
    """UIGC_KERNEL_INT8=1 (int8 one-hot contraction, int32 accumulation)
    must produce oracle-identical marks.  The subprocess arm validates
    the env wiring end-to-end (a fresh interpreter with the flag set);
    test_int8_ab_in_process covers the in-process A/B path."""
    import subprocess
    import sys

    _run_int8_subprocess(pin_cpu=True)


def _run_int8_subprocess(pin_cpu: bool):
    import os
    import subprocess
    import sys

    code = """
PIN_CPU
import numpy as np
from uigc_tpu.ops import pallas_trace, trace as trace_ops
assert pallas_trace._int8_mxu(), "int8 flag did not take effect"
import sys
sys.path.insert(0, "tests")
from test_pallas_trace import random_graph
rng = np.random.default_rng(3)
g = random_graph(rng, 1200, 5000)
assert np.array_equal(
    pallas_trace.trace_marks_pallas(*g), trace_ops.trace_marks_np(*g)
)
print("INT8 PARITY OK")
""".replace(
        "PIN_CPU",
        'import jax\njax.config.update("jax_platforms", "cpu")'
        if pin_cpu
        else "",
    )
    env = dict(os.environ, UIGC_KERNEL_INT8="1")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        cwd=str(__import__("pathlib").Path(__file__).resolve().parent.parent),
        timeout=500,
    )
    assert "INT8 PARITY OK" in out.stdout, out.stderr[-2000:]


@pytest.mark.tpu
def test_int8_mxu_compiled_parity():
    """The int8 contraction through the real Mosaic lowering — interpret
    mode cannot catch an int8-dot lowering failure."""
    _run_int8_subprocess(pin_cpu=False)


def test_int8_ab_in_process(monkeypatch):
    """UIGC_KERNEL_INT8 is read at kernel build time and keyed into the
    fn cache, so one process can A/B both MXU datapaths (VERDICT r4
    weak #6: the old import-time read froze the choice per process).
    The contraction is exact in both (operands are 0/1 bits)."""
    import numpy as np

    from uigc_tpu.models.graphgen import powerlaw_actor_graph
    from uigc_tpu.ops import pallas_trace as pt

    n = 1 << 11
    g = powerlaw_actor_graph(n, seed=5, garbage_fraction=0.4)
    prep = pt.prepare_chunks(
        g["edge_src"].astype(np.int32),
        g["edge_dst"].astype(np.int32),
        g["edge_weight"],
        g["supervisor"],
        n,
    )
    marks = {}
    keys_before = len(pt._fn_cache)
    for flag in ("0", "1"):
        monkeypatch.setenv("UIGC_KERNEL_INT8", flag)
        marks[flag] = np.asarray(
            pt.trace_marks_prepared(g["flags"], g["recv_count"], prep)
        )
    assert np.array_equal(marks["0"], marks["1"])
    assert len(pt._fn_cache) >= keys_before + 2  # one kernel per datapath
