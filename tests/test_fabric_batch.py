"""Batched-frame transport interop: the ``"fb"`` multi-frame wire units
(runtime/node.py writer coalescing) crossed with the FaultPlan, the
mixed-version hello negotiation, the no-reorder-within-a-link property,
and the bulk teardown cascade.

These are the contract tests for PR 5's fast path: batching must be
observably ON by default, must preserve every sequence-layer semantics
the chaos suite relies on (gap/duplicate/corrupt accounting, drop
injection per inner frame), must degrade to singleton units against a
peer that never advertised the capability, and must never let a burst
reorder within a link or cost more than one dispatcher batch per
dispatcher on teardown.
"""

import threading
import time

import pytest

from uigc_tpu import ActorSystem
from uigc_tpu.runtime import wire
from uigc_tpu.runtime.behaviors import RawBehavior
from uigc_tpu.runtime.cell import tell_bulk
from uigc_tpu.runtime.dispatcher import TimerService
from uigc_tpu.runtime.faults import FaultPlan
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.utils import events

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.crgc.shadow-graph": "array",
    "uigc.crgc.num-nodes": 2,
}
NO_BATCH = dict(BASE)
NO_BATCH["uigc.node.frame-batching"] = False


class Sink(RawBehavior):
    """Counts ("n", lane, i) payloads and records per-lane order."""

    def __init__(self):
        self.n = 0
        self.got = []
        self.order_violations = 0
        self._last = {}
        self._lock = threading.Lock()

    def on_message(self, msg):
        if isinstance(msg, tuple) and msg and msg[0] == "n":
            with self._lock:
                lane, i = msg[1], msg[2]
                if i <= self._last.get(lane, -1):
                    self.order_violations += 1
                self._last[lane] = i
                self.got.append(i)
                self.n += 1
        return None


class EventLog:
    def __init__(self):
        self.entries = []
        self._lock = threading.Lock()

    def __call__(self, name, fields):
        with self._lock:
            self.entries.append((name, fields))

    def count(self, name):
        with self._lock:
            return sum(1 for n, _ in self.entries if n == name)

    def of(self, name):
        with self._lock:
            return [f for n, f in self.entries if n == name]


@pytest.fixture
def event_log():
    log = EventLog()
    events.recorder.enable()
    events.recorder.add_listener(log)
    yield log
    events.recorder.disable()
    events.recorder.remove_listener(log)
    events.recorder.reset()


class Pair:
    def __init__(self, name, cfg_a=BASE, cfg_b=BASE, plan=None):
        self.fa = NodeFabric(fault_plan=plan)
        self.fb = NodeFabric(fault_plan=plan)
        self.a = ActorSystem(None, name=f"{name}-a", config=cfg_a, fabric=self.fa)
        self.b = ActorSystem(None, name=f"{name}-b", config=cfg_b, fabric=self.fb)
        self.sink = Sink()
        sink_cell = self.b.spawn_system_raw(self.sink, "sink")
        self.fb.register_name("sink", sink_cell)
        port = self.fb.listen()
        self.addr_b = self.fa.connect("127.0.0.1", port)
        self.proxy = self.fa.lookup(self.addr_b, "sink")

    def drive(self, n, lane=0):
        for i in range(n):
            self.proxy.tell(("n", lane, i))

    def settle(self, expected, timeout_s=20.0):
        deadline = time.monotonic() + timeout_s
        while self.sink.n < expected and time.monotonic() < deadline:
            time.sleep(0.01)
        return self.sink.n

    def close(self):
        for system in (self.a, self.b):
            try:
                system.terminate(timeout_s=5.0)
            except Exception:
                pass


# ------------------------------------------------------------------- #
# Wire codec units
# ------------------------------------------------------------------- #


def test_batch_codec_roundtrip():
    frames = [
        (1, ("app", 7, b"payload-bytes")),
        (2, ("app", 7, b"more", (123, 456))),
        (3, ("marker", 42)),
        (4, ("hb",)),
        (5, ("shard", 3, "uigc://x", {1: "uigc://y"})),
    ]
    body = wire.encode_batch(
        (seq, wire.encode_block(inner)) for seq, inner in frames
    )
    assert body[:4] == wire.FB_MAGIC
    decoded = wire.decode_batch(body)
    assert [(s, f) for s, f in decoded] == frames


def test_batch_codec_truncated_block_is_isolated():
    """A truncated inner block decodes to None; its neighbours and the
    batch framing survive."""
    blocks = [
        (1, wire.encode_block(("app", 1, b"x" * 64))),
        (2, wire.encode_block(("app", 2, b"y" * 64), truncate=True)),
        (3, wire.encode_block(("marker", 9))),
    ]
    decoded = wire.decode_batch(wire.encode_batch(blocks))
    assert decoded[0] == (1, ("app", 1, b"x" * 64))
    assert decoded[1] == (2, None)
    assert decoded[2] == (3, ("marker", 9))


def test_batch_codec_never_confused_with_pickle():
    """A pickled singleton body can never alias the batch magic
    (protocol-2+ pickles start with 0x80)."""
    import pickle

    body = pickle.dumps(("f", 1, ("hb",)), protocol=pickle.HIGHEST_PROTOCOL)
    assert body[:4] != wire.FB_MAGIC


def test_app_block_header_roundtrip_and_tolerance():
    block = wire.encode_block(("app", 5, b"pp", (11, 22)))
    assert wire.decode_block(block) == ("app", 5, b"pp", (11, 22))
    # a mangled trailing header is treated as absent, never an error
    assert wire.decode_block(block[:-1] + b"\xff") in (
        ("app", 5, b"pp"),
        ("app", 5, b"pp", (11, 22)),
    )


# ------------------------------------------------------------------- #
# Live-link batching
# ------------------------------------------------------------------- #


def test_batching_on_by_default_and_fifo(event_log):
    pair = Pair("fbdef")
    try:
        st = pair.fa._peer_state(pair.addr_b)
        assert "fb" in st.caps, "peer did not advertise the fb capability"
        pair.drive(3000)
        assert pair.settle(3000) == 3000
        assert pair.sink.order_violations == 0
        assert pair.sink.got == sorted(pair.sink.got)
        # coalescing visibly happened and no seq accidents occurred
        sizes = [f.get("size", 0) for f in event_log.of(events.FRAME_BATCH)]
        assert sizes and max(sizes) > 1
        assert event_log.count(events.FRAME_GAP) == 0
        assert event_log.count(events.FRAME_DUPLICATE) == 0
    finally:
        pair.close()


def test_raw_bytes_message_roundtrips():
    """A user message that IS a bytes object must be pickled like any
    other payload — sniffing isinstance(payload, bytes) as
    "already-encoded" would ship it raw and break the receiver's
    decode."""

    class Capture(RawBehavior):
        def __init__(self):
            self.got = []

        def on_message(self, msg):
            self.got.append(msg)
            return None

    fa = NodeFabric()
    fb = NodeFabric()
    a = ActorSystem(None, name="fbbytes-a", config=BASE, fabric=fa)
    b = ActorSystem(None, name="fbbytes-b", config=BASE, fabric=fb)
    try:
        cap = Capture()
        cap_cell = b.spawn_system_raw(cap, "cap")
        fb.register_name("cap", cap_cell)  # before the hello exchange
        port = fb.listen()
        addr_b = fa.connect("127.0.0.1", port)
        proxy = fa.lookup(addr_b, "cap")
        proxy.tell(b"raw-bytes-message")
        proxy.tell(("n", 0, 1))
        deadline = time.monotonic() + 10
        while len(cap.got) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert cap.got == [b"raw-bytes-message", ("n", 0, 1)]
    finally:
        for system in (a, b):
            try:
                system.terminate(timeout_s=5.0)
            except Exception:
                pass


@pytest.mark.parametrize(
    "cfg_a,cfg_b,label",
    [(BASE, NO_BATCH, "new-to-old"), (NO_BATCH, BASE, "old-to-new")],
)
def test_mixed_version_hello_degrades_to_singletons(
    cfg_a, cfg_b, label, event_log
):
    """A batching peer linked to a non-batching peer (legacy 5-element
    hello) must fall back to singleton units in the direction the
    capability is missing — and deliver everything, in order."""
    pair = Pair(f"fbmx-{label}", cfg_a=cfg_a, cfg_b=cfg_b)
    try:
        st = pair.fa._peer_state(pair.addr_b)
        if cfg_b is NO_BATCH:
            assert "fb" not in st.caps
        pair.drive(1000)
        assert pair.settle(1000) == 1000
        assert pair.sink.order_violations == 0
        # no direction of this link may have produced a batch unit
        assert event_log.count(events.FRAME_BATCH) == 0
        assert event_log.count(events.FRAME_GAP) == 0
    finally:
        pair.close()


def test_fault_plan_inner_frame_semantics(event_log):
    """Seeded drop/duplicate/truncate of individual frames inside the
    batched stream: exact loss accounting (drop + truncate are the only
    loss modes), duplicates discarded by the seq layer, truncation
    surfacing as frame_corrupt + a later gap — all while batching."""
    pair_names = ("uigc://fbfp-a", "uigc://fbfp-b")
    plan = (
        FaultPlan(11)
        .drop(src=pair_names[0], dst=pair_names[1], kind="app", count=7)
        .duplicate(src=pair_names[0], dst=pair_names[1], kind="app", count=6)
        .truncate(src=pair_names[0], dst=pair_names[1], kind="app", count=5)
    )
    pair = Pair("fbfp", plan=plan)
    try:
        n = 1200
        pair.drive(n)
        expected = n - 7 - 5
        assert pair.settle(expected) == expected
        assert pair.sink.order_violations == 0
        assert event_log.count(events.FRAME_DUPLICATE) >= 6
        assert event_log.count(events.FRAME_CORRUPT) >= 5
        # drops + truncations both register as gaps once later frames land
        missed = sum(f.get("missed", 0) for f in event_log.of(events.FRAME_GAP))
        assert missed >= 7
    finally:
        pair.close()


def test_fault_plan_reorder_and_delay_never_reorder_delivery(event_log):
    """Reorder holds and delay stalls inside the batched stream must
    never surface out-of-order messages: the late frame is discarded by
    the seq layer (the documented reorder loss), delayed frames release
    in order."""
    names = ("uigc://fbro-a", "uigc://fbro-b")
    plan = (
        FaultPlan(5)
        .reorder(src=names[0], dst=names[1], kind="app", count=3)
        .delay(src=names[0], dst=names[1], kind="app", count=2, frames=4)
    )
    pair = Pair("fbro", plan=plan)
    try:
        n = 600
        pair.drive(n)
        # Reordered frames are lost at the seq layer (early frame makes
        # a gap, the late one is discarded): at most 3 losses.
        got = pair.settle(n - 3)
        assert got >= n - 3
        assert pair.sink.order_violations == 0
    finally:
        pair.close()


def test_seq_never_reorders_within_link_under_concurrency():
    """Many sender threads, one link: per-lane FIFO must hold end to end
    (the writer assigns sequence numbers in queue order, the receiver
    delivers in seq order)."""
    pair = Pair("fbcc")
    try:
        lanes, per = 4, 500

        def sender(lane):
            for i in range(per):
                pair.proxy.tell(("n", lane, i))

        threads = [
            threading.Thread(target=sender, args=(lane,)) for lane in range(lanes)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert pair.settle(lanes * per) == lanes * per
        assert pair.sink.order_violations == 0
    finally:
        pair.close()


def test_send_frame_failure_surfaces_event(event_log):
    """A frame accepted for a peer whose link breaks surfaces a
    structured fabric.send_failed event instead of a silent True."""
    pair = Pair("fbsf")
    try:
        # Deterministic break: the live conn's flush path raises, so the
        # frame is accepted (True) but dies between queue and wire.
        conn = pair.fa._conn_for(pair.addr_b)

        class _BoomSock:
            def sendall(self, buf):
                raise OSError("injected link break")

            def recv(self, n):
                return b""

            def close(self):
                pass

        conn.sock = _BoomSock()
        accepted = pair.fa.send_frame(pair.addr_b, ("benchf", b"x"))
        assert accepted, "send_frame should accept a frame for a live link"
        deadline = time.monotonic() + 10
        while not event_log.count(events.SEND_FAILED) and time.monotonic() < deadline:
            time.sleep(0.01)
        failed = event_log.of(events.SEND_FAILED)
        assert failed, "no fabric.send_failed event for the broken link"
        assert any(f.get("kind") == "benchf" for f in failed)
        assert all(f.get("dst") == pair.addr_b for f in failed)
    finally:
        pair.close()


# ------------------------------------------------------------------- #
# Bulk teardown
# ------------------------------------------------------------------- #


class _CountingDispatcher:
    """Wraps a dispatcher, counting execute() submissions."""

    def __init__(self, inner):
        self.inner = inner
        self.submissions = 0
        self._lock = threading.Lock()

    def execute(self, runnable):
        with self._lock:
            self.submissions += 1
        self.inner.execute(runnable)


def test_tell_bulk_one_dispatcher_batch_per_kill_set():
    """K killed actors on one dispatcher must cost ONE dispatcher
    submission, not K (the teardown-cascade contract)."""
    system = ActorSystem(None, name="fbtd", config={"uigc.crgc.wakeup-interval": 50})
    try:
        k = 64
        cells = [
            system.spawn_system_raw(Sink(), f"bulk{i}") for i in range(k)
        ]
        # Let the initial batches drain so every cell is unscheduled.
        deadline = time.monotonic() + 10
        while (
            any(c._scheduled for c in cells) and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        counting = _CountingDispatcher(system.dispatcher)
        for cell in cells:
            cell._dispatcher = counting
        submissions = tell_bulk((cell, ("n", 0, 1)) for cell in cells)
        assert submissions == 1
        assert counting.submissions == 1
        deadline = time.monotonic() + 10
        while (
            any(c.behavior.n < 1 for c in cells) and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert all(c.behavior.n == 1 for c in cells)
    finally:
        system.terminate(timeout_s=5.0)


def test_collector_kill_cascade_is_batched_and_complete():
    """End to end: release K actors at once; the collector's sweep must
    stop them all (bulk path) and the system returns to its baseline
    actor count."""
    from uigc_tpu import Behaviors

    class Child:
        def __init__(self, ctx):
            self.context = ctx

        def on_message(self, msg):
            return self

        def on_signal(self, signal):
            return None

    class Root:
        def __init__(self, ctx, k):
            self.context = ctx
            self.children = [
                ctx.spawn(Behaviors.setup(lambda c: Child(c)), f"c{i}")
                for i in range(k)
            ]

        def on_message(self, msg):
            if msg == ("drop",):
                self.context.release(*self.children)
                self.children = []
            return self

        def on_signal(self, signal):
            return None

    system = ActorSystem(
        None,
        name="fbkc",
        config={"uigc.crgc.wakeup-interval": 10, "uigc.crgc.shadow-graph": "array"},
    )
    try:
        k = 120
        root = system.spawn_root(
            Behaviors.setup_root(lambda ctx: Root(ctx, k)), "root"
        )
        deadline = time.monotonic() + 20
        while system.live_actor_count < k and time.monotonic() < deadline:
            time.sleep(0.01)
        base = system.live_actor_count - k
        root.tell(("drop",))
        while system.live_actor_count > base and time.monotonic() < deadline:
            time.sleep(0.01)
        assert system.live_actor_count == base, (
            f"{system.live_actor_count - base} released actors survived"
        )
    finally:
        system.terminate(timeout_s=5.0)


# ------------------------------------------------------------------- #
# TimerService satellite: exact deadlines, no idle polling
# ------------------------------------------------------------------- #


def test_timer_service_fires_at_deadline_without_polling():
    timers = TimerService(name="fbtm")
    try:
        fired = []
        t0 = time.monotonic()
        timers.schedule_once(0.15, lambda: fired.append(time.monotonic() - t0))
        deadline = time.monotonic() + 5
        while not fired and time.monotonic() < deadline:
            time.sleep(0.005)
        assert fired, "timer never fired"
        assert 0.13 <= fired[0] <= 0.6
        # an idle service accepts new work after sleeping unbounded
        fired2 = []
        timers.schedule_once(0.05, lambda: fired2.append(True))
        deadline = time.monotonic() + 5
        while not fired2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert fired2, "timer scheduled onto an idle service never fired"
    finally:
        timers.shutdown()


def test_timer_service_far_deadline_preempted_by_near_one():
    timers = TimerService(name="fbtm2")
    try:
        order = []
        timers.schedule_once(30.0, lambda: order.append("far"))
        timers.schedule_once(0.05, lambda: order.append("near"))
        deadline = time.monotonic() + 5
        while not order and time.monotonic() < deadline:
            time.sleep(0.005)
        assert order == ["near"]
    finally:
        timers.shutdown()
