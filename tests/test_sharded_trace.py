"""Multi-device trace parity: the shard_map kernel must agree with the
single-host kernel on an 8-device virtual CPU mesh."""

import numpy as np
import pytest

from uigc_tpu.models import powerlaw_actor_graph, ring_graph
from uigc_tpu.ops import trace as trace_ops
from uigc_tpu.parallel import build_mesh, make_sharded_trace, shard_graph


@pytest.mark.parametrize(
    "graph",
    [
        powerlaw_actor_graph(4000, seed=3, garbage_fraction=0.4),
        ring_graph(n_rings=20, ring_size=13, live=False),
        ring_graph(n_rings=20, ring_size=13, live=True),
    ],
    ids=["powerlaw", "rings-garbage", "rings-live"],
)
def test_sharded_matches_host(graph):
    import jax

    n_devices = min(8, len(jax.devices()))
    mark_host = trace_ops.trace_marks_np(
        graph["flags"],
        graph["recv_count"],
        graph["supervisor"],
        graph["edge_src"],
        graph["edge_dst"],
        graph["edge_weight"],
    )

    packed = shard_graph(graph, n_devices)
    mesh = build_mesh(n_devices)
    traced = make_sharded_trace(mesh)
    mark_sharded = np.asarray(
        traced(
            packed["flags"],
            packed["recv_count"],
            packed["pair_src"],
            packed["pair_dst"],
        )
    )[: graph["flags"].shape[0]]

    assert np.array_equal(mark_host, mark_sharded)
    # And the generator's intended garbage is exactly the unmarked in-use set.
    in_use = (graph["flags"] & trace_ops.FLAG_IN_USE) != 0
    assert np.array_equal(in_use & ~mark_host, graph["expected_garbage"])
