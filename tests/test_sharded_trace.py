"""Multi-device trace parity: the shard_map kernel must agree with the
single-host kernel on an 8-device virtual CPU mesh."""

import numpy as np
import pytest

from uigc_tpu.models import powerlaw_actor_graph, ring_graph
from uigc_tpu.ops import trace as trace_ops
from uigc_tpu.parallel import build_mesh, make_sharded_trace, shard_graph


@pytest.mark.parametrize(
    "graph",
    [
        powerlaw_actor_graph(4000, seed=3, garbage_fraction=0.4),
        ring_graph(n_rings=20, ring_size=13, live=False),
        ring_graph(n_rings=20, ring_size=13, live=True),
    ],
    ids=["powerlaw", "rings-garbage", "rings-live"],
)
def test_sharded_matches_host(graph):
    import jax

    n_devices = min(8, len(jax.devices()))
    mark_host = trace_ops.trace_marks_np(
        graph["flags"],
        graph["recv_count"],
        graph["supervisor"],
        graph["edge_src"],
        graph["edge_dst"],
        graph["edge_weight"],
    )

    packed = shard_graph(graph, n_devices)
    mesh = build_mesh(n_devices)
    traced = make_sharded_trace(mesh)
    mark_sharded = np.asarray(
        traced(
            packed["flags"],
            packed["recv_count"],
            packed["pair_src"],
            packed["pair_dst"],
        )
    )[: graph["flags"].shape[0]]

    assert np.array_equal(mark_host, mark_sharded)
    # And the generator's intended garbage is exactly the unmarked in-use set.
    in_use = (graph["flags"] & trace_ops.FLAG_IN_USE) != 0
    assert np.array_equal(in_use & ~mark_host, graph["expected_garbage"])


@pytest.mark.parametrize(
    "seed,mode",
    [(0, "push"), (1, "push"), (0, "pull"), (0, "jump"), (0, "auto")],
)
def test_sharded_pallas_matches_host(seed, mode):
    """The per-shard Pallas layout plane (packed base + insert buckets)
    must agree with the host oracle on the virtual mesh, under every
    propagation strategy (jump modes take the replicated jump-parent
    operand; pull modes skip saturated local supertiles)."""
    import jax

    from uigc_tpu.ops import pallas_incremental as pinc
    from uigc_tpu.parallel import make_sharded_pallas_trace, pack_shard_layouts

    n_devices = min(8, len(jax.devices()))
    s_rows = 8  # 1024-node supertiles: shards span several at this n
    rng = np.random.default_rng(seed)
    graph = powerlaw_actor_graph(20_000, seed=seed, garbage_fraction=0.4)
    n = graph["flags"].shape[0]
    mark_host = trace_ops.trace_marks_np(
        graph["flags"],
        graph["recv_count"],
        graph["supervisor"],
        graph["edge_src"],
        graph["edge_dst"],
        graph["edge_weight"],
    )

    super_sz = s_rows * 128
    chunk = n_devices * super_sz
    n_pad = ((n + chunk - 1) // chunk) * chunk
    flags = np.zeros(n_pad, np.uint8)
    flags[:n] = graph["flags"]
    recv = np.zeros(n_pad, np.int64)
    recv[:n] = graph["recv_count"]

    psrc, pdst, kinds = pinc.IncrementalPallasLayout.pairs_from_graph(
        graph["edge_src"], graph["edge_dst"], graph["edge_weight"],
        graph["supervisor"],
    )
    # hold back a slice of pairs as "inserts" riding the bucket tier
    cut = psrc.size // 10
    order = rng.permutation(psrc.size)
    base_idx, ins_idx = order[cut:], order[:cut]

    stacked, meta, slot_vals = pack_shard_layouts(
        psrc[base_idx], pdst[base_idx], n_pad, n_devices, s_rows=s_rows
    )

    shard_size = meta["shard_size"]
    owner = pdst[ins_idx] // shard_size
    counts = np.bincount(owner, minlength=n_devices)
    m = max(64, int(counts.max(initial=1)))
    bsrc = np.full((n_devices, m), n_pad, np.int32)
    bdst = np.zeros((n_devices, m), np.int32)
    starts = np.zeros(n_devices, np.int64)
    starts[1:] = np.cumsum(counts)[:-1]
    so = np.argsort(owner, kind="stable")
    col = np.arange(ins_idx.size) - starts[owner[so]]
    bsrc[owner[so], col] = psrc[ins_idx][so]
    bdst[owner[so], col] = (pdst[ins_idx][so] - owner[so] * shard_size)

    mesh = build_mesh(n_devices)
    traced = make_sharded_pallas_trace(
        mesh,
        meta["n_pad"],
        shard_size,
        meta["n_blocks"],
        meta["r_rows"],
        s_rows,
        m,
        sub=meta["sub"],
        group=meta["group"],
        mode=mode,
    )
    from uigc_tpu.ops import pallas_trace as pt

    jump = (
        (pt.jump_parents(psrc, pdst, n_pad),)
        if mode in (pt.MODE_JUMP, pt.MODE_AUTO)
        else ()
    )
    mark = np.asarray(
        traced(
            flags,
            recv,
            stacked["bmeta1"],
            stacked["bmeta2"],
            stacked["row_pos"],
            stacked["emeta"],
            bsrc,
            bdst,
            *jump,
        )
    )[:n]
    assert np.array_equal(mark, mark_host)


@pytest.mark.parametrize("mode", ["push", "auto"])
def test_sharded_decremental_wakes(mode):
    """The closure+repair wake on the virtual mesh: flag churn (halts,
    de-seeding, frees, slots coming alive) and bucket-tier edge churn
    across wakes, each diffed against the from-scratch host oracle.  A
    zeroed previous state is the cold start.  ``auto`` additionally
    exercises the replicated jump-parent operand maintained across
    wakes exactly as the mesh backend does (min-fold on insert,
    invalidate on delete)."""
    import jax

    from uigc_tpu.ops import pallas_incremental as pinc
    from uigc_tpu.ops import pallas_trace as pt
    from uigc_tpu.parallel import (
        make_sharded_decremental_wake,
        pack_shard_layouts,
    )

    n_devices = min(8, len(jax.devices()))
    s_rows = 8
    rng = np.random.default_rng(5)
    graph = powerlaw_actor_graph(20_000, seed=5, garbage_fraction=0.4)
    n = graph["flags"].shape[0]

    super_sz = s_rows * 128
    chunk = n_devices * super_sz
    n_pad = ((n + chunk - 1) // chunk) * chunk
    flags = np.zeros(n_pad, np.uint8)
    flags[:n] = graph["flags"]
    recv = np.zeros(n_pad, np.int64)
    recv[:n] = graph["recv_count"]

    psrc, pdst, kinds = pinc.IncrementalPallasLayout.pairs_from_graph(
        graph["edge_src"], graph["edge_dst"], graph["edge_weight"],
        graph["supervisor"],
    )
    stacked, meta, slot_vals = pack_shard_layouts(
        psrc, pdst, n_pad, n_devices, s_rows=s_rows
    )
    shard_size = meta["shard_size"]
    m = 64  # bucket columns per shard
    bsrc = np.full((n_devices, m), n_pad, np.int32)
    bdst = np.zeros((n_devices, m), np.int32)
    bcount = np.zeros(n_devices, np.int64)

    wake = make_sharded_decremental_wake(
        mesh=build_mesh(n_devices),
        n_pad=n_pad,
        shard_size=shard_size,
        n_blocks=meta["n_blocks"],
        r_rows=meta["r_rows"],
        s_rows=s_rows,
        bucket_m=m,
        sub=meta["sub"],
        group=meta["group"],
        mode=mode,
    )
    use_jump = mode in (pt.MODE_JUMP, pt.MODE_AUTO)
    jp = pt.jump_parents(psrc, pdst, n_pad) if use_jump else None

    n_words = n_pad // 32
    zeros_w = np.zeros(n_words, np.int32)
    state = [zeros_w] * 5  # mark, seed, halted, iu, active
    live_pairs = list(zip(psrc.tolist(), pdst.tolist()))
    bucket_pairs = []

    def oracle():
        allp = live_pairs + bucket_pairs
        s = np.array([p[0] for p in allp], np.int32)
        d = np.array([p[1] for p in allp], np.int32)
        return trace_ops.trace_marks_np(
            flags[:n], recv[:n], np.full(n, -1, np.int32),
            s, d, np.ones(len(allp), np.int64),
        )

    def words_of(ids):
        w = np.zeros(n_words, np.uint32)
        ids = np.asarray(sorted(set(ids)), np.int64)
        if ids.size:
            np.bitwise_or.at(
                w, ids >> 5, np.uint32(1) << (ids & 31).astype(np.uint32)
            )
        return w.view(np.int32)

    def run_wake(del_ids, fresh_ids):
        nonlocal state
        out = wake(
            flags, recv, words_of(del_ids), words_of(fresh_ids),
            *state,
            stacked["bmeta1"], stacked["bmeta2"],
            stacked["row_pos"], stacked["emeta"],
            bsrc, bdst,
            *((jp,) if use_jump else ()),
        )
        mark = np.asarray(out[0])[:n]
        state = [np.asarray(o) for o in out[1:]]
        return mark

    # cold start = full derivation
    assert np.array_equal(run_wake([], []), oracle())

    for wk in range(3):
        del_ids, fresh_ids = [], []
        # flag churn
        for _ in range(20):
            i = int(rng.integers(0, n))
            r = rng.random()
            if r < 0.3:
                flags[i] |= trace_ops.FLAG_HALTED
            elif r < 0.5:
                flags[i] ^= trace_ops.FLAG_BUSY
            elif r < 0.7:
                recv[i] = 0 if recv[i] else 2
            elif r < 0.85:
                flags[i] = 0  # freed
            else:
                flags[i] = trace_ops.FLAG_IN_USE | trace_ops.FLAG_INTERNED
        # bucket-tier inserts (fresh pairs)
        for _ in range(10):
            s_, d_ = int(rng.integers(0, n)), int(rng.integers(0, n))
            sh = d_ // shard_size
            c = int(bcount[sh])
            if c >= m or (s_, d_) in bucket_pairs:
                continue
            bsrc[sh, c] = s_
            bdst[sh, c] = d_ - sh * shard_size
            bcount[sh] = c + 1
            bucket_pairs.append((s_, d_))
            fresh_ids.append(d_)
            if use_jump and s_ < jp[d_]:  # min-fold, as the mesh backend
                jp[d_] = s_
        # base-layout deletions via in-place slot masking
        for _ in range(10):
            j = int(rng.integers(0, len(live_pairs)))
            if live_pairs[j] is None:
                continue
            s_, d_ = live_pairs[j]
            live_pairs[j] = None
            if use_jump and jp[d_] == s_:  # invalidate, as the mesh backend
                jp[d_] = n_pad
            sv = int(slot_vals[j])
            sh, ri, col = sv >> 40, (sv >> 8) & 0xFFFFFFFF, sv & 0xFF
            stacked["row_pos"][sh, ri, col] = pt._PAD_ROW
            stacked["emeta"][sh, ri, col] = 0
            del_ids.append(d_)
        # live_pairs keeps None holes so slot_vals indices stay stable
        live_pairs_c = [p for p in live_pairs if p is not None]

        got = run_wake(del_ids, fresh_ids)
        allp = live_pairs_c + bucket_pairs
        s = np.array([p[0] for p in allp], np.int32)
        d = np.array([p[1] for p in allp], np.int32)
        expected = trace_ops.trace_marks_np(
            flags[:n], recv[:n], np.full(n, -1, np.int32),
            s, d, np.ones(len(allp), np.int64),
        )
        assert np.array_equal(got, expected), (
            f"wake {wk}: {int((got != expected).sum())} mismatches"
        )
