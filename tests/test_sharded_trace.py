"""Multi-device trace parity: the shard_map kernel must agree with the
single-host kernel on an 8-device virtual CPU mesh."""

import numpy as np
import pytest

from uigc_tpu.models import powerlaw_actor_graph, ring_graph
from uigc_tpu.ops import trace as trace_ops
from uigc_tpu.parallel import build_mesh, make_sharded_trace, shard_graph


@pytest.mark.parametrize(
    "graph",
    [
        powerlaw_actor_graph(4000, seed=3, garbage_fraction=0.4),
        ring_graph(n_rings=20, ring_size=13, live=False),
        ring_graph(n_rings=20, ring_size=13, live=True),
    ],
    ids=["powerlaw", "rings-garbage", "rings-live"],
)
def test_sharded_matches_host(graph):
    import jax

    n_devices = min(8, len(jax.devices()))
    mark_host = trace_ops.trace_marks_np(
        graph["flags"],
        graph["recv_count"],
        graph["supervisor"],
        graph["edge_src"],
        graph["edge_dst"],
        graph["edge_weight"],
    )

    packed = shard_graph(graph, n_devices)
    mesh = build_mesh(n_devices)
    traced = make_sharded_trace(mesh)
    mark_sharded = np.asarray(
        traced(
            packed["flags"],
            packed["recv_count"],
            packed["pair_src"],
            packed["pair_dst"],
        )
    )[: graph["flags"].shape[0]]

    assert np.array_equal(mark_host, mark_sharded)
    # And the generator's intended garbage is exactly the unmarked in-use set.
    in_use = (graph["flags"] & trace_ops.FLAG_IN_USE) != 0
    assert np.array_equal(in_use & ~mark_host, graph["expected_garbage"])


@pytest.mark.parametrize("seed", [0, 1])
def test_sharded_pallas_matches_host(seed):
    """The per-shard Pallas layout plane (packed base + insert buckets)
    must agree with the host oracle on the virtual mesh."""
    import jax

    from uigc_tpu.ops import pallas_incremental as pinc
    from uigc_tpu.parallel import make_sharded_pallas_trace, pack_shard_layouts

    n_devices = min(8, len(jax.devices()))
    s_rows = 8  # 1024-node supertiles: shards span several at this n
    rng = np.random.default_rng(seed)
    graph = powerlaw_actor_graph(20_000, seed=seed, garbage_fraction=0.4)
    n = graph["flags"].shape[0]
    mark_host = trace_ops.trace_marks_np(
        graph["flags"],
        graph["recv_count"],
        graph["supervisor"],
        graph["edge_src"],
        graph["edge_dst"],
        graph["edge_weight"],
    )

    super_sz = s_rows * 128
    chunk = n_devices * super_sz
    n_pad = ((n + chunk - 1) // chunk) * chunk
    flags = np.zeros(n_pad, np.uint8)
    flags[:n] = graph["flags"]
    recv = np.zeros(n_pad, np.int64)
    recv[:n] = graph["recv_count"]

    psrc, pdst, kinds = pinc.IncrementalPallasLayout.pairs_from_graph(
        graph["edge_src"], graph["edge_dst"], graph["edge_weight"],
        graph["supervisor"],
    )
    # hold back a slice of pairs as "inserts" riding the bucket tier
    cut = psrc.size // 10
    order = rng.permutation(psrc.size)
    base_idx, ins_idx = order[cut:], order[:cut]

    stacked, meta, slot_vals = pack_shard_layouts(
        psrc[base_idx], pdst[base_idx], n_pad, n_devices, s_rows=s_rows
    )

    shard_size = meta["shard_size"]
    owner = pdst[ins_idx] // shard_size
    counts = np.bincount(owner, minlength=n_devices)
    m = max(64, int(counts.max(initial=1)))
    bsrc = np.full((n_devices, m), n_pad, np.int32)
    bdst = np.zeros((n_devices, m), np.int32)
    starts = np.zeros(n_devices, np.int64)
    starts[1:] = np.cumsum(counts)[:-1]
    so = np.argsort(owner, kind="stable")
    col = np.arange(ins_idx.size) - starts[owner[so]]
    bsrc[owner[so], col] = psrc[ins_idx][so]
    bdst[owner[so], col] = (pdst[ins_idx][so] - owner[so] * shard_size)

    mesh = build_mesh(n_devices)
    traced = make_sharded_pallas_trace(
        mesh,
        meta["n_pad"],
        shard_size,
        meta["n_blocks"],
        meta["r_rows"],
        s_rows,
        m,
        sub=meta["sub"],
        group=meta["group"],
    )
    mark = np.asarray(
        traced(
            flags,
            recv,
            stacked["bmeta1"],
            stacked["bmeta2"],
            stacked["row_pos"],
            stacked["emeta"],
            bsrc,
            bdst,
        )
    )[:n]
    assert np.array_equal(mark, mark_host)
