"""Partition tolerance: split-brain resolution, fencing, heal-time merge.

Covers the PR 13 partition plane end to end:

- fault layer: asymmetric (one-way) partitions and scheduled heals
  (``FaultPlan.partition(oneway=True)`` / ``heal_after``);
- arbiter units: every strategy (keep-majority incl. the 50/50
  tie-break, static-quorum, keep-oldest via merged join stamps,
  down-all) reaching COMPLEMENTARY verdicts on both halves, and the
  below-``sbr-min-members`` legacy escape;
- fencing units: fence-first shard-table ordering, the ``mship``
  handshake codec (tolerant both directions), journal records carrying
  fences with the recovery-time conflict rule (lower-fence records
  that claim to supersede a higher-fence base are quarantined, plain
  history replays), and the frozen append plane refusing stale writes;
- chaos matrix (3-node NodeFabric clusters under traffic): symmetric,
  asymmetric and flapping partitions x SBR strategies, asserting that
  exactly ONE side serves each shard, the loser quarantines (drained
  to the journal, zero active entities), the uigcsan sanitizer stays
  clean on the survivors, and — after the heal — the rejoined peer
  re-enters placement with every key answering at full count.
"""

import threading
import time

import pytest

from uigc_tpu import ActorSystem, ClusterSharding, Entity
from uigc_tpu.cluster.journal import EntityJournal
from uigc_tpu.cluster.membership import MembershipArbiter
from uigc_tpu.cluster.sharding import ShardTable
from uigc_tpu.runtime import wire
from uigc_tpu.runtime.behaviors import RawBehavior
from uigc_tpu.runtime.faults import FaultPlan
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.utils import events

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.crgc.shadow-graph": "array",
    "uigc.cluster.tick-interval": 40,
    "uigc.cluster.handoff-retry": 120,
    "uigc.cluster.sbr-settle": 150,
    "uigc.node.heartbeat-interval": 40,
    # Lenient detector: the tier-1 suite runs these 3-node chaos tests
    # on a fully loaded host, where scheduler stalls of several hundred
    # ms are routine — a tight pause turns them into false verdicts
    # that cascade into spurious splits before the scripted one.
    "uigc.node.phi-threshold": 6.0,
    "uigc.node.heartbeat-pause": 700,
    "uigc.analysis.sanitizer": True,
}


def settle(predicate, timeout_s=25.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


class EventLog:
    def __init__(self):
        self.entries = []
        self._lock = threading.Lock()

    def __call__(self, name, fields):
        with self._lock:
            self.entries.append((name, fields))

    def of(self, name):
        with self._lock:
            return [f for n, f in self.entries if n == name]


@pytest.fixture
def event_log():
    log = EventLog()
    events.recorder.enable()
    events.recorder.add_listener(log)
    yield log
    events.recorder.disable()
    events.recorder.remove_listener(log)
    events.recorder.reset()


class Counter(Entity):
    def __init__(self, ctx, key, state):
        super().__init__(ctx, key)
        self.count = (state or {}).get("count", 0)

    def receive(self, msg):
        if msg[0] == "incr":
            self.count += 1
        elif msg[0] == "probe":
            msg[1].tell(("probed", self.key, self.count))
        return self

    def snapshot_state(self):
        return {"count": self.count}


def counter_factory(ctx, key, state):
    return Counter(ctx, key, state)


class Collector(RawBehavior):
    def __init__(self):
        self.got = {}
        self._lock = threading.Lock()

    def on_message(self, msg):
        if isinstance(msg, tuple) and msg and msg[0] == "probed":
            with self._lock:
                self.got[msg[1]] = msg[2]
        return None

    def snapshot(self):
        with self._lock:
            return dict(self.got)


class Node:
    __slots__ = ("fabric", "system", "cluster", "region", "port", "address")

    def __init__(self, name, config, plan=None):
        self.fabric = NodeFabric(fault_plan=plan)
        self.system = ActorSystem(None, name=name, config=config, fabric=self.fabric)
        self.port = self.fabric.listen()
        self.address = self.system.address
        self.cluster = ClusterSharding.attach(self.system)
        self.region = self.cluster.start("counter", counter_factory)


def build_cluster(names, plan=None, overrides=None, join_gap_s=0.0):
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = len(names)
    if overrides:
        config.update(overrides)
    nodes = []
    for name in names:
        nodes.append(Node(name, config, plan))
        if join_gap_s:
            time.sleep(join_gap_s)  # distinct keep-oldest join stamps
    return nodes


def connect_mesh(nodes):
    for i, a in enumerate(nodes):
        for b in nodes[i + 1 :]:
            a.fabric.connect("127.0.0.1", b.port)


def terminate_all(nodes):
    for n in nodes:
        try:
            n.system.terminate(timeout_s=5.0)
        except Exception:
            pass


def sanitizer_violations(node):
    san = getattr(node.system, "sanitizer", None)
    return list(san.violations) if san is not None else []


# ------------------------------------------------------------------- #
# Fault layer: one-way cuts + scheduled heals
# ------------------------------------------------------------------- #


def test_oneway_partition_drops_single_direction():
    plan = FaultPlan(7)
    plan.partition("uigc://a", "uigc://b", oneway=True)
    assert plan.outbound("uigc://a", "uigc://b", "app")[0] == "drop"
    assert plan.outbound("uigc://b", "uigc://a", "app")[0] == "deliver"
    # inbound verdicts agree with outbound ones
    assert plan.drop_inbound("uigc://a", "uigc://b", object())
    assert not plan.drop_inbound("uigc://b", "uigc://a", object())
    plan.heal("uigc://a", "uigc://b")
    assert plan.outbound("uigc://a", "uigc://b", "app")[0] == "deliver"


def test_oneway_isolate_silences_only_outbound():
    plan = FaultPlan(7)
    plan.isolate("uigc://c", oneway=True)
    assert plan.outbound("uigc://c", "uigc://a", "hb")[0] == "drop"
    assert plan.outbound("uigc://a", "uigc://c", "hb")[0] == "deliver"
    plan.heal("uigc://c", "*")
    assert plan.outbound("uigc://c", "uigc://a", "hb")[0] == "deliver"


def test_heal_after_schedules_mend():
    plan = FaultPlan(7)
    plan.partition("uigc://a", "uigc://b")
    plan.partition("uigc://a", "uigc://c", oneway=True)
    plan.heal_after(0.08)
    assert plan.outbound("uigc://a", "uigc://b", "app")[0] == "drop"
    time.sleep(0.1)
    # the due heal applies lazily on the next check, both cut kinds
    assert plan.outbound("uigc://a", "uigc://b", "app")[0] == "deliver"
    assert plan.outbound("uigc://a", "uigc://c", "app")[0] == "deliver"


def test_heal_after_specific_pair_leaves_other_cuts():
    plan = FaultPlan(7)
    plan.partition("uigc://a", "uigc://b")
    plan.partition("uigc://a", "uigc://c")
    plan.heal_after(0.05, "uigc://a", "uigc://b")
    time.sleep(0.08)
    assert plan.outbound("uigc://a", "uigc://b", "app")[0] == "deliver"
    assert plan.outbound("uigc://a", "uigc://c", "app")[0] == "drop"


# ------------------------------------------------------------------- #
# Arbiter units: complementary verdicts per strategy
# ------------------------------------------------------------------- #


def _halves(strategy, members, cut, **kw):
    """Build one arbiter per member, feed each side the other half's
    unreachability, and return {address: decision}."""
    arbiters = {}
    stamps = {}
    for i, address in enumerate(members):
        arb = MembershipArbiter(address, strategy=strategy, settle_s=0.01, **kw)
        arbiters[address] = arb
        stamps[address] = 1000 + i  # join order = seniority
    for address, arb in arbiters.items():
        for peer in members:
            if peer != address:
                arb.on_member_up(peer)
        arb.merge_stamps(stamps)
    decisions = {}
    for address, arb in arbiters.items():
        my_side = cut[0] if address in cut[0] else cut[1]
        other = cut[1] if address in cut[0] else cut[0]
        for peer in other:
            assert arb.track_unreachable(peer)
        time.sleep(0.02)
        decisions[address] = arb.poll()
        assert decisions[address] is not None, (strategy, address, my_side)
    return arbiters, decisions


def test_keep_majority_complementary_verdicts():
    members = ["uigc://a", "uigc://b", "uigc://c"]
    arbiters, decisions = _halves(
        "keep-majority", members, ({"uigc://a", "uigc://b"}, {"uigc://c"})
    )
    assert decisions["uigc://a"].survived and decisions["uigc://b"].survived
    assert not decisions["uigc://c"].survived
    assert arbiters["uigc://c"].quarantined
    # survivors bumped the fence, the loser froze
    assert arbiters["uigc://a"].fence == arbiters["uigc://b"].fence == 1
    assert arbiters["uigc://c"].fence == 0


def test_keep_majority_tie_keeps_lowest_address_side():
    members = ["uigc://aa", "uigc://ab", "uigc://ba", "uigc://bb"]
    _arb, decisions = _halves(
        "keep-majority",
        members,
        ({"uigc://aa", "uigc://ab"}, {"uigc://ba", "uigc://bb"}),
    )
    assert decisions["uigc://aa"].survived and decisions["uigc://ab"].survived
    assert not decisions["uigc://ba"].survived
    assert not decisions["uigc://bb"].survived


def test_static_quorum_strategy():
    members = ["uigc://a", "uigc://b", "uigc://c"]
    _arb, decisions = _halves(
        "static-quorum",
        members,
        ({"uigc://a", "uigc://b"}, {"uigc://c"}),
        quorum_size=2,
    )
    assert decisions["uigc://a"].survived
    assert not decisions["uigc://c"].survived
    # an unreachable quorum downs EVERY side
    _arb, decisions = _halves(
        "static-quorum",
        members,
        ({"uigc://a", "uigc://b"}, {"uigc://c"}),
        quorum_size=3,
    )
    assert not any(d.survived for d in decisions.values())


def test_keep_oldest_survives_even_in_minority():
    members = ["uigc://x", "uigc://y", "uigc://z"]
    # uigc://x has the earliest merged stamp: its SIDE survives even as
    # the 1-of-3 minority.
    _arb, decisions = _halves(
        "keep-oldest", members, ({"uigc://x"}, {"uigc://y", "uigc://z"})
    )
    assert decisions["uigc://x"].survived
    assert not decisions["uigc://y"].survived
    assert not decisions["uigc://z"].survived


def test_down_all_downs_every_side():
    members = ["uigc://a", "uigc://b", "uigc://c"]
    arbiters, decisions = _halves(
        "down-all", members, ({"uigc://a", "uigc://b"}, {"uigc://c"})
    )
    assert not any(d.survived for d in decisions.values())
    assert all(a.quarantined for a in arbiters.values())


def test_below_min_members_is_not_arbitrated():
    arb = MembershipArbiter("uigc://a", settle_s=0.01, min_members=3)
    arb.on_member_up("uigc://b")
    # 2-node topology: majority undefined — the verdict is immediate
    # (legacy availability semantics), never deferred or quarantined.
    assert not arb.track_unreachable("uigc://b")
    assert not arb.quarantined and arb.fence == 0


def test_flap_heal_before_settle_cancels_verdict():
    arb = MembershipArbiter("uigc://a", settle_s=0.2)
    arb.on_member_up("uigc://b")
    arb.on_member_up("uigc://c")
    assert arb.track_unreachable("uigc://c")
    # the peer reconnects before the settle window expires
    assert arb.on_member_up("uigc://c")
    time.sleep(0.25)
    assert arb.poll() is None
    assert arb.fence == 0 and not arb.quarantined


# ------------------------------------------------------------------- #
# Fencing units
# ------------------------------------------------------------------- #


def test_shard_table_fence_orders_before_lamport_pair():
    low = ShardTable(99, "uigc://a", {1: "uigc://a"}, fence=0)
    high = ShardTable(1, "uigc://b", {1: "uigc://b"}, fence=1)
    assert high.supersedes(low)
    assert not low.supersedes(high)
    # equal fences fall back to the (version, origin) lamport order
    v2 = ShardTable(2, "uigc://a", {1: "uigc://a"}, fence=1)
    assert v2.supersedes(high)


def test_mship_codec_round_trip_and_tolerance():
    frame = wire.encode_mship(
        "uigc://a", 3, ["uigc://a", "uigc://b"], {"uigc://a": 17}, True, 9
    )
    doc = wire.decode_mship(frame)
    assert doc["fence"] == 3
    assert doc["members"] == ["uigc://a", "uigc://b"]
    assert doc["stamps"] == {"uigc://a": 17}
    assert doc["quarantined"] is True
    # trailing elements tolerated; malformed payloads -> None
    assert wire.decode_mship(frame + ("future",))["fence"] == 3
    assert wire.decode_mship(("mship", "uigc://a", b"not json")) is None
    assert wire.decode_mship(("mship", "uigc://a", "not-bytes")) is None
    # grants carry fences, old 3-element grants decode as fence 0
    assert wire.decode_shard_grant(wire.encode_shard_grant(4, "uigc://a", 2)) == (
        4,
        "uigc://a",
        2,
    )
    assert wire.decode_shard_grant(("sgrant", 4, "uigc://a")) == (4, "uigc://a", 0)


def test_journal_fence_conflict_quarantined_not_merged(tmp_path, event_log):
    """The heal-time merge rule: a minority's post-partition records
    (lower fence, epochs reaching the survivor's base) are quarantined
    out of the replay; its plain pre-partition history replays."""
    shared = str(tmp_path)
    minority = EntityJournal(shared, "uigc://min", fsync="never")
    # pre-partition history at fence 0
    epoch0 = minority.open_epoch("t", 1, "k", b"base-state")
    minority.note_command("t", 1, "k", b"old-cmd")
    minority.checkpoint()
    # the survivor inherits the shard, bumps its fence, and activates —
    # its hybrid-logical epoch supersedes everything it SAW
    survivor = EntityJournal(shared, "uigc://maj", fsync="never")
    survivor.set_fence(1)
    epoch1 = survivor.open_epoch("t", 1, "k", b"survivor-state")
    assert epoch1 > epoch0
    survivor.note_command("t", 1, "k", b"survivor-cmd")
    survivor.checkpoint()
    # meanwhile the partitioned minority keeps appending under fence 0
    # with WALL-CLOCK epochs that overtake the survivor's numbers
    minority.begin_snapshot("t", 1, "k")
    minority.commit_snapshot(
        "t", 1, "k", minority._live[("t", "k")][0], b"divergent-state"
    )
    minority.note_command("t", 1, "k", b"divergent-cmd")
    minority.checkpoint()
    # a fresh reader (the post-heal owner) merges all files
    reader = EntityJournal(shared, "uigc://reader", fsync="never")
    state, cmds = reader.recover("t", 1, "k")
    assert state == b"survivor-state", "highest-fence base must win"
    assert b"survivor-cmd" in cmds
    assert b"divergent-cmd" not in cmds and b"divergent-state" != state
    assert reader.fence_conflicts > 0
    sites = [f.get("site") for f in event_log.of(events.FENCE_REJECTED)]
    assert "recovery" in sites
    for j in (minority, survivor, reader):
        j.close()


def test_journal_fence_continuation_epoch_is_not_a_conflict(tmp_path):
    """A SURVIVOR's live entity keeps journaling across its own fence
    bump: same epoch, records at both fences.  That is continuation,
    not dual activation — the pre-verdict snapshot and commands must
    replay in full (the rule that quarantined them lost acked state)."""
    shared = str(tmp_path)
    j = EntityJournal(shared, "uigc://surv", fsync="never")
    j.open_epoch("t", 4, "k4", b"base")
    j.note_command("t", 4, "k4", b"pre-verdict")
    j.set_fence(1)  # the split-brain verdict: stamp moves, epoch stays
    j.note_command("t", 4, "k4", b"post-verdict")
    j.checkpoint()
    reader = EntityJournal(shared, "uigc://reader", fsync="never")
    state, cmds = reader.recover("t", 4, "k4")
    assert state == b"base"
    assert cmds == [b"pre-verdict", b"post-verdict"]
    assert reader.fence_conflicts == 0
    j.close()
    reader.close()


def test_journal_foreign_writer_in_continuation_epoch_still_conflicts(
    tmp_path, event_log
):
    """The continuation carve-out is a (writer, epoch) property: the
    survivor continuing its own epoch across the fence must not excuse
    a DIFFERENT writer's concurrent records in that same epoch — that
    is dual activation even though no fresh activation ever opened."""
    import os
    import pickle
    import struct
    import zlib

    shared = str(tmp_path)

    def write_file(node, records):
        j = EntityJournal(shared, node, fsync="never")
        d = j._shard_dir("t", 5)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"{j.node_safe}.00000.uj")
        with open(path, "wb") as fh:
            for rec in records:
                payload = pickle.dumps(rec, protocol=4)
                fh.write(
                    struct.pack(
                        ">2sII", b"uJ", len(payload), zlib.crc32(payload)
                    )
                    + payload
                )
        j.close()

    # Writer A: epoch 10 continues across the fence bump (0 -> 1).
    write_file(
        "uigc://A",
        [
            ("k", 10, 0, "s", b"A-base", 0),
            ("k", 10, 1, "c", b"A-pre", 0),
            ("k", 10, 2, "c", b"A-post", 1),
        ],
    )
    # Writer B (the quarantined loser): a concurrent record in the
    # SAME wall-clock epoch, still at fence 0.
    write_file("uigc://B", [("k", 10, 3, "c", b"B-divergent", 0)])
    reader = EntityJournal(shared, "uigc://reader", fsync="never")
    state, cmds = reader.recover("t", 5, "k")
    assert state == b"A-base"
    assert b"A-pre" in cmds and b"A-post" in cmds
    assert b"B-divergent" not in cmds, "foreign writer must be quarantined"
    assert reader.fence_conflicts == 1
    reader.close()


def test_journal_writer_identity_survives_dotted_node_names(tmp_path):
    """Segment filenames are '<node_safe>.<NNNNN>.uj' and node_safe
    preserves dots ('10.0.0.5'): the merge must parse the writer from
    the RIGHT or dotted names alias (breaking the continuation
    carve-out) and live-vs-disk writers diverge for one node."""
    shared = str(tmp_path)
    dotted = "uigc://10.0.0.5:7001"
    j = EntityJournal(shared, dotted, fsync="never")
    j.open_epoch("t", 6, "k6", b"base")
    j.note_command("t", 6, "k6", b"pre")
    j.set_fence(1)  # same writer continues its epoch across the bump
    j.note_command("t", 6, "k6", b"post")
    j.checkpoint()
    reader = EntityJournal(shared, "uigc://10.0.0.6:7001", fsync="never")
    state, cmds = reader.recover("t", 6, "k6")
    assert state == b"base" and cmds == [b"pre", b"post"]
    assert reader.fence_conflicts == 0
    # and the disk-parsed writer matches the live-append writer
    cache = reader._load_shard("t", 6)
    assert {r[5] for r in cache["k6"]} == {j.node_safe}
    j.close()
    reader.close()


def test_quarantine_drain_not_settled_with_active_records(tmp_path):
    """An ACTIVE record (an activation that raced the quarantine gate)
    must hold the freeze open so the next sweep can capture it."""
    config = dict(BASE)
    config["uigc.cluster.journal-dir"] = str(tmp_path)
    node = Node("drain-a", config)
    try:
        node.region.deliver_local("stray", ("incr",))
        assert settle(lambda: node.region.active_count() == 1, 10.0)
        node.cluster._quarantined = True
        assert not node.cluster._quarantine_drained()
        # the re-scan captures it; the drain settles once it lands
        node.cluster._quarantine_scan()
        assert settle(node.cluster._quarantine_drained, 10.0)
    finally:
        node.cluster._quarantined = False
        terminate_all([node])


def test_heal_wildcard_sweeps_specific_pairs_either_order():
    """heal(x, '*') / heal('*', x) must mend EVERY cut naming x —
    specific symmetric pairs, one-way cuts and wildcard isolations —
    identically for both argument orders."""
    for order in ((lambda p, x: p.heal(x, "*")), (lambda p, x: p.heal("*", x))):
        plan = FaultPlan(3)
        plan.partition("uigc://x", "uigc://y")
        plan.partition("uigc://z", "uigc://x", oneway=True)
        plan.isolate("uigc://x")
        plan.partition("uigc://y", "uigc://z")  # unrelated: must survive
        order(plan, "uigc://x")
        assert plan.outbound("uigc://x", "uigc://y", "app")[0] == "deliver"
        assert plan.outbound("uigc://z", "uigc://x", "app")[0] == "deliver"
        assert plan.outbound("uigc://y", "uigc://z", "app")[0] == "drop"
    # a specific-pair heal leaves a wildcard isolation in place (it
    # covers more than the pair)
    plan = FaultPlan(3)
    plan.isolate("uigc://x")
    plan.heal("uigc://x", "uigc://y")
    assert plan.outbound("uigc://x", "uigc://y", "app")[0] == "drop"


def test_rejoin_waits_for_quarantine_drain(tmp_path):
    """A survivor's handshake arriving mid-drain must NOT unfreeze the
    journal: the remaining captures would stamp the loser's divergent
    state with the survivor's fence, making it unrejectable at the
    next merge.  The rejoin only proceeds once the drain settled."""
    config = dict(BASE)
    config["uigc.cluster.journal-dir"] = str(tmp_path)
    node = Node("gate-a", config)
    try:
        arb = node.cluster.arbiter
        assert arb is not None
        # Force the quarantined-mid-drain state directly (single node;
        # the transition machinery is exercised by the chaos matrix).
        node.cluster._quarantined = True
        node.cluster._quarantine_checkpointed = False
        arb.quarantined = True
        frame = wire.encode_mship(
            "uigc://gate-b", 7, ["uigc://gate-b"], {}, False, 1
        )
        node.cluster._on_mship("uigc://gate-b", frame)
        assert node.cluster.quarantined, "rejoin must wait for the drain"
        assert arb.fence == 0 and not node.cluster.journal.frozen
        # drain settles -> the retried handshake completes the rejoin
        node.cluster._quarantine_settle()
        assert node.cluster.journal.frozen
        node.cluster._on_mship("uigc://gate-b", frame)
        assert not node.cluster.quarantined
        assert arb.fence == 7
        assert not node.cluster.journal.frozen
        assert node.cluster.journal.fence == 7
    finally:
        terminate_all([node])


def test_journal_single_fence_replays_fully(tmp_path):
    """No fence divergence (the key was never dual-activated): the
    minority's whole suffix — snapshot and commands — replays."""
    shared = str(tmp_path)
    j = EntityJournal(shared, "uigc://solo", fsync="never")
    j.open_epoch("t", 2, "k2", b"s0")
    j.note_command("t", 2, "k2", b"c1")
    j.note_command("t", 2, "k2", b"c2")
    j.checkpoint()
    reader = EntityJournal(shared, "uigc://reader", fsync="never")
    state, cmds = reader.recover("t", 2, "k2")
    assert state == b"s0" and cmds == [b"c1", b"c2"]
    j.close()
    reader.close()


def test_frozen_journal_refuses_appends(tmp_path, event_log):
    j = EntityJournal(str(tmp_path), "uigc://q", fsync="never")
    j.open_epoch("t", 3, "k3", b"s")
    j.freeze()
    before = j.appended_records
    j.note_command("t", 3, "k3", b"post-verdict")
    assert j.appended_records == before, "frozen journal must not append"
    assert j.stats()["fence_rejected_appends"] >= 1
    assert any(
        f.get("site") == "journal" for f in event_log.of(events.FENCE_REJECTED)
    )
    j.unfreeze(5)
    j.note_command("t", 3, "k3", b"post-heal")
    assert j.appended_records == before + 1
    assert j.fence == 5
    j.close()


def test_journal_record_fence_stamp_and_legacy_tolerance(tmp_path):
    """Records carry the writer's fence; a pre-fencing 5-tuple record
    (an old build's file) scans as fence 0."""
    import pickle
    import struct
    import zlib

    j = EntityJournal(str(tmp_path), "uigc://w", fsync="never")
    j.set_fence(4)
    j.open_epoch("t", 0, "k", b"s")
    j.checkpoint()
    scanned = []
    shard_dir = j._shard_dir("t", 0)
    import os

    for name in os.listdir(shard_dir):
        scanned += j._scan_file(os.path.join(shard_dir, name))
    assert scanned and all(rec[5] == 4 for rec in scanned)
    # hand-write a legacy 5-tuple record into a fresh file
    payload = pickle.dumps(("k", 1, 0, "s", b"legacy"), protocol=4)
    legacy = struct.pack(">2sII", b"uJ", len(payload), zlib.crc32(payload)) + payload
    path = os.path.join(shard_dir, "old-node.00000.uj")
    with open(path, "wb") as fh:
        fh.write(legacy)
    recs = j._scan_file(path)
    assert recs == [("k", 1, 0, "s", b"legacy", 0)]
    assert j.torn_records == 0
    j.close()


# ------------------------------------------------------------------- #
# Chaos matrix: 3-node clusters under traffic
# ------------------------------------------------------------------- #

N_KEYS = 60


def _warm_keyspace(nodes):
    keys = [f"user-{i}" for i in range(N_KEYS)]
    for i, key in enumerate(keys):
        nodes[i % len(nodes)].cluster.entity_ref("counter", key).tell(("incr",))
    warmed = lambda: sum(n.region.active_count() for n in nodes) == N_KEYS
    if not settle(warmed, timeout_s=20.0):
        # Re-kick once: a table-convergence hiccup under full-suite
        # load can park the first burst in the deferred queue past its
        # flush; counts baseline AFTER warm-up, so re-telling is safe.
        for key in keys:
            nodes[0].cluster.entity_ref("counter", key).tell(("incr",))
    assert settle(warmed, timeout_s=30.0), [
        n.region.active_count() for n in nodes
    ]
    return keys


def _probe_all(node, keys, expect=None, timeout_s=45.0):
    coll = Collector()
    coll_cell = node.system.spawn_system_raw(coll, f"coll-{time.monotonic_ns()}")
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        snap = coll.snapshot()
        missing = [k for k in keys if k not in snap]
        short = (
            [k for k in keys if snap.get(k, -1) < expect.get(k, 0)]
            if expect
            else []
        )
        if not missing and not short:
            return snap
        for k in set(missing) | set(short):
            node.cluster.entity_ref("counter", k).tell(("probe", coll_cell))
        time.sleep(0.3)
    return coll.snapshot()


def _assert_single_side_serves(majority, minority, keys):
    """Exactly one side serves each shard: the majority's tables name
    no minority owner, and the quarantined minority hosts nothing."""
    for node in majority:
        owners = set(node.cluster.table_snapshot().assignments.values())
        assert minority.address not in owners, owners
    assert minority.cluster.quarantined
    assert minority.region.active_count() == 0, "quarantine must drain"
    assert minority.cluster.journal is None or minority.cluster.journal.frozen


def _partition_cycle(
    event_log,
    strategy="keep-majority",
    oneway=False,
    flap=False,
    overrides=None,
    journal_dir=None,
):
    plan = FaultPlan(99)
    conf = {"uigc.cluster.sbr-strategy": strategy}
    if journal_dir is not None:
        conf["uigc.cluster.journal-dir"] = journal_dir
        conf["uigc.cluster.journal-fsync"] = "interval"
    if overrides:
        conf.update(overrides)
    nodes = build_cluster(
        [f"part-{strategy}-a", f"part-{strategy}-b", f"part-{strategy}-c"],
        plan=plan,
        overrides=conf,
        join_gap_s=0.01,
    )
    a, b, c = nodes
    try:
        connect_mesh(nodes)
        assert settle(
            lambda: all(len(n.cluster.members()) == 3 for n in nodes),
            timeout_s=10.0,
        )
        keys = _warm_keyspace(nodes)
        pre = _probe_all(a, keys)
        assert len(pre) == N_KEYS

        if flap:
            # A short flap that heals before any verdict can settle:
            # the cluster must absorb it without a single down.
            plan.isolate(c.address)
            plan.heal_after(0.08, c.address, "*")
            time.sleep(0.4)
            assert not c.cluster.quarantined
            assert settle(
                lambda: all(len(n.cluster.members()) == 3 for n in nodes),
                timeout_s=15.0,
            ), "flap must heal without membership loss"

        # The real cut: c against the majority, >= 10 heartbeat windows.
        plan.isolate(c.address, oneway=oneway)
        assert settle(
            lambda: c.address not in a.cluster.members()
            and c.address not in b.cluster.members()
            and c.cluster.quarantined,
            timeout_s=30.0,
        ), (a.cluster.members(), b.cluster.members(), c.cluster.stats())
        # the quarantine drain settles: every entity stopped, and the
        # journal freezes one tick later
        assert settle(lambda: c.region.active_count() == 0, timeout_s=20.0)
        assert settle(
            lambda: c.cluster.journal is None or c.cluster.journal.frozen,
            timeout_s=10.0,
        )
        _assert_single_side_serves((a, b), c, keys)

        # Majority keeps serving the WHOLE keyspace during the cut.
        assert settle(
            lambda: a.cluster.migrations.pending_count() == 0
            and b.cluster.migrations.pending_count() == 0,
            timeout_s=20.0,
        )
        during = _probe_all(a, keys, expect=pre)
        assert len(during) == N_KEYS
        assert all(during[k] >= pre[k] for k in keys)

        assert not sanitizer_violations(a) and not sanitizer_violations(b)

        # -- heal: mend the fault plan, re-dial, handshake, rejoin ---- #
        plan.heal(c.address, "*")
        c.fabric.connect("127.0.0.1", a.port)
        c.fabric.connect("127.0.0.1", b.port)
        assert settle(
            lambda: not c.cluster.quarantined
            and all(len(n.cluster.members()) == 3 for n in nodes),
            timeout_s=30.0,
        ), (c.cluster.stats(), a.cluster.members())
        assert c.cluster.current_fence == a.cluster.current_fence
        # the rejoined peer serves again and no count regressed
        assert settle(
            lambda: all(
                n.cluster.migrations.pending_count() == 0 for n in nodes
            ),
            timeout_s=30.0,
        )
        post = _probe_all(a, keys, expect=during)
        assert len(post) == N_KEYS
        assert all(post[k] >= during[k] for k in keys), "acked state regressed"
        assert settle(lambda: c.region.active_count() > 0, timeout_s=30.0), (
            "rejoined peer never re-hosted a shard"
        )
        # rejoined peer's collector/sanitizer state is clean
        assert not sanitizer_violations(a)
        assert not sanitizer_violations(b)
        assert not sanitizer_violations(c)
        downs = event_log.of(events.SBR_DOWNED)
        assert any(f.get("strategy") == strategy for f in downs)
        assert event_log.of(events.SBR_REJOIN)
    finally:
        terminate_all(nodes)


def test_symmetric_partition_keep_majority_full_cycle(event_log, tmp_path):
    _partition_cycle(event_log, "keep-majority", journal_dir=str(tmp_path))


def test_symmetric_partition_static_quorum(event_log, tmp_path):
    _partition_cycle(
        event_log,
        "static-quorum",
        overrides={"uigc.cluster.sbr-quorum-size": 2},
        journal_dir=str(tmp_path),
    )


def test_asymmetric_partition_converges_to_one_side(event_log, tmp_path):
    """A one-way cut (c transmits into the void but still hears the
    majority) must still converge: the majority's verdicts stand, c
    eventually observes its own removal (EOF on the closed links) and
    quarantines, and the heal cycle completes."""
    _partition_cycle(event_log, "keep-majority", oneway=True, journal_dir=str(tmp_path))


def test_flapping_partition_absorbs_then_resolves(event_log, tmp_path):
    _partition_cycle(event_log, "keep-majority", flap=True, journal_dir=str(tmp_path))


def test_keep_oldest_majority_downs_itself(event_log):
    """keep-oldest with the oldest node isolated: the MAJORITY loses.
    Both b and c quarantine; the senior minority keeps serving its
    view of the keyspace."""
    plan = FaultPlan(7)
    nodes = build_cluster(
        ["oldest-a", "oldest-b", "oldest-c"],
        plan=plan,
        overrides={"uigc.cluster.sbr-strategy": "keep-oldest"},
        join_gap_s=0.01,
    )
    a, b, c = nodes
    try:
        connect_mesh(nodes)
        assert settle(
            lambda: all(len(n.cluster.members()) == 3 for n in nodes),
            timeout_s=10.0,
        )
        # let the mship gossip converge the join stamps
        time.sleep(0.5)
        _warm_keyspace(nodes)
        plan.isolate(a.address)
        assert settle(
            lambda: b.cluster.quarantined and c.cluster.quarantined,
            timeout_s=30.0,
        ), (b.cluster.stats(), c.cluster.stats())
        assert not a.cluster.quarantined
        # a's own verdict (its detectors starve on their own clock) may
        # lag b/c's quarantine — settle, don't assert instantly.
        assert settle(lambda: a.cluster.current_fence >= 1, timeout_s=20.0), (
            a.cluster.stats()
        )
        assert settle(
            lambda: b.region.active_count() == 0
            and c.region.active_count() == 0,
            timeout_s=20.0,
        )
        assert not sanitizer_violations(a)
    finally:
        terminate_all(nodes)


def test_down_all_quarantines_every_side(event_log):
    plan = FaultPlan(7)
    nodes = build_cluster(
        ["dall-a", "dall-b", "dall-c"],
        plan=plan,
        overrides={"uigc.cluster.sbr-strategy": "down-all"},
    )
    a, b, c = nodes
    try:
        connect_mesh(nodes)
        assert settle(
            lambda: all(len(n.cluster.members()) == 3 for n in nodes),
            timeout_s=10.0,
        )
        _warm_keyspace(nodes)
        plan.isolate(c.address)
        assert settle(
            lambda: all(n.cluster.quarantined for n in nodes), timeout_s=30.0
        ), [n.cluster.stats() for n in nodes]
        assert settle(
            lambda: all(n.region.active_count() == 0 for n in nodes),
            timeout_s=20.0,
        )
        downed = event_log.of(events.SBR_DOWNED)
        assert len(downed) >= 3
        assert all(f.get("strategy") == "down-all" for f in downed)
    finally:
        terminate_all(nodes)


# ------------------------------------------------------------------- #
# Lint: UL013 fenced-helper bypass rule
# ------------------------------------------------------------------- #


def test_ul013_flags_fence_bypasses_and_exempts_helpers(tmp_path):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "uigc_lint",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
            "uigc_lint.py",
        ),
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    cluster_dir = tmp_path / "cluster"
    cluster_dir.mkdir()
    bad = cluster_dir / "rogue.py"
    bad.write_text(
        "class Rogue:\n"
        "    def sneak(self, journal, blob):\n"
        "        journal.note_command('t', 1, 'k', blob)\n"
        "        journal.open_epoch('t', 1, 'k', None)\n"
        "        epoch = journal.begin_snapshot('t', 1, 'k')\n"
        "        journal.commit_snapshot('t', 1, 'k', epoch, blob)\n"
        "    def clobber(self, cluster, table):\n"
        "        cluster._table = table\n"
    )
    violations = [v for v in lint.lint_paths([str(bad)]) if v.rule == "UL013"]
    assert {v.line for v in violations} == {3, 4, 5, 6, 8}, [
        v.render() for v in violations
    ]
    # The fenced helper modules themselves are exempt, as is code
    # outside runtime//cluster/.
    sharding_like = cluster_dir / "sharding.py"
    sharding_like.write_text(bad.read_text())
    assert not [
        v for v in lint.lint_paths([str(sharding_like)]) if v.rule == "UL013"
    ]
    elsewhere = tmp_path / "tools_like"
    elsewhere.mkdir()
    free = elsewhere / "rogue.py"
    free.write_text(bad.read_text())
    assert not [v for v in lint.lint_paths([str(free)]) if v.rule == "UL013"]
    # The live repo is strict-clean for UL013.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_violations = [
        v
        for v in lint.lint_paths([os.path.join(repo, "uigc_tpu")])
        if v.rule == "UL013"
    ]
    assert not repo_violations, [v.render() for v in repo_violations]


def test_split_brain_suspected_disagreement_event(event_log):
    """An asymmetric verdict (a downs c, b still lists it live) must
    surface as a membership disagreement — the split_brain_suspected
    alert input — on the side that reached the verdict."""
    plan = FaultPlan(11)
    nodes = build_cluster(
        ["dis-a", "dis-b", "dis-c"],
        plan=plan,
        # b tolerates silence far longer than a: only a reaches a
        # verdict inside the test window, so the views disagree.
        overrides={"uigc.cluster.sbr-min-members": 4},
    )
    a, b, c = nodes
    try:
        connect_mesh(nodes)
        assert settle(
            lambda: all(len(n.cluster.members()) == 3 for n in nodes),
            timeout_s=10.0,
        )
        # min-members=4 keeps arbitration out of the way: a's verdict
        # removes c immediately (legacy path) while b keeps both.
        plan.partition(a.address, c.address)
        assert settle(
            lambda: c.address not in a.cluster.members(), timeout_s=30.0
        )
        assert c.address in b.cluster.members()
        # a's arbiter saw no verdict (not arbitrated) — plant one
        # explicitly at the arbiter level to exercise the detector.
        a.cluster.arbiter._downed.add(c.address)
        assert settle(
            lambda: bool(event_log.of(events.MEMBERSHIP_DISAGREEMENT)),
            timeout_s=15.0,
        ), "b's gossip listing c live must flag a disagreement on a"
        flagged = event_log.of(events.MEMBERSHIP_DISAGREEMENT)
        assert any(c.address in f.get("conflicts", []) for f in flagged)
    finally:
        terminate_all(nodes)
