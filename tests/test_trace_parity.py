"""Differential liveness-parity tests: oracle vs array vs device graphs.

The reference author debugged CRGC by folding the same entries into two
graphs and asserting equality (reference: ShadowGraph.java:176-199,
commented testGraph at LocalGC.scala:65,137-141).  We do the same, at the
verdict level: a randomized protocol simulator produces faithful entry
streams (same State/Entry machinery the engine uses), folds them into the
pointer-based oracle and the array/device graphs, and asserts the garbage
sets agree on every collection round.
"""

import random

import pytest

from uigc_tpu.engines.crgc import refob as refob_info
from uigc_tpu.engines.crgc.arrays import ArrayShadowGraph
from uigc_tpu.engines.crgc.refob import CrgcRefob
from uigc_tpu.engines.crgc.shadow import ShadowGraph
from uigc_tpu.engines.crgc.state import CrgcContext, CrgcState, Entry


class FakeSystem:
    def __init__(self, address="uigc://parity"):
        self.address = address


class FakeCell:
    """Just enough of ActorCell for the data plane: identity + address."""

    _count = 0

    def __init__(self, system):
        FakeCell._count += 1
        self.uid = FakeCell._count
        self.path = f"/sim/{self.uid}"
        self.system = system
        self.received_stop = False

    def tell(self, msg):
        self.received_stop = True

    def __repr__(self):
        return self.path


class SimActor:
    """A simulated mutator following the CRGC recording protocol exactly
    (the same sequences as CRGC.scala:100-221)."""

    def __init__(self, sim, cell, creator_ref, context):
        self.sim = sim
        self.cell = cell
        self.self_ref = CrgcRefob(cell)
        self.state = CrgcState(self.self_ref, context)
        self.state.record_new_refob(self.self_ref, self.self_ref)
        if creator_ref is not None:
            self.state.record_new_refob(creator_ref, self.self_ref)
        else:
            self.state.mark_as_root()
        self.acquaintances = []  # refobs this actor owns
        self.inbox = []  # in-flight messages: lists of refobs carried
        self.alive = True

    def flush(self, is_busy=False):
        entry = Entry(self.sim.context)
        self.state.flush_to_entry(is_busy, entry)
        self.sim.entries.append(entry)

    # Engine-mirroring operations --------------------------------- #

    def spawn(self):
        child_cell = FakeCell(self.sim.system)
        child = SimActor(self.sim, child_cell, self.self_ref, self.sim.context)
        self.sim.actors[child_cell] = child
        self.sim.children.setdefault(self.cell, []).append(child_cell)
        ref = CrgcRefob(child_cell)
        if not self.state.can_record_new_actor():
            self.flush(is_busy=True)
        self.state.record_new_actor(ref)
        self.acquaintances.append(ref)
        # Child's initial flush (on-block style start batch).
        child.flush()
        return child

    def create_ref(self, target_ref, owner_ref):
        ref = CrgcRefob(target_ref.target)
        if not self.state.can_record_new_refob():
            self.flush(is_busy=True)
        self.state.record_new_refob(owner_ref, target_ref)
        return ref

    def send(self, target_ref, carried_refs=()):
        if not target_ref.can_inc_send_count() or not self.state.can_record_updated_refob(
            target_ref
        ):
            self.flush(is_busy=True)
        target_ref.inc_send_count()
        self.state.record_updated_refob(target_ref)
        target = self.sim.actors[target_ref.target]
        # CRGC soundness: a collected actor never receives another message
        # from a LIVE actor.  (In-flight messages between mutually-garbage
        # actors are legitimately dropped.)
        assert target.alive or not self.alive, (
            f"live {self.cell} sent to collected {target.cell} — GC unsound"
        )
        target.inbox.append(list(carried_refs))

    def receive(self):
        if not self.inbox:
            return
        carried = self.inbox.pop(0)
        if not self.state.can_record_message_received():
            self.flush(is_busy=True)
        self.state.record_message_received()
        self.acquaintances.extend(carried)
        self.flush()  # on-block: drained the mailbox

    def release(self, ref):
        if not self.state.can_record_updated_refob(ref):
            self.flush(is_busy=True)
        ref.deactivate()
        self.state.record_updated_refob(ref)
        if ref in self.acquaintances:
            self.acquaintances.remove(ref)
        self.flush()


def graph_cells(graph):
    """The set of actors currently interned in a graph, regardless of
    backend (oracle/array/native)."""
    if hasattr(graph, "shadow_map"):
        return set(graph.shadow_map.keys())
    if hasattr(graph, "slot_of"):
        return set(graph.slot_of.keys())
    return set(graph._id_of_cell.keys())


class Sim:
    def __init__(self, seed, backend="array"):
        self.rng = random.Random(seed)
        self.system = FakeSystem()
        self.context = CrgcContext(delta_graph_size=64, entry_field_size=4)
        self.entries = []
        self.actors = {}
        self.children = {}
        self.oracle = ShadowGraph(self.context, self.system.address)
        if backend == "native":
            from uigc_tpu.native import NativeShadowGraph

            self.array = NativeShadowGraph(self.context, self.system.address)
        elif backend in ("mesh", "mesh-decremental"):
            from uigc_tpu.engines.crgc.mesh import MeshShadowGraph

            self.array = MeshShadowGraph(
                self.context,
                self.system.address,
                decremental=(backend == "mesh-decremental"),
            )
        else:
            self.array = ArrayShadowGraph(
                self.context,
                self.system.address,
                use_device=(backend in ("device", "decremental")),
                decremental=(backend == "decremental"),
            )
        root_cell = FakeCell(self.system)
        self.root = SimActor(self, root_cell, None, self.context)
        self.actors[root_cell] = self.root
        self.root.flush()

    def live_actors(self):
        return [a for a in self.actors.values() if a.alive]

    def random_step(self):
        actors = self.live_actors()
        actor = self.rng.choice(actors)
        p = self.rng.random()
        if p < 0.15 and len(self.actors) < 400:
            actor.spawn()
        elif p < 0.35 and actor.acquaintances:
            # Share a ref: create for a random owner, deliver in a message.
            owner_ref = self.rng.choice(actor.acquaintances)
            target_ref = self.rng.choice(actor.acquaintances)
            new_ref = actor.create_ref(target_ref, owner_ref)
            actor.send(owner_ref, carried_refs=[new_ref])
        elif p < 0.55 and actor.acquaintances:
            actor.send(self.rng.choice(actor.acquaintances))
        elif p < 0.7 and actor.acquaintances:
            actor.release(self.rng.choice(actor.acquaintances))
        else:
            actor.receive()
        # CRGC's on-block invariant: every processing batch ends with a
        # flush before the actor goes idle (reference: CRGC.scala:84-88).
        # An actor that appears blocked in the folded view has therefore
        # flushed everything it did — soundness depends on this.
        actor.flush()

    def drain_inboxes(self):
        progressed = True
        while progressed:
            progressed = False
            for actor in self.live_actors():
                if actor.inbox:
                    actor.receive()
                    progressed = True

    def collect_round(self):
        """Fold all pending entries into both graphs, trace, compare."""
        for entry in self.entries:
            self.oracle.merge_entry(entry)
            self.array.merge_entry(entry)
        self.entries = []

        before_oracle = set(self.oracle.shadow_map.keys())
        before_array = graph_cells(self.array)
        assert before_oracle == before_array

        self.oracle.trace(should_kill=False)
        self.array.trace(should_kill=False)

        after_oracle = set(self.oracle.shadow_map.keys())
        after_array = graph_cells(self.array)
        garbage_oracle = before_oracle - after_oracle
        garbage_array = before_array - after_array
        assert garbage_oracle == garbage_array, (
            f"verdict divergence: oracle-only="
            f"{sorted(c.path for c in garbage_oracle - garbage_array)} "
            f"array-only={sorted(c.path for c in garbage_array - garbage_oracle)}"
        )
        assert after_oracle == after_array

        # Apply the verdicts: garbage actors (and their subtrees, via the
        # runtime's stop cascade) terminate.
        for cell in garbage_oracle:
            actor = self.actors.get(cell)
            if actor is not None:
                # Soundness: any in-flight message to a collected actor
                # must come from an actor that is itself garbage (dropped
                # as a dead-to-dead send); the send-to-dead assertion in
                # SimActor.send covers the live-sender case.
                actor.alive = False
                # Death accounting, mirroring CRGC.pre_signal(PostStop):
                # count undelivered messages as received, release their
                # carried refs, and flush a final entry.
                for carried in actor.inbox:
                    if not actor.state.can_record_message_received():
                        actor.flush(is_busy=True)
                    actor.state.record_message_received()
                    for ref in carried:
                        if not actor.state.can_record_updated_refob(ref):
                            actor.flush(is_busy=True)
                        ref.deactivate()
                        actor.state.record_updated_refob(ref)
                actor.inbox.clear()
                actor.flush()
        return garbage_oracle


from conftest import NATIVE_AVAILABLE, NATIVE_BACKEND


@pytest.mark.parametrize(
    "backend",
    ["array", "device", "mesh", "decremental", "mesh-decremental",
     NATIVE_BACKEND],
)
@pytest.mark.parametrize("seed", [7, 42, 20260729])
def test_random_protocol_parity(seed, backend):
    sim = Sim(seed, backend=backend)
    for round_no in range(20):
        for _ in range(150):
            sim.random_step()
        sim.collect_round()

    # Quiesce: deliver everything, then release the whole world from the
    # root and make sure both graphs agree it all collapses.
    sim.drain_inboxes()
    for actor in sim.live_actors():
        for ref in list(actor.acquaintances):
            actor.release(ref)
    sim.drain_inboxes()
    for actor in sim.live_actors():
        actor.flush()

    for _ in range(5):
        sim.collect_round()

    survivors = {a.cell for a in sim.live_actors()}
    # Everything except the root must eventually be collected in both
    # graphs (completeness).
    assert survivors == {sim.root.cell}, (
        f"{len(survivors) - 1} actors never collected"
    )


def test_supervisor_marking_parity():
    """A live child must keep its (otherwise-garbage) parent alive in both
    implementations (reference: ShadowGraph.java:242-267)."""
    backends = ["array", "device"] + (["native"] if NATIVE_AVAILABLE else [])
    for backend in backends:
        sim = Sim(1, backend=backend)
        parent = sim.root.spawn()
        parent_ref = sim.root.acquaintances[0]
        child = parent.spawn()
        child_ref = parent.acquaintances[0]
        # Give parent a ref back to root, so it can reply.
        to_root = sim.root.create_ref(sim.root.self_ref, parent_ref)
        sim.root.send(parent_ref, carried_refs=[to_root])
        parent.receive()
        root_ref = parent.acquaintances[-1]
        # Parent hands root a direct ref to the child.
        for_root = parent.create_ref(child_ref, root_ref)
        parent.send(root_ref, carried_refs=[for_root])
        sim.root.receive()
        parent.flush()
        # Parent releases everything it owns; root releases the parent but
        # keeps its ref to the child.
        for r in list(parent.acquaintances):
            parent.release(r)
        sim.root.release(parent_ref)
        sim.drain_inboxes()
        for a in sim.live_actors():
            a.flush()

        garbage = sim.collect_round()
        # Parent is garbage-in-waiting but must NOT be collected while the
        # child lives.
        assert parent.cell not in garbage
        assert child.cell not in garbage

        # Now the root releases the child too: both collapse.
        for r in list(sim.root.acquaintances):
            sim.root.release(r)
        sim.drain_inboxes()
        for a in sim.live_actors():
            a.flush()
        garbage = sim.collect_round()
        assert parent.cell in garbage and child.cell in garbage


@pytest.mark.parametrize("seed", [3, 99])
def test_debug_inspectors_parity(seed):
    """The debug inspectors (reference: ShadowGraph.java:331-394) must
    agree between the oracle and the array backend on an identical
    entry stream."""
    sim = Sim(seed, backend="array")
    for _ in range(10):
        for _ in range(120):
            sim.random_step()
        sim.collect_round()

    assert sim.oracle.addresses_in_graph() == sim.array.addresses_in_graph()
    o = sim.oracle.investigate_live_set()
    a = sim.array.investigate_live_set()
    assert o == a, f"live-set dumps diverged:\noracle={o}\narray={a}"


def test_inspectors_cross_locality():
    """Cross-locality acquaintances show up in the live-set dump: a
    local actor holding a ref to a remote one is reported (the leak
    shape the reference prints these inspectors for)."""
    system = FakeSystem("uigc://local")
    remote_system = FakeSystem("uigc://remote")
    context = CrgcContext(delta_graph_size=64, entry_field_size=4)
    graphs = [
        ShadowGraph(context, system.address),
        ArrayShadowGraph(context, system.address),
    ]
    local_cell = FakeCell(system)
    remote_cell = FakeCell(remote_system)
    for g in graphs:
        e = Entry(context)
        e.self_ref = CrgcRefob(local_cell)
        e.is_busy = False
        e.is_root = True
        e.created_owners[0] = CrgcRefob(local_cell)
        e.created_targets[0] = CrgcRefob(remote_cell)
        g.merge_entry(e)
    dumps = [g.investigate_live_set() for g in graphs]
    assert dumps[0] == dumps[1]
    d = dumps[0]
    assert d["roots"] == 1
    assert d["nonlocal"] == 1
    assert d["local_to_remote"] == [(local_cell.path, remote_cell.path, 1)]
    addr = [g.addresses_in_graph() for g in graphs]
    assert addr[0] == addr[1] == {
        "uigc://local": 1,
        "uigc://remote": 1,
    }
