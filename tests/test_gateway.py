"""Ingress gateway: protocol, admission, routing, flow control, chaos.

Covers the front-door subsystem (``uigc_tpu/gateway``) end to end:

- client value codec hostile input: truncation, depth bombs, length
  bombs, unknown tags — every malformed body is a clean
  ``ClientDecodeError``, never an exception escape or a code load;
- framing: raw length-prefixed round trip, ``decode_gateway_reply``
  rejecting malformed reply frames, the minimal websocket upgrade
  (RFC 6455 accept key, masked client frames, server frames);
- admission units: token auth, per-tenant connection caps and msg/s
  buckets, the overload controller's hysteresis band;
- end to end over real sockets: CONNECT -> AUTH_OK -> SEND -> ACK
  through a proxy-only gateway into sharded entities, SUBSCRIBE ->
  PUSH fan-out, clean seq-addressed ERROR frames for auth/quota/proto
  rejections, drain;
- the proxy-only membership contract: the gateway routes by the peer
  table but never owns shards and never re-enters its own member view
  (the fabric's subscribe replay includes ourselves);
- flow control one hop further: egress backlog maps to per-connection
  read throttling with ``fabric.backpressure{site=gateway}`` events;
- client-socket fault units (slowloris / half-open / truncate / flood)
  and the chaos acceptance: faulted clients plus one entity-node death
  mid-run, and still every admitted command is acked or cleanly
  errored with zero acked-then-lost state.
"""

import importlib.util
import os
import socket
import struct
import threading
import time

import pytest

from uigc_tpu import ActorSystem, ClusterSharding, Entity
from uigc_tpu.gateway import IngressGateway, protocol
from uigc_tpu.gateway.admission import (
    OverloadController,
    TenantQuotas,
    TokenAuth,
)
from uigc_tpu.gateway.session import ClientRef
from uigc_tpu.runtime import faults, schema, wire
from uigc_tpu.runtime.faults import FaultPlan
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.utils import events

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_ib = _load_tool("ingress_bench")
BenchClient = _ib.BenchClient
_read_one_frame = _ib._read_one_frame

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.crgc.shadow-graph": "array",
    "uigc.cluster.tick-interval": 40,
    "uigc.cluster.handoff-retry": 120,
}


def settle(predicate, timeout_s=20.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


class EventLog:
    def __init__(self):
        self.entries = []
        self._lock = threading.Lock()

    def __call__(self, name, fields):
        with self._lock:
            self.entries.append((name, fields))

    def of(self, name):
        with self._lock:
            return [f for n, f in self.entries if n == name]


@pytest.fixture
def event_log():
    log = EventLog()
    events.recorder.enable()
    events.recorder.add_listener(log)
    yield log
    events.recorder.disable()
    events.recorder.remove_listener(log)
    events.recorder.reset()


class GwCounter(Entity):
    """Counts gateway commands; pushes every increment to subscribers."""

    def __init__(self, ctx, key, state):
        super().__init__(ctx, key)
        state = state or {}
        self.count = state.get("count", 0)
        self.subscribers = []

    def receive(self, msg):
        if not (isinstance(msg, tuple) and msg):
            return self
        if msg[0] == "gw-cmd":
            _kind, ref, seq, cmd = msg
            if not (isinstance(cmd, dict) and cmd.get("probe")):
                self.count += 1
                for sub in self.subscribers:
                    sub.tell(("push", {"key": self.key, "count": self.count}))
            ref.tell(("ack", seq, self.count))
        elif msg[0] == "gw-sub":
            if msg[1] not in self.subscribers:
                self.subscribers.append(msg[1])
        return self

    def snapshot_state(self):
        return {"count": self.count}


def counter_factory(ctx, key, state):
    return GwCounter(ctx, key, state)


class DataNode:
    __slots__ = ("fabric", "system", "cluster", "region", "port", "address")

    def __init__(self, name, config, plan=None):
        self.fabric = NodeFabric(fault_plan=plan)
        self.system = ActorSystem(
            None, name=name, config=config, fabric=self.fabric
        )
        self.port = self.fabric.listen()
        self.address = self.system.address
        self.cluster = ClusterSharding.attach(self.system)
        self.region = self.cluster.start("counter", counter_factory)


class GatewayNode:
    """Proxy-only member + IngressGateway, the bench topology."""

    __slots__ = (
        "fabric", "system", "cluster", "gateway", "port", "address",
        "client_port",
    )

    def __init__(self, name, config, plan=None):
        self.fabric = NodeFabric(fault_plan=plan)
        self.system = ActorSystem(
            None, name=name, config=config, fabric=self.fabric
        )
        self.port = self.fabric.listen()
        self.address = self.system.address
        self.cluster = ClusterSharding.attach(self.system, proxy_only=True)
        self.gateway = IngressGateway(self.system)
        self.client_port = None

    def listen(self):
        self.client_port = self.gateway.listen()
        return self.client_port


def build_edge(n_data, overrides=None, plan=None, gw_plan=None,
               journal_dir=None):
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = n_data + 1
    if journal_dir is not None:
        config["uigc.cluster.journal-dir"] = str(journal_dir)
    if overrides:
        config.update(overrides)
    nodes = [DataNode(f"gwt-d{i}", config, plan) for i in range(n_data)]
    gw = GatewayNode("gwt-gw", config, gw_plan)
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            a.fabric.connect("127.0.0.1", b.port)
    for n in nodes:
        gw.fabric.connect("127.0.0.1", n.port)
    assert settle(
        lambda: len(gw.cluster.members()) == n_data
        and all(len(n.cluster.members()) == n_data for n in nodes)
        and gw.cluster.home_of("k-0") is not None
    ), "edge topology never settled"
    gw.listen()
    return nodes, gw


def teardown_edge(nodes, gw):
    try:
        gw.gateway.close()
    except Exception:
        pass
    for n in [gw] + list(nodes):
        try:
            n.system.terminate(timeout_s=5.0)
        except Exception:
            pass


def raw_connect(port, tenant="public", token=None, timeout=10.0):
    sock = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    body = {"tenant": tenant}
    if token is not None:
        body["token"] = token
    sock.sendall(protocol.encode_frame(protocol.OP_CONNECT, body))
    return sock


def expect_eof(sock, timeout_s=10.0):
    """Drain until the peer closes (any reset counts as closed)."""
    sock.settimeout(timeout_s)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            if not sock.recv(4096):
                return True
        except socket.timeout:
            return False
        except OSError:
            return True
    return False


# ------------------------------------------------------------------- #
# Client value codec: the closed decoder under hostile bytes
# ------------------------------------------------------------------- #


def test_client_value_codec_round_trip():
    samples = [
        None,
        True,
        False,
        0,
        -1,
        2 ** 60,
        -(2 ** 60),
        3.5,
        "tenant-a",
        "ünïcode",
        b"\x00\xffbytes",
        [1, "two", [3.0, None]],
        {"seq": 7, "cmd": {"op": "inc", "args": [1, 2]}},
    ]
    for value in samples:
        assert schema.decode_client_value(
            schema.encode_client_value(value)
        ) == value
    # Tuples are a server-side convenience: they encode as lists.
    assert schema.decode_client_value(
        schema.encode_client_value((1, 2))
    ) == [1, 2]


def test_client_value_codec_rejects_hostile_input():
    good = schema.encode_client_value({"k": [1, 2, 3], "s": "x" * 50})
    hostile = [
        b"",  # empty body
        good[:-1],  # truncated tail
        good[: len(good) // 2],  # truncated middle
        b"Z",  # unknown tag
        good + b"\x00",  # trailing bytes
        b"i" + b"\xff" * 11,  # varint longer than the int bound
        b"s\xff\xff\xff\xff\x0f",  # string length >> body
        b"l\xff\xff\xff\xff\x0f",  # list count >> body
        b"d\xff\xff\xff\xff\x0f",  # dict count >> body
        b"d\x01l\x00N",  # unhashable dict key (a list)
        b"f\x00",  # truncated double
    ]
    deep = b"l\x01" * (schema.CLIENT_MAX_DEPTH + 2) + b"N"  # depth bomb
    hostile.append(deep)
    for body in hostile:
        with pytest.raises(schema.ClientDecodeError):
            schema.decode_client_value(body)
    # And hostile bytes through the frame layer are a ProtocolError,
    # never an escape.
    with pytest.raises(protocol.ProtocolError):
        protocol.decode_frame_body(bytes([protocol.OP_SEND]) + b"Z")
    with pytest.raises(protocol.ProtocolError):
        protocol.decode_frame_body(b"")


def test_client_value_codec_refuses_server_types_on_encode():
    with pytest.raises(TypeError):
        schema.encode_client_value(object())
    with pytest.raises(TypeError):
        schema.encode_client_value({"ref": ClientRef("uigc://gw", 1)})


# ------------------------------------------------------------------- #
# Framing: raw frames, gateway reply frames, websocket upgrade
# ------------------------------------------------------------------- #


def test_protocol_frame_round_trip_and_error_bodies():
    raw = protocol.encode_frame(protocol.OP_SEND, {"seq": 1, "key": "k"})
    (length,) = struct.unpack_from(">I", raw, 0)
    assert length == len(raw) - 4
    op, value = protocol.decode_frame_body(raw[4:])
    assert (op, value) == (protocol.OP_SEND, {"seq": 1, "key": "k"})

    eop, ebody = protocol.encode_error(
        protocol.ERR_MSG_RATE, "slow down", retry_after_ms=250, seq=9
    )
    assert eop == protocol.OP_ERROR
    assert ebody["code"] == protocol.ERR_MSG_RATE
    assert ebody["retry_after_ms"] == 250
    assert ebody["seq"] == 9


def test_decode_gateway_reply_rejects_malformed_frames():
    frame = wire.encode_gateway_reply(7, b"payload")
    assert frame[0] == wire.GATEWAY_FRAME_KIND
    assert wire.decode_gateway_reply(frame) == (7, b"payload")
    # Malformed reply frames decode to None — the gateway drops them
    # without killing the link's receive loop.  (Kind dispatch is the
    # fabric's job; the decoder checks shape, not the tag.)
    assert wire.decode_gateway_reply(("gwr",)) is None
    assert wire.decode_gateway_reply(("gwr", "not-an-int", b"x")) is None
    assert wire.decode_gateway_reply(("gwr", 1, "not-bytes")) is None
    # The tolerance contract accepts trailing elements from newer peers.
    assert wire.decode_gateway_reply(("gwr", 1, b"x", "extra")) == (1, b"x")


def test_websocket_accept_key_and_decoder_upgrade():
    # The RFC 6455 worked example.
    assert (
        protocol.ws_accept_key("dGhlIHNhbXBsZSBub25jZQ==")
        == "s3pPLMBiTxaQ9kYGzzhZRbK+xOo="
    )
    dec = protocol.TransportDecoder(1 << 20)
    request = (
        b"GET /chat HTTP/1.1\r\n"
        b"Host: gw\r\n"
        b"Upgrade: websocket\r\n"
        b"Connection: Upgrade\r\n"
        b"Sec-WebSocket-Key: dGhlIHNhbXBsZSBub25jZQ==\r\n"
        b"Sec-WebSocket-Version: 13\r\n\r\n"
    )
    frames, out, closed = dec.feed(request)
    assert frames == [] and not closed
    assert b"101 Switching Protocols" in out
    assert b"s3pPLMBiTxaQ9kYGzzhZRbK+xOo=" in out

    # A masked client frame carrying one protocol body.
    body = protocol.encode_frame_body(
        protocol.OP_CONNECT, {"tenant": "ws"}
    )
    mask = b"\x01\x02\x03\x04"
    masked = bytes(b ^ mask[i % 4] for i, b in enumerate(body))
    header = bytes([0x82, 0x80 | len(body)]) + mask  # FIN+binary, masked
    frames, out, closed = dec.feed(header + masked)
    assert frames == [(protocol.OP_CONNECT, {"tenant": "ws"})] and not closed
    # Replies come back ws-framed.
    reply = dec.encode(protocol.OP_AUTH_OK, {"conn": 1})
    assert reply[0] == 0x82


def test_websocket_handshake_split_across_reads():
    dec = protocol.TransportDecoder(1 << 20)
    request = (
        b"GET / HTTP/1.1\r\n"
        b"Upgrade: websocket\r\n"
        b"Sec-WebSocket-Key: dGhlIHNhbXBsZSBub25jZQ==\r\n\r\n"
    )
    out_all = b""
    for i in range(len(request)):
        frames, out, closed = dec.feed(request[i : i + 1])
        assert frames == [] and not closed
        out_all += out
    assert b"101 Switching Protocols" in out_all


# ------------------------------------------------------------------- #
# Admission units: pure bookkeeping, no sockets
# ------------------------------------------------------------------- #


def test_token_auth_open_and_spec_modes():
    open_auth = TokenAuth("")
    assert open_auth.authenticate(None, "t1") == "t1"
    assert open_auth.authenticate("anything", None) == "public"
    closed = TokenAuth("tok-a=alpha,tok-b=beta")
    assert closed.authenticate("tok-a", None) == "alpha"
    assert closed.authenticate("tok-b", "ignored") == "beta"
    assert closed.authenticate("nope", None) is None
    assert closed.authenticate(None, "alpha") is None
    assert closed.authenticate(123, None) is None


def test_tenant_quotas_connections_and_msg_bucket():
    q = TenantQuotas(max_conns=2, msgs_per_sec=10)
    assert q.try_connect("t") and q.try_connect("t")
    assert not q.try_connect("t")
    q.disconnect("t")
    assert q.try_connect("t")
    # Bucket: burst == rate, prefix admission, refill by elapsed time.
    assert q.admit_msgs("t", 25, now=100.0) == 10
    assert q.admit_msgs("t", 5, now=100.0) == 0
    assert q.admit_msgs("t", 8, now=100.5) == 5  # 0.5s -> 5 tokens
    # Disabled rate limiting admits everything.
    assert TenantQuotas(0, 0).admit_msgs("t", 1000, now=0.0) == 1000


def test_overload_controller_hysteresis_and_dwell():
    ctl = OverloadController(p99_band_ms=100.0, depth_band=50)
    now = 0.0
    assert not ctl.shedding(now)
    for _ in range(200):
        ctl.observe(500.0)
    now += 1.0
    assert ctl.shedding(now)
    assert ctl.shed_entered_total == 1
    # Within the dwell window the verdict is frozen even if signals
    # recover instantly.
    ctl._ring.clear()
    for _ in range(200):
        ctl.observe(1.0)
    assert ctl.shedding(now + 0.1)
    # Past the dwell, recovery needs BOTH signals under the exit band.
    ctl.note_depth(49)  # < band but >= exit fraction (25)
    assert ctl.shedding(now + 1.0)
    ctl.note_depth(10)
    assert not ctl.shedding(now + 2.0)


# ------------------------------------------------------------------- #
# End to end: real sockets through a proxy-only gateway
# ------------------------------------------------------------------- #


def test_gateway_end_to_end_ack_push_and_ping(event_log):
    nodes, gw = build_edge(2)
    try:
        client = BenchClient("127.0.0.1", gw.client_port, tenant="t-e2e")
        client.send_cmd(1, "k-0", {"op": "inc"})
        client.send_cmd(2, "k-0", {"op": "inc"})
        client.send_cmd(3, "k-17", {"op": "inc"})
        assert settle(lambda: len(client.acked) == 3, 15.0)
        assert client.acked[2][0] == 2  # counted in order on one key
        assert client.acked[3][0] == 1
        assert not client.errors

        # SUBSCRIBE: a second client's increments push to this one.
        sub = raw_connect(gw.client_port, tenant="t-sub")
        op, _ = _read_one_frame(sub, 10.0)
        assert op == protocol.OP_AUTH_OK
        sub.sendall(
            protocol.encode_frame(
                protocol.OP_SUBSCRIBE, {"type": "counter", "key": "k-0"}
            )
        )
        time.sleep(0.3)  # let the subscription land on the entity
        client.send_cmd(4, "k-0", {"op": "inc"})
        op, value = _read_one_frame(sub, 10.0)
        assert op == protocol.OP_PUSH
        assert value == {"data": {"key": "k-0", "count": 3}}

        # PING keeps the connection honest.
        sub.sendall(protocol.encode_frame(protocol.OP_PING, None))
        op, _ = _read_one_frame(sub, 10.0)
        assert op == protocol.OP_PONG
        sub.close()
        client.close()
        assert settle(lambda: gw.gateway.connection_count() == 0, 10.0)
        opens = [
            f for f in event_log.of(events.GATEWAY_CONNECTION)
            if f.get("action") == "open"
        ]
        assert len(opens) == 2
        assert sum(
            f.get("count", 0) for f in event_log.of(events.GATEWAY_MSG)
        ) == 4
    finally:
        teardown_edge(nodes, gw)


def test_gateway_auth_conn_limit_and_msg_rate_shed(event_log):
    nodes, gw = build_edge(
        1,
        overrides={
            "uigc.gateway.auth-tokens": "tok-a=alpha",
            "uigc.gateway.tenant-max-connections": 1,
            "uigc.gateway.tenant-msgs-per-sec": 5,
        },
    )
    try:
        # Bad token: clean ERR_AUTH, then close.
        bad = raw_connect(gw.client_port, token="wrong")
        op, value = _read_one_frame(bad, 10.0)
        assert (op, value["code"]) == (protocol.OP_ERROR, protocol.ERR_AUTH)
        assert expect_eof(bad)
        bad.close()

        # First tenant connection admitted, second over the cap.
        first = raw_connect(gw.client_port, token="tok-a")
        op, _ = _read_one_frame(first, 10.0)
        assert op == protocol.OP_AUTH_OK
        second = raw_connect(gw.client_port, token="tok-a")
        op, value = _read_one_frame(second, 10.0)
        assert (op, value["code"]) == (
            protocol.OP_ERROR,
            protocol.ERR_CONN_LIMIT,
        )
        assert value["retry_after_ms"] > 0
        second.close()

        # A 20-send burst against a 5/s bucket: the prefix is acked,
        # the excess is seq-addressed ERR_MSG_RATE — nothing silent.
        for seq in range(1, 21):
            first.sendall(
                protocol.encode_frame(
                    protocol.OP_SEND,
                    {"seq": seq, "type": "counter", "key": "k-b",
                     "cmd": {"op": "inc"}},
                )
            )
        acked, errored = {}, {}
        first.settimeout(15.0)
        while len(acked) + len(errored) < 20:
            op, value = _read_one_frame(first, 15.0)
            if op == protocol.OP_ACK:
                acked[value["seq"]] = value["result"]
            elif op == protocol.OP_ERROR:
                assert value["code"] == protocol.ERR_MSG_RATE
                assert value["retry_after_ms"] > 0
                errored[value["seq"]] = value["code"]
        assert len(acked) == 5
        assert sorted(acked) == [1, 2, 3, 4, 5]  # prefix admission
        assert len(errored) == 15
        first.close()
        shed_reasons = {
            f["reason"] for f in event_log.of(events.GATEWAY_SHED)
        }
        assert {"auth", "conn-limit", "msg-rate"} <= shed_reasons
    finally:
        teardown_edge(nodes, gw)


def test_gateway_proto_violation_and_oversize_close_cleanly():
    nodes, gw = build_edge(
        1, overrides={"uigc.gateway.max-frame-bytes": 4096}
    )
    try:
        # Garbage that parses as a frame but not as a client value.
        sock = raw_connect(gw.client_port)
        op, _ = _read_one_frame(sock, 10.0)
        assert op == protocol.OP_AUTH_OK
        sock.sendall(struct.pack(">I", 3) + b"\x7fZZ")
        op, value = _read_one_frame(sock, 10.0)
        assert (op, value["code"]) == (protocol.OP_ERROR, protocol.ERR_PROTO)
        assert expect_eof(sock)
        sock.close()

        # A frame header past max-frame-bytes drops the connection
        # without reading the body.
        big = raw_connect(gw.client_port)
        op, _ = _read_one_frame(big, 10.0)
        assert op == protocol.OP_AUTH_OK
        big.sendall(struct.pack(">I", 1 << 30))
        assert expect_eof(big)
        big.close()
        # The gateway itself is unharmed.
        ok = raw_connect(gw.client_port)
        op, _ = _read_one_frame(ok, 10.0)
        assert op == protocol.OP_AUTH_OK
        ok.close()
    finally:
        teardown_edge(nodes, gw)


def test_gateway_drain_is_clean_and_refuses_new_connects():
    nodes, gw = build_edge(1)
    try:
        sock = raw_connect(gw.client_port)
        op, _ = _read_one_frame(sock, 10.0)
        assert op == protocol.OP_AUTH_OK
        gw.gateway.drain()
        op, value = _read_one_frame(sock, 10.0)
        assert (op, value["code"]) == (
            protocol.OP_ERROR,
            protocol.ERR_DRAINING,
        )
        assert value["retry_after_ms"] > 0
        assert expect_eof(sock)
        sock.close()
        assert settle(lambda: gw.gateway.connection_count() == 0, 10.0)
        # The listener is closed: a late connect is refused — or, on
        # loopback, may "succeed" as a kernel self-connect (ephemeral
        # source port == destination port) with no server behind it.
        # Either way the gateway admits no new session.
        try:
            late = socket.create_connection(
                ("127.0.0.1", gw.client_port), timeout=2.0
            )
        except OSError:
            pass
        else:
            assert late.getpeername() == late.getsockname()
            late.close()
        assert gw.gateway.connection_count() == 0
    finally:
        teardown_edge(nodes, gw)


def test_gateway_proxy_member_owns_no_shards_and_excludes_self():
    """Regression: the fabric's subscribe replay includes the node's
    own address; a proxy-only member must not re-enter its own
    placement view (a table claiming the whole keyspace for a node
    with no regions would blackhole every route)."""
    nodes, gw = build_edge(2)
    try:
        data_addrs = {n.address for n in nodes}
        assert set(gw.cluster.members()) == data_addrs
        assert gw.address not in gw.cluster.members()
        for n in nodes:
            assert gw.address not in n.cluster.members()
        homes = {gw.cluster.home_of(f"k-{i}") for i in range(64)}
        assert homes <= data_addrs
        assert gw.address not in homes
    finally:
        teardown_edge(nodes, gw)


def test_gateway_egress_backlog_throttles_reads(event_log):
    """Flow control one hop past PR 12: a client that stops draining
    its replies gets its READS throttled (kernel TCP backpressure does
    the rest), accounted as fabric.backpressure{site=gateway}, and
    resumes once the egress queue drains."""
    nodes, gw = build_edge(
        1, overrides={"uigc.gateway.egress-queue-limit": 120}
    )
    try:
        sock = raw_connect(gw.client_port, tenant="t-slow")
        op, _ = _read_one_frame(sock, 10.0)
        assert op == protocol.OP_AUTH_OK
        # 100 PINGs, replies unread: the egress queue passes half its
        # bound (60) and the read path must throttle this connection.
        ping = protocol.encode_frame(protocol.OP_PING, None)
        sock.sendall(ping * 100)
        assert settle(lambda: gw.gateway.stats["throttle"] >= 1, 15.0)
        throttles = [
            f for f in event_log.of(events.BACKPRESSURE)
            if f.get("site") == "gateway" and f.get("action") == "throttle"
        ]
        assert throttles and throttles[0]["dst"] == "t-slow"
        # Drain the replies: every PONG arrives (throttling reads never
        # drops queued egress), then the reader resumes the connection.
        for _ in range(100):
            op, _ = _read_one_frame(sock, 15.0)
            assert op == protocol.OP_PONG
        assert settle(lambda: gw.gateway.stats["resume"] >= 1, 15.0)
        resumed = [
            f for f in event_log.of(events.BACKPRESSURE)
            if f.get("site") == "gateway" and f.get("action") == "resume"
        ]
        assert resumed
        sock.close()
    finally:
        teardown_edge(nodes, gw)


def test_gateway_slow_consumer_past_egress_bound_is_shed(event_log):
    nodes, gw = build_edge(
        1, overrides={"uigc.gateway.egress-queue-limit": 16}
    )
    try:
        sock = raw_connect(gw.client_port)
        op, _ = _read_one_frame(sock, 10.0)
        assert op == protocol.OP_AUTH_OK
        ping = protocol.encode_frame(protocol.OP_PING, None)
        # Far past the bound in one burst: enqueue fails, the gateway
        # closes the connection rather than buffer without limit.
        sock.sendall(ping * 200)
        assert settle(
            lambda: gw.gateway.stats["shed:slow-consumer"] >= 1, 15.0
        )
        sock.close()
    finally:
        teardown_edge(nodes, gw)


# ------------------------------------------------------------------- #
# Client-socket fault units
# ------------------------------------------------------------------- #


def test_client_fault_flood_and_slowloris(event_log):
    plan = FaultPlan(seed=11).client_fault(faults.FLOOD, count=2)
    nodes, gw = build_edge(1, gw_plan=plan)
    try:
        # The first two accepts are slammed shut before admission.
        for _ in range(2):
            sock = socket.create_connection(
                ("127.0.0.1", gw.client_port), timeout=5.0
            )
            sock.settimeout(5.0)
            assert expect_eof(sock)
            sock.close()
        assert gw.gateway.stats["shed:flood"] == 2
        # The budget is spent: the third connection admits normally.
        ok = raw_connect(gw.client_port)
        op, _ = _read_one_frame(ok, 10.0)
        assert op == protocol.OP_AUTH_OK
        ok.close()
    finally:
        teardown_edge(nodes, gw)

    # Slowloris: the CONNECT trickles in at ~1 byte per select round.
    # A selector reader must complete the handshake anyway, without a
    # worker thread held hostage.
    plan = FaultPlan(seed=12).client_fault(faults.SLOWLORIS)
    nodes, gw = build_edge(1, gw_plan=plan)
    try:
        sock = raw_connect(gw.client_port)
        op, _ = _read_one_frame(sock, 30.0)
        assert op == protocol.OP_AUTH_OK
        sock.close()
    finally:
        teardown_edge(nodes, gw)


def test_client_fault_half_open_and_truncate():
    plan = FaultPlan(seed=13).client_fault(faults.HALF_OPEN, count=1)
    nodes, gw = build_edge(1, gw_plan=plan)
    try:
        # The half-open victim's bytes vanish: no AUTH_OK ever comes,
        # but the gateway holds the session without crashing and keeps
        # serving everyone else.
        ghost = raw_connect(gw.client_port)
        ghost.settimeout(1.0)
        with pytest.raises(TimeoutError):
            _read_one_frame(ghost, 1.0)
        ok = raw_connect(gw.client_port)
        op, _ = _read_one_frame(ok, 10.0)
        assert op == protocol.OP_AUTH_OK
        ok.close()
        ghost.close()
    finally:
        teardown_edge(nodes, gw)

    plan = FaultPlan(seed=14).client_fault(faults.TRUNCATE, count=1)
    nodes, gw = build_edge(1, gw_plan=plan)
    try:
        # The truncated connection dies mid-frame; the gateway reaps it
        # and the next connection is unaffected.
        torn = raw_connect(gw.client_port)
        assert expect_eof(torn, 15.0)
        torn.close()
        ok = raw_connect(gw.client_port)
        op, _ = _read_one_frame(ok, 10.0)
        assert op == protocol.OP_AUTH_OK
        ok.close()
    finally:
        teardown_edge(nodes, gw)


# ------------------------------------------------------------------- #
# Chaos acceptance
# ------------------------------------------------------------------- #


def test_chaos_faulted_clients_and_node_death_lose_nothing(
    tmp_path, event_log
):
    """3 entity nodes + 1 gateway under client-socket faults and one
    abrupt entity-node death mid-run: every command an un-faulted
    client sent resolves to an ACK or a clean seq-addressed ERROR, and
    after rehoming no acked increment has vanished."""
    gw_plan = FaultPlan(seed=21).client_fault(faults.FLOOD, count=1)
    nodes, gw = build_edge(
        3,
        journal_dir=tmp_path,
        gw_plan=gw_plan,
        overrides={"uigc.gateway.tenant-msgs-per-sec": 0},
    )
    try:
        # The flood budget burns on the first accept so the real
        # clients below admit deterministically.
        burn = socket.create_connection(
            ("127.0.0.1", gw.client_port), timeout=5.0
        )
        assert expect_eof(burn)
        burn.close()
        assert gw.gateway.stats["shed:flood"] == 1

        keys = [f"c-{i}" for i in range(16)]
        clients = [
            BenchClient("127.0.0.1", gw.client_port, tenant=f"t{i}")
            for i in range(3)
        ]
        seq = 0
        stop = threading.Event()
        lock = threading.Lock()
        key_of = {}  # seq -> key, the senders' ledger

        def pump(client, offset):
            nonlocal seq
            i = offset
            while not stop.is_set():
                key = keys[i % len(keys)]
                with lock:
                    seq += 1
                    s = seq
                    key_of[s] = key
                try:
                    client.send_cmd(s, key, {"op": "inc"})
                except OSError:
                    return
                i += 1
                time.sleep(0.01)

        threads = [
            threading.Thread(target=pump, args=(c, i), daemon=True)
            for i, c in enumerate(clients)
        ]
        for t in threads:
            t.start()
        time.sleep(2.0)
        # One entity node dies abruptly mid-traffic.
        victim = nodes[2]
        victim.fabric.die()
        survivors = nodes[:2]
        assert settle(
            lambda: all(
                len(n.cluster.members()) == 2 for n in survivors
            ) and len(gw.cluster.members()) == 2,
            30.0,
        ), "survivors never converged after die()"
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)

        # Drain with bounded retries: an abrupt death can orphan an
        # in-flight command (applied-but-unacked on the victim, or
        # dropped from a bounded re-route buffer).  A real client
        # retries through the front door; retries are at-least-once,
        # which the ledger check below tolerates (it is a >=).
        def unresolved(c):
            with c.lock:
                return [
                    s for s in c.sent_at
                    if s not in c.acked and s not in c.errors
                ]

        for _round in range(4):
            if settle(
                lambda: all(c.outstanding() == 0 for c in clients), 20.0
            ):
                break
            for c in clients:
                for s in unresolved(c):
                    try:
                        c.send_cmd(s, key_of[s], {"op": "inc"})
                    except OSError:
                        pass
        assert all(c.outstanding() == 0 for c in clients), [
            c.outstanding() for c in clients
        ]

        acked = sum(len(c.acked) for c in clients)
        errored = sum(len(c.errors) for c in clients)
        assert acked > 0
        assert acked + errored == sum(len(c.sent_at) for c in clients)

        # acked-then-lost must be zero: every ACK result is the
        # post-apply count, so each key's final count (probed through
        # the same front door, after rehoming) must cover the highest
        # result any client was acked for that key.
        max_acked = {}
        for c in clients:
            with c.lock:
                entries = list(c.acked.items())
            for s, (result, _lat) in entries:
                key = key_of.get(s)
                if (
                    key is not None
                    and isinstance(result, int)
                    and result > max_acked.get(key, 0)
                ):
                    max_acked[key] = result
        prober = clients[0]
        probe_base = 10_000_000
        for i, key in enumerate(keys):
            prober.send_cmd(probe_base + i, key, {"probe": True})
        assert settle(lambda: prober.outstanding() == 0, 30.0)
        finals = {
            key: prober.acked.get(probe_base + i, (None,))[0]
            for i, key in enumerate(keys)
        }
        lost = {
            key: (high, finals.get(key))
            for key, high in max_acked.items()
            if not isinstance(finals.get(key), int) or finals[key] < high
        }
        assert not lost, lost
        for c in clients:
            c.close()
    finally:
        teardown_edge(nodes, gw)
