"""Differential tests for the incremental (base+delta) Pallas layout.

The layout must produce byte-identical mark vectors to the numpy oracle
at every point of a random mutation history — inserts into the delta,
in-place base masking on delete, supervisor retargeting, forced repacks,
and delete-then-reinsert of the same pair (the masked-slot path).  On
CPU the kernel runs in Pallas interpret mode; the graph-level test also
drives the whole engine fold path through it (reference semantics:
ShadowGraph.java:205-289).
"""

import numpy as np
import pytest

from uigc_tpu.ops import pallas_incremental as pinc
from uigc_tpu.ops import trace as trace_ops

F = trace_ops


class GroundTruth:
    """Plain dict/array mirror of the live pair set."""

    def __init__(self, rng, n):
        self.rng = rng
        self.n = n
        self.edges = {}  # (src, dst) -> True
        self.supervisor = np.full(n, -1, dtype=np.int32)
        self.flags = np.zeros(n, dtype=np.uint8)
        in_use = rng.random(n) < 0.9
        self.flags[in_use] |= F.FLAG_IN_USE
        self.flags[rng.random(n) < 0.8] |= F.FLAG_INTERNED
        self.flags[rng.random(n) < 0.06] |= F.FLAG_BUSY
        self.flags[rng.random(n) < 0.04] |= F.FLAG_ROOT
        self.flags[rng.random(n) < 0.08] |= F.FLAG_HALTED
        self.recv = np.zeros(n, dtype=np.int64)
        self.recv[rng.random(n) < 0.1] = 3

    def edge_arrays(self):
        m = len(self.edges)
        src = np.fromiter((k[0] for k in self.edges), np.int32, m)
        dst = np.fromiter((k[1] for k in self.edges), np.int32, m)
        w = np.ones(m, dtype=np.int64)
        return src, dst, w

    def mutate(self, layout):
        """One random pair transition, mirrored into the layout."""
        rng = self.rng
        p = rng.random()
        if p < 0.5 or not self.edges:
            src = int(rng.integers(0, self.n))
            dst = int(rng.integers(0, self.n))
            if (src, dst) in self.edges:
                return
            self.edges[(src, dst)] = True
            layout.insert(src, dst, pinc.EDGE)
        elif p < 0.8:
            idx = int(rng.integers(0, len(self.edges)))
            key = list(self.edges)[idx]
            del self.edges[key]
            layout.remove(key[0], key[1], pinc.EDGE)
        else:
            child = int(rng.integers(0, self.n))
            old = int(self.supervisor[child])
            new = int(rng.integers(-1, self.n))
            if old == new:
                return
            if old >= 0:
                layout.remove(child, old, pinc.SUP)
            if new >= 0:
                layout.insert(child, new, pinc.SUP)
            self.supervisor[child] = new

    def expected_marks(self):
        src, dst, w = self.edge_arrays()
        return trace_ops.trace_marks_np(
            self.flags, self.recv, self.supervisor, src, dst, w
        )


def run_history(seed, n, steps, check_every, interpret=True, **layout_kw):
    rng = np.random.default_rng(seed)
    gt = GroundTruth(rng, n)
    # seed an initial population so the base layout is non-trivial
    for _ in range(n * 2):
        src = int(rng.integers(0, n))
        dst = int(rng.integers(0, n))
        gt.edges[(src, dst)] = True
    sup_mask = rng.random(n) < 0.3
    gt.supervisor[sup_mask] = rng.integers(0, n, size=int(sup_mask.sum()))

    # s_rows=8 keeps supertiles at 1024 nodes so these graph sizes span
    # several of them (the compact-tier super_ids scatter and out-block
    # revisit logic need multi-supertile coverage; the production default
    # of 32 would collapse n=2500 into one supertile).
    layout_kw.setdefault("s_rows", 8)
    layout = pinc.IncrementalPallasLayout(n, interpret=interpret, **layout_kw)
    src, dst, w = gt.edge_arrays()
    layout.rebuild(src, dst, w, gt.supervisor)

    checks = 0
    for step in range(steps):
        gt.mutate(layout)
        if (step + 1) % check_every == 0:
            if layout.needs_repack:
                src, dst, w = gt.edge_arrays()
                layout.rebuild(src, dst, w, gt.supervisor)
            got = layout.trace(gt.flags, gt.recv)
            expected = gt.expected_marks()
            assert np.array_equal(got, expected), f"divergence at step {step}"
            checks += 1
    assert checks > 0
    assert layout.stats["anomalies"] == 0
    return layout


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_incremental_matches_oracle(seed):
    # n spans multiple supertiles (super = 8 * 128 = 1024 nodes here)
    layout = run_history(seed, n=2500, steps=600, check_every=60)
    # the whole point: churn was absorbed without full repacks
    assert layout.stats["rebuilds"] == 1


def test_forced_repacks_stay_correct():
    layout = run_history(
        7, n=1500, steps=400, check_every=40, min_repack=32, repack_fraction=0.01
    )
    assert layout.stats["rebuilds"] > 1


def test_freeze_and_consolidate_stay_correct():
    """Tiny thresholds force the full tier lifecycle: live tier -> frozen
    compact chain -> consolidation, with deletes masking frozen slots."""
    layout = run_history(
        13, n=2500, steps=500, check_every=25, freeze_threshold=24, max_frozen=2
    )
    assert layout.stats["freezes"] > 2
    assert layout.stats["consolidations"] >= 1
    assert layout.stats["rebuilds"] == 1


def test_delete_then_reinsert_base_pair():
    n = 1200
    rng = np.random.default_rng(3)
    gt = GroundTruth(rng, n)
    # one deterministic keep-alive chain through three supertile-crossing hops
    a, b, c = 5, 600, 1100
    gt.flags[[a, b, c]] = F.FLAG_IN_USE | F.FLAG_INTERNED
    gt.flags[a] |= F.FLAG_ROOT
    gt.edges[(a, b)] = True
    gt.edges[(b, c)] = True
    layout = pinc.IncrementalPallasLayout(n, s_rows=8, interpret=True)
    src, dst, w = gt.edge_arrays()
    layout.rebuild(src, dst, w, gt.supervisor)
    assert layout.trace(gt.flags, gt.recv)[c]

    # delete (a,b) from the base -> c unreachable
    del gt.edges[(a, b)]
    layout.remove(a, b, pinc.EDGE)
    got = layout.trace(gt.flags, gt.recv)
    assert not got[b] and not got[c]
    assert np.array_equal(got, gt.expected_marks())

    # re-insert the same pair -> lands in the delta, reachability restored
    gt.edges[(a, b)] = True
    layout.insert(a, b, pinc.EDGE)
    got = layout.trace(gt.flags, gt.recv)
    assert got[b] and got[c]
    assert np.array_equal(got, gt.expected_marks())
    assert layout.stats["anomalies"] == 0


def test_graph_level_protocol_parity(monkeypatch):
    """Drive the full entry-fold path (ArrayShadowGraph) through the
    incremental Pallas layout in interpret mode: the _pair_log plumbing
    between graph mutations and the layout is what's under test."""
    from uigc_tpu.engines.crgc.arrays import ArrayShadowGraph
    from test_trace_parity import Sim

    monkeypatch.setattr(ArrayShadowGraph, "_on_tpu", lambda self: True)

    sim = Sim(11, backend="device")
    for _ in range(6):
        for _ in range(80):
            sim.random_step()
        sim.collect_round()

    sim.drain_inboxes()
    for actor in sim.live_actors():
        for ref in list(actor.acquaintances):
            actor.release(ref)
    sim.drain_inboxes()
    for actor in sim.live_actors():
        actor.flush()
    for _ in range(5):
        sim.collect_round()
    survivors = {a.cell for a in sim.live_actors()}
    assert survivors == {sim.root.cell}

    inc = sim.array._inc
    assert inc is not None and inc.stats["anomalies"] == 0


@pytest.mark.parametrize("seed", [0, 1])
def test_trace_device_matches_trace(seed):
    """The device-resident operand path (trace_device: mirrors + O(churn)
    masking scatters) must produce the same marks as the host-operand
    trace across a mutation history with freezes and consolidations —
    including after rebuilds, which must invalidate the mirrors."""
    import jax

    rng = np.random.default_rng(seed)
    n = 2500
    gt = GroundTruth(rng, n)
    for _ in range(n * 2):
        gt.edges[(int(rng.integers(0, n)), int(rng.integers(0, n)))] = True
    layout = pinc.IncrementalPallasLayout(
        n, s_rows=8, interpret=True, freeze_threshold=24, max_frozen=2
    )
    src, dst, w = gt.edge_arrays()
    layout.rebuild(src, dst, w, gt.supervisor)

    flags_dev = jax.device_put(gt.flags)
    recv_dev = jax.device_put(gt.recv)
    for step in range(8):
        for _ in range(40):
            gt.mutate(layout)
        got = np.asarray(layout.trace_device(flags_dev, recv_dev))
        expected = gt.expected_marks()
        assert np.array_equal(got, expected), f"divergence at step {step}"
    assert layout.stats["anomalies"] == 0
    # the run must actually exercise the frozen-tier mirrors and their
    # GC at consolidation, or this test is not covering what it claims
    assert layout.stats["freezes"] > 0
    assert layout.stats["consolidations"] >= 1

    # a forced rebuild must drop stale mirrors
    src, dst, w = gt.edge_arrays()
    layout.rebuild(src, dst, w, gt.supervisor)
    got = np.asarray(layout.trace_device(flags_dev, recv_dev))
    assert np.array_equal(got, gt.expected_marks())
