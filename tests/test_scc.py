"""Device SCC kernel: differential vs Tarjan, and the MAC detector's
large-set device path.

The detector path test forces ``device-scc-threshold: 0`` so even a tiny
blocked set routes through ops/scc.py — the cycle must still be found,
confirmed, and killed exactly as with host Tarjan.
"""

import time

import numpy as np
import pytest

from uigc_tpu import ActorTestKit, Behaviors
from uigc_tpu.ops import scc


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_scc_matches_tarjan(seed):
    rng = np.random.default_rng(seed)
    for _ in range(10):
        n = int(rng.integers(2, 120))
        m = int(rng.integers(0, n * 3))
        src = rng.integers(0, n, m).astype(np.int32)
        dst = rng.integers(0, n, m).astype(np.int32)
        active = rng.random(n) < 0.8
        pad = int(rng.integers(0, 5))
        src_p = np.concatenate([src, np.full(pad, -1, np.int32)])
        dst_p = np.concatenate([dst, np.full(pad, -1, np.int32)])
        expected = scc.scc_labels_np(n, src, dst, active)
        got = scc.scc_labels_jax(n, src_p, dst_p, active)
        assert np.array_equal(got, expected)


def test_scc_ring_and_chain():
    # One 5-ring plus a 5-chain: the ring is one SCC, chain nodes are
    # singletons.
    ring = np.arange(5, dtype=np.int32)
    src = np.concatenate([ring, np.arange(5, 9, dtype=np.int32)])
    dst = np.concatenate([np.roll(ring, -1), np.arange(6, 10, dtype=np.int32)])
    labels = scc.scc_labels_jax(10, src, dst)
    assert (labels[:5] == 4).all()
    assert (labels[5:] == np.arange(5, 10)).all()


def test_mac_cycle_collected_via_device_scc():
    from test_mac import Drop, Root, Share, Stopped

    kit = ActorTestKit(
        {
            "uigc.engine": "mac",
            "uigc.mac.cycle-detection": True,
            "uigc.mac.wakeup-interval": 10,
            "uigc.mac.device-scc-threshold": 0,
        }
    )
    try:
        probe = kit.create_test_probe(timeout_s=30.0)
        root = kit.spawn(Behaviors.setup_root(lambda c: Root(c, probe)), "root")
        root.tell(Share(None))
        time.sleep(0.2)
        root.tell(Drop())
        probe.expect_message_type(Stopped)
        probe.expect_message_type(Stopped)
        assert kit.system.engine.detector.total_cycles_collected >= 1
    finally:
        kit.shutdown()
