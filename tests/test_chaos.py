"""Chaos suite: seeded fault plans against the real TCP node transport.

Three NodeFabrics (one ActorSystem each) live in THIS process, talking
over real localhost sockets — the same wire stack as the multi-process
tests, but with every node's state inspectable and with deterministic,
seeded fault injection (runtime/faults.py) at the frame edges:

- drop / duplicate / reorder / delay / truncate faults on the links of a
  doomed node while application churn is in flight;
- silent node death (links muted, engine stopped, sockets left open) that
  only the phi-accrual heartbeat (runtime/heartbeat.py) can detect;
- post-mortem frames to reclaimed uids, which must still tally on the
  ingress and release carried refs (the dead-letter accounting path);
- torn sockets healed by reconnect-with-backoff under frame sequence
  numbering (duplicates discarded, gaps detected).

The invariants asserted are CRGC's crash-safety contract: no actor that
should be alive is ever collected, recv balances return to zero once the
responsible node's undo log folds, and the same seed yields the same
outcome.
"""

import threading
import time

import pytest

from uigc_tpu import AbstractBehavior, Behaviors, Message, NoRefs, PostStop
from uigc_tpu.runtime.behaviors import RawBehavior
from uigc_tpu.runtime.faults import FaultPlan
from uigc_tpu.runtime.heartbeat import PhiAccrualFailureDetector
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.runtime.system import ActorSystem
from uigc_tpu.runtime.testkit import TestProbe
from uigc_tpu.utils import events

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.crgc.shadow-graph": "array",
}


class Ping(NoRefs):
    pass


class Share(Message):
    def __init__(self, ref):
        self.ref = ref

    @property
    def refs(self):
        return (self.ref,) if self.ref is not None else ()


class Drop(NoRefs):
    pass


class Spawned(NoRefs):
    def __init__(self, name):
        self.name = name


class Stopped(NoRefs):
    def __init__(self, name):
        self.name = name


class RemoteProbe:
    """Probe facade whose .ref is a ProxyCell of node A's forwarder."""

    def __init__(self, cell):
        self.ref = cell


class ProbeForwarder(RawBehavior):
    def __init__(self, probe):
        self.probe = probe

    def on_message(self, msg):
        self.probe._offer(msg)
        return None


class Worker(AbstractBehavior):
    def __init__(self, context, probe):
        super().__init__(context)
        self.probe = probe
        self.pings = 0
        probe.ref.tell(Spawned(context.name))

    def on_message(self, msg):
        if isinstance(msg, Ping):
            self.pings += 1
        return self

    def on_signal(self, signal):
        if signal is PostStop:
            self.probe.ref.tell(Stopped(self.context.name))
        return None


class Holder(AbstractBehavior):
    """Root on the doomed node, holding the only ref to a remote worker
    and pinging it (churn on the doomed links)."""

    def __init__(self, context):
        super().__init__(context)
        self.held = None

    def on_message(self, msg):
        if isinstance(msg, Share):
            self.held = msg.ref
        if self.held is not None:
            self.held.tell(Ping(), self.context)
        return self


class Owner(AbstractBehavior):
    """Root on node B owning a worker; hands the ref to the doomed
    node's holder, then releases its own."""

    def __init__(self, context, probe, holder_ref):
        super().__init__(context)
        self.worker = context.spawn(
            Behaviors.setup(lambda ctx: Worker(ctx, probe)), "worker"
        )
        self.holder_ref = holder_ref

    def on_message(self, msg):
        ctx = self.context
        if isinstance(msg, Share):
            self.holder_ref.tell(
                Share(ctx.create_ref(self.worker, self.holder_ref)), ctx
            )
        elif isinstance(msg, Drop):
            ctx.release(self.worker)
        return self


class KeptWorkerRoot(AbstractBehavior):
    """Root on node A holding a worker it spawned remotely-by-share; its
    worker must SURVIVE every chaos run (the over-collection canary)."""

    def __init__(self, context, worker_ref):
        super().__init__(context)
        self.worker = worker_ref

    def on_message(self, msg):
        if isinstance(msg, Ping) and self.worker is not None:
            self.worker.tell(Ping(), self.context)
        return self


class Node:
    __slots__ = ("fabric", "system", "port", "address")

    def __init__(self, name, config, plan):
        self.fabric = NodeFabric(fault_plan=plan)
        self.system = ActorSystem(None, name=name, config=config, fabric=self.fabric)
        self.port = self.fabric.listen()
        self.address = self.system.address


def build_cluster(names, plan=None, overrides=None):
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = len(names)
    if overrides:
        config.update(overrides)
    nodes = [Node(n, config, plan) for n in names]
    return nodes


def connect_mesh(nodes):
    for i, a in enumerate(nodes):
        for b in nodes[i + 1 :]:
            a.fabric.connect("127.0.0.1", b.port)


def terminate_all(nodes):
    for n in nodes:
        try:
            n.system.terminate(timeout_s=5.0)
        except Exception:
            pass


def settle(predicate, timeout_s=20.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


def nonzero_recv(node):
    return node.system.engine.bookkeeper.shadow_graph.investigate_live_set()[
        "nonzero_recv"
    ]


class EventLog:
    """Capture the structured failure-event stream for assertions."""

    def __init__(self):
        self.entries = []
        self._lock = threading.Lock()

    def __call__(self, name, fields):
        with self._lock:
            self.entries.append((name, fields))

    def names(self):
        with self._lock:
            return [n for n, _ in self.entries]

    def of(self, name):
        with self._lock:
            return [f for n, f in self.entries if n == name]


@pytest.fixture
def event_log():
    log = EventLog()
    events.recorder.enable()
    events.recorder.add_listener(log)
    yield log
    events.recorder.disable()
    events.recorder.remove_listener(log)
    events.recorder.reset()


# ------------------------------------------------------------------- #
# Unit layer: the plan and the detector
# ------------------------------------------------------------------- #


def test_fault_plan_is_deterministic_per_seed():
    def draw(seed):
        plan = (
            FaultPlan(seed)
            .drop(src="a", dst="b", kind="app", prob=0.4)
            .duplicate(src="a", dst="b", prob=0.3)
            .truncate(src="b", dst="a", prob=0.5)
        )
        return (
            [plan.outbound("a", "b", "app")[0] for _ in range(50)],
            [plan.outbound("b", "a", "app")[0] for _ in range(50)],
        )

    assert draw(11) == draw(11)
    assert draw(11) != draw(12)


def test_fault_plan_links_are_independent_streams():
    plan = FaultPlan(3).drop(prob=0.5)
    ab = [plan.outbound("a", "b", "app")[0] for _ in range(40)]
    # interleaving traffic on another link must not perturb a->b draws
    plan2 = FaultPlan(3).drop(prob=0.5)
    ab2 = []
    for _ in range(40):
        plan2.outbound("c", "d", "app")
        ab2.append(plan2.outbound("a", "b", "app")[0])
    assert ab == ab2


def test_fault_plan_partition_and_crash_budget():
    plan = FaultPlan(0).partition("a", "b").crash_at("a", 3)
    assert plan.outbound("a", "b", "app")[0] == "drop"
    assert plan.outbound("b", "a", "hb")[0] == "drop"
    plan.heal("a", "b")
    assert plan.outbound("a", "b", "app")[0] == "deliver"
    assert [plan.record_sent("a") for _ in range(4)] == [False, False, True, False]


def test_phi_accrual_detector_rises_on_silence():
    det = PhiAccrualFailureDetector(threshold=8.0, acceptable_pause_s=0.1)
    t = 0.0
    for _ in range(30):
        det.heartbeat(t)
        t += 0.05
    assert det.phi(t + 0.05) < 1.0  # a normal gap is unsuspicious
    assert det.phi(t + 5.0) > 8.0  # long silence crosses the threshold
    det.heartbeat(t + 6.0)
    assert det.phi(t + 6.05) < 1.0  # recovery resets suspicion


# ------------------------------------------------------------------- #
# Integration layer: real sockets, seeded chaos
# ------------------------------------------------------------------- #


def _run_crash_scenario(seed):
    """One full run of the acceptance scenario: three nodes, churn, a
    seeded fault barrage on the doomed node's links, then silent death
    detected by the heartbeat.  Returns the outcome tuple the
    determinism assertion compares."""
    names = [f"chs{seed}a", f"chs{seed}b", f"chs{seed}c"]
    plan = FaultPlan(seed)
    nodes = build_cluster(
        names,
        plan=plan,
        overrides={
            "uigc.node.heartbeat-interval": 40,
            "uigc.node.phi-threshold": 6.0,
            "uigc.node.heartbeat-pause": 400,
        },
    )
    a, b, c = nodes
    try:
        probe = TestProbe(default_timeout_s=30.0)
        probe_cell = a.system.spawn_system_raw(ProbeForwarder(probe), "probe-fwd")
        a.fabric.register_name("probe", probe_cell)
        connect_mesh(nodes)

        # Seeded barrage on the doomed node's app links, both directions.
        for src, dst in ((b.address, c.address), (c.address, b.address),
                         (a.address, c.address), (c.address, a.address)):
            plan.drop(src=src, dst=dst, kind="app", prob=0.2)
            plan.duplicate(src=src, dst=dst, kind="app", prob=0.2)
            plan.reorder(src=src, dst=dst, kind="app", prob=0.1)
            plan.truncate(src=src, dst=dst, kind="app", prob=0.1)

        remote_probe = RemoteProbe(probe_cell)
        holder = c.system.spawn_root(
            Behaviors.setup_root(lambda ctx: Holder(ctx)), "holder"
        )
        # B's route to C's holder: the cached proxy for its (address, uid)
        # token (what a name lookup would resolve to).
        holder_proxy = b.fabric._proxy(c.address, holder.cell.uid)
        owner = b.system.spawn_root(
            Behaviors.setup_root(
                lambda ctx: Owner(
                    ctx, remote_probe, ctx.engine.to_root_refob(holder_proxy)
                )
            ),
            "owner",
        )
        spawned = probe.expect_message_type(Spawned)

        owner.tell(Share(None))  # hand the only surviving ref to C
        # churn: C's holder pings the worker across the faulty link
        for _ in range(30):
            holder.tell(Ping())
            time.sleep(0.005)
        owner.tell(Drop())  # B releases; only C's ref keeps the worker
        probe.expect_no_message(0.4)

        # Silent death: C's links go dark and its engine stops, but the
        # sockets stay open — no EOF.  Only the heartbeat can see this.
        plan.isolate(c.address)
        c.system.engine.on_crash()

        stopped = probe.expect_message_type(Stopped, timeout_s=30.0)
        assert stopped.name == spawned.name

        # Survivors converge: every recv balance folds back to zero.
        assert settle(lambda: nonzero_recv(a) == 0 and nonzero_recv(b) == 0), (
            f"recv balances never converged: A={nonzero_recv(a)} "
            f"B={nonzero_recv(b)}"
        )
        assert c.address not in a.fabric.members()
        assert c.address not in b.fabric.members()
        return (
            stopped.name,
            sorted(a.fabric.members()),
            sorted(b.fabric.members()),
        )
    finally:
        terminate_all(nodes)


@pytest.mark.parametrize("seed", [101, 202])
def test_chaos_silent_crash_heartbeat_recovery(seed, event_log):
    """The acceptance scenario: a seeded FaultPlan batters the doomed
    node's links mid-churn, the node dies silently, the phi-accrual
    heartbeat declares it dead, finalize_dead_link + the undo-log quorum
    revert its claims, and the only-held-by-the-dead worker collapses —
    with zero surviving recv imbalance."""
    outcome = _run_crash_scenario(seed)

    names = event_log.names()
    downs = [
        f for f in event_log.of(events.NODE_DOWN) if f.get("reason") == "heartbeat"
    ]
    assert downs, f"no heartbeat-driven down verdict in {set(names)}"
    assert events.DEAD_LINK_FINALIZED in names
    assert events.UNDO_FOLD in names
    # fault injection visibly happened on the wire
    assert events.FRAME_DROPPED in names

    assert outcome[0].endswith("/worker")


@pytest.mark.slow
def test_chaos_silent_crash_is_deterministic():
    """Two runs of the same seed produce the same outcome (collected
    actor, surviving membership)."""
    assert _run_crash_scenario(77) == _run_crash_scenario(77)


@pytest.mark.parametrize("seed", [5, 6])
def test_chaos_churn_never_overcollects(seed, event_log):
    """Bounded drop/duplicate/reorder/truncate faults on a surviving
    link must never collect a live actor: the canary worker (held by a
    live root throughout) survives the barrage, and the seq layer's
    duplicate/gap detections are visible."""
    names = [f"chn{seed}a", f"chn{seed}b"]
    plan = FaultPlan(seed)
    nodes = build_cluster(names)
    a, b = nodes
    try:
        probe = TestProbe(default_timeout_s=20.0)
        probe_cell = a.system.spawn_system_raw(ProbeForwarder(probe), "probe-fwd")
        a.fabric.register_name("probe", probe_cell)
        connect_mesh(nodes)

        remote_probe = RemoteProbe(probe_cell)
        # worker lives on B, held by a root on B that keeps it pinned
        worker_holder = b.system.spawn_root(
            Behaviors.setup_root(
                lambda ctx: KeptWorkerRoot(
                    ctx,
                    ctx.spawn(
                        Behaviors.setup(lambda c2: Worker(c2, remote_probe)),
                        "canary",
                    ),
                )
            ),
            "keeper",
        )
        spawned = probe.expect_message_type(Spawned)
        assert spawned.name.endswith("/canary")

        # Bounded faults (count=) so the link heals by exhaustion.
        plan.drop(src=a.address, dst=b.address, kind="app", prob=0.3, count=10)
        plan.duplicate(src=a.address, dst=b.address, prob=0.3, count=10)
        plan.reorder(src=a.address, dst=b.address, kind="app", prob=0.2, count=6)
        plan.truncate(src=a.address, dst=b.address, kind="app", prob=0.2, count=6)
        a.fabric.set_fault_plan(plan)
        b.fabric.set_fault_plan(plan)

        for _ in range(120):
            worker_holder.tell(Ping())
        time.sleep(1.0)

        # The canary never died, membership never wavered.
        probe.expect_no_message(0.5)
        assert sorted(a.fabric.members()) == sorted([a.address, b.address])
        assert sorted(b.fabric.members()) == sorted([a.address, b.address])
        st = b.fabric._peer_state(a.address)
        dup_events = event_log.of(events.FRAME_DUPLICATE)
        gap_events = event_log.of(events.FRAME_GAP)
        assert st.dups == len(
            [f for f in dup_events if f.get("src") == a.address]
        )
        assert (st.dups + st.gaps) > 0 or (len(dup_events) + len(gap_events)) > 0
    finally:
        terminate_all(nodes)


def test_postmortem_dead_letter_tally(event_log):
    """Regression for the node.py dead-letter hole: app frames to a
    reclaimed uid must still tally on the ingress, keyed by the uid's
    tombstone proxy.  A managed root on A sends pings to a uid that
    never resolves on B; the sender's claims (delta gossip) and B's
    dead-letter accounting must cancel, so the tombstone's recv balance
    converges to zero instead of leaking a permanently nonzero count."""
    names = ["dlta", "dltb"]
    nodes = build_cluster(names)
    a, b = nodes
    try:
        connect_mesh(nodes)
        bogus_uid = 10**9  # never allocated on B
        tomb_proxy = a.fabric._proxy(b.address, bogus_uid)

        class DeadLetterRoot(AbstractBehavior):
            def __init__(self, context):
                super().__init__(context)
                self.tomb = context.engine.to_root_refob(tomb_proxy)

            def on_message(self, msg):
                if isinstance(msg, Ping):
                    self.tomb.tell(Ping(), self.context)
                return self

        root = a.system.spawn_root(
            Behaviors.setup_root(lambda ctx: DeadLetterRoot(ctx)), "dlroot"
        )
        dead_letters_before = b.system.dead_letters
        for _ in range(20):
            root.tell(Ping())
        assert settle(
            lambda: b.system.dead_letters >= dead_letters_before + 20
        ), "post-mortem frames were not routed through dead-letter accounting"

        # Sender claims (A's deltas) + B's dead-letter tallies cancel:
        # the tombstone's recv balance converges to zero on B.
        assert settle(lambda: nonzero_recv(b) == 0, timeout_s=15.0), (
            f"tombstone recv balance leaked: {nonzero_recv(b)}"
        )
        assert event_log.of(events.DEAD_LETTER)
    finally:
        terminate_all(nodes)


def test_postmortem_share_releases_carried_ref(event_log):
    """The ref-release half of the dead-letter fix: a worker on B kept
    alive only by an edge owned by a dead uid must be collected once the
    Share lands in the dead-letter path and deactivates the ref."""
    names = ["dlra", "dlrb"]
    nodes = build_cluster(names)
    a, b = nodes
    try:
        probe = TestProbe(default_timeout_s=20.0)
        probe_cell = a.system.spawn_system_raw(ProbeForwarder(probe), "probe-fwd")
        a.fabric.register_name("probe", probe_cell)
        connect_mesh(nodes)
        remote_probe = RemoteProbe(probe_cell)

        bogus_uid = 10**9 + 7
        tomb_proxy = a.fabric._proxy(b.address, bogus_uid)

        class SharingOwner(AbstractBehavior):
            """Root on B: owns the worker, shares it to A's root."""

            def __init__(self, context, a_root):
                super().__init__(context)
                self.worker = context.spawn(
                    Behaviors.setup(lambda c2: Worker(c2, remote_probe)),
                    "worker",
                )
                self.a_root = a_root

            def on_message(self, msg):
                ctx = self.context
                if isinstance(msg, Share):
                    self.a_root.tell(
                        Share(ctx.create_ref(self.worker, self.a_root)), ctx
                    )
                elif isinstance(msg, Drop):
                    ctx.release(self.worker)
                return self

        class AHolder(AbstractBehavior):
            """Root on A: receives the worker ref, then re-homes it onto
            the dead uid and releases its own copy."""

            def __init__(self, context):
                super().__init__(context)
                self.tomb = context.engine.to_root_refob(tomb_proxy)
                self.worker = None

            def on_message(self, msg):
                ctx = self.context
                if isinstance(msg, Share) and msg.ref is not None:
                    self.worker = msg.ref
                elif isinstance(msg, Drop) and self.worker is not None:
                    self.tomb.tell(
                        Share(ctx.create_ref(self.worker, self.tomb)), ctx
                    )
                    ctx.release(self.worker)
                    self.worker = None
                return self

        a_root = a.system.spawn_root(
            Behaviors.setup_root(lambda ctx: AHolder(ctx)), "aholder"
        )
        a_root_proxy = b.fabric._proxy(a.address, a_root.cell.uid)
        owner = b.system.spawn_root(
            Behaviors.setup_root(
                lambda ctx: SharingOwner(
                    ctx, ctx.engine.to_root_refob(a_root_proxy)
                )
            ),
            "sowner",
        )
        spawned = probe.expect_message_type(Spawned)

        owner.tell(Share(None))  # B shares worker -> A's root
        time.sleep(0.4)
        a_root.tell(Drop())  # A re-homes the ref onto the dead uid
        time.sleep(0.4)
        owner.tell(Drop())  # B releases its own; only the dead uid holds it

        stopped = probe.expect_message_type(Stopped, timeout_s=30.0)
        assert stopped.name == spawned.name
        assert settle(lambda: nonzero_recv(b) == 0, timeout_s=15.0)
    finally:
        terminate_all(nodes)


def test_reconnect_heals_torn_socket(event_log):
    """A torn TCP connection with reconnect-retries > 0 heals without a
    membership change: the dialer re-dials with backoff, sequence
    numbers bridge the streams, and traffic resumes."""
    names = ["rca", "rcb"]
    nodes = build_cluster(
        names,
        overrides={
            "uigc.node.reconnect-retries": 6,
            "uigc.node.reconnect-backoff": 30,
        },
    )
    a, b = nodes
    try:
        probe = TestProbe(default_timeout_s=20.0)
        probe_cell = a.system.spawn_system_raw(ProbeForwarder(probe), "probe-fwd")
        a.fabric.register_name("probe", probe_cell)
        connect_mesh(nodes)
        remote_probe = RemoteProbe(probe_cell)

        keeper = b.system.spawn_root(
            Behaviors.setup_root(
                lambda ctx: KeptWorkerRoot(
                    ctx,
                    ctx.spawn(
                        Behaviors.setup(lambda c2: Worker(c2, remote_probe)),
                        "canary",
                    ),
                )
            ),
            "keeper",
        )
        probe.expect_message_type(Spawned)

        # Tear the socket out from under both fabrics.
        a.fabric._conns[b.address].sock.close()

        assert settle(
            lambda: bool(event_log.of(events.LINK_RECONNECT)), timeout_s=10.0
        ), "link never reconnected"
        # No member was removed on either side.
        assert sorted(a.fabric.members()) == sorted([a.address, b.address])
        assert sorted(b.fabric.members()) == sorted([a.address, b.address])
        # Traffic still flows end to end after the heal.
        keeper.tell(Ping())
        probe.expect_no_message(0.3)  # canary alive, no Stopped
        assert not event_log.of(events.NODE_DOWN)
    finally:
        terminate_all(nodes)


@pytest.mark.slow
def test_chaos_randomized_long_haul():
    """Long randomized churn across many seeds: crash recovery must
    converge for every seed (superset of the fast two-seed smoke)."""
    for seed in (301, 302, 303):
        outcome = _run_crash_scenario(seed)
        assert outcome[0].endswith("/worker")
