"""Durability plane: journal framing/recovery, backpressure, drain.

Covers PR 12 end to end:

- journal unit layer: CRC frame round-trip, torn-tail stop (replay
  ends at the last valid frame, ``journal.torn_record`` reported),
  segment roll + compaction keeping recovery exact;
- crash recovery: entities journaled on one node are reconstructed —
  snapshot + command replay — by the node that inherits their shards
  after ``NodeFabric.die()``; passivated-only nodes recover too (the
  StateStore's durable backend);
- torn-record fault injection: ``FaultPlan.torn_journal_append``
  tears a record mid-write; replay stops cleanly at the tear and
  everything before it survives;
- backpressure: bounded mailboxes (shed-oldest accounting, the error
  policy raising to local senders, blocked-sender propagation) and the
  capped EntityRef handoff buffer
  (``uigc_entity_buffer_dropped_total``);
- drain: a drained node hands every entity off with zero loss and its
  table excludes it;
- acceptance: a 3-node cluster with >= 200 journaled sessions under
  sustained acked traffic has EVERY node drained + restarted in
  sequence plus one abrupt ``die()`` — and loses zero acknowledged
  commands (journal replay verified against the client ledger), with
  the uigcsan sanitizer clean on the survivors.
"""

import os
import threading
import time

import pytest

from uigc_tpu import ActorSystem, ClusterSharding, Entity
from uigc_tpu.cluster.journal import EntityJournal, _frame_record
from uigc_tpu.runtime import wire
from uigc_tpu.runtime.behaviors import RawBehavior
from uigc_tpu.runtime.cell import MailboxOverflowError
from uigc_tpu.runtime.faults import FaultPlan
from uigc_tpu.runtime.node import NodeFabric
from uigc_tpu.utils import events

BASE = {
    "uigc.crgc.wakeup-interval": 10,
    "uigc.crgc.egress-finalize-interval": 5,
    "uigc.crgc.shadow-graph": "array",
    "uigc.cluster.tick-interval": 40,
    "uigc.cluster.handoff-retry": 120,
}


def settle(predicate, timeout_s=20.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


class EventLog:
    def __init__(self):
        self.entries = []
        self._lock = threading.Lock()

    def __call__(self, name, fields):
        with self._lock:
            self.entries.append((name, fields))

    def of(self, name):
        with self._lock:
            return [f for n, f in self.entries if n == name]


@pytest.fixture
def event_log():
    log = EventLog()
    events.recorder.enable()
    events.recorder.add_listener(log)
    yield log
    events.recorder.disable()
    events.recorder.remove_listener(log)
    events.recorder.reset()


class Counter(Entity):
    def __init__(self, ctx, key, state):
        super().__init__(ctx, key)
        state = state or {}
        self.count = state.get("count", 0)

    def receive(self, msg):
        kind = msg[0]
        if kind == "incr":
            self.count += 1
        elif kind == "incr-ack":
            self.count += 1
            msg[1].tell(("ack", self.key, self.count))
        elif kind == "probe":
            msg[1].tell(("probed", self.key, self.count))
        elif kind == "slow":
            time.sleep(msg[1])  # uigc-lint: disable=UL003
        return self

    def snapshot_state(self):
        return {"count": self.count}


def counter_factory(ctx, key, state):
    return Counter(ctx, key, state)


class Collector(RawBehavior):
    def __init__(self):
        self.got = {}
        self.acked = {}
        self._lock = threading.Lock()

    def on_message(self, msg):
        if isinstance(msg, tuple) and msg:
            if msg[0] == "probed":
                with self._lock:
                    self.got[msg[1]] = msg[2]
            elif msg[0] == "ack":
                with self._lock:
                    if msg[2] > self.acked.get(msg[1], 0):
                        self.acked[msg[1]] = msg[2]
        return None

    def snapshot(self):
        with self._lock:
            return dict(self.got)

    def acked_snapshot(self):
        with self._lock:
            return dict(self.acked)


class Node:
    __slots__ = ("fabric", "system", "cluster", "region", "port", "address")

    def __init__(self, name, config, plan=None, passivate_after_s=None):
        self.fabric = NodeFabric(fault_plan=plan)
        self.system = ActorSystem(None, name=name, config=config, fabric=self.fabric)
        self.port = self.fabric.listen()
        self.address = self.system.address
        self.cluster = ClusterSharding.attach(self.system)
        self.region = self.cluster.start(
            "counter", counter_factory, passivate_after_s=passivate_after_s
        )


def build_cluster(names, journal_dir, plan=None, overrides=None,
                  passivate_after_s=None):
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = len(names)
    config["uigc.cluster.journal-dir"] = str(journal_dir)
    if overrides:
        config.update(overrides)
    return [Node(n, config, plan, passivate_after_s) for n in names]


def connect_mesh(nodes):
    for i, a in enumerate(nodes):
        for b in nodes[i + 1 :]:
            a.fabric.connect("127.0.0.1", b.port)


def terminate_all(nodes):
    for n in nodes:
        try:
            n.system.terminate(timeout_s=5.0)
        except Exception:
            pass


# ------------------------------------------------------------------- #
# Unit layer: framing, torn records, compaction
# ------------------------------------------------------------------- #


def test_journal_round_trip_and_torn_tail(tmp_path, event_log):
    j = EntityJournal(str(tmp_path), "uigc://jr", fsync="never")
    j.open_epoch("t", 3, "k1", b"S0")
    for i in range(5):
        j.note_command("t", 3, "k1", b"C%d" % i)
    j.checkpoint()
    state, cmds = j.recover("t", 3, "k1")
    assert state == b"S0" and cmds == [b"C0", b"C1", b"C2", b"C3", b"C4"]

    # Tear the segment's tail mid-frame: replay stops at the last
    # valid frame and reports journal.torn_record — never raises,
    # never guesses at bytes past the tear.
    shard_dir = j._shard_dir("t", 3)
    (seg,) = [n for n in os.listdir(shard_dir) if n.endswith(".uj")]
    path = os.path.join(shard_dir, seg)
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size - 7)
    j2 = EntityJournal(str(tmp_path), "uigc://jr2", fsync="never")
    state, cmds = j2.recover("t", 3, "k1")
    assert state == b"S0" and cmds == [b"C0", b"C1", b"C2", b"C3"]
    assert j2.torn_records == 1
    torn = event_log.of(events.JOURNAL_TORN)
    assert torn and torn[0]["path"] == path and torn[0]["offset"] > 0
    # Garbage INSIDE a frame (crc mismatch) stops the scan too.
    with open(path, "r+b") as fh:
        fh.seek(12)
        fh.write(b"\xff\xff")
    j3 = EntityJournal(str(tmp_path), "uigc://jr3", fsync="never")
    found = j3.recover("t", 3, "k1")
    assert found is None or found[0] is None  # base snap was corrupted
    j.close()
    j2.close()
    j3.close()


def test_journal_epoch_supersedes_and_missing_snapshot_replays(tmp_path):
    j = EntityJournal(str(tmp_path), "uigc://je", fsync="never")
    j.open_epoch("t", 0, "k", b"OLD")
    j.note_command("t", 0, "k", b"c1")
    # Periodic snapshot: bump first (enqueue time), commit later.
    epoch = j.begin_snapshot("t", 0, "k")
    j.note_command("t", 0, "k", b"c2-new-epoch")
    j.commit_snapshot("t", 0, "k", epoch, b"NEW")
    state, cmds = j.recover("t", 0, "k")
    assert state == b"NEW" and cmds == [b"c2-new-epoch"]
    # A bump whose snapshot never lands (crash between): the previous
    # snapshot replays, PLUS the new epoch's commands on top.
    j.begin_snapshot("t", 0, "k")
    j.note_command("t", 0, "k", b"c3-unsnapped")
    j2 = EntityJournal(str(tmp_path), "uigc://je2", fsync="never")
    state, cmds = j2.recover("t", 0, "k")
    assert state == b"NEW" and cmds == [b"c2-new-epoch", b"c3-unsnapped"]
    j.close()
    j2.close()


def test_open_epoch_min_epoch_supersedes_source_capture(tmp_path):
    """The rolling-restart acked-highwater flake, pinned: a handoff's
    destination activation must open STRICTLY past the source's
    capture epoch even when the destination's shard scan is stale and
    both land in the same wall-clock millisecond.  Without the floor,
    the recovery merge sorts the source's capture snapshot past the
    destination's later acked commands and replays short of them."""
    import unittest.mock as mock

    from uigc_tpu.cluster import journal as journal_mod

    j_src = EntityJournal(str(tmp_path), "uigc://src", fsync="never")
    j_dst = EntityJournal(str(tmp_path), "uigc://dst", fsync="never")
    # Freeze the wall floor: every epoch decision lands "in the same
    # millisecond", the regime where only the causal floor can order
    # the two writers.
    frozen = journal_mod._epoch_floor()
    with mock.patch.object(journal_mod, "_epoch_floor", lambda: frozen):
        j_src.open_epoch("t", 0, "k", b"S0")
        for i in range(3):
            j_src.note_command("t", 0, "k", b"C%d" % i)
        # Prime the destination's shard scan BEFORE the capture: the
        # stale view the real race depends on (shard indexes are
        # cached between membership changes).
        j_dst.keys_for_shard("t", 0)
        cap = j_src.open_epoch("t", 0, "k", b"S3")  # migration capture
        # Destination applies the shipped state, floor = the capture
        # epoch that rode the mig frame.
        dst_epoch = j_dst.open_epoch("t", 0, "k", b"S3", min_epoch=cap)
        assert dst_epoch > cap
        # Two more ACKED commands land at the destination.
        j_dst.note_command("t", 0, "k", b"C3")
        j_dst.note_command("t", 0, "k", b"C4")
    # A fresh reader (the node inheriting the shard after a die())
    # must replay the destination's acked tail on top of the shipped
    # snapshot — not resurrect the source's capture as the base.
    j_reader = EntityJournal(str(tmp_path), "uigc://rdr", fsync="never")
    state, cmds = j_reader.recover("t", 0, "k")
    assert state == b"S3" and cmds == [b"C3", b"C4"]
    # Mixed-version tolerance: a PR-14 peer's mig frame carries no
    # epoch element — it decodes as floor 0 and the wall/known floors
    # apply exactly as before.
    frame = ("mig", "t", "k", ("uigc://src", 1), b"blob", 0)
    assert wire.decode_migration_frame(frame)[5] == 0
    j_src.close()
    j_dst.close()
    j_reader.close()


def test_journal_segment_roll_and_compaction(tmp_path):
    j = EntityJournal(
        str(tmp_path), "uigc://jc", fsync="never", segment_bytes=512,
        snapshot_every=1000,
    )
    j.open_epoch("t", 1, "k", b"S")
    for i in range(60):
        due = j.note_command("t", 1, "k", b"payload-%03d" % i)
        if due:  # segment rolled: the region would re-snapshot; do it
            epoch = j.begin_snapshot("t", 1, "k")
            j.commit_snapshot("t", 1, "k", epoch, b"S%03d" % i)
    assert j.segment_count() >= 2
    # Rolling re-snapshots let old segments compact away...
    assert j.segment_count() < 60
    # ...without ever losing the recovery invariant.
    j2 = EntityJournal(str(tmp_path), "uigc://jc2", fsync="never")
    found = j2.recover("t", 1, "k")
    assert found is not None
    state, cmds = found
    assert state is not None and state.startswith(b"S")
    j.close()
    j2.close()


def test_frame_record_is_crc_framed():
    frame = _frame_record(b"hello")
    assert frame[:2] == b"uJ" and len(frame) == 10 + 5


# ------------------------------------------------------------------- #
# Crash recovery across nodes
# ------------------------------------------------------------------- #


def test_die_recovers_journaled_entities_on_survivor(tmp_path, event_log):
    nodes = build_cluster(["jda", "jdb"], tmp_path)
    a, b = nodes
    try:
        connect_mesh(nodes)
        assert settle(lambda: len(a.cluster.members()) == 2)
        keys = [f"k{i}" for i in range(40)]
        for i, k in enumerate(keys):
            ref = a.cluster.entity_ref("counter", k)
            for _ in range(i % 4 + 1):
                ref.tell(("incr",))
        assert settle(
            lambda: a.region.active_count() + b.region.active_count() == 40
        )
        dead_keys = [k for k in keys if a.cluster.home_of(k) == b.address]
        assert dead_keys, "no key homed on the doomed node?"
        b.fabric.die()
        assert settle(lambda: b.address not in a.cluster.members())
        # Eager recovery: the survivor reconstructs the dead node's
        # entities from the shared journal without waiting for traffic.
        assert settle(
            lambda: a.region.active_count() == 40, timeout_s=30.0
        ), (a.region.active_count(), len(dead_keys))
        coll = Collector()
        cell = a.system.spawn_system_raw(coll, "coll")
        for k in keys:
            a.cluster.entity_ref("counter", k).tell(("probe", cell))
        assert settle(lambda: len(coll.snapshot()) == 40)
        expected = {k: i % 4 + 1 for i, k in enumerate(keys)}
        assert coll.snapshot() == expected, {
            k: (coll.snapshot().get(k), expected[k])
            for k in keys
            if coll.snapshot().get(k) != expected[k]
        }
        recovered = event_log.of(events.JOURNAL_RECOVERED)
        assert len(recovered) >= len(dead_keys)
        assert all(f["duration_s"] >= 0 for f in recovered)
    finally:
        terminate_all(nodes)


def test_passivated_entities_survive_node_death(tmp_path, event_log):
    """The StateStore satellite: a node holding ONLY passivated
    entities dies; its spilled snapshots came through the journal, so
    the survivor recovers them with state intact."""
    nodes = build_cluster(
        ["jpa", "jpb"], tmp_path, passivate_after_s=0.12
    )
    a, b = nodes
    try:
        connect_mesh(nodes)
        assert settle(lambda: len(a.cluster.members()) == 2)
        keys = [f"k{i}" for i in range(24)]
        for i, k in enumerate(keys):
            ref = a.cluster.entity_ref("counter", k)
            for _ in range(i + 1):
                ref.tell(("incr",))
        # All 24 exist (active OR already idled out — under full-suite
        # load the 0.12s passivation can outrun the tail of the spawn
        # burst, so a pure active_count==24 settle races by design).
        assert settle(
            lambda: a.region.active_count() + b.region.active_count()
            + a.region.passive_count() + b.region.passive_count() == 24
        )
        # Idle out: every entity passivates (spilling through the
        # journal), leaving B with passivated-only state.
        assert settle(
            lambda: a.region.passive_count() + b.region.passive_count() == 24,
            timeout_s=10.0,
        )
        b_keys = [k for k in keys if a.cluster.home_of(k) == b.address]
        assert b_keys, "no key homed on the doomed node?"
        b.fabric.die()
        assert settle(lambda: b.address not in a.cluster.members())
        coll = Collector()
        cell = a.system.spawn_system_raw(coll, "coll")
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and len(coll.snapshot()) < 24:
            for k in keys:
                if k not in coll.snapshot():
                    a.cluster.entity_ref("counter", k).tell(("probe", cell))
            time.sleep(0.3)
        expected = {f"k{i}": i + 1 for i in range(24)}
        assert coll.snapshot() == expected, {
            k: (coll.snapshot().get(k), expected[k])
            for k in keys
            if coll.snapshot().get(k) != expected[k]
        }
    finally:
        terminate_all(nodes)


def test_torn_append_replay_stops_at_last_valid_frame(tmp_path, event_log):
    """FaultPlan crash-at-byte injection: node B's journal tears on its
    N-th append (the process 'dies inside write(2)'); B then crashes.
    The survivor's replay stops at the tear, keeps everything before
    it, and reports journal.torn_record."""
    plan = FaultPlan(7)
    nodes = build_cluster(["jta", "jtb"], tmp_path, plan=plan)
    a, b = nodes
    try:
        connect_mesh(nodes)
        assert settle(lambda: len(a.cluster.members()) == 2)
        keys = [f"k{i}" for i in range(30)]
        b_key = next(k for k in keys if a.cluster.home_of(k) == b.address)
        ref = b.cluster.entity_ref("counter", b_key)
        for _ in range(10):
            ref.tell(("incr",))
        assert settle(
            lambda: b.region.active_count() >= 1
        )
        coll = Collector()
        cell = b.system.spawn_system_raw(coll, "c0")
        b.cluster.entity_ref("counter", b_key).tell(("probe", cell))
        assert settle(lambda: coll.snapshot().get(b_key) == 10)
        # Arm the tear: the NEXT append on B is written only halfway,
        # then B's journal is dead (everything later is lost).
        plan.torn_journal_append(b.address, after_appends=1)
        for _ in range(5):
            ref.tell(("incr",))
        assert settle(lambda: b.cluster.journal.stats()["dead"], 10.0)
        b.fabric.die()
        assert settle(lambda: b.address not in a.cluster.members())
        coll2 = Collector()
        cell2 = a.system.spawn_system_raw(coll2, "c1")
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and b_key not in coll2.snapshot():
            a.cluster.entity_ref("counter", b_key).tell(("probe", cell2))
            time.sleep(0.2)
        # 10 journaled commands, then the 11th tore mid-frame and the
        # rest never reached the file: recovery replays exactly the
        # clean prefix.
        assert coll2.snapshot().get(b_key) == 10, coll2.snapshot()
        assert event_log.of(events.JOURNAL_TORN), "tear never reported"
    finally:
        terminate_all(nodes)


# ------------------------------------------------------------------- #
# Backpressure
# ------------------------------------------------------------------- #


def test_bounded_mailbox_shed_oldest_accounts(event_log):
    config = dict(
        BASE,
        **{
            "uigc.crgc.num-nodes": 1,
            "uigc.runtime.mailbox-limit": 8,
            "uigc.runtime.overflow-policy": "shed-oldest",
        },
    )
    system = ActorSystem(None, name="bp-shed", config=config)
    try:
        cluster = ClusterSharding.attach(system)
        region = cluster.start("counter", counter_factory)
        ref = region.entity_ref("k")
        ref.tell(("slow", 0.4))
        time.sleep(0.05)  # entity is busy; the mailbox now backs up
        for _ in range(40):
            ref.tell(("incr",))
        assert settle(lambda: bool(event_log.of(events.BACKPRESSURE)), 5.0)
        sheds = [
            f
            for f in event_log.of(events.BACKPRESSURE)
            if f.get("site") == "mailbox" and f.get("action") == "shed"
        ]
        assert sheds, event_log.of(events.BACKPRESSURE)
        coll = Collector()
        cell = system.spawn_system_raw(coll, "coll")
        ref.tell(("probe", cell))
        assert settle(lambda: "k" in coll.snapshot(), 10.0)
        # Some increments were shed (dead-lettered), the rest landed.
        assert coll.snapshot()["k"] < 40
        assert system.dead_letters > 0
    finally:
        system.terminate()


def test_bounded_mailbox_error_policy_raises_locally():
    config = dict(
        BASE,
        **{
            "uigc.crgc.num-nodes": 1,
            "uigc.runtime.mailbox-limit": 4,
            "uigc.runtime.overflow-policy": "error",
        },
    )
    system = ActorSystem(None, name="bp-err", config=config)
    try:
        cluster = ClusterSharding.attach(system)
        region = cluster.start("counter", counter_factory)
        ref = region.entity_ref("k")
        ref.tell(("slow", 0.5))
        time.sleep(0.05)
        with pytest.raises(MailboxOverflowError) as exc:
            for _ in range(40):
                ref.tell(("incr",))
        assert exc.value.rule == "mailbox.overflow"
    finally:
        system.terminate()


def test_bounded_mailbox_block_propagates_and_recovers(event_log):
    """The block policy: senders WAIT for a saturated entity instead of
    growing its mailbox; once the consumer catches up everything that
    was admitted is processed — nothing lost, nothing unbounded."""
    config = dict(
        BASE,
        **{
            "uigc.crgc.num-nodes": 1,
            "uigc.runtime.mailbox-limit": 16,
            "uigc.runtime.overflow-policy": "block",
            "uigc.runtime.mailbox-block-ms": 4000,
        },
    )
    system = ActorSystem(None, name="bp-block", config=config)
    try:
        cluster = ClusterSharding.attach(system)
        region = cluster.start("counter", counter_factory)
        ref = region.entity_ref("k")
        ref.tell(("slow", 0.3))
        time.sleep(0.05)
        sent = 80
        t0 = time.monotonic()
        for _ in range(sent):
            ref.tell(("incr",))
        blocked_s = time.monotonic() - t0
        waits = [
            f
            for f in event_log.of(events.BACKPRESSURE)
            if f.get("site") == "mailbox" and f.get("action") == "wait"
        ]
        assert waits, "full mailbox never blocked the sender"
        assert blocked_s > 0.05, "sender never actually waited"
        coll = Collector()
        cell = system.spawn_system_raw(coll, "coll")
        ref.tell(("probe", cell))
        assert settle(lambda: coll.snapshot().get("k") == sent, 15.0), (
            coll.snapshot()
        )
    finally:
        system.terminate()


def test_error_policy_degrades_on_remote_and_rerouted_paths(event_log):
    """The "error" overflow policy raises only to a LOCAL
    EntityRef.tell; a remote 'ent'-frame delivery must degrade to
    shed-oldest on the transport thread (a raise there would kill the
    link's receive loop) and the link must stay healthy."""
    config = dict(
        BASE,
        **{
            "uigc.crgc.num-nodes": 2,
            "uigc.runtime.mailbox-limit": 8,
            "uigc.runtime.overflow-policy": "error",
        },
    )
    nodes = [Node(n, config) for n in ("erra", "errb")]
    a, b = nodes
    try:
        connect_mesh(nodes)
        assert settle(lambda: len(a.cluster.members()) == 2)
        b_key = next(
            f"k{i}" for i in range(200) if a.cluster.home_of(f"k{i}") == b.address
        )
        ref = a.cluster.entity_ref("counter", b_key)
        ref.tell(("slow", 0.4))
        time.sleep(0.1)
        for _ in range(60):  # floods B's bounded mailbox over the wire
            ref.tell(("incr",))
        # The receive loop survived: the entity still answers, the link
        # never went down, and the overflow surfaced as sheds.
        coll = Collector()
        cell = a.system.spawn_system_raw(coll, "coll")
        assert settle(
            lambda: (
                a.cluster.entity_ref("counter", b_key).tell(("probe", cell))
                or b_key in coll.snapshot()
            ),
            timeout_s=15.0,
        )
        assert not event_log.of(events.NODE_DOWN)
        sheds = [
            f
            for f in event_log.of(events.BACKPRESSURE)
            if f.get("site") == "mailbox" and f.get("action") == "shed"
        ]
        assert sheds, "remote overflow never degraded to shed-oldest"
    finally:
        terminate_all(nodes)


def test_handoff_buffer_bound_sheds_with_accounting(tmp_path, event_log):
    """The EntityRef buffer-during-handoff satellite: a key stuck in
    transition cannot buffer unboundedly — past the cap the oldest
    parked message is shed with shard.buffer_dropped accounting."""
    config = dict(
        BASE,
        **{
            "uigc.crgc.num-nodes": 1,
            "uigc.cluster.buffer-limit": 10,
        },
    )
    system = ActorSystem(None, name="bufcap", config=config)
    try:
        cluster = ClusterSharding.attach(system)
        region = cluster.start("counter", counter_factory)
        region.entity_ref("k").tell(("incr",))
        assert settle(lambda: region.active_count() == 1)
        # Wedge the key mid-transition (simulate a handoff that never
        # completes) and flood it.
        from collections import deque

        from uigc_tpu.cluster.sharding import _HANDOFF

        with region._lock:
            region._entities["k"].status = _HANDOFF
            region._buffers.setdefault("k", deque())
        for _ in range(50):
            region.entity_ref("k").tell(("incr",))
        assert region.buffered_depth() == 10, region.buffered_depth()
        drops = event_log.of(events.SHARD_BUFFER_DROPPED)
        assert len(drops) == 40 and drops[0]["site"] == "handoff"
        with region._lock:
            region._entities["k"].status = "active"
    finally:
        system.terminate()


def test_writer_queue_backpressure_event(tmp_path, event_log):
    """A saturated remote consumer surfaces on the SENDER as writer-
    queue pushback with a structured fabric.backpressure event."""
    config = dict(
        BASE,
        **{
            "uigc.crgc.num-nodes": 2,
            "uigc.node.writer-queue-limit": 32,
        },
    )
    nodes = [Node(n, config) for n in ("wqa", "wqb")]
    a, b = nodes
    try:
        connect_mesh(nodes)
        assert settle(lambda: len(a.cluster.members()) == 2)
        keys = [f"k{i}" for i in range(100)]
        b_keys = [k for k in keys if a.cluster.home_of(k) == b.address]
        # Slow B's intake: a long-running entity invocation stalls its
        # dispatcher while A floods the link.
        a.cluster.entity_ref("counter", b_keys[0]).tell(("slow", 0.3))
        for _ in range(3000):
            for k in b_keys[:4]:
                a.cluster.entity_ref("counter", k).tell(("incr",))
            if any(
                f.get("site") == "writer-queue"
                for f in event_log.of(events.BACKPRESSURE)
            ):
                break
        waits = [
            f
            for f in event_log.of(events.BACKPRESSURE)
            if f.get("site") == "writer-queue"
        ]
        assert waits and waits[0]["depth"] >= 32
    finally:
        terminate_all(nodes)


# ------------------------------------------------------------------- #
# Drain
# ------------------------------------------------------------------- #


def test_drain_hands_off_everything_zero_loss(tmp_path, event_log):
    nodes = build_cluster(["dra", "drb"], tmp_path)
    a, b = nodes
    try:
        connect_mesh(nodes)
        assert settle(lambda: len(a.cluster.members()) == 2)
        keys = [f"k{i}" for i in range(50)]
        for i, k in enumerate(keys):
            ref = a.cluster.entity_ref("counter", k)
            for _ in range(i % 3 + 1):
                ref.tell(("incr",))
        assert settle(
            lambda: a.region.active_count() + b.region.active_count() == 50
        )
        assert b.region.active_count() > 0, "nothing to drain?"
        assert b.fabric.drain(timeout_s=20.0)
        # Everything lives on A now; B's region is empty and the
        # shared table excludes B.
        assert a.region.active_count() == 50
        assert b.region.active_count() == 0
        assert all(
            owner == a.address
            for owner in a.cluster.table_snapshot().assignments.values()
        )
        drained = event_log.of(events.NODE_DRAINED)
        assert drained and drained[-1]["complete"]
        coll = Collector()
        cell = a.system.spawn_system_raw(coll, "coll")
        for k in keys:
            a.cluster.entity_ref("counter", k).tell(("probe", cell))
        assert settle(lambda: len(coll.snapshot()) == 50)
        expected = {k: i % 3 + 1 for i, k in enumerate(keys)}
        assert coll.snapshot() == expected
    finally:
        terminate_all(nodes)


# ------------------------------------------------------------------- #
# Lint: UL012 unbounded-queue rule
# ------------------------------------------------------------------- #


def test_ul012_flags_unbounded_queues_and_accepts_annotated(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "uigc_lint", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
            "uigc_lint.py",
        ),
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    runtime_dir = tmp_path / "runtime"
    runtime_dir.mkdir()
    bad = runtime_dir / "q.py"
    bad.write_text(
        "from collections import deque\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self.outq = deque()\n"
        "        self._buffers = []\n"
        "        self.pending: list = list()\n"
        "        self.bounded = deque(maxlen=16)\n"
        "        self.okq = deque()  # unbounded: drained by a fixed pool\n"
        "        self.names = []\n"
    )
    violations = [
        v for v in lint.lint_paths([str(bad)]) if v.rule == "UL012"
    ]
    assert {v.line for v in violations} == {4, 5, 6}, [
        v.render() for v in violations
    ]
    # Outside runtime//cluster/ the rule stays silent.
    elsewhere = tmp_path / "tools_like"
    elsewhere.mkdir()
    free = elsewhere / "q.py"
    free.write_text(bad.read_text())
    assert not [
        v for v in lint.lint_paths([str(free)]) if v.rule == "UL012"
    ]
    # The live repo is strict-clean for UL012 under its allowlist.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_violations = [
        v
        for v in lint.lint_paths(
            [os.path.join(repo, "uigc_tpu"), os.path.join(repo, "tools")]
        )
        if v.rule == "UL012"
    ]
    budget = lint._load_allowlist(
        os.path.join(repo, "tools", "uigc_lint_allow.txt")
    )
    _grandfathered, fresh = lint.apply_allowlist(repo_violations, budget)
    assert not fresh, [v.render() for v in fresh]


def test_bench_check_scenario_family_gates_lost_acked(tmp_path):
    """bench_check's SCENARIO family: a doctored newest round that
    lost acked commands (or collapsed throughput) must FAIL against
    the committed trajectory."""
    import importlib.util
    import json as _json

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_check", os.path.join(repo, "tools", "bench_check.py")
    )
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)
    assert "SCENARIO" in bc.FAMILIES
    with open(os.path.join(repo, "BENCH_SCENARIO_r01.json")) as fh:
        doc = _json.load(fh)
    doc["ledger"]["lost_acked"] = 3
    doctored = tmp_path / "BENCH_SCENARIO_r99.json"
    doctored.write_text(_json.dumps(doc))
    rows = bc.check_family(repo, "SCENARIO", newest_override=str(doctored))
    by_metric = {r["metric"]: r["status"] for r in rows}
    assert by_metric.get("ledger.lost_acked") == "FAIL", rows
    # The honest copy passes.
    doc["ledger"]["lost_acked"] = 0
    doctored.write_text(_json.dumps(doc))
    rows = bc.check_family(repo, "SCENARIO", newest_override=str(doctored))
    assert all(r["status"] in ("PASS", "SKIP") for r in rows), rows


# ------------------------------------------------------------------- #
# Acceptance: rolling restart chaos
# ------------------------------------------------------------------- #


def test_rolling_restart_chaos_loses_zero_acked_state(tmp_path, event_log):
    """The acceptance scenario: >= 200 journaled sessions on 3 nodes
    under sustained ACKED mixed traffic; every node is drained +
    restarted in sequence; then one restarted node is killed abruptly
    (die()); the survivors journal-recover its sessions.  The client
    ledger's acked highwater per key must be covered by the final
    probed counts — zero acknowledged commands lost — and the uigcsan
    sanitizer must be clean on the survivors."""
    overrides = {
        "uigc.analysis.sanitizer": True,
        # A loaded CI host can stretch a drain past the default 3s
        # hold-timeout; an expired hold reopens the stale-recovery-vs-
        # migration race the grant protocol exists to close.  The
        # timeout is a wedge safety valve, not a pacing device — give
        # it slack.
        "uigc.cluster.hold-timeout": 15000,
    }
    names = ["roll-a", "roll-b", "roll-c"]
    by_name = dict(zip(names, build_cluster(names, tmp_path, overrides=overrides)))
    config = dict(BASE)
    config["uigc.crgc.num-nodes"] = 3
    config["uigc.cluster.journal-dir"] = str(tmp_path)
    config.update(overrides)
    acked = {}
    #: the node client traffic enters through; rebound when it rolls
    frontend = {"name": "roll-a"}

    def merge_acked(coll):
        for k, v in coll.acked_snapshot().items():
            if v > acked.get(k, 0):
                acked[k] = v

    try:
        connect_mesh(list(by_name.values()))
        assert settle(
            lambda: all(
                len(n.cluster.members()) == 3 for n in by_name.values()
            ),
            timeout_s=15.0,
        )
        n_entities = 210
        keys = [f"user-{i}" for i in range(n_entities)]

        def frontend_node():
            return by_name[frontend["name"]]

        coll = Collector()
        coll_cell = frontend_node().system.spawn_system_raw(coll, "led0")
        for key in keys:
            frontend_node().cluster.entity_ref("counter", key).tell(
                ("incr-ack", coll_cell)
            )
        assert settle(
            lambda: sum(
                n.region.active_count() for n in by_name.values()
            )
            == n_entities,
            timeout_s=30.0,
        )

        # sustained mixed traffic (acked writes + probes) from a
        # background churner addressing the CURRENT frontend
        churn_stop = threading.Event()
        churn_pause = threading.Event()

        def churn():
            i = 0
            while not churn_stop.is_set():
                if churn_pause.is_set():
                    time.sleep(0.01)
                    continue
                key = keys[i % n_entities]
                try:
                    fe = frontend_node()
                    fe.cluster.entity_ref("counter", key).tell(
                        ("incr-ack", coll_cell)
                    )
                    if i % 7 == 0:
                        fe.cluster.entity_ref("counter", key).tell(
                            ("probe", coll_cell)
                        )
                except Exception:
                    pass
                i += 1
                time.sleep(0.002)

        churner = threading.Thread(target=churn, daemon=True)
        churner.start()
        time.sleep(0.5)

        # -- roll b and c with traffic running ---------------------- #
        for name in ("roll-b", "roll-c"):
            node = by_name[name]
            assert node.fabric.drain(timeout_s=30.0), f"{name} drain residue"
            node.system.terminate(timeout_s=10.0)
            assert settle(
                lambda: node.address
                not in frontend_node().cluster.members(),
                timeout_s=20.0,
            )
            fresh = Node(name, config)
            by_name[name] = fresh
            for other_name, other in by_name.items():
                if other_name != name:
                    fresh.fabric.connect("127.0.0.1", other.port)
            assert settle(
                lambda: len(fresh.cluster.members()) == 3
                and all(
                    n.cluster.migrations.pending_count() == 0
                    for n in by_name.values()
                )
                and fresh.region.active_count() > 0,
                timeout_s=40.0,
            ), f"{name} never rejoined"

        # -- roll a (the frontend): move client + ledger first ------ #
        churn_pause.set()
        time.sleep(0.2)
        merge_acked(coll)
        a_old = by_name["roll-a"]
        coll = Collector()
        coll_cell = by_name["roll-b"].system.spawn_system_raw(coll, "led1")
        frontend["name"] = "roll-b"
        churn_pause.clear()
        assert a_old.fabric.drain(timeout_s=30.0), "roll-a drain residue"
        a_old.system.terminate(timeout_s=10.0)
        assert settle(
            lambda: a_old.address not in by_name["roll-b"].cluster.members(),
            timeout_s=20.0,
        )
        fresh_a = Node("roll-a", config)
        by_name["roll-a"] = fresh_a
        for other_name, other in by_name.items():
            if other_name != "roll-a":
                fresh_a.fabric.connect("127.0.0.1", other.port)
        assert settle(
            lambda: len(fresh_a.cluster.members()) == 3
            and all(
                n.cluster.migrations.pending_count() == 0
                for n in by_name.values()
            ),
            timeout_s=40.0,
        ), "roll-a never rejoined"

        # -- one abrupt kill on top: c dies, journal recovers ------- #
        time.sleep(0.5)
        victim = by_name["roll-c"]
        churn_pause.set()
        time.sleep(0.2)
        merge_acked(coll)
        victim.fabric.die()
        assert settle(
            lambda: victim.address
            not in by_name["roll-b"].cluster.members(),
            timeout_s=20.0,
        )
        churn_stop.set()
        churner.join(timeout=5)
        survivors = [by_name["roll-a"], by_name["roll-b"]]
        assert settle(
            lambda: all(
                s.cluster.migrations.pending_count() == 0 for s in survivors
            ),
            timeout_s=30.0,
        )

        # -- the ledger check: zero acked commands lost ------------- #
        merge_acked(coll)
        probe = Collector()
        probe_cell = by_name["roll-b"].system.spawn_system_raw(probe, "led2")
        deadline = time.monotonic() + 60.0
        lost = keys
        while time.monotonic() < deadline:
            got = probe.snapshot()
            lost = [k for k in keys if got.get(k, -1) < acked.get(k, 0)]
            if not lost:
                break
            for k in lost:
                by_name["roll-b"].cluster.entity_ref("counter", k).tell(
                    ("probe", probe_cell)
                )
            time.sleep(0.3)
        assert not lost, (
            f"{len(lost)} sessions below their acked highwater, e.g. "
            f"{[(k, probe.snapshot().get(k), acked.get(k)) for k in lost[:5]]}"
        )
        assert sum(acked.values()) > n_entities, "ledger never accumulated"
        recovered = event_log.of(events.JOURNAL_RECOVERED)
        assert recovered, "the kill never exercised journal recovery"

        # Sanitizer clean on the survivors: GC soundness held through
        # three drains, three rejoins and an abrupt death.
        for node in survivors:
            violations = node.system.sanitizer.violations
            assert not violations, [str(v) for v in violations]
    finally:
        try:
            churn_stop.set()
        except Exception:
            pass
        terminate_all(list(by_name.values()))
