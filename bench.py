"""Benchmark: garbage detection throughput on a power-law actor graph.

BASELINE config 5: a synthetic power-law refob graph, batched device trace.
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The north-star target (BASELINE.json) is >=10M garbage actors/sec with
<=10ms p50 detection latency at a 10M-actor graph; vs_baseline is
throughput relative to that 10M/s target (no published reference numbers
exist — BASELINE.md documents the absence).

``--config`` selects the other BASELINE workloads, which drive the live
actor runtime end to end instead of the raw device kernel:
  churn    (1) CRGC, acyclic ownership tree of 10k actors
  mac      (2) MAC weighted-refcount, flat acyclic garbage
  rings    (3) CRGC cyclic garbage: 100 rings of 100 actors
  cluster  (4) CRGC 3-node crash recovery with injected message drops
  powerlaw (5) the default: batched device trace on a 10M-actor graph
Configs 1-4 report end-to-end collected actors/sec; no reference numbers
exist to normalize against, so their vs_baseline is null.
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

# uigc_tpu imports stay function-local: the module-level code here must
# not touch the package (and transitively jax) before probe_platform has
# subprocess-guarded the flaky TPU backend.


def probe_platform(
    timeout_s: float = None, attempts: int = None, backoff_s: float = 5.0
) -> dict:
    """Decide which JAX platform the benchmark can actually use.

    TPU backend init on this transport is flaky: it can crash
    (``UNAVAILABLE: TPU backend setup/compile error``) or hang outright.
    Either failure mode in-process would kill the benchmark before it
    printed its JSON line, so the probe runs ``jax.devices()`` in a
    *subprocess* with a hard timeout, retrying with backoff, and falls
    back to CPU on persistent failure.  The returned dict records the
    chosen platform and whether it is a degradation, so the emitted
    result line always carries a visible ``"platform"``.
    """
    from uigc_tpu.utils.platform import is_tpu_request

    if timeout_s is None:
        timeout_s = float(os.environ.get("UIGC_BENCH_PROBE_TIMEOUT", "240"))
    if attempts is None:
        attempts = int(os.environ.get("UIGC_BENCH_PROBE_ATTEMPTS", "3"))
    forced = os.environ.get("JAX_PLATFORMS", "").lower()
    # A real-TPU request (incl. this machine's "axon" tunnel plugin)
    # needs the guarded probe.  Anything else explicitly forced
    # (cpu, ...) is honored as-is.
    device_like = (not forced) or is_tpu_request(forced)
    if not device_like:
        return {"platform": forced.split(",")[0], "degraded": False, "probe": "forced"}

    log = []
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import jax; print(jax.devices()[0].platform)",
                ],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            log.append(f"attempt {attempt}: timeout after {timeout_s}s")
        else:
            platform = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
            if proc.returncode == 0 and platform:
                return {
                    "platform": platform,
                    "degraded": False,
                    "probe": f"ok after {attempt + 1} attempt(s)",
                }
            tail = (proc.stderr or "").strip().splitlines()[-1:] or ["no output"]
            log.append(f"attempt {attempt}: rc={proc.returncode} {tail[0][:200]}")
        if attempt + 1 < attempts:
            time.sleep(backoff_s * (attempt + 1))

    # Persistent failure: run on CPU, but keep the degradation visible
    # (stderr warning + "platform_degraded" in the result line).  Set
    # UIGC_BENCH_STRICT_PLATFORM=1 to fail loudly instead — e.g. a CI
    # gate that must never accept a CPU number against the TPU target.
    from uigc_tpu.utils.platform import env_flag

    detail = "; ".join(log)
    if env_flag("UIGC_BENCH_STRICT_PLATFORM"):
        raise RuntimeError(f"TPU backend unavailable (strict mode): {detail}")
    print(f"bench: TPU backend unavailable, degrading to CPU ({detail})", file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    return {"platform": "cpu", "degraded": True, "probe": detail}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=None, help="number of actors")
    parser.add_argument("--reps", type=int, default=None)
    parser.add_argument("--garbage-fraction", type=float, default=0.5)
    parser.add_argument("--small", action="store_true", help="quick CPU-sized run")
    parser.add_argument(
        "--impl",
        choices=["pallas", "xla"],
        default=None,
        help="trace implementation (default: pallas on TPU, xla elsewhere)",
    )
    parser.add_argument(
        "--layout",
        choices=["static", "incremental"],
        default="static",
        help=(
            "pallas pair layout: one static pack, or the live collector's "
            "incremental base+delta layout with device-resident operands "
            "(ops/pallas_incremental.trace_device)"
        ),
    )
    parser.add_argument(
        "--sub",
        type=int,
        default=None,
        help="kernel walk geometry override: slot sub-blocks per grid step",
    )
    parser.add_argument(
        "--group",
        type=int,
        default=None,
        help="kernel walk geometry override: 8-row chunks per walk iteration",
    )
    parser.add_argument(
        "--config",
        choices=["powerlaw", "churn", "mac", "rings", "cluster"],
        default="powerlaw",
        help="BASELINE workload config (default: powerlaw, config 5)",
    )
    args = parser.parse_args()

    if args.config != "powerlaw":
        # The live configs run the host actor runtime, but a device
        # shadow-graph backend (or any jax import inside the workload)
        # would still hit the flaky TPU init — give them the same probe
        # protection as the device path.
        probe_platform()
        run_live_config(args)
        return

    probe = probe_platform()

    import jax

    from uigc_tpu.utils.platform import apply_platform_override, is_tpu_platform

    apply_platform_override()

    import numpy as np

    # The probe ran in a subprocess; init here can still fail on a flaky
    # backend.  Retry with backoff, then force CPU as the last resort so
    # the benchmark always emits its JSON line.
    platform = None
    for attempt in range(3):
        try:
            platform = jax.devices()[0].platform
            break
        except Exception as exc:  # backend init failure
            probe["probe"] += f"; in-process attempt {attempt}: {str(exc)[:200]}"
            if attempt < 2:
                time.sleep(5.0 * (attempt + 1))
    if platform is None:
        probe["degraded"] = True
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        try:
            platform = jax.devices()[0].platform
        except Exception as exc:
            # jax can cache a fatal backend-init error; even forced-CPU
            # init then re-raises.  Emit a degraded result line rather
            # than dying without the JSON contract line.
            print(
                json.dumps(
                    {
                        "metric": "garbage_actors_per_sec",
                        "value": 0.0,
                        "unit": "actors/s",
                        "vs_baseline": 0.0,
                        "platform": "none",
                        "platform_degraded": True,
                        "probe": probe["probe"] + f"; cpu fallback failed: {str(exc)[:200]}",
                        "error": "jax backend unavailable on every platform",
                    }
                )
            )
            return
    is_tpu = is_tpu_platform(platform)
    if args.n is None:
        if args.small:
            n = 1 << 16
        elif is_tpu:
            n = 10_000_000
        else:
            n = 1 << 20
    else:
        n = args.n

    from uigc_tpu.models import powerlaw_actor_graph
    from uigc_tpu.ops import trace as trace_ops

    impl = args.impl or ("pallas" if is_tpu else "xla")
    if args.layout == "incremental" and impl != "pallas":
        parser.error("--layout incremental requires the pallas impl")

    graph = powerlaw_actor_graph(n, seed=0, garbage_fraction=args.garbage_fraction)

    def build(impl):
        if impl == "pallas" and args.layout == "incremental":
            from uigc_tpu.ops import pallas_incremental

            layout = pallas_incremental.IncrementalPallasLayout(
                n, sub=args.sub, group=args.group
            )
            layout.rebuild(
                graph["edge_src"],
                graph["edge_dst"],
                graph["edge_weight"],
                graph["supervisor"],
            )

            def fn(flags_dev, recv_dev):
                return layout.trace_device(flags_dev, recv_dev)

            host_args = (graph["flags"], graph["recv_count"])
        elif impl == "pallas":
            from uigc_tpu.ops import pallas_trace

            prep = pallas_trace.prepare_chunks(
                graph["edge_src"].astype(np.int32),
                graph["edge_dst"].astype(np.int32),
                graph["edge_weight"],
                graph["supervisor"],
                n,
                sub=args.sub,
                group=args.group,
            )
            fn = pallas_trace.get_trace_fn(prep)
            host_args = (
                graph["flags"],
                graph["recv_count"],
            ) + pallas_trace.device_args(prep)
        else:
            if "fn" not in trace_ops._jax_trace_cache:
                trace_ops._jax_trace_cache["fn"] = trace_ops._build_jax_trace()
            fn = trace_ops._jax_trace_cache["fn"]
            host_args = (
                graph["flags"],
                graph["recv_count"],
                graph["supervisor"],
                graph["edge_src"].astype(np.int32),
                graph["edge_dst"].astype(np.int32),
                graph["edge_weight"],
            )
        return fn, [jax.device_put(x) for x in host_args]

    fn, dev_args = build(impl)

    # Warmup / compile, and verify verdicts.  If the auto-chosen Pallas
    # path fails to compile on this backend, degrade to the XLA trace
    # rather than dying without a result line (an explicit --impl pallas
    # request is allowed to fail loudly).
    try:
        mark = fn(*dev_args)
    except Exception as exc:
        if args.impl is not None or impl != "pallas" or args.layout == "incremental":
            raise
        probe["probe"] += f"; pallas warmup failed: {str(exc)[:200]}"
        impl = "xla"
        fn, dev_args = build(impl)
        mark = fn(*dev_args)
    in_use = (graph["flags"] & trace_ops.FLAG_IN_USE) != 0
    garbage = in_use & ~np.asarray(mark)
    n_garbage = int(garbage.sum())
    assert np.array_equal(garbage, graph["expected_garbage"]), "wrong verdicts"

    # One-shot wall latency (includes the driver tunnel's ~70ms sync floor
    # per host round-trip; only value readback actually syncs on this
    # transport — block_until_ready does not).
    t0 = time.perf_counter()
    one = fn(*dev_args)
    int(one.sum())
    one_shot = time.perf_counter() - t0

    # Sustained collector throughput.  Two regimes:
    #
    # - Fast traces (<< sync floor): chain reps inside one jit with an
    #   optimization barrier between them so per-trace time is measurable.
    #   The chain length is capped so one device program stays well under
    #   the transport's execution watchdog (a single program that runs for
    #   minutes kills the TPU worker).
    # - Slow traces: per-call timing with readback; the sync floor is
    #   noise at this scale.  Never enqueue a multi-minute mega-program.
    budget_s = 20.0
    # The incremental layout's wake fn does host-side layout maintenance,
    # so it cannot be chained inside one jitted program.
    chainable = args.layout != "incremental"
    if one_shot < 0.25 and chainable:
        import jax.numpy as jnp

        @jax.jit
        def chained(chain_len, *state0):
            def body(_, carry):
                acc, state = carry
                mark = fn(*state)
                # Real data dependency so no trace can be elided or fused
                # away across iterations.
                acc = acc + jnp.count_nonzero(mark)
                state = jax.lax.optimization_barrier(state)
                return acc, state

            # Dynamic bound (lowered to while_loop): one compile covers
            # every chain length, so calibration costs no extra compiles.
            acc, _ = jax.lax.fori_loop(0, chain_len, body, (0, state0))
            return acc

        int(chained(2, *dev_args))  # compile
        # Calibrate per-trace cost from the *difference* of two chain
        # lengths, which cancels the transport's ~70ms per-call sync
        # floor — sizing reps from the one-shot wall latency would fold
        # that floor into the estimate and understate throughput.  The
        # median of three pairs guards against a transport hiccup in any
        # single sample producing a near-zero estimate (which would size
        # a watchdog-killing mega-chain); the one-shot-derived floor is a
        # second, independent guard.
        cal_len = 34
        estimates = []
        for _ in range(3):
            t0 = time.perf_counter()
            int(chained(2, *dev_args))
            t_short = time.perf_counter() - t0
            t0 = time.perf_counter()
            int(chained(cal_len, *dev_args))
            t_long = time.perf_counter() - t0
            estimates.append(max((t_long - t_short) / (cal_len - 2), 1e-6))
        per_trace = max(statistics.median(estimates), one_shot / 1000.0)

        n_chains = 3
        # Fill the budget, but keep any single device program well under
        # the transport's execution watchdog (a single program that runs
        # for minutes kills the TPU worker).
        max_chain_s = 6.0
        reps_cap = args.reps if args.reps is not None else 100_000
        reps = max(
            2,
            min(
                reps_cap,
                int(budget_s / n_chains / per_trace),
                int(max_chain_s / per_trace) + 1,
            ),
        )

        # Median of per-chain means, so the reported statistic matches the
        # slow regime's median (one chain can be skewed by a transport
        # hiccup).
        times = []
        for _ in range(n_chains):
            t0 = time.perf_counter()
            int(chained(reps, *dev_args))  # forces full completion via readback
            times.append((time.perf_counter() - t0) / reps)
        p50 = statistics.median(times)
        reps = reps * n_chains
    else:
        reps = max(1, min(args.reps or 20, int(budget_s / one_shot) + 1))
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            m = fn(*dev_args)
            int(m.sum())
            times.append(time.perf_counter() - t0)
        p50 = statistics.median(times)

    throughput = n_garbage / p50
    target = 10_000_000.0  # north-star garbage actors/sec (BASELINE.json)

    result = {
        "metric": "garbage_actors_per_sec",
        "value": round(throughput, 1),
        "unit": "actors/s",
        "vs_baseline": round(throughput / target, 4),
        "p50_detection_ms": round(p50 * 1e3, 3),
        "one_shot_ms": round(one_shot * 1e3, 3),
        "n_actors": n,
        "n_garbage": n_garbage,
        "n_edges": int(graph["edge_src"].shape[0]),
        "timing_reps": reps,
        "platform": platform,
        "platform_degraded": probe["degraded"],
        "probe": probe["probe"],
        "impl": impl,
        "layout": args.layout,
    }
    print(json.dumps(result))


def run_live_config(args) -> None:
    """BASELINE configs 1-4: end-to-end collection through the live
    runtime (see uigc_tpu/models/workloads.py)."""
    from uigc_tpu.models import workloads

    n = args.n
    if args.config == "churn":
        r = workloads.run_tree(n_actors=n or 10_000, fanout=8, engine="crgc")
    elif args.config == "mac":
        r = workloads.run_tree(n_actors=n or 10_000, fanout=1 << 30, engine="mac")
    elif args.config == "rings":
        rings = max(1, (n or 10_000) // 100)
        r = workloads.run_rings(n_rings=rings, ring_size=100)
    else:  # cluster
        r = workloads.run_cluster_recovery(n_workers=n or 200)

    throughput = r["n_collected"] / r["collect_s"]
    result = {
        "metric": f"{args.config}_collected_actors_per_sec",
        "value": round(throughput, 1),
        "unit": "actors/s",
        "vs_baseline": None,  # no reference numbers exist (BASELINE.md)
        "collect_s": round(r["collect_s"], 3),
        "build_s": round(r["build_s"], 3),
        "n_collected": r["n_collected"],
        "config": args.config,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
