"""Vector-clock race detector for the cell/dispatcher scheduling layer.

Checks the documented invariants of :mod:`uigc_tpu.runtime.cell` from
the ``sched.*`` event stream alone — no runtime internals are consulted,
so the detector can run against a live recorder listener or a replayed
event log:

1. **Single-threaded cell processing** — a cell is processed by at most
   one dispatcher thread at a time (cell.py: the ``_scheduled`` flag).
   Observed as: no two ``batch_start``/``batch_end`` intervals for the
   same cell may overlap, and every batch pair must be happens-before
   ordered with its predecessor.
2. **System-before-app ordering** — system messages enqueued before a
   batch began must be invoked before that batch's first application
   message (cell.py: the sysbox drains first).
3. **Children-stop-before-PostStop** — a cell's PostStop runs only after
   every child has terminated (cell.py: ``_initiate_stop`` /
   ``_finalize``).

Event ordering: every committed event carries a ``seq`` field stamped
under the recorder lock (utils/events.py), a process-wide total order
consistent with real time.  Happens-before is tracked with genuine
vector clocks indexed by dispatcher thread: program order per thread,
release/acquire edges through each cell's mailbox (enqueue → the batch
that drains it) and through batch hand-off (batch_end → next
batch_start on the same cell).  A violated invariant therefore comes
with both interleaving evidence (the seq window) and causality evidence
(VC-concurrent batches).

In the spirit of the vector-clock race detection literature (PAPERS.md:
Tascade's atomic-free reduction-tree verification concerns), a report
is raised only when the event stream *proves* the violation — the
detector never guesses from timing alone.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ..utils import events
from ..utils.validation import InvariantViolation


class RaceViolation(InvariantViolation):
    """A scheduling invariant did not hold in the observed stream."""


class VectorClock:
    """A sparse vector clock over dispatcher-thread ids."""

    __slots__ = ("clock",)

    def __init__(self, clock: Optional[Dict[Any, int]] = None):
        self.clock: Dict[Any, int] = dict(clock) if clock else {}

    def tick(self, tid: Any) -> None:
        self.clock[tid] = self.clock.get(tid, 0) + 1

    def join(self, other: "VectorClock") -> None:
        for tid, t in other.clock.items():
            if t > self.clock.get(tid, 0):
                self.clock[tid] = t

    def copy(self) -> "VectorClock":
        return VectorClock(self.clock)

    def happened_before(self, other: "VectorClock") -> bool:
        """self -> other: every component <=, and the clocks differ."""
        for tid, t in self.clock.items():
            if t > other.clock.get(tid, 0):
                return False
        return self.clock != other.clock

    def concurrent_with(self, other: "VectorClock") -> bool:
        return not self.happened_before(other) and not other.happened_before(
            self
        )

    def __repr__(self) -> str:  # pragma: no cover
        return f"VC({self.clock!r})"


class _OpenBatch:
    __slots__ = ("cell", "path", "thread", "start_seq", "start_vc", "app_seen")

    def __init__(self, cell: int, path: str, thread: Any, seq: int, vc: VectorClock):
        self.cell = cell
        self.path = path
        self.thread = thread
        self.start_seq = seq
        self.start_vc = vc
        self.app_seen = False


class RaceDetector:
    """Collects ``sched.*`` events (live via :meth:`attach`, or replayed
    via :meth:`feed`) and reports invariant violations from
    :meth:`analyze`."""

    SCHED_PREFIX = "sched."

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: List[Tuple[int, str, Dict[str, Any]]] = []
        self._listener = None

    # -- collection --------------------------------------------------- #

    def attach(self) -> "RaceDetector":
        """Subscribe to the process recorder (which must be enabled, and
        the system must run with ``uigc.analysis.sched-events`` on)."""

        def listener(name: str, fields: Dict[str, Any]) -> None:
            if name.startswith(self.SCHED_PREFIX):
                with self._lock:
                    self._events.append((fields.get("seq", 0), name, fields))

        self._listener = listener
        events.recorder.add_listener(listener)
        return self

    def detach(self) -> None:
        if self._listener is not None:
            events.recorder.remove_listener(self._listener)
            self._listener = None

    def feed(self, stream: Any) -> "RaceDetector":
        """Ingest a replayed stream of ``(name, fields)`` pairs; missing
        ``seq`` fields fall back to stream order."""
        with self._lock:
            base = len(self._events)
            for i, (name, fields) in enumerate(stream):
                if name.startswith(self.SCHED_PREFIX):
                    self._events.append(
                        (fields.get("seq", base + i), name, fields)
                    )
        return self

    # -- analysis ------------------------------------------------------ #

    def analyze(self) -> List[RaceViolation]:
        with self._lock:
            stream = sorted(self._events, key=lambda e: e[0])
        violations: List[RaceViolation] = []

        # Vector-clock state.
        thread_vc: Dict[Any, VectorClock] = {}
        mailbox_vc: Dict[int, VectorClock] = {}  # release clock per cell
        handoff_vc: Dict[int, VectorClock] = {}  # clock at last batch_end

        open_batches: Dict[int, _OpenBatch] = {}
        # Per-cell FIFO of pending system enqueue seqs, matched to sys
        # invokes (the runtime's sysbox is a deque).  Enqueue events are
        # committed outside the cell lock, so an invoke's commit can
        # overtake its own enqueue's commit; such an invoke banks a
        # credit that cancels the late-arriving enqueue instead of
        # leaving a ghost pending entry (a false positive otherwise).
        pending_sys: Dict[int, List[int]] = {}
        sys_credit: Dict[int, int] = {}
        children: Dict[int, List[Tuple[int, str]]] = {}
        terminated: Dict[int, int] = {}  # cell -> seq of termination

        def vc_of(tid: Any) -> VectorClock:
            vc = thread_vc.get(tid)
            if vc is None:
                vc = thread_vc[tid] = VectorClock()
            return vc

        for seq, name, fields in stream:
            cell = fields.get("cell")
            # A missing thread id (hand-written replay stream) gets a
            # unique synthetic component per event — one shared fallback
            # clock would fabricate happens-before edges between
            # causally unrelated events.
            tid = fields.get("thread", f"?{seq}")
            vc = vc_of(tid)
            vc.tick(tid)

            if name == events.SCHED_ENQUEUE:
                # Release into the cell's mailbox.
                released = mailbox_vc.get(cell)
                if released is None:
                    released = mailbox_vc[cell] = VectorClock()
                released.join(vc)
                if fields.get("kind") == "sys":
                    if sys_credit.get(cell, 0) > 0:
                        sys_credit[cell] -= 1  # already invoked, commit raced
                    else:
                        pending_sys.setdefault(cell, []).append(seq)

            elif name == events.SCHED_BATCH_START:
                prev = open_batches.get(cell)
                if prev is not None:
                    # Invariant 1: the previous batch never ended.
                    violations.append(
                        RaceViolation(
                            "sched.overlap",
                            "two dispatcher threads processed one cell "
                            "concurrently",
                            cell=fields.get("path", cell),
                            first_thread=prev.thread,
                            second_thread=tid,
                            first_start_seq=prev.start_seq,
                            second_start_seq=seq,
                            vc_concurrent=prev.start_vc.concurrent_with(vc),
                        )
                    )
                # Acquire: mailbox releases + the previous batch's end.
                released = mailbox_vc.get(cell)
                if released is not None:
                    vc.join(released)
                ended = handoff_vc.get(cell)
                if ended is not None:
                    vc.join(ended)
                open_batches[cell] = _OpenBatch(
                    cell, fields.get("path", ""), tid, seq, vc.copy()
                )

            elif name == events.SCHED_INVOKE:
                released = mailbox_vc.get(cell)
                if released is not None:
                    vc.join(released)
                batch = open_batches.get(cell)
                if fields.get("kind") == "sys":
                    queue = pending_sys.get(cell)
                    if queue:
                        queue.pop(0)
                    else:
                        sys_credit[cell] = sys_credit.get(cell, 0) + 1
                elif batch is not None and not batch.app_seen:
                    batch.app_seen = True
                    # Invariant 2: any system message enqueued strictly
                    # before this batch began must already be invoked.
                    stale = [
                        s
                        for s in pending_sys.get(cell, ())
                        if s < batch.start_seq
                    ]
                    if stale:
                        violations.append(
                            RaceViolation(
                                "sched.sys_after_app",
                                "application message invoked while earlier "
                                "system messages were pending",
                                cell=fields.get("path", cell),
                                batch_start_seq=batch.start_seq,
                                app_invoke_seq=seq,
                                pending_sys_seqs=stale,
                            )
                        )

            elif name == events.SCHED_BATCH_END:
                open_batches.pop(cell, None)
                handoff_vc[cell] = vc.copy()

            elif name == events.SCHED_SPAWN:
                parent = fields.get("parent")
                children.setdefault(parent, []).append(
                    (cell, fields.get("path", ""))
                )

            elif name == events.SCHED_POSTSTOP:
                alive = [
                    path
                    for child, path in children.get(cell, ())
                    if child not in terminated or terminated[child] > seq
                ]
                if alive:
                    # Invariant 3.
                    violations.append(
                        RaceViolation(
                            "sched.poststop_before_children",
                            "PostStop ran while children were still alive",
                            cell=fields.get("path", cell),
                            poststop_seq=seq,
                            live_children=alive,
                        )
                    )

            elif name == events.SCHED_TERMINATED:
                terminated[cell] = seq

        return violations

    def event_count(self) -> int:
        with self._lock:
            return len(self._events)
