"""UL001-UL016: the uigc-lint rule set as a pass over the shared parse.

Ported verbatim from ``tools/uigc_lint.py`` (which is now a thin
wrapper over this module): rule ids, message texts, suppression
comments and allowlist semantics are bit-compatible — the refactor
changed where the AST comes from (one shared ``ast.parse`` per file
for ALL passes), not what the rules say.  See the wrapper's docstring
for the rule catalogue.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Diagnostic, ParsedFile, call_name

RULES = {
    "UL001": "ref captured in closure without create_ref registration",
    "UL002": "message stores refs its refs property does not export",
    "UL003": "blocking call inside a behavior callback",
    "UL004": "bare assert used for a runtime invariant in library code",
    "UL005": "inconsistent lock-acquisition order",
    "UL006": "direct ProxyCell construction outside runtime/",
    "UL007": "blocking socket call while holding a _PeerState lock",
    "UL008": "snapshot/inspect code mutates engine state",
    "UL009": "metric name violates the uigc_ prefix / unit-suffix convention",
    "UL010": "direct pickle call on a runtime hot-path module outside wire.py",
    "UL011": "unannotated device->host transfer on an engines/ops hot path",
    "UL012": "unbounded queue-shaped attribute in runtime//cluster/ "
    "without a bound or an '# unbounded:' rationale",
    "UL013": "journal append or shard-table mutation bypassing the "
    "fenced helpers in cluster/sharding.py / cluster/journal.py",
    "UL014": "shadow-graph slot mutated outside the owning partition's "
    "fold path (route through the dmark/delta plane)",
    "UL015": "dmark/dmack payload built outside the schema-codec "
    "helpers (no ad-hoc frames or JSON coordinate lists on the "
    "distributed hot path)",
    "UL016": "pickle/marshal call inside the ingress gateway (client "
    "bytes meet only the closed client value codec)",
}

_QUEUE_ATTR = re.compile(
    r"(queue|buf|pending|deferred|backlog|outq|box|_q$)", re.IGNORECASE
)
_NUMPY_QUALS = {"np", "numpy", "_np"}
_PICKLE_CALLS = {"dumps", "loads", "dump", "load", "Pickler", "Unpickler"}
_JOURNAL_APPEND_CALLS = {
    "open_epoch",
    "note_command",
    "commit_snapshot",
    "begin_snapshot",
}
_SHADOW_SLOT_ATTRS = {"interned", "is_halted", "supervisor"}
_SHADOW_FOLD_MODULES = (
    "engines/crgc/shadow.py",
    "engines/crgc/delta.py",
    "engines/crgc/distributed.py",
    "engines/crgc/state.py",
    "analysis/sanitizer.py",
)
_DMARK_FRAME_KINDS = {"dmark", "dmack"}
_METRIC_UNIT_SUFFIXES = ("_seconds", "_bytes", "_total", "_ratio")
_METRIC_REGISTRARS = {"counter", "gauge", "histogram"}
_ENGINE_MUTATORS = {
    "merge_entry",
    "merge_entries",
    "merge_packed",
    "merge_delta",
    "merge_undo_log",
    "trace",
    "harvest_trace",
    "launch_trace",
    "expire_stalled_wake",
    "start_wave",
    "tell",
    "tell_bulk",
    "tell_system",
    "tell_batch",
    "stop",
    "collect",
    "spawn",
    "release",
    "register_frame_handler",
    "send_frame",
    "die",
    "link",
    "attach_packed_plane",
}
_SOCKET_CALLS = {
    "sendall",
    "send_bytes",
    "recv",
    "accept",
    "connect",
    "create_connection",
    "makefile",
}
_REF_NAME = re.compile(r"(^|_)refs?($|_)|refob", re.IGNORECASE)
_LOCK_NAME = re.compile(r"(^|_)(lock|rlock|cv|cond)$", re.IGNORECASE)
_BLOCKING_CALLS = {
    ("time", "sleep"),
    ("socket", "recv"),
    ("socket", "accept"),
    ("queue", "get"),
    ("subprocess", "run"),
    ("subprocess", "check_output"),
}
_BLOCKING_METHODS = {"join", "wait", "acquire", "recv", "accept", "get"}
_NONBLOCKING_HINTS = {"get"}  # dict.get — exempt unless a timeout arg is used
_BLOCKING_BARE = {"input"}


def _contains_call(tree: ast.AST, name: str) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and call_name(node)[1] == name:
            return True
    return False


def _is_behavior_class(node: ast.ClassDef) -> bool:
    for item in node.body:
        if isinstance(item, ast.FunctionDef) and item.name in (
            "on_message",
            "on_signal",
        ):
            return True
    return False


class FileLinter:
    """All file-local UL rules over one ParsedFile."""

    def __init__(self, pf: ParsedFile):
        self.pf = pf
        self.path = pf.path
        self.tree = pf.tree
        self.violations: List[Diagnostic] = []
        #: (outer_lock, inner_lock) -> first line observed, for UL005
        self.lock_pairs: Dict[Tuple[str, str], int] = {}

    def add(self, line: int, rule: str, message: str) -> None:
        if self.pf.suppressed_on(line, rule):
            return
        self.violations.append(Diagnostic(self.path, line, rule, message))

    # -- rules ------------------------------------------------------- #

    def run(self, lint_asserts: bool) -> None:
        parts = self.pf.parts
        in_runtime = "runtime" in parts
        norm = self.pf.norm
        pickle_guarded = in_runtime and not norm.endswith("runtime/wire.py")
        device_plane = bool({"engines", "ops", "parallel"} & set(parts))
        gateway_plane = "gateway" in parts
        bounded_plane = in_runtime or bool({"cluster", "gateway"} & set(parts))
        fence_plane = bounded_plane and not (
            norm.endswith("cluster/sharding.py")
            or norm.endswith("cluster/journal.py")
        )
        slot_plane = (
            "uigc_tpu" in parts
            and "tests" not in parts
            and not norm.endswith(_SHADOW_FOLD_MODULES)
        )
        dmark_plane = "uigc_tpu" in parts and "tests" not in parts
        is_wire = norm.endswith("runtime/wire.py")
        if is_wire:
            self._lint_dmark_payload_json()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._lint_class(node)
            elif isinstance(node, (ast.Tuple, ast.List)):
                if dmark_plane and not is_wire:
                    self._lint_dmark_frame_literal(node)
            elif isinstance(node, ast.Call):
                if not in_runtime:
                    self._lint_proxycell(node)
                if pickle_guarded:
                    self._lint_pickle_hot_path(node)
                if gateway_plane:
                    self._lint_gateway_codec(node)
                if device_plane:
                    self._lint_host_transfer(node)
                if fence_plane:
                    self._lint_fenced_journal(node)
                if slot_plane:
                    self._lint_shadow_slot_call(node)
                self._lint_metric_name(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._lint_socket_under_peer_lock(node)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                if bounded_plane:
                    self._lint_unbounded_queue(node)
                if fence_plane:
                    self._lint_table_mutation(node)
                if slot_plane:
                    self._lint_shadow_slot_store(node)
            elif isinstance(node, ast.AugAssign):
                if slot_plane:
                    self._lint_shadow_slot_store(node)
        if norm.endswith("telemetry/inspect.py"):
            self._lint_inspect_readonly()
        if lint_asserts:
            self._lint_asserts()
        self._collect_lock_pairs()

    def _lint_inspect_readonly(self) -> None:
        """UL008: the liveness inspector is read-only by contract."""

        def import_names(node) -> List[str]:
            if isinstance(node, ast.Import):
                return [alias.name for alias in node.names]
            if isinstance(node, ast.ImportFrom):
                module = node.module or ""
                # Relative imports: from ..engines.x / from ..runtime
                # resolve inside uigc_tpu; absolute spell it out.
                return [module]
            return []

        def is_type_checking_if(node: ast.AST) -> bool:
            if not isinstance(node, ast.If):
                return False
            test = node.test
            name = (
                test.id
                if isinstance(test, ast.Name)
                else getattr(test, "attr", "")
            )
            return name == "TYPE_CHECKING"

        def walk_imports(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if is_type_checking_if(child):
                    continue  # annotation-only: never executes
                if isinstance(child, (ast.Import, ast.ImportFrom)):
                    for module in import_names(child):
                        parts = module.split(".")
                        if "engines" in parts or "runtime" in parts:
                            self.add(
                                child.lineno,
                                "UL008",
                                f"runtime import of {module or '(relative)'!r}: "
                                "inspect code reaches engine/runtime state "
                                "duck-typed only (TYPE_CHECKING imports OK)",
                            )
                else:
                    walk_imports(child)

        def store_root(target: ast.AST):
            """(root name, crosses-an-attribute?) of a store target."""
            has_attr = False
            node = target
            while isinstance(node, (ast.Attribute, ast.Subscript)):
                if isinstance(node, ast.Attribute):
                    has_attr = True
                node = node.value
            if isinstance(node, ast.Name):
                return node.id, has_attr
            return None, has_attr

        def check_target(target: ast.AST, line: int) -> None:
            if isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    check_target(elt, line)
                return
            root, has_attr = store_root(target)
            if has_attr and root is not None and root != "self":
                self.add(
                    line,
                    "UL008",
                    f"store through attribute of {root!r}: inspect code "
                    "may only mutate its own objects (root must be self)",
                )

        walk_imports(self.tree)
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for target in targets:
                    check_target(target, node.lineno)
            elif isinstance(node, ast.Delete):
                for target in node.targets:
                    check_target(target, node.lineno)
            elif isinstance(node, ast.Call):
                qual, name = call_name(node)
                if name in _ENGINE_MUTATORS and isinstance(
                    node.func, ast.Attribute
                ):
                    self.add(
                        node.lineno,
                        "UL008",
                        f"call to engine mutator .{name}() from read-only "
                        "inspect code",
                    )

    def _lint_socket_under_peer_lock(self, fn: ast.AST) -> None:
        """UL007: blocking socket I/O under a _PeerState lock.

        A 'peer lock' is approximated as ``<name>.lock`` / ``<name>.rlock``
        where ``<name>`` is the conventional ``st`` or was assigned from a
        ``_peer_state(...)`` call in the same function — the exact shape
        the pre-writer transport used (sendall under ``st.lock``)."""
        peer_vars = {"st"}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if call_name(node.value)[1] == "_peer_state":
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            peer_vars.add(target.id)

        def holds_peer_lock(with_node: ast.With) -> bool:
            for item in with_node.items:
                expr = item.context_expr
                if (
                    isinstance(expr, ast.Attribute)
                    and expr.attr in ("lock", "rlock")
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id in peer_vars
                ):
                    return True
            return False

        def walk(node: ast.AST, held: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # A nested def's body runs later, not under the
                    # lock — and the outer ast.walk dispatch will lint
                    # it as its own function, so don't descend here
                    # (that would double-report its violations).
                    continue
                if held and isinstance(child, ast.Call):
                    name = call_name(child)[1]
                    if name in _SOCKET_CALLS:
                        self.add(
                            child.lineno,
                            "UL007",
                            f"blocking socket call {name}() while holding a "
                            "_PeerState lock; claim the seq under the lock, "
                            "write on the peer's writer thread",
                        )
                if isinstance(child, ast.With):
                    walk(child, held or holds_peer_lock(child))
                else:
                    walk(child, held)

        walk(fn, False)

    def _lint_metric_name(self, call: ast.Call) -> None:
        """UL009: metric names registered via ``.counter/.gauge/
        .histogram(...)`` must carry the ``uigc_`` prefix; counters and
        histograms also need a unit suffix."""
        fn = call.func
        if not isinstance(fn, ast.Attribute) or fn.attr not in _METRIC_REGISTRARS:
            return
        if not call.args:
            return
        first = call.args[0]
        if not isinstance(first, ast.Constant) or not isinstance(
            first.value, str
        ):
            return  # dynamic name: nothing to check statically
        name = first.value
        if not name.startswith("uigc_"):
            self.add(
                call.lineno,
                "UL009",
                f"metric {name!r} lacks the uigc_ prefix",
            )
            return
        if fn.attr != "gauge" and not name.endswith(_METRIC_UNIT_SUFFIXES):
            self.add(
                call.lineno,
                "UL009",
                f"{fn.attr} {name!r} lacks a unit suffix "
                f"({'/'.join(_METRIC_UNIT_SUFFIXES)})",
            )

    def _lint_host_transfer(self, call: ast.Call) -> None:
        """UL011: device->host crossing idioms under engines/, ops/ or
        parallel/ must be annotated (``# readback: <why>``) or routed
        through the accounted ``arrays._readback`` helper.  The flagged
        shapes: ``jax.device_get(x)``, zero-arg ``.item()``, and
        ``np.asarray(x)`` without a ``dtype=`` keyword (the dtype'd
        form is host list conversion, never a readback)."""
        if call.lineno in self.pf.readback_lines:
            return
        qual, name = call_name(call)
        hit = None
        if qual == "jax" and name == "device_get":
            hit = "jax.device_get()"
        elif (
            name == "item"
            # Any attribute receiver, not just a bare name — the common
            # in-method forms are self._dev_x.item() / marks[0].item(),
            # for which call_name's qualifier is None.
            and isinstance(call.func, ast.Attribute)
            and not call.args
            and not call.keywords
        ):
            hit = f"{qual or '<expr>'}.item()"
        elif (
            name == "asarray"
            and qual in _NUMPY_QUALS
            and not any(kw.arg == "dtype" for kw in call.keywords)
        ):
            hit = f"{qual}.asarray() without dtype="
        if hit is not None:
            self.add(
                call.lineno,
                "UL011",
                f"{hit} on a device-plane module: a device->host "
                "transfer here dodges the observatory's accounting; "
                "route through arrays._readback or annotate the line "
                "with '# readback: <why>'",
            )

    def _lint_fenced_journal(self, node: ast.Call) -> None:
        """UL013 (call half): the journal append plane may only be
        driven through the fenced region helpers — a direct
        ``open_epoch``/``note_command``/``commit_snapshot``/
        ``begin_snapshot`` call anywhere else in runtime//cluster/
        bypasses fence stamping, the frozen-journal reject site and the
        epoch-bump-at-enqueue ordering."""
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _JOURNAL_APPEND_CALLS
        ):
            self.add(
                node.lineno,
                "UL013",
                f"direct journal append '{func.attr}(...)' outside the "
                "fenced helpers (route through the ShardRegion "
                "_journal_* helpers in cluster/sharding.py)",
            )

    def _lint_table_mutation(self, node: ast.AST) -> None:
        """UL013 (store half): the shard table is installed only by
        cluster/sharding.py's fence-aware transitions
        (``_recompute_table``/``_adopt_table``); any other
        ``<x>._table = ...`` store skips the fence comparison and the
        grant/hold bookkeeping."""
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for target in targets:
            if isinstance(target, ast.Attribute) and target.attr == "_table":
                self.add(
                    node.lineno,
                    "UL013",
                    "shard-table store bypasses the fenced transition "
                    "helpers in cluster/sharding.py",
                )

    @staticmethod
    def _receiver_name(expr: ast.AST) -> str:
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return expr.attr
        return ""

    def _lint_shadow_slot_store(self, node: ast.AST) -> None:
        """UL014 (store half): authoritative shadow slots — flags,
        supervisor pointers, receive balances, edge maps — are written
        only by the fold plane (_SHADOW_FOLD_MODULES), which the
        distributed collector routes every fact through so it lands at
        the owning partition.  A direct store anywhere else mutates
        state this node may not own — exactly the class the per-sweep
        fold-locality audit catches at runtime."""
        if isinstance(node, ast.Assign):
            targets = node.targets
        else:
            targets = [node.target]
        for target in targets:
            if isinstance(target, ast.Attribute):
                recv = self._receiver_name(target.value)
                if recv == "self":
                    continue
                hit = target.attr in _SHADOW_SLOT_ATTRS or (
                    target.attr == "recv_count" and "shadow" in recv.lower()
                )
                if hit:
                    self.add(
                        node.lineno,
                        "UL014",
                        f"shadow slot .{target.attr} written outside the "
                        "fold plane; route the fact through the "
                        "dmark/delta plane (engines/crgc/delta.py fold_* "
                        "-> owner merge)",
                    )
            elif isinstance(target, ast.Subscript):
                value = target.value
                if (
                    isinstance(value, ast.Attribute)
                    and value.attr == "outgoing"
                ):
                    self.add(
                        node.lineno,
                        "UL014",
                        "shadow edge map .outgoing[...] written outside "
                        "the fold plane; route through the dmark/delta "
                        "plane",
                    )

    def _lint_shadow_slot_call(self, call: ast.Call) -> None:
        """UL014 (call half): mutating calls on a shadow's edge map and
        the ``_update_outgoing`` helper are fold-plane-only for the
        same ownership reason."""
        qual, name = call_name(call)
        if name == "_update_outgoing":
            self.add(
                call.lineno,
                "UL014",
                "_update_outgoing(...) outside the fold plane mutates a "
                "shadow edge map directly; route through the dmark/delta "
                "plane",
            )
            return
        fn = call.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in ("clear", "pop", "setdefault", "update")
            and isinstance(fn.value, ast.Attribute)
            and fn.value.attr == "outgoing"
        ):
            self.add(
                call.lineno,
                "UL014",
                f"shadow edge map .outgoing.{fn.attr}(...) outside the "
                "fold plane; route through the dmark/delta plane",
            )

    def _lint_dmark_frame_literal(self, node: ast.AST) -> None:
        """UL015 (frame half): a ``("dmark", ...)``/``("dmack", ...)``
        literal outside runtime/wire.py builds a boundary-mark frame by
        hand — bypassing the payload codec, the suffix-watermark
        elements and the legacy-peer negotiation the wire helpers
        carry."""
        elts = getattr(node, "elts", ())
        if not elts:
            return
        head = elts[0]
        if (
            isinstance(head, ast.Constant)
            and head.value in _DMARK_FRAME_KINDS
        ):
            self.add(
                node.lineno,
                "UL015",
                f"ad-hoc ({head.value!r}, ...) frame literal; construct "
                "boundary-mark frames through wire.encode_dmark/"
                "encode_dmack",
            )

    def _lint_dmark_payload_json(self) -> None:
        """UL015 (payload half): inside runtime/wire.py, the dmark/
        dmack codec functions must delegate payload bytes to the
        runtime/schema.py key-set helpers — a direct json.dumps/loads
        there re-creates the ad-hoc JSON coordinate list on the hot
        path."""
        for node in self.tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            name = node.name.lower()
            if "dmark" not in name and "dmack" not in name:
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                qual, fn_name = call_name(call)
                if qual == "json" and fn_name in ("dumps", "loads"):
                    self.add(
                        call.lineno,
                        "UL015",
                        f"json.{fn_name} inside {node.name}; dmark/dmack "
                        "payloads go through the schema-codec key-set "
                        "helpers (runtime/schema.py encode_keyset / "
                        "decode_keyset_any)",
                    )

    def _lint_unbounded_queue(self, node: ast.AST) -> None:
        """UL012: queue-shaped attributes in runtime//cluster/ must be
        bounded or carry an explicit '# unbounded: <why>' rationale —
        the silent-deque-growth class the durability/backpressure plane
        (PR 12) exists to eliminate.  Heuristic by construction: only
        ``self.<queueish> = deque() | [] | list()`` assignments fire."""
        if node.lineno in self.pf.unbounded_lines:
            return
        value = node.value
        if value is None:
            return
        unbounded = False
        if isinstance(value, ast.Call):
            name = call_name(value)[1]
            if name == "deque":
                has_maxlen = any(kw.arg == "maxlen" for kw in value.keywords)
                if not has_maxlen and len(value.args) < 2:
                    unbounded = True
            elif name == "list" and not value.args:
                unbounded = True
        elif isinstance(value, ast.List) and not value.elts:
            unbounded = True
        if not unbounded:
            return
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for target in targets:
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
                and _QUEUE_ATTR.search(target.attr)
            ):
                self.add(
                    node.lineno,
                    "UL012",
                    f"queue-shaped attribute self.{target.attr} is an "
                    "unbounded deque()/list; bound it (maxlen / admission "
                    "check) or annotate the line with '# unbounded: <why>'",
                )

    def _lint_pickle_hot_path(self, call: ast.Call) -> None:
        """UL010: pickle stays behind the wire.py fallback on runtime
        hot-path modules — a stray direct call reintroduces per-message
        protocol dispatch (or un-negotiated bytes) the schema codec
        removed."""
        qual, name = call_name(call)
        if qual == "pickle" and name in _PICKLE_CALLS:
            self.add(
                call.lineno,
                "UL010",
                f"direct pickle.{name}() on a runtime hot-path module; "
                "route through wire.encode_message_schema / "
                "wire.decode_message (pickle is the sanctioned fallback "
                "inside runtime/wire.py only)",
            )

    def _lint_gateway_codec(self, call: ast.Call) -> None:
        """UL016: no pickle/marshal anywhere under uigc_tpu/gateway/ —
        gateway modules sit on the untrusted side of the trust boundary
        and client bytes must only meet the closed client value codec
        (runtime/schema.py).  Node-plane replies cross back through
        runtime/wire.py helpers, never a local deserializer call."""
        qual, name = call_name(call)
        if (qual == "pickle" and name in _PICKLE_CALLS) or (
            qual == "marshal" and name in ("dumps", "loads", "dump", "load")
        ):
            self.add(
                call.lineno,
                "UL016",
                f"direct {qual}.{name}() inside the ingress gateway; "
                "client-plane values go through "
                "schema.encode_client_value / decode_client_value and "
                "node-plane replies through runtime/wire.py — a "
                "code-loading deserializer here is one bug away from "
                "attacker bytes",
            )

    def _lint_proxycell(self, call: ast.Call) -> None:
        """UL006: ProxyCell must come from the fabric's cache (or, for
        entity code, stay behind EntityRef) — never be constructed."""
        if call_name(call)[1] == "ProxyCell":
            self.add(
                call.lineno,
                "UL006",
                "direct ProxyCell construction bypasses the fabric's "
                "identity cache; use fabric._proxy (transport code) or "
                "EntityRef (entity code)",
            )

    def _lint_class(self, cls: ast.ClassDef) -> None:
        bases = {
            b.id if isinstance(b, ast.Name) else getattr(b, "attr", "")
            for b in cls.bases
        }
        if "Message" in bases or "NoRefs" in bases:
            self._lint_message_class(cls, bases)
        if _is_behavior_class(cls):
            for item in cls.body:
                if isinstance(item, ast.FunctionDef):
                    if item.name in ("on_message", "on_signal", "__init__"):
                        self._lint_behavior_callback(item)

    def _lint_message_class(self, cls: ast.ClassDef, bases: Set[str]) -> None:
        """UL002: stored ref-like constructor params vs the refs export."""
        init = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        if init is None:
            return
        stored_refs: List[Tuple[str, int]] = []
        for node in ast.walk(init):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                        and _REF_NAME.search(target.attr)
                    ):
                        stored_refs.append((target.attr, node.lineno))
        if not stored_refs:
            return
        refs_prop = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "refs"
            ),
            None,
        )
        if "NoRefs" in bases:
            attr, line = stored_refs[0]
            self.add(
                line,
                "UL002",
                f"class {cls.name} derives NoRefs but stores ref-like "
                f"attribute {attr!r}; derive Message and export it via refs",
            )
            return
        if refs_prop is None:
            attr, line = stored_refs[0]
            self.add(
                cls.lineno,
                "UL002",
                f"class {cls.name} stores ref-like attribute {attr!r} but "
                "defines no refs property",
            )
            return
        # refs property returning a constant empty tuple while refs are
        # stored: the classic silent leak.
        returns = [
            n for n in ast.walk(refs_prop) if isinstance(n, ast.Return)
        ]
        if returns and all(
            isinstance(r.value, ast.Tuple) and not r.value.elts
            for r in returns
            if r.value is not None
        ):
            attr, line = stored_refs[0]
            self.add(
                refs_prop.lineno,
                "UL002",
                f"class {cls.name} stores ref-like attribute {attr!r} but "
                "its refs property always returns ()",
            )

    def _lint_behavior_callback(self, fn: ast.FunctionDef) -> None:
        """UL001 + UL003 inside one behavior callback."""
        has_create_ref = _contains_call(fn, "create_ref")
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                self._check_blocking(node)
                qual, name = call_name(node)
                if name in ("setup", "setup_root", "spawn", "spawn_anonymous"):
                    for arg in node.args:
                        if isinstance(arg, ast.Lambda):
                            self._check_closure_capture(
                                fn, node, arg, has_create_ref
                            )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not fn:
                    self._check_nested_def_capture(fn, node, has_create_ref)

    def _closure_captured_refs(
        self, fn: ast.FunctionDef, closure: ast.AST
    ) -> List[str]:
        """Ref-like names used inside ``closure`` but bound outside it."""
        if isinstance(closure, ast.Lambda):
            params = {a.arg for a in closure.args.args}
            body = closure.body
        elif isinstance(closure, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = {a.arg for a in closure.args.args}
            body = ast.Module(body=closure.body, type_ignores=[])
        else:
            return []
        captured = []
        for node in ast.walk(body):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id not in params
                and _REF_NAME.search(node.id)
            ):
                captured.append(node.id)
            elif (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and _REF_NAME.search(node.attr)
            ):
                captured.append(f"self.{node.attr}")
        return captured

    def _check_closure_capture(
        self,
        fn: ast.FunctionDef,
        call: ast.Call,
        closure: ast.AST,
        has_create_ref: bool,
    ) -> None:
        if has_create_ref:
            return
        captured = self._closure_captured_refs(fn, closure)
        if captured:
            self.add(
                call.lineno,
                "UL001",
                f"closure passed to {call_name(call)[1]} captures "
                f"{sorted(set(captured))} without a create_ref registration "
                f"in {fn.name}",
            )

    def _check_nested_def_capture(
        self, fn: ast.FunctionDef, nested: ast.AST, has_create_ref: bool
    ) -> None:
        if has_create_ref:
            return
        captured = self._closure_captured_refs(fn, nested)
        if captured:
            self.add(
                nested.lineno,
                "UL001",
                f"nested function {nested.name!r} captures "
                f"{sorted(set(captured))} without a create_ref registration "
                f"in {fn.name}",
            )

    def _check_blocking(self, call: ast.Call) -> None:
        qual, name = call_name(call)
        line = call.lineno
        if name in _BLOCKING_BARE and qual is None:
            self.add(line, "UL003", f"blocking call {name}() in a behavior callback")
            return
        if qual is not None and (qual, name) in _BLOCKING_CALLS:
            self.add(
                line, "UL003", f"blocking call {qual}.{name}() in a behavior callback"
            )
            return
        if qual is not None and name in _BLOCKING_METHODS:
            if name in _NONBLOCKING_HINTS and not call.args and not call.keywords:
                return
            # Attribute-based heuristic: obj.join()/obj.wait()/... on
            # thread/queue/event-like receivers.
            if re.search(
                r"thread|queue|event|cond|proc|sock|future|lock",
                qual,
                re.IGNORECASE,
            ):
                self.add(
                    line,
                    "UL003",
                    f"blocking call {qual}.{name}() in a behavior callback",
                )

    def _lint_asserts(self) -> None:
        """UL004: bare asserts in library code."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assert):
                self.add(
                    node.lineno,
                    "UL004",
                    "bare assert is stripped under python -O; raise a "
                    "structured error from uigc_tpu.utils.validation instead",
                )

    def _collect_lock_pairs(self) -> None:
        """Record nested with-lock orders for the cross-file UL005 pass."""

        def lock_attr(expr: ast.AST) -> Optional[str]:
            # with self._lock: / with link.recv_lock: / with st.rlock:
            if isinstance(expr, ast.Attribute) and _LOCK_NAME.search(expr.attr):
                return expr.attr
            if isinstance(expr, ast.Name) and _LOCK_NAME.search(expr.id):
                return expr.id
            return None

        def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.With):
                    acquired = []
                    for item in child.items:
                        name = lock_attr(item.context_expr)
                        if name is not None:
                            acquired.append(name)
                    for outer in held:
                        for inner in acquired:
                            if outer != inner:
                                self.lock_pairs.setdefault(
                                    (outer, inner), child.lineno
                                )
                    walk(child, held + tuple(acquired))
                else:
                    walk(child, held)

        walk(self.tree, ())


def run_lint(
    files: List[ParsedFile], lint_asserts: bool = True
) -> List[Diagnostic]:
    """The full UL pass over pre-parsed files: per-file rules plus the
    cross-file UL005 lock-order pairing.  Diagnostic order matches the
    original ``lint_paths`` (per-file in path order, UL005 appended)."""
    violations: List[Diagnostic] = []
    all_lock_pairs: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for pf in files:
        linter = FileLinter(pf)
        # Library code gets the assert rule; test trees keep asserts.
        linter.run(lint_asserts=lint_asserts and not pf.in_tests)
        violations.extend(linter.violations)
        for pair, line in linter.lock_pairs.items():
            all_lock_pairs.setdefault(pair, (pf.path, line))
    # UL005: cross-file order cycle detection over the lock-name digraph.
    for (outer, inner), (path, line) in sorted(all_lock_pairs.items()):
        reverse = all_lock_pairs.get((inner, outer))
        if reverse is not None and (outer, inner) < (inner, outer):
            rpath, rline = reverse
            violations.append(
                Diagnostic(
                    path,
                    line,
                    "UL005",
                    f"locks {outer!r} then {inner!r} here, but "
                    f"{inner!r} then {outer!r} at {rpath}:{rline}",
                )
            )
    return violations
