"""Surface registry: harvest the repo's five stringly-typed planes.

The system coordinates config keys (``uigc.*`` dotted strings), event
names (``crgc.*``/``fabric.*``/``tpu.*``/``telemetry.*``), metric
names (``uigc_*``), NodeFabric frame kinds (the codec tables in
``runtime/wire.py`` + ``register_frame_handler`` sites + the inline
dispatch in ``runtime/node.py``) and schema-codec ids — and nothing
type-checks the seams: a typo'd config key silently reads a default,
an unhandled frame kind silently drops.  This pass harvests every
surface into one machine-readable registry document and runs
cross-plane rules over the seams:

UC101  config key read in code but absent from GUIDE.md's config
       documentation (no backticked mention anywhere in the guide)
UC102  config key present in ``config.py`` DEFAULTS but never read
       anywhere, or documented in GUIDE.md but not a known key —
       dead or stale configuration surface
UC103  event name committed but never consumed: not bridged into a
       metric by any telemetry module and never asserted in tests
UC104  frame-kind coverage hole: a kind that is produced (encoder or
       frame literal) with no consumer (no handler registration, no
       inline dispatch), or consumed but never produced
UC105  a ``decode_*`` wire codec with no test reference — the
       malformed-input (``-> None``) tolerance contract is unpinned
UC106  CONFIG.md drifted from the harvested config surface (stale
       generated doc; regenerate with ``uigc_check --write-config``)
UC107  metric registered but never fed: its handle is never
       inc/observe/set and no other plane references the name
UC108  config key read via a literal that is not in DEFAULTS — the
       typo class (the read raises KeyError at runtime, or silently
       diverges from the documented surface when a local default is
       supplied)
UC401  a pickle-class deserializer (pickle/marshal/wire.decode_message)
       reachable from the ingress gateway's client-input entry points —
       untrusted client bytes must only ever meet the closed client
       value codec (runtime/schema.py), never a code-loading decoder

The registry document (``--registry-out``) is versioned and
shape-stable; ``tests/test_check.py`` pins the schema.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Any, Dict, List, Optional, Set, Tuple

from .core import (
    Diagnostic,
    ParsedFile,
    call_name,
    const_str,
    dotted_name,
)

RULES = {
    "UC101": "config key read but undocumented in GUIDE.md",
    "UC102": "config key defaulted/documented but never read (dead surface)",
    "UC103": "event committed but never bridged to a metric nor "
    "asserted in tests",
    "UC104": "frame kind with a producer but no consumer (or consumer "
    "with no producer)",
    "UC105": "wire decoder without a malformed-input tolerance test",
    "UC106": "CONFIG.md drifted from the harvested config surface",
    "UC107": "metric registered but never updated, sampled, nor referenced",
    "UC108": "config key read but absent from config DEFAULTS (typo class)",
    "UC401": "unsafe deserializer reachable from gateway client-input paths",
}

REGISTRY_VERSION = 1

_CONFIG_GETTERS = {"get", "get_int", "get_bool", "get_string", "get_float"}
_METRIC_REGISTRARS = {"counter", "gauge", "histogram"}
_METRIC_UPDATES = ("inc", "observe", "set", "labels", "add")
_FRAME_SUBSCRIPT_ROOTS = {"frame", "inner", "unit", "job"}


def _site(pf: ParsedFile, line: int) -> str:
    return f"{pf.norm}:{line}"


class Harvest:
    """Mutable accumulator for the five planes."""

    def __init__(self) -> None:
        # config
        self.defaults: Dict[str, Any] = {}
        self.default_docs: Dict[str, str] = {}
        self.default_lines: Dict[str, int] = {}
        self.config_reads: Dict[str, List[str]] = {}
        self.config_pf: Optional[ParsedFile] = None
        # events
        self.event_consts: Dict[str, str] = {}  # CONST -> name
        self.event_names: Dict[str, str] = {}  # name -> CONST
        self.event_lines: Dict[str, int] = {}
        self.event_commits: Dict[str, List[str]] = {}
        self.events_pf: Optional[ParsedFile] = None
        # metrics
        self.metrics: Dict[str, Dict[str, Any]] = {}
        self.metrics_seen: bool = False
        # frames
        self.frame_consts: Dict[str, str] = {}  # CONST/tuple name -> kind(s)
        self.kind_constants: Dict[str, List[str]] = {}  # kind -> const names
        self.kind_tuples: Dict[str, Tuple[str, ...]] = {}  # tuple const -> kinds
        self.encoders: Dict[str, List[str]] = {}  # kind -> encoder sites
        self.decoders: Dict[str, str] = {}  # decoder fn name -> site
        self.handlers: Dict[str, List[str]] = {}  # kind -> handler sites
        self.dispatch: Dict[str, List[str]] = {}  # kind -> inline dispatch sites
        self.producers: Dict[str, List[str]] = {}  # kind -> tuple-literal sites
        self.caps: Dict[str, List[str]] = {}  # capability -> sites
        self.wire_pf: Optional[ParsedFile] = None
        # schemas
        self.schema_ids: Dict[str, Dict[str, Any]] = {}
        self.schema_pf: Optional[ParsedFile] = None


# ------------------------------------------------------------------- #
# Per-plane harvesters
# ------------------------------------------------------------------- #


def _harvest_defaults(pf: ParsedFile, h: Harvest) -> None:
    """The DEFAULTS dict in uigc_tpu/config.py, with the contiguous
    comment block above each key as its documentation."""
    h.config_pf = pf
    for node in pf.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):  # DEFAULTS: Dict[str, Any] = {...}
            targets = [node.target]
        else:
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "DEFAULTS" for t in targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for key_node, val_node in zip(node.value.keys, node.value.values):
            key = const_str(key_node)
            if key is None:
                continue
            try:
                value = ast.literal_eval(val_node)
            except (ValueError, SyntaxError):
                value = ast.get_source_segment(pf.source, val_node)
            h.defaults[key] = value
            h.default_lines[key] = key_node.lineno
            # Doc: contiguous '#' lines immediately above the key.
            doc_lines: List[str] = []
            i = key_node.lineno - 2  # 0-based line above
            while i >= 0:
                stripped = pf.lines[i].strip()
                if stripped.startswith("#"):
                    text = stripped.lstrip("# ").rstrip()
                    # Section banners ("--- Durability plane ... ---",
                    # possibly wrapped) delimit groups, not keys:
                    # stop, don't absorb.
                    if text.startswith("---") or text.endswith("---"):
                        break
                    doc_lines.append(text)
                    i -= 1
                else:
                    break
            doc_lines.reverse()
            doc = " ".join(doc_lines).strip()
            # One-line doc: cut at the first sentence end or the
            # reference parenthetical, whichever comes first.
            doc = re.sub(r"\s*\(reference:.*$", "", doc)
            if ". " in doc:
                doc = doc.split(". ")[0] + "."
            h.default_docs[key] = doc


def _harvest_config_reads(pf: ParsedFile, h: Harvest) -> None:
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute) or fn.attr not in _CONFIG_GETTERS:
            continue
        if not node.args:
            continue
        key = const_str(node.args[0])
        if key is None or not key.startswith("uigc."):
            continue
        h.config_reads.setdefault(key, []).append(_site(pf, node.lineno))


def _harvest_events(pf: ParsedFile, h: Harvest) -> None:
    """Module-level NAME = "category.event" constants in utils/events.py."""
    h.events_pf = pf
    for node in pf.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        value = const_str(node.value)
        if value is None or "." not in value:
            continue
        for target in node.targets:
            if isinstance(target, ast.Name) and target.id.isupper():
                h.event_consts[target.id] = value
                h.event_names[value] = target.id
                h.event_lines[value] = node.lineno


def _harvest_event_commits(pf: ParsedFile, h: Harvest) -> None:
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute) or fn.attr != "commit":
            continue
        if not node.args:
            continue
        first = node.args[0]
        name: Optional[str] = None
        lit = const_str(first)
        if lit is not None and "." in lit:
            name = lit
        elif isinstance(first, ast.Attribute):
            name = h.event_consts.get(first.attr)
        elif isinstance(first, ast.Name):
            name = h.event_consts.get(first.id)
        if name is not None:
            h.event_commits.setdefault(name, []).append(_site(pf, node.lineno))


def _harvest_metrics(pf: ParsedFile, h: Harvest, parents: Dict[int, ast.AST]) -> None:
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute) or fn.attr not in _METRIC_REGISTRARS:
            continue
        if not node.args:
            continue
        name = const_str(node.args[0])
        if name is None:
            continue
        h.metrics_seen = True
        entry = h.metrics.setdefault(
            name,
            {
                "kind": fn.attr,
                "sites": [],
                "callback": False,
                "handles": [],
            },
        )
        entry["sites"].append(_site(pf, node.lineno))
        if any(kw.arg == "fn" for kw in node.keywords):
            entry["callback"] = True
        # The binding the registration result lands in, for the
        # updated-handle check: self._x = r.counter(...) / x = ...
        parent = parents.get(id(node))
        if isinstance(parent, ast.Assign):
            for target in parent.targets:
                if isinstance(target, ast.Attribute):
                    entry["handles"].append((pf.norm, f".{target.attr}."))
                elif isinstance(target, ast.Name):
                    entry["handles"].append((pf.norm, f"{target.id}."))


def _harvest_wire(pf: ParsedFile, h: Harvest) -> None:
    """Frame-kind constants, encoder return tuples and decoder functions
    in runtime/wire.py — the codec table."""
    h.wire_pf = pf
    for node in pf.tree.body:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if not isinstance(target, ast.Name):
                    continue
                if target.id.endswith("_FRAME_KIND"):
                    kind = const_str(node.value)
                    if kind is not None:
                        h.kind_constants.setdefault(kind, []).append(target.id)
                        h.frame_consts[target.id] = kind
                elif target.id.endswith("_FRAME_KINDS"):
                    try:
                        kinds = ast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        continue
                    if isinstance(kinds, tuple):
                        h.kind_tuples[target.id] = kinds
                        for kind in kinds:
                            h.kind_constants.setdefault(kind, []).append(
                                target.id
                            )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith("decode_"):
                h.decoders[node.name] = _site(pf, node.lineno)
            if node.name.startswith("encode_"):
                for ret in ast.walk(node):
                    if isinstance(ret, ast.Return) and isinstance(
                        ret.value, ast.Tuple
                    ):
                        elts = ret.value.elts
                        if elts:
                            kind = const_str(elts[0])
                            if kind is not None:
                                h.encoders.setdefault(kind, []).append(
                                    f"{pf.norm}:{ret.lineno}:{node.name}"
                                )


def _harvest_handlers(pf: ParsedFile, h: Harvest) -> None:
    """register_frame_handler sites: literal kinds, wire.X constants,
    and loop variables iterating a wire kinds tuple.  Duck-typed
    aliases (``reg = getattr(fabric, "register_frame_handler", None)``)
    count as registration calls too."""
    # Loop-variable bindings: for kind in wire.SHARD_FRAME_KINDS: ...
    loop_kinds: Dict[int, Tuple[str, Tuple[str, ...]]] = {}
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            dn = dotted_name(node.iter)
            if dn is not None:
                tuple_name = dn.split(".")[-1]
                kinds = h.kind_tuples.get(tuple_name)
                if kinds is not None:
                    for call in ast.walk(node):
                        if isinstance(call, ast.Call):
                            loop_kinds[id(call)] = (node.target.id, kinds)
    aliases: Set[str] = set()
    for node in ast.walk(pf.tree):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and call_name(node.value)[1] == "getattr"
            and len(node.value.args) >= 2
            and const_str(node.value.args[1]) == "register_frame_handler"
        ):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    aliases.add(target.id)
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call):
            continue
        qual, name = call_name(node)
        if name != "register_frame_handler" and not (
            qual is None and name in aliases
        ):
            continue
        if not node.args:
            continue
        first = node.args[0]
        kinds: List[str] = []
        lit = const_str(first)
        if lit is not None:
            kinds = [lit]
        elif isinstance(first, ast.Attribute):
            kind = h.frame_consts.get(first.attr)
            if kind is not None:
                kinds = [kind]
        elif isinstance(first, ast.Name):
            bound = loop_kinds.get(id(node))
            if bound is not None and bound[0] == first.id:
                kinds = list(bound[1])
        for kind in kinds:
            h.handlers.setdefault(kind, []).append(_site(pf, node.lineno))


def _harvest_dispatch(pf: ParsedFile, h: Harvest) -> None:
    """Inline frame dispatch: ``kind == "lit"`` / ``frame[0] == "lit"``
    comparisons — the transport's built-in receive switch.  Only the
    transport modules themselves count: elsewhere a ``kind`` variable
    belongs to another domain (inspector record kinds, timeseries
    series kinds) and would pollute the frame universe."""
    if not pf.endswith("runtime/node.py", "runtime/fabric.py", "runtime/wire.py"):
        return
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Compare):
            continue
        left = node.left
        is_kind_expr = False
        if isinstance(left, ast.Name) and left.id == "kind":
            is_kind_expr = True
        elif isinstance(left, ast.Subscript) and isinstance(
            left.value, ast.Name
        ):
            if left.value.id in _FRAME_SUBSCRIPT_ROOTS:
                sl = left.slice
                if isinstance(sl, ast.Constant) and sl.value == 0:
                    is_kind_expr = True
        if not is_kind_expr:
            continue
        for comp in node.comparators:
            lit = const_str(comp)
            if lit is not None:
                h.dispatch.setdefault(lit, []).append(_site(pf, node.lineno))


def _harvest_producers(pf: ParsedFile, h: Harvest, universe: Set[str]) -> None:
    """Tuple literals whose head is a known frame kind: the frames the
    mutator plane actually builds and sends."""
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Tuple) or not node.elts:
            continue
        kind = const_str(node.elts[0])
        if kind is not None and kind in universe:
            h.producers.setdefault(kind, []).append(_site(pf, node.lineno))


def _harvest_caps(pf: ParsedFile, h: Harvest) -> None:
    """Hello capability advertisements (caps.append) and checks
    (``"x" in st.caps``)."""
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "append"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "caps"
                and node.args
            ):
                lit = const_str(node.args[0])
                label = lit if lit is not None else "<dynamic>"
                h.caps.setdefault(label, []).append(_site(pf, node.lineno))
        elif isinstance(node, ast.Compare):
            if len(node.ops) == 1 and isinstance(node.ops[0], ast.In):
                lit = const_str(node.left)
                comp = node.comparators[0]
                comp_name = dotted_name(comp) or ""
                if lit is not None and comp_name.endswith("caps"):
                    h.caps.setdefault(lit, []).append(_site(pf, node.lineno))


def _harvest_schemas(pf: ParsedFile, h: Harvest) -> None:
    h.schema_pf = pf
    for node in pf.tree.body:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if (
                    isinstance(target, ast.Name)
                    and target.id.startswith("SCHEMA_")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, int)
                ):
                    h.schema_ids[target.id] = {
                        "id": node.value.value,
                        "line": node.lineno,
                        "constructed": [],
                    }
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Call) and call_name(node)[1] == "Schema":
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in h.schema_ids:
                    h.schema_ids[arg.id]["constructed"].append(
                        _site(pf, node.lineno)
                    )


def _build_parent_map(tree: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


# ------------------------------------------------------------------- #
# Cross-plane context: texts outside the analyzed file set
# ------------------------------------------------------------------- #


class RepoTexts:
    """Lazily read repo documents the cross-plane rules consult (the
    guide, the generated CONFIG.md, and the test tree's source text)."""

    def __init__(self, repo_root: str):
        self.repo_root = repo_root
        self._cache: Dict[str, str] = {}

    def read(self, rel: str) -> str:
        if rel not in self._cache:
            path = os.path.join(self.repo_root, rel)
            try:
                with open(path, encoding="utf-8") as fh:
                    self._cache[rel] = fh.read()
            except OSError:
                self._cache[rel] = ""
        return self._cache[rel]

    def tree_text(self, rel_dir: str) -> str:
        key = rel_dir + "//"
        if key not in self._cache:
            chunks: List[str] = []
            base = os.path.join(self.repo_root, rel_dir)
            if os.path.isdir(base):
                for root, dirs, files in os.walk(base):
                    dirs[:] = [
                        d for d in dirs if not d.startswith((".", "__pycache__"))
                    ]
                    for name in sorted(files):
                        if name.endswith(".py"):
                            try:
                                with open(
                                    os.path.join(root, name), encoding="utf-8"
                                ) as fh:
                                    chunks.append(fh.read())
                            except OSError:
                                pass
            self._cache[key] = "\n".join(chunks)
        return self._cache[key]


# ------------------------------------------------------------------- #
# The pass
# ------------------------------------------------------------------- #


def harvest(files: List[ParsedFile]) -> Harvest:
    h = Harvest()
    # Pass 1: anchor files first (constants other files refer to).
    for pf in files:
        if pf.endswith("uigc_tpu/config.py"):
            _harvest_defaults(pf, h)
        elif pf.endswith("utils/events.py"):
            _harvest_events(pf, h)
        elif pf.endswith("runtime/wire.py"):
            _harvest_wire(pf, h)
        if pf.endswith("runtime/schema.py"):
            _harvest_schemas(pf, h)
    # Pass 2: the whole tree.
    for pf in files:
        if pf.in_tests:
            continue
        _harvest_config_reads(pf, h)
        _harvest_event_commits(pf, h)
        _harvest_metrics(pf, h, _build_parent_map(pf.tree))
        _harvest_handlers(pf, h)
        _harvest_dispatch(pf, h)
        _harvest_caps(pf, h)
    universe = (
        set(h.kind_constants)
        | set(h.encoders)
        | set(h.handlers)
        | set(h.dispatch)
    )
    for pf in files:
        if not pf.in_tests:
            _harvest_producers(pf, h, universe)
    return h


def build_registry(h: Harvest, texts: RepoTexts) -> Dict[str, Any]:
    """The machine-readable surface registry document."""
    guide = texts.read("GUIDE.md")
    tests_text = texts.tree_text("tests")
    telemetry_text = texts.tree_text(os.path.join("uigc_tpu", "telemetry"))
    tools_text = texts.tree_text("tools")

    config: Dict[str, Any] = {}
    for key in sorted(set(h.defaults) | set(h.config_reads)):
        config[key] = {
            "default": h.defaults.get(key),
            "doc": h.default_docs.get(key, ""),
            "readers": sorted(h.config_reads.get(key, [])),
            "in_defaults": key in h.defaults,
            "documented_guide": f"`{key}`" in guide or f'"{key}"' in guide,
        }

    events: Dict[str, Any] = {}
    for name in sorted(set(h.event_names) | set(h.event_commits)):
        const = h.event_names.get(name, "")
        # Three spellings count as a reference: the constant, the
        # dotted literal, and the underscore form (how the name
        # resurfaces inside a metric: shard.handoff_buffered ->
        # uigc_shard_handoff_buffered).
        refs = [t for t in (const, name, name.replace(".", "_")) if t]
        bridged = any(re.search(re.escape(r), telemetry_text) for r in refs)
        tested = any(re.search(re.escape(r), tests_text) for r in refs)
        events[name] = {
            "constant": const,
            "commit_sites": sorted(h.event_commits.get(name, [])),
            "bridged": bridged,
            "tested": tested,
        }

    metrics: Dict[str, Any] = {}
    for name in sorted(h.metrics):
        entry = h.metrics[name]
        updated = entry["callback"]
        if not updated:
            for norm, handle in entry["handles"]:
                # The handle is "used" when it appears with an update
                # method anywhere beyond the registration line.
                module_text = texts.read(norm) or ""
                pat = re.escape(handle) + "(?:" + "|".join(_METRIC_UPDATES) + r")\("
                if re.search(pat, module_text):
                    updated = True
                    break
        referenced = (
            name in tests_text or name in tools_text or name in guide
        )
        metrics[name] = {
            "kind": entry["kind"],
            "sites": sorted(entry["sites"]),
            "callback": entry["callback"],
            "updated": updated,
            "referenced": referenced,
        }

    frames: Dict[str, Any] = {}
    universe = (
        set(h.kind_constants)
        | set(h.encoders)
        | set(h.handlers)
        | set(h.dispatch)
        | set(h.producers)
    )
    for kind in sorted(universe):
        frames[kind] = {
            "constants": sorted(h.kind_constants.get(kind, [])),
            "encoders": sorted(h.encoders.get(kind, [])),
            "handlers": sorted(h.handlers.get(kind, [])),
            "dispatch": sorted(h.dispatch.get(kind, [])),
            "producers": sorted(h.producers.get(kind, [])),
        }

    decoders: Dict[str, Any] = {}
    for name in sorted(h.decoders):
        decoders[name] = {
            "site": h.decoders[name],
            "tested": name in tests_text,
        }

    schemas: Dict[str, Any] = {
        name: dict(h.schema_ids[name]) for name in sorted(h.schema_ids)
    }

    caps: Dict[str, Any] = {c: sorted(s) for c, s in sorted(h.caps.items())}

    return {
        "version": REGISTRY_VERSION,
        "config": config,
        "events": events,
        "metrics": metrics,
        "frames": frames,
        "decoders": decoders,
        "schemas": schemas,
        "caps": caps,
    }


def _diag_for(
    files: List[ParsedFile], norm_site: str
) -> Tuple[Optional[ParsedFile], int]:
    """Resolve a ``path:line`` harvest site back to its ParsedFile so
    suppression comments apply."""
    path, _, line = norm_site.rpartition(":")
    for pf in files:
        if pf.norm == path:
            try:
                return pf, int(line)
            except ValueError:
                return pf, 1
    return None, 1


def run_surface(
    files: List[ParsedFile],
    texts: RepoTexts,
    registry: Optional[Dict[str, Any]] = None,
    config_md_rel: str = "CONFIG.md",
) -> Tuple[List[Diagnostic], Dict[str, Any], Dict[str, str]]:
    """Harvest + rules.  Returns (diagnostics, registry, plane_status)
    where plane_status maps plane name -> "ok" | "skip"."""
    from . import configdoc

    h = harvest(files)
    if registry is None:
        registry = build_registry(h, texts)
    out: List[Diagnostic] = []

    def add(site: str, rule: str, message: str) -> None:
        pf, line = _diag_for(files, site)
        if pf is not None:
            if pf.suppressed_on(line, rule):
                return
            out.append(Diagnostic(pf.path, line, rule, message))
        else:
            path, _, lineno = site.rpartition(":")
            try:
                out.append(Diagnostic(path, int(lineno), rule, message))
            except ValueError:
                out.append(Diagnostic(site, 1, rule, message))

    status: Dict[str, str] = {
        "config": "ok" if h.config_pf is not None else "skip",
        "events": "ok" if h.events_pf is not None else "skip",
        "metrics": "ok" if h.metrics_seen else "skip",
        "frames": "ok" if h.wire_pf is not None else "skip",
        "schemas": "ok" if h.schema_pf is not None else "skip",
    }

    # ---- config plane ---------------------------------------------- #
    if h.config_pf is not None:
        config_pf = h.config_pf
        for key, info in registry["config"].items():
            readers = info["readers"]
            if readers and not info["in_defaults"]:
                add(
                    readers[0],
                    "UC108",
                    f"config key {key!r} read here is not in config.py "
                    "DEFAULTS — a typo'd key raises KeyError (or silently "
                    "diverges from the documented surface)",
                )
            if readers and info["in_defaults"] and not info["documented_guide"]:
                add(
                    f"{config_pf.norm}:{h.default_lines.get(key, 1)}",
                    "UC101",
                    f"config key {key!r} is read "
                    f"({len(readers)} site(s), first {readers[0]}) but "
                    "GUIDE.md never documents it",
                )
            if info["in_defaults"] and not readers:
                add(
                    f"{config_pf.norm}:{h.default_lines.get(key, 1)}",
                    "UC102",
                    f"config key {key!r} has a default but no reader "
                    "anywhere in the analyzed tree — dead surface",
                )
        # GUIDE-documented keys that are not known config surface.
        guide = texts.read("GUIDE.md")
        known = set(registry["config"])
        for m in sorted(set(re.findall(r"`(uigc\.[a-z0-9.-]+)`", guide))):
            if m not in known:
                add(
                    f"{config_pf.norm}:1",
                    "UC102",
                    f"GUIDE.md documents config key {m!r} which is not in "
                    "DEFAULTS and never read — stale doc or doc typo",
                )
        # CONFIG.md drift.
        expected = configdoc.render_config_md(registry)
        actual = texts.read(config_md_rel)
        if actual != expected:
            add(
                f"{config_pf.norm}:1",
                "UC106",
                f"{config_md_rel} is out of date with the config surface; "
                "regenerate with 'uigc_check --write-config'",
            )

    # ---- event plane ----------------------------------------------- #
    if h.events_pf is not None:
        for name, info in registry["events"].items():
            if not info["commit_sites"]:
                continue
            if not info["bridged"] and not info["tested"]:
                add(
                    info["commit_sites"][0],
                    "UC103",
                    f"event {name!r} is committed but no telemetry module "
                    "bridges it to a metric and no test asserts it — "
                    "an observability dead end",
                )

    # ---- metric plane ---------------------------------------------- #
    if h.metrics_seen:
        for name, info in registry["metrics"].items():
            if info["callback"] or info["updated"] or info["referenced"]:
                continue
            add(
                info["sites"][0],
                "UC107",
                f"metric {name!r} is registered but its handle is never "
                "inc/observe/set and nothing references the name — it "
                "scrapes as a permanently-zero series",
            )

    # ---- frame plane ------------------------------------------------ #
    if h.wire_pf is not None:
        for kind, info in registry["frames"].items():
            produced = info["encoders"] or info["producers"]
            consumed = info["handlers"] or info["dispatch"]
            if produced and not consumed:
                site = (info["encoders"] or info["producers"])[0]
                site = ":".join(site.split(":")[:2])
                add(
                    site,
                    "UC104",
                    f"frame kind {kind!r} has a producer but no receiver "
                    "(no register_frame_handler site, no inline dispatch) — "
                    "it silently drops at every peer",
                )
            elif consumed and not produced:
                site = (info["handlers"] or info["dispatch"])[0]
                add(
                    site,
                    "UC104",
                    f"frame kind {kind!r} is handled but nothing in the "
                    "tree ever produces it — dead dispatch arm or a "
                    "missing encoder",
                )
        for name, info in registry["decoders"].items():
            if not info["tested"]:
                add(
                    info["site"],
                    "UC105",
                    f"wire decoder {name}() has no test reference — its "
                    "malformed-input (-> None) tolerance contract is "
                    "unpinned",
                )

    # ---- gateway client-input plane --------------------------------- #
    # UC401: unsafe deserializers reachable from the gateway's
    # client-input entry points.  Entry points are every function in
    # the client protocol module (gateway/protocol.py parses raw socket
    # bytes) plus any gateway function named client_*/_client_* (the
    # helpers that touch pre-auth input).  Reachability is a transitive
    # closure over callee NAMES — a deliberate over-approximation: a
    # false edge costs one review, a missed edge ships pickle.loads on
    # attacker bytes.  wire.decode_message counts as a sink here too:
    # it is the trusted NODE-plane codec (pickle under a persistent-id
    # allowlist) and must never see client bytes.
    gateway_files = [
        pf
        for pf in files
        if not pf.in_tests
        and "/gateway/" in "/" + pf.norm.replace("\\", "/")
    ]
    if gateway_files:
        gw_defs: Dict[str, List[Tuple[ParsedFile, ast.AST]]] = {}
        for pf in gateway_files:
            for node in ast.walk(pf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    gw_defs.setdefault(node.name, []).append((pf, node))
        entries: Set[str] = set()
        for fn_name, sites in gw_defs.items():
            if fn_name.startswith(("client_", "_client_")):
                entries.add(fn_name)
            for pf, _node in sites:
                if pf.endswith("gateway/protocol.py"):
                    entries.add(fn_name)
        gw_calls: Dict[str, Set[str]] = {}
        gw_sinks: Dict[str, List[Tuple[str, str]]] = {}
        for fn_name, sites in gw_defs.items():
            for pf, fnode in sites:
                for call in ast.walk(fnode):
                    if not isinstance(call, ast.Call):
                        continue
                    qual, cname = call_name(call)
                    if not cname:
                        continue
                    if cname in gw_defs:
                        gw_calls.setdefault(fn_name, set()).add(cname)
                    unsafe = (
                        (qual == "pickle" and cname in ("loads", "load", "Unpickler"))
                        or (qual == "marshal" and cname in ("loads", "load"))
                        or cname == "decode_message"
                    )
                    if unsafe:
                        label = f"{qual}.{cname}" if qual else cname
                        gw_sinks.setdefault(fn_name, []).append(
                            (_site(pf, call.lineno), label)
                        )
        reached: Set[str] = set()
        frontier = sorted(entries)
        while frontier:
            fn_name = frontier.pop()
            if fn_name in reached:
                continue
            reached.add(fn_name)
            frontier.extend(gw_calls.get(fn_name, ()))
        for fn_name in sorted(reached):
            for sink_site, sink in gw_sinks.get(fn_name, []):
                add(
                    sink_site,
                    "UC401",
                    f"{sink}() is reachable from gateway client-input "
                    f"entry points (via {fn_name}) — untrusted client "
                    "bytes must only meet the closed client value codec "
                    "(runtime/schema.py), never a code-loading "
                    "deserializer",
                )

    return out, registry, status
