"""uigc-check: whole-repo cross-plane static analysis.

The static half of the correctness tooling (the online half is uigcsan,
``uigc_tpu/analysis/sanitizer.py``).  One shared parse of the analyzed
tree feeds four passes:

``lint``     the UL001-UL015 file-local rules uigc-lint established
             (:mod:`.lint_rules`; ``tools/uigc_lint.py`` is now a thin
             wrapper over this pass)
``surface``  the cross-plane surface registry: config keys, event
             names, metric names, NodeFabric frame kinds and schema
             ids harvested into one machine-readable document, with
             UC1xx rules over the seams between them (:mod:`.surface`)
``locks``    the interprocedural lock-order graph: per-class lock
             identities, ``with``-acquisitions connected through a
             call graph, cycle witnesses and blocking-call-under-lock
             (:mod:`.locks`)
``purity``   trace purity: functions reachable from ``jax.jit`` /
             Pallas entry points must not mutate Python state, call
             RNG/time, or read back off-device unannotated; plus jit
             recompile hazards (:mod:`.purity`)

Every pass consumes the same :class:`~.core.ParsedFile` list (one
``ast.parse`` per file, ever), reports through the same structured
:class:`~.core.Diagnostic`, honors the same ``# uigc-lint:
disable=RULE`` suppression comments, and shares the one allowlist
budget file.  ``tools/uigc_check.py`` is the CLI.
"""

from .core import (  # noqa: F401
    Diagnostic,
    ParsedFile,
    apply_allowlist,
    iter_py_files,
    load_allowlist,
    parse_paths,
)
from .cli import run_check, main  # noqa: F401
