"""uigc-check CLI: one parse, four passes, one verdict.

Usage (via the ``tools/uigc_check.py`` shim)::

    python tools/uigc_check.py uigc_tpu/ tools/            # advisory
    python tools/uigc_check.py --strict uigc_tpu/ tools/   # CI gate
    python tools/uigc_check.py --rules 'UL*' uigc_tpu/     # lint only
    python tools/uigc_check.py --json --registry-out registry.json ...
    python tools/uigc_check.py --write-config uigc_tpu/ tools/

Exit codes follow uigc-lint: 0 clean or advisory, 1 strict violations
beyond the allowlist budget, 2 usage error.  Passes that find nothing
to analyze (e.g. the surface pass run on a tree without ``config.py``)
report ``SKIP`` honestly instead of a vacuous ``ok``.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys
from collections import Counter
from typing import Any, Dict, List, Optional

from . import configdoc, lint_rules, locks, purity, surface
from .core import Diagnostic, apply_allowlist, load_allowlist, parse_paths

JSON_VERSION = 1

#: repo root relative to this module: uigc_tpu/analysis/check/cli.py
_DEFAULT_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

#: pass name -> the rule ids it can emit (UL000 is the parse-error rule)
PASS_RULES: Dict[str, List[str]] = {
    "lint": ["UL000"] + sorted(lint_rules.RULES),
    "surface": sorted(surface.RULES),
    "locks": sorted(locks.RULES),
    "purity": sorted(purity.RULES),
}

ALL_RULES: Dict[str, str] = {}
ALL_RULES.update(lint_rules.RULES)
ALL_RULES.update(surface.RULES)
ALL_RULES.update(locks.RULES)
ALL_RULES.update(purity.RULES)


def _wanted_rules(patterns: Optional[List[str]]) -> Optional[set]:
    """Expand glob patterns (``UL*``, ``UC2*``, ``UC104``) against the
    full rule universe.  None means everything."""
    if not patterns:
        return None
    universe = set(ALL_RULES) | {"UL000"}
    out = set()
    for pattern in patterns:
        pattern = pattern.strip().upper()
        if not pattern:
            continue
        out.update(r for r in universe if fnmatch.fnmatch(r, pattern))
    return out


def _pass_enabled(name: str, wanted: Optional[set]) -> bool:
    if wanted is None:
        return True
    return any(rule in wanted for rule in PASS_RULES[name])


def run_check(
    paths: List[str],
    rules: Optional[List[str]] = None,
    allowlist_path: Optional[str] = None,
    repo_root: Optional[str] = None,
    registry_out: Optional[str] = None,
    write_config: bool = False,
    lint_asserts: bool = True,
) -> Dict[str, Any]:
    """Run the selected passes; returns the structured result the CLI
    and the tests both consume."""
    root = repo_root or _DEFAULT_ROOT
    wanted = _wanted_rules(rules)
    files, parse_errors = parse_paths(paths)
    texts = surface.RepoTexts(root)

    diagnostics: List[Diagnostic] = []
    passes: Dict[str, Dict[str, Any]] = {}
    registry: Optional[Dict[str, Any]] = None

    # ---- lint pass -------------------------------------------------- #
    if _pass_enabled("lint", wanted):
        lint_diags = list(parse_errors) + lint_rules.run_lint(
            files, lint_asserts=lint_asserts
        )
        diagnostics.extend(lint_diags)
        passes["lint"] = {
            "status": "ok" if files else "skip",
            "findings": len(lint_diags),
        }

    # ---- surface pass ----------------------------------------------- #
    if _pass_enabled("surface", wanted):
        surf_diags, registry, plane_status = surface.run_surface(files, texts)
        diagnostics.extend(surf_diags)
        status = (
            "ok"
            if any(s == "ok" for s in plane_status.values())
            else "skip"
        )
        passes["surface"] = {
            "status": status,
            "planes": plane_status,
            "findings": len(surf_diags),
        }

    # ---- lock pass -------------------------------------------------- #
    if _pass_enabled("locks", wanted):
        lock_diags, lock_summary = locks.run_locks(files)
        diagnostics.extend(lock_diags)
        passes["locks"] = {
            "status": "ok" if lock_summary["locks"] else "skip",
            "findings": len(lock_diags),
            "locks": len(lock_summary["locks"]),
            "edges": len(lock_summary["edges"]),
        }
        if registry is not None:
            registry["locks"] = lock_summary

    # ---- purity pass ------------------------------------------------ #
    if _pass_enabled("purity", wanted):
        pure_diags, pure_summary = purity.run_purity(files)
        diagnostics.extend(pure_diags)
        passes["purity"] = {
            "status": "ok" if pure_summary["entries"] else "skip",
            "findings": len(pure_diags),
            "entries": len(pure_summary["entries"]),
            "reachable": pure_summary["reachable"],
        }
        if registry is not None:
            registry["purity"] = pure_summary

    # ---- write-backs ------------------------------------------------ #
    if write_config and registry is not None:
        config_path = os.path.join(root, "CONFIG.md")
        with open(config_path, "w", encoding="utf-8") as fh:
            fh.write(configdoc.render_config_md(registry))
        # The file is current now; the drift finding no longer applies.
        diagnostics = [d for d in diagnostics if d.rule != "UC106"]
    if registry_out and registry is not None:
        with open(registry_out, "w", encoding="utf-8") as fh:
            json.dump(registry, fh, indent=2, sort_keys=True)
            fh.write("\n")

    # ---- rule filter + allowlist ------------------------------------ #
    if wanted is not None:
        diagnostics = [d for d in diagnostics if d.rule in wanted]
    diagnostics.sort(key=lambda d: (d.path, d.line, d.rule, d.message))
    budget = load_allowlist(allowlist_path)
    grandfathered, fresh = apply_allowlist(diagnostics, budget)

    return {
        "files": len(files),
        "passes": passes,
        "diagnostics": diagnostics,
        "grandfathered": grandfathered,
        "fresh": fresh,
        "registry": registry,
    }


def _to_json(result: Dict[str, Any], strict: bool) -> Dict[str, Any]:
    counts = Counter(d.rule for d in result["fresh"])
    return {
        "version": JSON_VERSION,
        "strict": strict,
        "files": result["files"],
        "passes": result["passes"],
        "counts": dict(sorted(counts.items())),
        "fresh": [d.to_json() for d in result["fresh"]],
        "grandfathered": len(result["grandfathered"]),
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="uigc-check", description=__doc__.splitlines()[0]
    )
    parser.add_argument("paths", nargs="+", help="files or directories")
    parser.add_argument(
        "--strict",
        action="store_true",
        help="exit nonzero on findings beyond the allowlist budget",
    )
    parser.add_argument(
        "--rules",
        default="",
        help="comma-separated rule ids or globs (UL*, UC1*, UC104); "
        "default: all passes",
    )
    parser.add_argument(
        "--allowlist",
        default=os.path.join(_DEFAULT_ROOT, "tools", "uigc_lint_allow.txt"),
        help="path:RULE:count budget file (default: tools/uigc_lint_allow.txt)",
    )
    parser.add_argument(
        "--no-allowlist", action="store_true", help="ignore the allowlist"
    )
    parser.add_argument(
        "--json",
        action="store_true",
        dest="as_json",
        help="machine-readable result on stdout (bench_check-style)",
    )
    parser.add_argument(
        "--registry-out",
        default=None,
        help="write the surface registry document to this path",
    )
    parser.add_argument(
        "--write-config",
        action="store_true",
        help="regenerate CONFIG.md from the surface registry",
    )
    parser.add_argument(
        "--repo-root",
        default=None,
        help="repository root for GUIDE.md/CONFIG.md/tests cross-refs "
        "(default: inferred from the package location)",
    )
    args = parser.parse_args(argv)

    rules = [p for p in args.rules.split(",") if p.strip()] or None
    result = run_check(
        args.paths,
        rules=rules,
        allowlist_path=None if args.no_allowlist else args.allowlist,
        repo_root=args.repo_root,
        registry_out=args.registry_out,
        write_config=args.write_config,
    )

    if args.as_json:
        print(json.dumps(_to_json(result, args.strict), indent=2, sort_keys=True))
    else:
        for diag in result["fresh"]:
            print(diag.render())
        skipped = [
            name
            for name, info in result["passes"].items()
            if info["status"] == "skip"
        ]
        summary = ", ".join(
            f"{name}: {info['findings']} finding(s)"
            if info["status"] == "ok"
            else f"{name}: SKIP"
            for name, info in result["passes"].items()
        )
        print(
            f"uigc-check: {result['files']} file(s); {summary}",
            file=sys.stderr,
        )
        if skipped:
            print(
                "uigc-check: SKIP means the pass found nothing to "
                f"analyze in the given paths ({', '.join(skipped)})",
                file=sys.stderr,
            )
        if result["grandfathered"]:
            print(
                f"uigc-check: {len(result['grandfathered'])} grandfathered "
                "finding(s) suppressed by allowlist",
                file=sys.stderr,
            )
        if result["fresh"]:
            print(
                f"uigc-check: {len(result['fresh'])} new finding(s)",
                file=sys.stderr,
            )
    if result["fresh"] and args.strict:
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
