"""Interprocedural lock-order graph.

UL005/UL007 see one file at a time and identify locks by bare attribute
name, so a cross-module inversion — ``ShardRouter._table_lock`` held
across a call into ``NodeFabric`` code that takes ``_peer_lock``, while
another path nests them the other way — is invisible to them.  This
pass builds a repo-wide graph:

* **Lock identities are per-class attributes.**  ``self._lock`` inside
  class ``A`` is the node ``A._lock``, not "``_lock``"; a non-``self``
  acquisition (``st.lock``) resolves through the repo-wide table of
  lock attributes (``self.X = threading.Lock()`` assignments) when
  exactly one class owns that attribute name, and is dropped as
  ambiguous otherwise — precision over recall.
* **``with``-acquisitions connect through a call graph.**  Each
  function gets a may-acquire summary (the locks any call chain out of
  it can take, with a witness chain), propagated to fixpoint; holding
  ``L1`` across a call whose summary contains ``L2`` adds the edge
  ``L1 -> L2`` carrying the full call path.
* **Cycles report witness paths** (UC201): every strongly-connected
  component of the lock graph with more than one lock (or a self-loop
  via distinct sites) is a potential deadlock, reported once with the
  complete per-edge acquisition chains so the inversion can be read
  straight from the finding.
* **Blocking under any held lock** (UC203): socket sends/receives,
  ``Event.wait``/``join``/condition-``wait`` without a timeout, and
  ``time.sleep`` reached — directly or transitively — while a lock is
  held generalize UL007 beyond ``_PeerState``.  A ``cv.wait()`` whose
  receiver *is* the held lock is exempt (the condition releases it).

The pass is deliberately flow-insensitive within a function (a lock
acquired anywhere in a ``with`` body counts as held for every nested
statement) and resolves calls conservatively: ``self.m()`` to the same
class, bare ``f()`` to the same module, and ``obj.m()`` only when
exactly one analyzed class defines ``m``.
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from .core import Diagnostic, ParsedFile, call_name

RULES = {
    "UC201": "lock-order inversion cycle (potential deadlock)",
    "UC203": "blocking call reachable while a lock is held",
}

_LOCK_NAME = re.compile(r"(^|_)(lock|rlock|cv|cond)$", re.IGNORECASE)
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_SOCKET_BLOCKING = {
    "sendall",
    "recv",
    "recv_into",
    "recvfrom",
    "accept",
    "connect",
    "create_connection",
    "makefile",
}
_MAX_CHAIN = 6

# Lock identity: (owner, attr). owner is a class name, a module norm
# for module-level lock globals, or "<local>" markers are never built —
# unresolvable acquisitions are skipped.
LockId = Tuple[str, str]
# Witness chain: [(function qualname, line), ...] ending at the event.
Chain = Tuple[Tuple[str, int], ...]


class FuncInfo:
    __slots__ = (
        "qual",
        "pf",
        "node",
        "cls",
        "acquires",
        "calls",
        "blocking",
        "may_acquire",
        "may_block",
    )

    def __init__(
        self,
        qual: str,
        pf: ParsedFile,
        node: ast.AST,
        cls: Optional[str],
    ):
        self.qual = qual
        self.pf = pf
        self.node = node
        self.cls = cls
        # direct acquisitions: lock -> first with-statement line
        self.acquires: Dict[LockId, int] = {}
        # call sites: (callee qual, line, frozenset of held locks,
        #              receiver lock id if the call receiver is itself
        #              a resolvable lock — used for the cv.wait exemption)
        self.calls: List[Tuple[str, int, frozenset, Optional[LockId]]] = []
        # direct blocking sites: (line, description, receiver lock id,
        #                          frozenset of held locks)
        self.blocking: List[
            Tuple[int, str, Optional[LockId], frozenset]
        ] = []
        # fixpoint summaries
        self.may_acquire: Dict[LockId, Chain] = {}
        self.may_block: Optional[Tuple[str, Chain]] = None


class LockGraph:
    """The repo-wide analysis: build, propagate, report."""

    def __init__(self, files: List[ParsedFile]):
        self.files = [pf for pf in files if not pf.in_tests]
        # class name -> set of lock attribute names it assigns
        self.class_lock_attrs: Dict[str, Set[str]] = defaultdict(set)
        # lock attr name -> owning classes (for unique resolution)
        self.attr_owners: Dict[str, Set[str]] = defaultdict(set)
        # method name -> {qualnames} across all classes
        self.method_index: Dict[str, Set[str]] = defaultdict(set)
        # module norm -> {function name -> qual}
        self.module_funcs: Dict[str, Dict[str, str]] = defaultdict(dict)
        # class name -> {method name -> qual}
        self.class_methods: Dict[str, Dict[str, str]] = defaultdict(dict)
        self.funcs: Dict[str, FuncInfo] = {}
        # module norm -> module-level lock globals
        self.module_locks: Dict[str, Set[str]] = defaultdict(set)

    # ---- phase 1: indexes ------------------------------------------ #

    def build_indexes(self) -> None:
        for pf in self.files:
            for node in pf.tree.body:
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    if call_name(node.value)[1] in _LOCK_CTORS:
                        for target in node.targets:
                            if isinstance(target, ast.Name):
                                self.module_locks[pf.norm].add(target.id)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{pf.norm}:{node.name}"
                    self.module_funcs[pf.norm][node.name] = qual
                    self.funcs[qual] = FuncInfo(qual, pf, node, None)
                elif isinstance(node, ast.ClassDef):
                    for item in node.body:
                        if isinstance(
                            item, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            qual = f"{pf.norm}:{node.name}.{item.name}"
                            self.class_methods[node.name][item.name] = qual
                            self.method_index[item.name].add(qual)
                            self.funcs[qual] = FuncInfo(
                                qual, pf, item, node.name
                            )
                            for sub in ast.walk(item):
                                if (
                                    isinstance(sub, ast.Assign)
                                    and isinstance(sub.value, ast.Call)
                                    and call_name(sub.value)[1] in _LOCK_CTORS
                                ):
                                    for target in sub.targets:
                                        if (
                                            isinstance(target, ast.Attribute)
                                            and isinstance(
                                                target.value, ast.Name
                                            )
                                            and target.value.id == "self"
                                        ):
                                            self.class_lock_attrs[
                                                node.name
                                            ].add(target.attr)
                                            self.attr_owners[
                                                target.attr
                                            ].add(node.name)

    # ---- phase 2: per-function facts ------------------------------- #

    def _lock_id(
        self, info: FuncInfo, expr: ast.AST
    ) -> Optional[LockId]:
        if isinstance(expr, ast.Attribute):
            if not _LOCK_NAME.search(expr.attr):
                return None
            base = expr.value
            if (
                isinstance(base, ast.Name)
                and base.id == "self"
                and info.cls is not None
            ):
                return (info.cls, expr.attr)
            owners = self.attr_owners.get(expr.attr, set())
            if len(owners) == 1:
                return (next(iter(owners)), expr.attr)
            return None  # ambiguous or unknown receiver type
        if isinstance(expr, ast.Name):
            if not _LOCK_NAME.search(expr.id):
                return None
            if expr.id in self.module_locks.get(info.pf.norm, ()):
                return (info.pf.norm, expr.id)
            return None
        return None

    def _resolve_callee(
        self, info: FuncInfo, call: ast.Call
    ) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name):
            return self.module_funcs.get(info.pf.norm, {}).get(fn.id)
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if (
                isinstance(base, ast.Name)
                and base.id == "self"
                and info.cls is not None
            ):
                return self.class_methods.get(info.cls, {}).get(fn.attr)
            candidates = self.method_index.get(fn.attr, set())
            if len(candidates) == 1:
                return next(iter(candidates))
        return None

    def _blocking_desc(
        self, info: FuncInfo, call: ast.Call
    ) -> Optional[str]:
        """Describe a directly-blocking call, or None."""
        qual, name = call_name(call)
        has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
        if name in _SOCKET_BLOCKING and qual is not None:
            if re.search(r"sock|conn|link", qual, re.IGNORECASE):
                return f"{qual}.{name}()"
        if name == "wait" and not has_timeout and not call.args:
            if qual is not None:
                return f"{qual}.wait() without timeout"
        if name == "join" and not has_timeout and not call.args:
            if qual is not None and re.search(
                r"thread|proc|worker|queue", qual, re.IGNORECASE
            ):
                return f"{qual}.join() without timeout"
        if (qual, name) == ("time", "sleep"):
            return "time.sleep()"
        return None

    def collect_facts(self) -> None:
        for info in self.funcs.values():
            self._walk(info, info.node, frozenset())

    def _walk(
        self, info: FuncInfo, node: ast.AST, held: frozenset
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ) and child is not info.node:
                continue  # nested defs are separate functions
            if isinstance(child, ast.With):
                acquired: List[LockId] = []
                for item in child.items:
                    lock = self._lock_id(info, item.context_expr)
                    if lock is not None:
                        acquired.append(lock)
                        info.acquires.setdefault(lock, child.lineno)
                self._walk(info, child, held | frozenset(acquired))
                continue
            if isinstance(child, ast.Call):
                self._visit_call(info, child, held)
            self._walk(info, child, held)

    def _visit_call(
        self, info: FuncInfo, call: ast.Call, held: frozenset
    ) -> None:
        fn = call.func
        receiver_lock: Optional[LockId] = None
        if isinstance(fn, ast.Attribute):
            receiver_lock = self._lock_id(info, fn.value)
        desc = self._blocking_desc(info, call)
        if desc is not None:
            info.blocking.append((call.lineno, desc, receiver_lock, held))
        callee = self._resolve_callee(info, call)
        if callee is not None and callee != info.qual:
            info.calls.append((callee, call.lineno, held, receiver_lock))

    # ---- phase 3: fixpoint summaries -------------------------------- #

    def propagate(self) -> None:
        for info in self.funcs.values():
            for lock, line in info.acquires.items():
                info.may_acquire[lock] = ((info.qual, line),)
            for line, desc, recv, _held in info.blocking:
                if info.may_block is None:
                    info.may_block = (desc, ((info.qual, line),))
        changed = True
        iterations = 0
        while changed and iterations < 50:
            changed = False
            iterations += 1
            for info in self.funcs.values():
                for callee_qual, line, _held, _recv in info.calls:
                    callee = self.funcs.get(callee_qual)
                    if callee is None:
                        continue
                    for lock, chain in callee.may_acquire.items():
                        if lock not in info.may_acquire and len(chain) < _MAX_CHAIN:
                            info.may_acquire[lock] = (
                                (info.qual, line),
                            ) + chain
                            changed = True
                    if info.may_block is None and callee.may_block is not None:
                        desc, chain = callee.may_block
                        if len(chain) < _MAX_CHAIN:
                            info.may_block = (
                                desc,
                                ((info.qual, line),) + chain,
                            )
                            changed = True

    # ---- phase 4: edges and findings -------------------------------- #

    def edges(self) -> Dict[Tuple[LockId, LockId], Tuple[str, int, Chain]]:
        """lock-order edges: (L1, L2) -> (path, line, witness chain)."""
        out: Dict[Tuple[LockId, LockId], Tuple[str, int, Chain]] = {}
        for info in self.funcs.values():
            # direct nesting
            self._direct_edges(info, info.node, frozenset(), out)
            # through calls
            for callee_qual, line, held, _recv in info.calls:
                callee = self.funcs.get(callee_qual)
                if callee is None or not held:
                    continue
                for lock, chain in callee.may_acquire.items():
                    for outer in held:
                        if outer == lock:
                            continue
                        key = (outer, lock)
                        if key not in out:
                            out[key] = (
                                info.pf.path,
                                line,
                                ((info.qual, line),) + chain,
                            )
        return out

    def _direct_edges(
        self,
        info: FuncInfo,
        node: ast.AST,
        held: frozenset,
        out: Dict[Tuple[LockId, LockId], Tuple[str, int, Chain]],
    ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ) and child is not info.node:
                continue
            if isinstance(child, ast.With):
                acquired = []
                for item in child.items:
                    lock = self._lock_id(info, item.context_expr)
                    if lock is not None:
                        acquired.append(lock)
                for outer in held:
                    for inner in acquired:
                        if outer != inner:
                            key = (outer, inner)
                            if key not in out:
                                out[key] = (
                                    info.pf.path,
                                    child.lineno,
                                    ((info.qual, child.lineno),),
                                )
                self._direct_edges(
                    info, child, held | frozenset(acquired), out
                )
            else:
                self._direct_edges(info, child, held, out)


def _fmt_lock(lock: LockId) -> str:
    return f"{lock[0]}.{lock[1]}"


def _fmt_chain(chain: Chain) -> str:
    return " -> ".join(f"{q.split(':', 1)[-1]} (line {ln})" for q, ln in chain)


def _sccs(
    nodes: Set[LockId], adj: Dict[LockId, Set[LockId]]
) -> List[List[LockId]]:
    """Tarjan's strongly connected components, iteratively."""
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on_stack: Set[LockId] = set()
    stack: List[LockId] = []
    out: List[List[LockId]] = []
    counter = [0]

    for root in sorted(nodes):
        if root in index:
            continue
        work: List[Tuple[LockId, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recurse = False
            succs = sorted(adj.get(node, ()))
            for i in range(pi, len(succs)):
                succ = succs[i]
                if succ not in index:
                    work[-1] = (node, i + 1)
                    work.append((succ, 0))
                    recurse = True
                    break
                if succ in on_stack:
                    low[node] = min(low[node], index[succ])
            if recurse:
                continue
            if low[node] == index[node]:
                comp: List[LockId] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return out


def run_locks(files: List[ParsedFile]) -> Tuple[List[Diagnostic], Dict]:
    """Returns (diagnostics, summary) where summary feeds the registry
    (`locks` section: nodes, edges, cycles)."""
    graph = LockGraph(files)
    graph.build_indexes()
    graph.collect_facts()
    graph.propagate()
    edges = graph.edges()

    out: List[Diagnostic] = []
    pf_by_path = {pf.path: pf for pf in files}

    def add(path: str, line: int, rule: str, message: str) -> None:
        pf = pf_by_path.get(path)
        if pf is not None and pf.suppressed_on(line, rule):
            return
        out.append(Diagnostic(path, line, rule, message))

    # UC201: cycles.
    adj: Dict[LockId, Set[LockId]] = defaultdict(set)
    nodes: Set[LockId] = set()
    for (a, b) in edges:
        adj[a].add(b)
        nodes.add(a)
        nodes.add(b)
    reported_cycles = []
    for comp in _sccs(nodes, adj):
        comp_set = set(comp)
        witness_lines = []
        anchor: Optional[Tuple[str, int]] = None
        for (a, b), (path, line, chain) in sorted(edges.items()):
            if a in comp_set and b in comp_set:
                if anchor is None:
                    anchor = (path, line)
                witness_lines.append(
                    f"{_fmt_lock(a)} -> {_fmt_lock(b)} via {_fmt_chain(chain)}"
                )
        if anchor is None:
            continue
        locks_s = ", ".join(_fmt_lock(lock) for lock in comp)
        add(
            anchor[0],
            anchor[1],
            "UC201",
            f"lock-order inversion among {{{locks_s}}}: "
            + "; ".join(witness_lines),
        )
        reported_cycles.append(
            {"locks": [_fmt_lock(lock) for lock in comp], "edges": witness_lines}
        )

    # UC203: blocking while holding a lock — direct sites and call paths.
    seen_block: Set[Tuple[str, int]] = set()
    for info in graph.funcs.values():
        for line, desc, recv, held in info.blocking:
            effective = set(held)
            if recv is not None:
                effective.discard(recv)  # cv.wait releases its own lock
            if not effective:
                continue
            key = (info.pf.path, line)
            if key in seen_block:
                continue
            seen_block.add(key)
            locks_s = ", ".join(sorted(_fmt_lock(lock) for lock in effective))
            add(
                info.pf.path,
                line,
                "UC203",
                f"blocking call {desc} while holding {locks_s}",
            )
        for callee_qual, line, held, recv in info.calls:
            if not held:
                continue
            callee = graph.funcs.get(callee_qual)
            if callee is None or callee.may_block is None:
                continue
            effective = set(held)
            if recv is not None:
                effective.discard(recv)
            if not effective:
                continue
            desc, chain = callee.may_block
            key = (info.pf.path, line)
            if key in seen_block:
                continue
            seen_block.add(key)
            locks_s = ", ".join(sorted(_fmt_lock(lock) for lock in effective))
            add(
                info.pf.path,
                line,
                "UC203",
                f"call path reaches blocking {desc} while holding "
                f"{locks_s}: {_fmt_chain(((info.qual, line),) + chain)}",
            )

    summary = {
        "locks": sorted(
            {
                _fmt_lock(lock)
                for info in graph.funcs.values()
                for lock in info.acquires
            }
        ),
        "edges": [
            {
                "from": _fmt_lock(a),
                "to": _fmt_lock(b),
                "witness": _fmt_chain(chain),
            }
            for (a, b), (_path, _line, chain) in sorted(edges.items())
        ],
        "cycles": reported_cycles,
    }
    return out, summary
