"""CONFIG.md: the generated config-surface reference.

Rendered deterministically from the surface registry so it can never
drift silently: the surface pass re-renders on every run and raises
UC106 when the committed file differs.  Regenerate with::

    python tools/uigc_check.py --write-config uigc_tpu/ tools/
"""

from __future__ import annotations

import os
from typing import Any, Dict

_HEADER = """\
# Configuration reference

<!-- GENERATED FILE — do not edit by hand.
     Rendered by `python tools/uigc_check.py --write-config uigc_tpu/ tools/`
     from the surface registry; `uigc_check --strict` fails on drift (UC106). -->

Every key is read through `uigc_tpu.config.Config` (`get`, `get_int`,
`get_bool`, `get_float`, `get_string`) and defaults live in the
`DEFAULTS` dict in `uigc_tpu/config.py`. The *read by* column names the
first module that reads the key; see GUIDE.md for the narrative
documentation of each subsystem's knobs.

| key | default | read by | doc |
| --- | --- | --- | --- |
"""


def _fmt_default(value: Any) -> str:
    if isinstance(value, str):
        return f'`"{value}"`'
    return f"`{value!r}`"


def _reader_module(sites: list) -> str:
    if not sites:
        return "—"
    first = sites[0]
    path = first.rsplit(":", 1)[0]
    # uigc_tpu/runtime/node.py -> runtime/node (the sites may carry an
    # absolute prefix when the CLI was handed absolute paths; the
    # rendered document must not depend on the spelling).
    path = path.replace(os.sep, "/")
    marker = "uigc_tpu/"
    idx = path.rfind(marker)
    if idx >= 0:
        path = path[idx + len(marker):]
    if path.endswith(".py"):
        path = path[: -len(".py")]
    extra = len(sites) - 1
    return f"`{path}`" + (f" (+{extra})" if extra else "")


def _escape(text: str) -> str:
    return text.replace("|", "\\|")


def render_config_md(registry: Dict[str, Any]) -> str:
    rows = []
    for key in sorted(registry.get("config", {})):
        info = registry["config"][key]
        if not info.get("in_defaults"):
            continue  # typo-class keys are diagnostics, not documentation
        doc = info.get("doc") or ""
        rows.append(
            "| `{key}` | {default} | {reader} | {doc} |".format(
                key=key,
                default=_fmt_default(info.get("default")),
                reader=_reader_module(info.get("readers", [])),
                doc=_escape(doc),
            )
        )
    return _HEADER + "\n".join(rows) + "\n"
