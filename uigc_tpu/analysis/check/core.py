"""Shared single-parse framework for the uigc-check passes.

Every analyzer pass (lint rules, surface registry, lock graph, trace
purity) consumes the same :class:`ParsedFile` objects — the tree is
``ast.parse``'d exactly once per file per run, and the per-file comment
planes (suppressions, ``# readback:`` / ``# unbounded:`` annotations)
are extracted once alongside it.

Also home to the structured :class:`Diagnostic` and the allowlist
budget machinery, whose semantics are bit-compatible with the original
``tools/uigc_lint.py``: ``path:RULE:count`` budget lines, suffix-path
matching, ``--strict`` failing only beyond the budget.
"""

from __future__ import annotations

import ast
import os
import re
import sys
import tokenize
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Set, Tuple

_SUPPRESS = re.compile(r"#\s*uigc-lint:\s*disable=([A-Za-z0-9,\s]+)")


class Diagnostic:
    """One structured finding: ``path:line: RULE message``."""

    __slots__ = ("path", "line", "rule", "message")

    def __init__(self, path: str, line: int, rule: str, message: str):
        self.path = path
        self.line = line
        self.rule = rule
        self.message = message

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"

    def to_json(self) -> Dict[str, object]:
        return {
            "path": self.path.replace(os.sep, "/"),
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Diagnostic({self.render()!r})"

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Diagnostic):
            return NotImplemented
        return (
            self.path == other.path
            and self.line == other.line
            and self.rule == other.rule
            and self.message == other.message
        )

    def __hash__(self) -> int:
        return hash((self.path, self.line, self.rule, self.message))


def _suppressed_lines(source: str) -> Dict[int, Set[str]]:
    """Map line -> set of rule codes disabled on that line."""
    out: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                match = _SUPPRESS.search(tok.string)
                if match:
                    codes = {
                        c.strip().upper()
                        for c in match.group(1).split(",")
                        if c.strip()
                    }
                    out[tok.start[0]] = codes
    except (tokenize.TokenError, IndentationError):
        pass
    return out


class ParsedFile:
    """One analyzed file: source, AST and the comment planes, parsed once."""

    __slots__ = (
        "path",
        "norm",
        "parts",
        "source",
        "lines",
        "tree",
        "suppressed",
        "readback_lines",
        "unbounded_lines",
        "in_tests",
    )

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.norm = path.replace(os.sep, "/")
        self.parts = path.split(os.sep)
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.suppressed = _suppressed_lines(source)
        self.readback_lines = {
            i + 1 for i, line in enumerate(self.lines) if "# readback:" in line
        }
        self.unbounded_lines = {
            i + 1 for i, line in enumerate(self.lines) if "# unbounded:" in line
        }
        self.in_tests = "tests" in self.parts

    def suppressed_on(self, line: int, rule: str) -> bool:
        codes = self.suppressed.get(line, ())
        return rule in codes or "ALL" in codes

    def endswith(self, *suffixes: str) -> bool:
        return self.norm.endswith(suffixes)


class Reporter:
    """Diagnostic sink that applies per-line suppression comments."""

    def __init__(self) -> None:
        self.diagnostics: List[Diagnostic] = []

    def add(self, pf: ParsedFile, line: int, rule: str, message: str) -> None:
        if pf.suppressed_on(line, rule):
            return
        self.diagnostics.append(Diagnostic(pf.path, line, rule, message))

    def add_raw(self, path: str, line: int, rule: str, message: str) -> None:
        self.diagnostics.append(Diagnostic(path, line, rule, message))


def iter_py_files(paths: Iterable[str]) -> List[str]:
    out = []
    for path in paths:
        if os.path.isfile(path) and path.endswith(".py"):
            out.append(path)
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = [
                    d for d in dirs if not d.startswith((".", "__pycache__"))
                ]
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
    return sorted(out)


def parse_paths(
    paths: Iterable[str],
) -> Tuple[List[ParsedFile], List[Diagnostic]]:
    """Parse every .py file under ``paths`` once.  Unparseable files
    become UL000 diagnostics, exactly as uigc-lint reported them."""
    files: List[ParsedFile] = []
    errors: List[Diagnostic] = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError) as exc:
            errors.append(Diagnostic(path, 1, "UL000", f"unparseable: {exc}"))
            continue
        files.append(ParsedFile(path, source, tree))
    return files, errors


# ------------------------------------------------------------------- #
# Allowlist budgets (bit-compatible with tools/uigc_lint.py)
# ------------------------------------------------------------------- #


def load_allowlist(path: Optional[str]) -> Dict[Tuple[str, str], int]:
    budget: Dict[Tuple[str, str], int] = {}
    if path is None or not os.path.exists(path):
        return budget
    with open(path) as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            try:
                file_part, rule, count = line.rsplit(":", 2)
                budget[(file_part, rule.upper())] = int(count)
            except ValueError:
                print(
                    f"uigc-lint: bad allowlist line: {line!r}", file=sys.stderr
                )
    return budget


def apply_allowlist(
    violations: List[Diagnostic], budget: Dict[Tuple[str, str], int]
) -> Tuple[List[Diagnostic], List[Diagnostic]]:
    """Split diagnostics into (grandfathered, new) against per-file
    per-rule budgets.  Budget paths match exactly or as a path suffix,
    so relative allowlist entries cover absolute invocations."""

    def budget_key(path: str, rule: str) -> Optional[Tuple[str, str]]:
        path = path.replace(os.sep, "/")
        if (path, rule) in budget:
            return (path, rule)
        for (allowed, allowed_rule) in budget:
            if allowed_rule == rule and path.endswith("/" + allowed):
                return (allowed, allowed_rule)
        return None

    counts: Dict[Tuple[str, str], int] = defaultdict(int)
    grandfathered: List[Diagnostic] = []
    fresh: List[Diagnostic] = []
    for v in violations:
        key = budget_key(v.path, v.rule)
        if key is None:
            fresh.append(v)
            continue
        counts[key] += 1
        if counts[key] <= budget[key]:
            grandfathered.append(v)
        else:
            fresh.append(v)
    return grandfathered, fresh


# ------------------------------------------------------------------- #
# Small AST helpers shared by the passes
# ------------------------------------------------------------------- #


def call_name(node: ast.Call) -> Tuple[Optional[str], str]:
    """(qualifier, name) of a call: foo.bar(...) -> ("foo", "bar")."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        base = fn.value
        if isinstance(base, ast.Name):
            return base.id, fn.attr
        return None, fn.attr
    if isinstance(fn, ast.Name):
        return None, fn.id
    return None, ""


def dotted_name(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as ``a.b.c``; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
