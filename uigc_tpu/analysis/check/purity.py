"""Trace-purity pass.

UL011 flags host-transfer idioms by *module directory* — every
``.item()`` under ``ops/`` looks the same to it, whether or not the
enclosing function is ever traced.  This pass knows which functions
actually run under a tracer: it discovers ``jax.jit`` / Pallas /
``shard_map`` entry points in ``ops/``, ``parallel/`` and
``engines/crgc/``, closes them over the call graph, and only then
applies the purity rules — so a host-side helper that happens to live
in ``ops/`` is no longer collateral, and a traced function calling
into an impure helper two modules away *is* caught.

UC301  a traced-reachable function mutates Python state visible
       outside the trace (``global``/``nonlocal`` rebinding, or
       mutation of a module-level container) — the mutation runs once
       at trace time, then never again
UC302  a traced-reachable function calls host RNG or wall-clock time
       (``random.*``, ``np.random.*``, ``time.*``, ``datetime.*``) —
       the value freezes into the compiled program; ``jax.random`` is
       the keyed, traceable alternative and is exempt
UC303  a traced-reachable function reads back off-device
       (``jax.device_get``, zero-arg ``.item()``, dtype-less
       ``np.asarray``) without a ``# readback: <why>`` annotation —
       the reachability-aware refinement of UL011
UC304  recompile hazard at a jit call site: jitting a lambda or
       locally-defined function inside another function (a fresh
       callable object per call — the cache never hits), or passing
       an unhashable literal (list/dict/set) in a static-argument
       position of a known jitted callable

Entry-point discovery covers decorator forms (``@jax.jit``,
``@partial(jax.jit, ...)``), wrapper-call forms (``f = jax.jit(g)``,
``pl.pallas_call(kernel, ...)``), and ``shard_map``/``pmap``.
"""

from __future__ import annotations

import ast
import re
from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from .core import Diagnostic, ParsedFile, call_name, dotted_name

RULES = {
    "UC301": "traced function mutates Python state",
    "UC302": "traced function calls host RNG or wall-clock time",
    "UC303": "traced function reads back off-device without '# readback:'",
    "UC304": "jit recompile hazard (per-call callable or unhashable static arg)",
}

_TRACERS = {"jit", "pallas_call", "shard_map", "pmap", "checkpoint"}
_NUMPY_QUALS = {"np", "numpy", "jnp"}
_DEVICE_DIRS = ("/ops/", "/parallel/", "/engines/crgc/")
_RNG_TIME = re.compile(
    r"^(random|numpy\.random|np\.random|time|datetime(\.datetime)?)\."
)
_CONTAINER_MUTATORS = {
    "append",
    "add",
    "update",
    "setdefault",
    "pop",
    "extend",
    "insert",
    "clear",
    "remove",
}
_MAX_DEPTH = 8


def _is_device_module(pf: ParsedFile) -> bool:
    return any(d in pf.norm for d in _DEVICE_DIRS)


def _walk_shallow(fn: ast.AST):
    """Walk a function body without descending into nested defs — those
    are separate functions with their own reachability entries."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(node))


def _tracer_call(node: ast.Call) -> Optional[str]:
    """'jit' / 'pallas_call' / ... when this Call invokes a tracer."""
    dn = dotted_name(node.func)
    if dn is None:
        return None
    last = dn.split(".")[-1]
    if last not in _TRACERS:
        return None
    # jax.jit / jit / pl.pallas_call / jax.experimental.shard_map.shard_map
    return last


class FuncEntry:
    __slots__ = ("qual", "pf", "node", "cls")

    def __init__(
        self, qual: str, pf: ParsedFile, node: ast.AST, cls: Optional[str]
    ):
        self.qual = qual
        self.pf = pf
        self.node = node
        self.cls = cls


class PurityPass:
    def __init__(self, files: List[ParsedFile]):
        self.files = [pf for pf in files if not pf.in_tests]
        self.funcs: Dict[str, FuncEntry] = {}
        self.module_funcs: Dict[str, Dict[str, str]] = defaultdict(dict)
        self.class_methods: Dict[str, Dict[str, str]] = defaultdict(dict)
        self.method_index: Dict[str, Set[str]] = defaultdict(set)
        self.module_globals: Dict[str, Set[str]] = defaultdict(set)
        # module-level jitted names with literal static positions:
        # (module norm, name) -> set of static argument indices
        self.static_positions: Dict[Tuple[str, str], Set[int]] = {}
        self.entries: List[Tuple[str, str]] = []  # (qual, how)
        self.diagnostics: List[Diagnostic] = []

    # ---- indexes ---------------------------------------------------- #

    def build(self) -> None:
        for pf in self.files:
            for node in pf.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{pf.norm}:{node.name}"
                    self.module_funcs[pf.norm][node.name] = qual
                    self.funcs[qual] = FuncEntry(qual, pf, node, None)
                    # nested defs
                    self._index_nested(pf, node, qual, None)
                elif isinstance(node, ast.ClassDef):
                    for item in node.body:
                        if isinstance(
                            item, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            qual = f"{pf.norm}:{node.name}.{item.name}"
                            self.class_methods[node.name][item.name] = qual
                            self.method_index[item.name].add(qual)
                            self.funcs[qual] = FuncEntry(
                                qual, pf, item, node.name
                            )
                            self._index_nested(pf, item, qual, node.name)
                elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for target in targets:
                        if isinstance(target, ast.Name):
                            self.module_globals[pf.norm].add(target.id)

    def _index_nested(
        self,
        pf: ParsedFile,
        fn: ast.AST,
        parent_qual: str,
        cls: Optional[str],
    ) -> None:
        for sub in ast.walk(fn):
            if (
                isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub is not fn
            ):
                qual = f"{parent_qual}.<{sub.name}>"
                self.funcs.setdefault(qual, FuncEntry(qual, pf, sub, cls))

    # ---- entry-point discovery -------------------------------------- #

    def find_entries(self) -> None:
        for pf in self.files:
            if not _is_device_module(pf):
                continue
            # Decorator forms on module/class functions.
            for qual, entry in list(self.funcs.items()):
                if entry.pf is not pf:
                    continue
                node = entry.node
                for dec in getattr(node, "decorator_list", []):
                    how = self._decorator_tracer(dec)
                    if how is not None:
                        self.entries.append((qual, how))
            # Wrapper-call forms anywhere in the module.
            for node in ast.walk(pf.tree):
                if not isinstance(node, ast.Call):
                    continue
                tracer = _tracer_call(node)
                if tracer is None:
                    continue
                for target_qual in self._traced_operands(pf, node):
                    self.entries.append((target_qual, tracer))
                self._note_static_positions(pf, node)

    def _decorator_tracer(self, dec: ast.AST) -> Optional[str]:
        dn = dotted_name(dec)
        if dn is not None and dn.split(".")[-1] in _TRACERS:
            return dn.split(".")[-1]
        if isinstance(dec, ast.Call):
            tracer = _tracer_call(dec)
            if tracer is not None:
                return tracer
            # @partial(jax.jit, ...)
            if call_name(dec)[1] == "partial" and dec.args:
                inner = dotted_name(dec.args[0])
                if inner is not None and inner.split(".")[-1] in _TRACERS:
                    return inner.split(".")[-1]
        return None

    def _traced_operands(
        self, pf: ParsedFile, call: ast.Call
    ) -> List[str]:
        """Resolve `jax.jit(f)` / `pallas_call(kernel, ...)` operands to
        known function qualnames in the same module."""
        out: List[str] = []
        operands = list(call.args[:1])
        for kw in call.keywords:
            if kw.arg in ("fun", "f", "kernel"):
                operands.append(kw.value)
        for op in operands:
            if isinstance(op, ast.Name):
                qual = self.module_funcs.get(pf.norm, {}).get(op.id)
                if qual is not None:
                    out.append(qual)
                else:
                    # nested def in the enclosing function
                    for q, entry in self.funcs.items():
                        if entry.pf is pf and q.endswith(f".<{op.id}>"):
                            out.append(q)
        return out

    def _note_static_positions(self, pf: ParsedFile, call: ast.Call) -> None:
        """Record `f = jax.jit(g, static_argnums=(1,))` so later calls
        to f can be checked for unhashable literals at static slots."""
        positions: Set[int] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                try:
                    value = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    continue
                if isinstance(value, int):
                    positions.add(value)
                elif isinstance(value, (tuple, list)):
                    positions.update(v for v in value if isinstance(v, int))
        if not positions:
            return
        # Find the Assign this call is the value of (module level only).
        for node in pf.tree.body:
            if isinstance(node, ast.Assign) and node.value is call:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        self.static_positions[(pf.norm, target.id)] = positions

    # ---- reachability ----------------------------------------------- #

    def _resolve_callee(
        self, entry: FuncEntry, call: ast.Call
    ) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Name):
            qual = self.module_funcs.get(entry.pf.norm, {}).get(fn.id)
            if qual is not None:
                return qual
            # nested def captured by name inside the same parent
            nested = f"{entry.qual}.<{fn.id}>"
            if nested in self.funcs:
                return nested
            return None
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if (
                isinstance(base, ast.Name)
                and base.id == "self"
                and entry.cls is not None
            ):
                return self.class_methods.get(entry.cls, {}).get(fn.attr)
            candidates = self.method_index.get(fn.attr, set())
            if len(candidates) == 1:
                return next(iter(candidates))
        return None

    def reachable(self) -> Dict[str, Tuple[str, ...]]:
        """qual -> witness chain of quals from an entry point."""
        seen: Dict[str, Tuple[str, ...]] = {}
        work: List[Tuple[str, Tuple[str, ...]]] = []
        for qual, _how in self.entries:
            if qual not in seen:
                seen[qual] = (qual,)
                work.append((qual, (qual,)))
        while work:
            qual, chain = work.pop()
            if len(chain) >= _MAX_DEPTH:
                continue
            entry = self.funcs.get(qual)
            if entry is None:
                continue
            for node in ast.walk(entry.node):
                if isinstance(node, ast.Call):
                    callee = self._resolve_callee(entry, node)
                    if callee is not None and callee not in seen:
                        seen[callee] = chain + (callee,)
                        work.append((callee, chain + (callee,)))
        return seen

    # ---- the rules --------------------------------------------------- #

    def check(self) -> None:
        reach = self.reachable()

        def add(
            pf: ParsedFile, line: int, rule: str, message: str
        ) -> None:
            if pf.suppressed_on(line, rule):
                return
            self.diagnostics.append(Diagnostic(pf.path, line, rule, message))

        def via(chain: Tuple[str, ...]) -> str:
            if len(chain) <= 1:
                return ""
            names = " -> ".join(q.split(":", 1)[-1] for q in chain)
            return f" (traced via {names})"

        for qual, chain in reach.items():
            entry = self.funcs.get(qual)
            if entry is None:
                continue
            pf = entry.pf
            fn = entry.node
            declared: Set[str] = set()
            for node in _walk_shallow(fn):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    declared.update(node.names)
            for node in _walk_shallow(fn):
                # UC301: state mutation
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for target in targets:
                        if (
                            isinstance(target, ast.Name)
                            and target.id in declared
                        ):
                            add(
                                pf,
                                node.lineno,
                                "UC301",
                                f"traced function {fn.name!r} rebinds "
                                f"{target.id!r} via global/nonlocal — the "
                                "mutation happens once at trace time, not "
                                f"per call{via(chain)}",
                            )
                        elif isinstance(target, ast.Subscript) and isinstance(
                            target.value, ast.Name
                        ):
                            base = target.value.id
                            if base in self.module_globals.get(pf.norm, ()):
                                add(
                                    pf,
                                    node.lineno,
                                    "UC301",
                                    f"traced function {fn.name!r} mutates "
                                    f"module-level container {base!r} — "
                                    "trace-time side effect"
                                    f"{via(chain)}",
                                )
                elif isinstance(node, ast.Call):
                    dn = dotted_name(node.func) or ""
                    qualifier, name = call_name(node)
                    # UC301: module container mutation via method
                    if (
                        name in _CONTAINER_MUTATORS
                        and qualifier is not None
                        and qualifier
                        in self.module_globals.get(pf.norm, ())
                    ):
                        add(
                            pf,
                            node.lineno,
                            "UC301",
                            f"traced function {fn.name!r} mutates "
                            f"module-level container {qualifier!r} via "
                            f".{name}() — trace-time side effect"
                            f"{via(chain)}",
                        )
                    # UC302: RNG / time
                    if _RNG_TIME.match(dn) and not dn.startswith(
                        "jax.random."
                    ):
                        add(
                            pf,
                            node.lineno,
                            "UC302",
                            f"traced function {fn.name!r} calls {dn}() — "
                            "the value freezes into the compiled program; "
                            "thread a jax.random key through instead"
                            f"{via(chain)}",
                        )
                    # UC303: readback without annotation
                    hit = self._readback(node)
                    if hit is not None and node.lineno not in pf.readback_lines:
                        add(
                            pf,
                            node.lineno,
                            "UC303",
                            f"traced function {fn.name!r} reads back "
                            f"off-device via {hit} without a "
                            f"'# readback: <why>' annotation{via(chain)}",
                        )

        # UC304: recompile hazards, repo-wide over device modules.
        for pf in self.files:
            if not _is_device_module(pf):
                continue
            self._check_recompile(pf, add)

    @staticmethod
    def _readback(call: ast.Call) -> Optional[str]:
        qualifier, name = call_name(call)
        if qualifier == "jax" and name == "device_get":
            return "jax.device_get()"
        if (
            name == "item"
            and isinstance(call.func, ast.Attribute)
            and not call.args
            and not call.keywords
        ):
            return f"{qualifier or '<expr>'}.item()"
        if (
            name == "asarray"
            and qualifier in _NUMPY_QUALS
            and qualifier != "jnp"
            and not any(kw.arg == "dtype" for kw in call.keywords)
        ):
            return f"{qualifier}.asarray() without dtype="
        return None

    def _check_recompile(self, pf: ParsedFile, add) -> None:
        # (a) a fresh traced callable built *and consumed* per call:
        #     `jit(f)(x)` immediately invoked, or a jit/pallas_call
        #     wrapping constructed inside a loop body.  The build-once
        #     factory idiom — `return jax.jit(f)` / `self._fn = jit(f)`
        #     — is the repo's standard caching pattern and is exempt:
        #     the wrapper object survives, so the jit cache hits.
        parents: Dict[int, ast.AST] = {}
        loop_depth: Dict[int, int] = {}

        def map_tree(node: ast.AST, depth: int) -> None:
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
                child_depth = depth
                if isinstance(child, (ast.For, ast.While)):
                    child_depth += 1
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    child_depth = 0  # a nested def resets the loop context
                loop_depth[id(child)] = child_depth
                map_tree(child, child_depth)

        map_tree(pf.tree, 0)
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            tracer = _tracer_call(node)
            if tracer is None:
                continue
            parent = parents.get(id(node))
            invoked = isinstance(parent, ast.Call) and parent.func is node
            in_loop = loop_depth.get(id(node), 0) > 0
            if not invoked and not in_loop:
                continue
            operand = node.args[0] if node.args else None
            label = (
                "lambda ..."
                if isinstance(operand, ast.Lambda)
                else operand.id
                if isinstance(operand, ast.Name)
                else "..."
            )
            where = (
                "is invoked immediately"
                if invoked
                else "is rebuilt inside a loop"
            )
            add(
                pf,
                node.lineno,
                "UC304",
                f"recompile hazard: {tracer}({label}) {where} — a fresh "
                "traced callable per call means the jit cache never "
                "hits; build once (module scope or cached attribute) "
                "and reuse",
            )
        # (b) unhashable literal at a known static position.
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Name):
                continue
            positions = self.static_positions.get((pf.norm, node.func.id))
            if not positions:
                continue
            for idx in positions:
                if idx < len(node.args) and isinstance(
                    node.args[idx], (ast.List, ast.Dict, ast.Set)
                ):
                    add(
                        pf,
                        node.lineno,
                        "UC304",
                        f"recompile hazard: call to jitted "
                        f"{node.func.id!r} passes an unhashable "
                        f"{type(node.args[idx]).__name__.lower()} literal "
                        f"at static position {idx} — jit static args must "
                        "hash; pass a tuple or hoist the constant",
                    )


def run_purity(files: List[ParsedFile]) -> Tuple[List[Diagnostic], Dict]:
    p = PurityPass(files)
    p.build()
    p.find_entries()
    p.check()
    summary = {
        "entries": sorted({f"{q} [{how}]" for q, how in p.entries}),
        "reachable": len(p.reachable()),
    }
    return p.diagnostics, summary
