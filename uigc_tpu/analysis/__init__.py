"""Correctness tooling for the actor runtime and its GC engines.

Three parts (see GUIDE.md "Correctness tooling"):

- :mod:`uigc_tpu.analysis.sanitizer` — **uigcsan**, an online sanitizer
  that wraps a system's engine and collector with an independent shadow
  oracle and cross-checks every collection cycle (quiescence verdicts,
  send/recv balances, created/released pairing, undo-log fold
  discipline, monotone sequence invariants).
- :mod:`uigc_tpu.analysis.race` — a vector-clock race detector over the
  ``sched.*`` scheduling event stream that checks the documented
  invariants of :mod:`uigc_tpu.runtime.cell` (single-threaded cell
  processing, system-before-app ordering, children-stop-before-PostStop).
- ``tools/uigc_lint.py`` — the AST lint suite (not importable from the
  package; run it on source trees).
"""

from .race import RaceDetector, VectorClock
from .sanitizer import Sanitizer, SanitizerViolation

__all__ = ["Sanitizer", "SanitizerViolation", "RaceDetector", "VectorClock"]
