"""uigcsan: an online GC-soundness sanitizer.

The reference debugged its collector by folding the same entry stream
into two graphs and asserting equality (reference:
ShadowGraph.java:176-199 ``assertEquals``).  uigcsan makes that
discipline a wrappable runtime facility: :meth:`Sanitizer.attach` hooks
a live :class:`~uigc_tpu.runtime.system.ActorSystem` so that

- every fact the collector folds (object entries, packed rows, peer
  delta graphs, undo logs) is *also* folded into an independent
  pointer-based oracle (:class:`~uigc_tpu.engines.crgc.shadow.ShadowGraph`);
- every collection cycle cross-checks the engine's quiescence verdict
  against the oracle's (``verdict.mismatch``);
- the engine-hook taps (:class:`~uigc_tpu.engines.engine.EngineTap`)
  observe sends/receives/creates/releases on the mutator side, giving a
  ground truth the folded facts must reconcile with;
- fold discipline is checked online: undo logs fold exactly once and
  only after the finalization quorum, delta gossip sequence numbers are
  monotone per peer, packed flush stamps are unique per drained batch.

Violations are **structured diagnostics**, never bare asserts: each is
a :class:`SanitizerViolation` carrying the mismatching entries in its
payload, recorded on the sanitizer (and emitted as an
``analysis.violation`` event) — and additionally *raised* at the point
of detection when ``uigc.analysis.sanitizer-raise`` is on.  Raise mode
is fail-fast debugging, not clean propagation: a raise from an engine
hook or collector fold lands in the cell batch's default supervision,
which prints the traceback and stops the affected actor (the
Bookkeeper, for collector-side checks — halting GC loudly).  The
record-first ordering means ``system.sanitizer.violations`` keeps the
evidence either way.

Violation catalog (``rule`` values):

==========================  ==============================================
``verdict.mismatch``        engine and oracle disagree on a cycle's
                            garbage count
``release.double``          a refob was released twice without an
                            intervening flush
``terminate.premature``     the engine stopped an actor the oracle still
                            proves reachable
``undo.premature_fold``     an undo log folded before its finalization
                            quorum was satisfied
``undo.double_fold``        an undo log folded twice for the same node
``delta.seq_regression``    a peer's delta gossip arrived with a
                            non-increasing sequence number
``packed.seq_duplicate``    two packed rows in one drained batch carry
                            the same flush stamp
``balance.nonzero_recv``    a receive balance failed to return to zero at
                            quiescence (dropped recv fact, duplicate
                            frame tally, lost send claim)
``edges.negative``          a reference edge is persistently negative at
                            quiescence (double release across flushes)
``balance.recv_without_send``  an actor received more local messages than
                            were ever sent to it (duplicate delivery)
==========================  ==============================================

Engines other than CRGC (MAC, DRL, manual) get the engine-hook taps
only — the oracle mirror requires CRGC's entry stream.
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Set

from ..engines.engine import EngineTap
from ..utils import events
from ..utils.validation import InvariantViolation

if TYPE_CHECKING:  # pragma: no cover
    from ..runtime.system import ActorSystem


class SanitizerViolation(InvariantViolation):
    """A GC-soundness invariant the sanitizer watches did not hold."""


def _path(cell: Any) -> str:
    return getattr(cell, "path", repr(cell))


class _Tap(EngineTap):
    """Mutator-side ground truth: every send/recv/create/release as the
    engine performs it, before any recording machinery can lose it."""

    def __init__(self, san: "Sanitizer"):
        self.san = san

    def on_send(self, target: Any, remote: bool = False) -> None:
        san = self.san
        with san._lock:
            san.sends[target] = san.sends.get(target, 0) + 1
            if remote:
                san.tainted.add(target)

    def on_recv(self, cell: Any, crossed: bool = False) -> None:
        san = self.san
        with san._lock:
            recvs = san.recvs.get(cell, 0) + 1
            san.recvs[cell] = recvs
            if crossed:
                # Crossed a node boundary: the matching send was counted
                # by the peer's sanitizer; local send/recv comparison is
                # meaningless for this actor from here on.
                san.tainted.add(cell)
                return
            if cell in san.tainted:
                return
            sends = san.sends.get(cell, 0)
            if recvs > sends:
                san.record(
                    "balance.recv_without_send",
                    "actor received more local messages than were sent to it",
                    actor=_path(cell),
                    recvs=recvs,
                    sends=sends,
                )

    def on_create(self, owner: Any, target: Any) -> None:
        san = self.san
        with san._lock:
            san.creates[target] = san.creates.get(target, 0) + 1

    def on_release(self, ref: Any, already_released: bool = False) -> None:
        san = self.san
        if already_released:
            san.record(
                "release.double",
                "refob released twice without an intervening flush",
                refob=repr(ref),
                target=_path(getattr(ref, "target", None)),
            )
            return
        target = getattr(ref, "target", None)
        with san._lock:
            san.releases[target] = san.releases.get(target, 0) + 1

    def on_migrate_out(self, cell: Any, key: str) -> None:
        # A live migration moves the entity's remaining balance to
        # another node's books: local send/recv comparison for this
        # cell is meaningless from here on (same verdict as a message
        # that crossed a node boundary).
        san = self.san
        with san._lock:
            san.tainted.add(cell)

    def on_migrate_in(self, cell: Any, key: str) -> None:
        # The reconstructed incarnation's history (creates/sends under
        # the old uid) lives on the source node; never compare local
        # ground truth against it.
        san = self.san
        with san._lock:
            san.tainted.add(cell)

    def on_stop_decision(self, cell: Any, msg: Any) -> None:
        san = self.san
        if san.oracle is None:
            return
        with san._lock:
            shadow = san.oracle.shadow_map.get(cell)
            if shadow is None or not shadow.interned:
                # Unknown to the oracle, or known only through other
                # actors' unresolved claims — not provably live.
                return
            live = san._oracle_reachable()
        if shadow in live:
            san.record(
                "terminate.premature",
                "engine stopped an actor the oracle still proves reachable",
                actor=_path(cell),
                trigger=repr(msg),
                shadow=repr(shadow),
            )


class _MirrorGraph:
    """Wraps the collector's shadow graph: forwards every call to the
    real backend, folds the same facts into the sanitizer's oracle, and
    cross-checks each trace's verdict.  Unwrapped attributes (pipelined
    wake control, diagnostics, packed-plane wiring) pass straight
    through."""

    def __init__(self, real: Any, san: "Sanitizer"):
        # Instance dict bypass: __setattr__ below guards forwarding.
        object.__setattr__(self, "_real", real)
        object.__setattr__(self, "_san", san)

    def __getattr__(self, name: str) -> Any:
        return getattr(object.__getattribute__(self, "_real"), name)

    def __setattr__(self, name: str, value: Any) -> None:
        setattr(object.__getattribute__(self, "_real"), name, value)

    # -- folds ------------------------------------------------------ #

    def merge_entry(self, entry: Any) -> None:
        self._san._fold_entry(entry)
        self._real.merge_entry(entry)

    def merge_entries(self, batch: Any) -> None:
        for entry in batch:
            self._san._fold_entry(entry)
        real_batch = getattr(self._real, "merge_entries", None)
        if real_batch is not None:
            real_batch(batch)
        else:
            for entry in batch:
                self._real.merge_entry(entry)

    def merge_packed(self, rows: Any) -> None:
        self._san._fold_packed(rows)
        self._real.merge_packed(rows)

    def merge_delta(self, delta: Any) -> None:
        self._san._fold_delta(delta)
        self._real.merge_delta(delta)

    def merge_undo_log(self, log: Any) -> None:
        self._san._fold_undo(log)
        self._real.merge_undo_log(log)

    # -- distributed-mode folds -------------------------------------- #

    def reset_partition(self, partitions: Any) -> int:
        # The absorb path (engines/crgc/distributed.py): the gained
        # slices are cleared and re-folded from retained journals.  The
        # oracle must reset the SAME slice, or the journal re-fold
        # (which arrives through merge_delta above) double-counts every
        # balance and edge for the gained partitions.
        real = object.__getattribute__(self, "_real")
        self._san._reset_partition(partitions, real.partition_map)
        return real.reset_partition(partitions)

    # -- verdicts ---------------------------------------------------- #

    def trace(self, should_kill: bool) -> int:
        n = self._real.trace(should_kill)
        self._san._check_trace(n, compare=True)
        return n

    def harvest_trace(self, should_kill: bool) -> int:
        # Pipelined verdicts were computed from an earlier snapshot; the
        # oracle holds newer facts, so count equality is not expected —
        # fold-side checks still ran, and the oracle is compacted here.
        n = self._real.harvest_trace(should_kill)
        self._san._check_trace(n, compare=False)
        return n


class Sanitizer:
    """uigcsan.  Create via :meth:`attach`, ideally before any managed
    actor is spawned (the config key ``uigc.analysis.sanitizer`` does
    this at system construction)."""

    def __init__(self, system: "ActorSystem"):
        self.system = system
        self.engine = system.engine
        self._lock = threading.RLock()
        self.violations: List[SanitizerViolation] = []
        self.raise_on_violation = system.config.get_bool(
            "uigc.analysis.sanitizer-raise"
        )
        # Mutator-side ground truth (keyed by cell identity; remote
        # targets key by their proxy).
        self.sends: Dict[Any, int] = {}
        self.recvs: Dict[Any, int] = {}
        self.creates: Dict[Any, int] = {}
        self.releases: Dict[Any, int] = {}
        self.tainted: Set[Any] = set()
        # CRGC mirror state.
        self.oracle: Optional[Any] = None
        self.bookkeeper: Optional[Any] = None
        self._folded_undo: Set[str] = set()
        self._delta_seq: Dict[str, int] = {}
        self._seen_packed_seqs: Set[int] = set()
        #: memoized pseudo-root closure; invalidated by every fold so a
        #: cascade of stop decisions costs one traversal, not one each.
        self._reach_cache: Optional[Set[Any]] = None
        self.checks = 0
        # Distributed-collector mode (engines/crgc/distributed.py): the
        # per-node oracle holds only the owned slice (facts are routed,
        # not broadcast), so single-node verdict checks cannot judge a
        # cross-node cycle — the sweep instead records its verdicts
        # here, and :func:`cross_check_distributed` merges every node's
        # oracle into one global graph to judge them.
        #: cumulative (address, uid) keys this node's distributed
        #: sweeps declared garbage
        self.dist_garbage_keys: Set[Any] = set()
        #: the last sweep's live (marked, owned) key set
        self.dist_live_keys: Set[Any] = set()
        #: wave id of the last recorded distributed sweep
        self.dist_last_wave = 0
        self.dist_sweeps = 0

    # -- attachment --------------------------------------------------- #

    @classmethod
    def attach(cls, system: "ActorSystem") -> "Sanitizer":
        san = cls(system)
        engine = system.engine
        engine.tap = _Tap(san)
        bookkeeper = getattr(engine, "bookkeeper", None)
        if bookkeeper is not None and hasattr(bookkeeper, "shadow_graph"):
            from ..engines.crgc.shadow import ShadowGraph

            san.bookkeeper = bookkeeper
            san.oracle = ShadowGraph(engine.crgc_context, system.address)
            bookkeeper.shadow_graph = _MirrorGraph(
                bookkeeper.shadow_graph, san
            )
            san._wrap_bookkeeper(bookkeeper)
        system.sanitizer = san
        return san

    def _wrap_bookkeeper(self, bookkeeper: Any) -> None:
        """Observe the collector's control-plane stream for the monotone
        sequence invariant on peer delta gossip."""
        from ..engines.crgc.collector import DeltaMsg
        from ..runtime.fabric import MemberRemoved, MemberUp

        orig = bookkeeper.on_message

        def on_message(msg: Any) -> Any:
            if isinstance(msg, MemberRemoved):
                # A rejoining FRESH incarnation of this address starts
                # its gossip sequence from zero — the monotonicity
                # window is per incarnation, not per address.
                with self._lock:
                    self._delta_seq.pop(msg.address, None)
            if isinstance(msg, MemberUp):
                # Re-admission of a previously-downed address (restart
                # rejoin, or a heal after a partition verdict): the
                # collector reset its undo state, so a LATER legitimate
                # fold for this address must not read as a double fold
                # — and the healed peer's delta stream continues its
                # own numbering, so the window re-learns from scratch.
                with self._lock:
                    self._folded_undo.discard(msg.address)
                    self._delta_seq.pop(msg.address, None)
            if isinstance(msg, DeltaMsg) and msg.graph.address is not None:
                addr = msg.graph.address
                with self._lock:
                    last = self._delta_seq.get(addr)
                    # Keep the observed maximum so a replayed frame
                    # below it is still caught after a flagged dip.
                    self._delta_seq[addr] = max(
                        msg.seqnum, last if last is not None else msg.seqnum
                    )
                if last is not None and msg.seqnum <= last:
                    self.record(
                        "delta.seq_regression",
                        "peer delta gossip sequence number did not increase",
                        peer=addr,
                        last=last,
                        got=msg.seqnum,
                    )
            return orig(msg)

        bookkeeper.on_message = on_message

    # -- violation plumbing ------------------------------------------- #

    def record(self, rule: str, detail: str, **payload: Any) -> None:
        violation = SanitizerViolation(rule, detail, **payload)
        with self._lock:
            self.violations.append(violation)
        events.recorder.commit(
            events.ANALYSIS_VIOLATION,
            rule=rule,
            detail=detail,
            node=self.system.address,
        )
        if self.raise_on_violation:
            raise violation

    def by_rule(self, rule: str) -> List[SanitizerViolation]:
        with self._lock:
            return [v for v in self.violations if v.rule == rule]

    def report(self) -> Dict[str, Any]:
        """Structured summary for tests and post-mortems."""
        with self._lock:
            rules: Dict[str, int] = {}
            for v in self.violations:
                rules[v.rule] = rules.get(v.rule, 0) + 1
            return {
                "node": self.system.address,
                "checks": self.checks,
                "violations": [str(v) for v in self.violations],
                "by_rule": rules,
                "tap": {
                    "sends": sum(self.sends.values()),
                    "recvs": sum(self.recvs.values()),
                    "creates": sum(self.creates.values()),
                    "releases": sum(self.releases.values()),
                    "tainted": len(self.tainted),
                },
                "oracle_population": (
                    len(self.oracle.from_set) if self.oracle is not None else None
                ),
            }

    # -- oracle folds (collector thread) ------------------------------ #
    # These replicate ShadowGraph.merge_entry semantics but look shadows
    # up by cell, never through refob.target_shadow — the oracle must not
    # poison the shared refob shadow caches the real backend relies on.

    def _fold_entry(self, entry: Any) -> None:
        from ..engines.crgc import refob as refob_info
        from ..engines.crgc.shadow import _update_outgoing

        g = self.oracle
        with self._lock:
            self._reach_cache = None
            self_shadow = g.get_shadow(entry.self_ref.target)
            self_shadow.interned = True
            self_shadow.is_local = True
            self_shadow.recv_count += entry.recv_count
            self_shadow.is_busy = entry.is_busy
            self_shadow.is_root = entry.is_root

            field_size = self.engine.crgc_context.entry_field_size
            for i in range(field_size):
                owner = entry.created_owners[i]
                if owner is None:
                    break
                target_shadow = g.get_shadow(entry.created_targets[i].target)
                _update_outgoing(
                    g.get_shadow(owner.target).outgoing, target_shadow, 1
                )
            for i in range(field_size):
                child = entry.spawned_actors[i]
                if child is None:
                    break
                g.get_shadow(child.target).supervisor = self_shadow
            for i in range(field_size):
                target = entry.updated_refs[i]
                if target is None:
                    break
                target_shadow = g.get_shadow(target.target)
                info = entry.updated_infos[i]
                send_count = refob_info.count(info)
                if send_count > 0:
                    target_shadow.recv_count -= send_count
                if not refob_info.is_active(info):
                    _update_outgoing(self_shadow.outgoing, target_shadow, -1)

    def _fold_packed(self, rows: Any) -> None:
        """Decode a drained batch of packed rows (packed.py row layout)
        into the oracle, in flush order, resolving uids the same way the
        real fold does (plane pin first, weak registry second; facts
        naming proven-garbage uids drop)."""
        import numpy as np

        from ..engines.crgc.shadow import _update_outgoing

        seqs = rows[:, 0]
        uniq, counts = np.unique(seqs, return_counts=True)
        with self._lock:
            # Flush stamps are globally unique (plane.next_seq is
            # atomic): a repeat within or across drained batches means a
            # row was replayed.  The seen-set grows with total flushes —
            # acceptable for a debugging tool.
            replayed = [
                s for s in uniq.tolist() if s in self._seen_packed_seqs
            ]
            self._seen_packed_seqs.update(uniq.tolist())
        dup_stamps = uniq[counts > 1].tolist() + replayed
        if dup_stamps:
            self.record(
                "packed.seq_duplicate",
                "duplicate flush stamps in the packed entry stream",
                stamps=sorted(set(dup_stamps)),
            )
        plane = self.engine.packed_plane
        resolve = self.system.resolve_cell
        pins = plane.uid_strong

        def cell_of(uid: int) -> Any:
            cell = pins.get(uid)
            return cell if cell is not None else resolve(uid)

        g = self.oracle
        field_size = self.engine.crgc_context.entry_field_size
        order = np.argsort(seqs, kind="stable")
        with self._lock:
            self._reach_cache = None
            for row in rows[order]:
                row = row.tolist()
                base = 4
                # Created pairs survive an unresolvable flusher, exactly
                # like ArrayShadowGraph.merge_packed.
                for i in range(field_size):
                    owner_uid = row[base + 2 * i]
                    if owner_uid < 0:
                        continue
                    owner = cell_of(owner_uid)
                    target = cell_of(row[base + 2 * i + 1])
                    if owner is None or target is None:
                        continue
                    _update_outgoing(
                        g.get_shadow(owner).outgoing, g.get_shadow(target), 1
                    )
                self_cell = cell_of(row[1])
                if self_cell is None:
                    continue
                self_shadow = g.get_shadow(self_cell)
                self_shadow.interned = True
                self_shadow.is_local = True
                self_shadow.is_busy = bool(row[2] & 1)
                self_shadow.is_root = bool(row[2] & 2)
                self_shadow.recv_count += row[3]
                base = 4 + 2 * field_size
                for i in range(field_size):
                    child_uid = row[base + i]
                    if child_uid < 0:
                        continue
                    child = cell_of(child_uid)
                    if child is not None:
                        g.get_shadow(child).supervisor = self_shadow
                base = 4 + 3 * field_size
                for i in range(field_size):
                    target_uid = row[base + 2 * i]
                    if target_uid < 0:
                        continue
                    info = row[base + 2 * i + 1]
                    target = cell_of(target_uid)
                    if target is None:
                        continue
                    target_shadow = g.get_shadow(target)
                    send_count = info >> 1
                    if send_count > 0:
                        target_shadow.recv_count -= send_count
                    if info & 1:
                        _update_outgoing(
                            self_shadow.outgoing, target_shadow, -1
                        )

    def _fold_delta(self, delta: Any) -> None:
        with self._lock:
            self._reach_cache = None
            self.oracle.merge_delta(delta)

    def _fold_undo(self, log: Any) -> None:
        addr = log.node_address
        bookkeeper = self.bookkeeper
        if addr in self._folded_undo:
            self.record(
                "undo.double_fold",
                "undo log folded twice for the same dead node",
                address=addr,
            )
        else:
            my_addr = self.system.address
            expected = {my_addr}
            if bookkeeper is not None:
                expected.update(bookkeeper.remote_gcs)
            missing = sorted(expected - log.finalized_by)
            if missing:
                self.record(
                    "undo.premature_fold",
                    "undo log folded before its finalization quorum",
                    address=addr,
                    finalized_by=sorted(log.finalized_by),
                    missing=missing,
                )
        self._folded_undo.add(addr)
        with self._lock:
            self._reach_cache = None
            self.oracle.merge_undo_log(log)

    # -- verdict cross-check (collector thread) ------------------------ #

    def _check_trace(self, n_real: int, compare: bool) -> None:
        with self._lock:
            self._reach_cache = None  # the trace compacts the oracle
            # Muted: the oracle re-runs the instrumented trace pipeline;
            # letting it commit crgc.tracing/crgc.sweep would make every
            # metrics consumer double-count the wave with oracle timings.
            with events.recorder.suppressed():
                n_oracle = self.oracle.trace(should_kill=False)
            self.checks += 1
        events.recorder.commit(
            events.ANALYSIS_CHECK,
            node=self.system.address,
            n_garbage=n_real,
            oracle_garbage=n_oracle,
        )
        if compare and n_oracle != n_real:
            self.record(
                "verdict.mismatch",
                "engine and oracle disagree on a collection verdict",
                engine_garbage=n_real,
                oracle_garbage=n_oracle,
                oracle_addresses=self.oracle.addresses_in_graph(),
            )

    # -- distributed mode (collector thread) ---------------------------- #

    def _reset_partition(self, partitions: Any, pmap: Any) -> None:
        """Mirror of PartitionedShadowGraph.reset_partition over the
        oracle: clear the authoritative state of every oracle shadow in
        the gained partitions (objects kept — other shadows' edges
        reference them by identity) so the journal re-fold rebuilds the
        oracle and the real slice from the same blank."""
        if pmap is None:
            return
        from ..engines.crgc.shadow import clear_authoritative_state
        from ..parallel.partition import cell_key

        with self._lock:
            self._reach_cache = None
            for shadow in self.oracle.from_set:
                key = cell_key(shadow.self_cell)
                if pmap.partition_of(key) in partitions:
                    clear_authoritative_state(shadow)

    def note_dist_sweep(self, wave: int, garbage_keys: Any, live_keys: Any) -> None:
        """One distributed sweep's verdicts for this node's owned slice.
        Recorded, not judged: a cross-node cycle's liveness is not
        decidable from one node's oracle — :func:`cross_check_distributed`
        merges every node's oracle and judges the accumulated verdicts
        against the global graph."""
        with self._lock:
            self.dist_garbage_keys.update(garbage_keys)
            self.dist_live_keys = set(live_keys)
            self.dist_last_wave = wave
            self.dist_sweeps += 1
        events.recorder.commit(
            events.ANALYSIS_CHECK,
            node=self.system.address,
            n_garbage=len(garbage_keys),
            oracle_garbage=-1,  # judged globally, not per node
        )

    def oracle_slice(self, pmap: Any) -> Dict[Any, Dict[str, Any]]:
        """This node's owned slice of the oracle as plain data keyed by
        (address, uid) — the unit :func:`merged_oracle` aggregates.
        Only keys the given partition map assigns to this node are
        exported: mirror shadows (non-owned edge endpoints) carry no
        authoritative state here and undo folds may have adjusted their
        balances redundantly, so the owner's record is the one that
        counts."""
        from ..parallel.partition import cell_key

        out: Dict[Any, Dict[str, Any]] = {}
        with self._lock:
            for shadow in self.oracle.from_set:
                key = cell_key(shadow.self_cell)
                if pmap is not None and not pmap.owns(key):
                    continue
                out[key] = {
                    "interned": shadow.interned,
                    "is_root": shadow.is_root,
                    "is_busy": shadow.is_busy,
                    "is_halted": shadow.is_halted,
                    "recv": shadow.recv_count,
                    "supervisor": (
                        cell_key(shadow.supervisor.self_cell)
                        if shadow.supervisor is not None
                        else None
                    ),
                    "outgoing": {
                        cell_key(t.self_cell): c
                        for t, c in shadow.outgoing.items()
                        if c != 0
                    },
                }
        return out

    # -- reachability / quiescence ------------------------------------- #

    def _oracle_reachable(self) -> Set[Any]:
        """Non-mutating pseudo-root closure over the oracle (caller holds
        the lock), memoized until the next fold.  Mirrors
        ShadowGraph.trace without touching marks."""
        if self._reach_cache is not None:
            return self._reach_cache
        g = self.oracle
        frontier = [s for s in g.from_set if g.is_pseudo_root(s)]
        live = set(frontier)
        while frontier:
            shadow = frontier.pop()
            if shadow.is_halted:
                continue
            for target, count in shadow.outgoing.items():
                if count > 0 and target not in live:
                    live.add(target)
                    frontier.append(target)
            supervisor = shadow.supervisor
            if supervisor is not None and supervisor not in live:
                live.add(supervisor)
                frontier.append(supervisor)
        self._reach_cache = live
        return live

    def check_quiescent(self) -> List[SanitizerViolation]:
        """Balance checks that only hold once the system has settled (no
        in-flight messages, collector caught up): every receive balance
        back at zero and no persistently negative reference edge.  Call
        from tests after a settle loop; returns the new violations.  In
        raise mode the whole scan still runs (recording every
        violation) and the first one is raised at the end, so no
        evidence is lost."""
        found: List[SanitizerViolation] = []
        before = len(self.violations)
        raise_mode, self.raise_on_violation = self.raise_on_violation, False
        if self.oracle is not None:
            with self._lock:
                shadows = list(self.oracle.from_set)
                taps = {
                    "sends": dict(self.sends),
                    "recvs": dict(self.recvs),
                    "tainted": set(self.tainted),
                }
            for shadow in shadows:
                if shadow.is_halted:
                    continue
                cell = shadow.self_cell
                if shadow.recv_count != 0:
                    self.record(
                        "balance.nonzero_recv",
                        "receive balance did not return to zero at quiescence",
                        actor=_path(cell),
                        balance=shadow.recv_count,
                        tap_sends=taps["sends"].get(cell, 0),
                        tap_recvs=taps["recvs"].get(cell, 0),
                        crossed_link=cell in taps["tainted"],
                    )
                negative = {
                    _path(t.self_cell): c
                    for t, c in shadow.outgoing.items()
                    if c < 0
                }
                if negative:
                    self.record(
                        "edges.negative",
                        "reference edge persistently negative at quiescence",
                        owner=_path(cell),
                        edges=negative,
                    )
        else:
            with self._lock:
                for cell, recvs in self.recvs.items():
                    if cell in self.tainted:
                        continue
                    sends = self.sends.get(cell, 0)
                    if recvs > sends:
                        self.record(
                            "balance.recv_without_send",
                            "actor received more messages than were sent",
                            actor=_path(cell),
                            recvs=recvs,
                            sends=sends,
                        )
        self.raise_on_violation = raise_mode
        with self._lock:
            found = self.violations[before:]
        if raise_mode and found:
            raise found[0]
        return found


# ------------------------------------------------------------------- #
# Distributed mode: merge per-node oracles, judge every sweep verdict
# against the global graph (engines/crgc/distributed.py).
# ------------------------------------------------------------------- #


class MergedOracle:
    """The union of every node's owned oracle slice — the pointer-exact
    global shadow graph no single node of the partitioned collector is
    allowed to hold.  State is owner-authoritative: each actor's record
    comes from the oracle of the node whose partition map owns it, so a
    mirror's redundant undo-fold adjustments can never double-count.

    ``live`` / ``garbage`` partition the key space by the same
    pseudo-root closure the single-host trace runs (halted actors can be
    marked but never propagate), which is the fixpoint the distributed
    wave protocol must iterate to."""

    def __init__(self, state: Dict[Any, Dict[str, Any]], nodes: List[str]):
        self.state = state
        self.nodes = nodes
        self.live: Set[Any] = set()
        self._close()
        self.garbage: Set[Any] = set(state) - self.live

    def _close(self) -> None:
        state = self.state
        frontier = []
        for key, rec in state.items():
            pseudo_root = (
                rec["is_root"]
                or rec["is_busy"]
                or rec["recv"] != 0
                or not rec["interned"]
            ) and not rec["is_halted"]
            if pseudo_root:
                self.live.add(key)
                frontier.append(key)
        while frontier:
            key = frontier.pop()
            rec = state.get(key)
            if rec is None or rec["is_halted"]:
                continue
            for target, count in rec["outgoing"].items():
                if count > 0 and target not in self.live:
                    self.live.add(target)
                    frontier.append(target)
            sup = rec["supervisor"]
            if sup is not None and sup not in self.live:
                self.live.add(sup)
                frontier.append(sup)


def merged_oracle(systems: Any) -> MergedOracle:
    """Merge the live systems' sanitizer oracles into one global graph.
    Every system must be sanitizer-attached and running the distributed
    collector (so each oracle holds exactly its owned slice)."""
    state: Dict[Any, Dict[str, Any]] = {}
    nodes: List[str] = []
    for system in systems:
        san = getattr(system, "sanitizer", None)
        if san is None or san.oracle is None:
            continue
        pmap = getattr(system.engine.bookkeeper, "pmap", None)
        nodes.append(system.address)
        state.update(san.oracle_slice(pmap))
    return MergedOracle(state, nodes)


def cross_check_distributed(systems: Any) -> List[SanitizerViolation]:
    """The distributed verdict check: every key any node's sweeps
    declared garbage must be unreachable in the merged global oracle.
    Garbage is monotone in CRGC, so a correct past verdict stays
    unreachable; a premature collection stays visible because the live
    holder's positive edge to the victim is still in its owner's oracle.
    Each violation is recorded on the judged node's own sanitizer (so
    per-node "sanitizer clean" assertions catch it) and the new
    violations are returned."""
    merged = merged_oracle(systems)
    found: List[SanitizerViolation] = []
    for system in systems:
        san = getattr(system, "sanitizer", None)
        if san is None:
            continue
        with san._lock:
            swept = set(san.dist_garbage_keys)
        bad = swept & merged.live
        if bad:
            before = len(san.violations)
            raise_mode, san.raise_on_violation = san.raise_on_violation, False
            san.record(
                "verdict.mismatch",
                "distributed sweep collected actors the merged oracle "
                "proves reachable",
                node=system.address,
                keys=sorted(f"{a}#{u}" for a, u in bad),
                merged_nodes=merged.nodes,
            )
            san.raise_on_violation = raise_mode
            with san._lock:
                found.extend(san.violations[before:])
    return found
