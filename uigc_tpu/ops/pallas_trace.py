"""Pallas TPU kernel for the liveness-trace propagation step.

The trace (ops/trace.py) is an iterative frontier expansion whose inner op
is, per propagation pair (src, dst): OR the source's active bit into the
destination's mark.  XLA lowers both the gather of source bits and the
scatter into destinations to serialized per-element loops (~7 ns/edge
measured) — the bottleneck at graph scale.  This kernel vectorizes both
sides with the primitives the TPU VPU/MXU actually has:

**Gather side.**  The active bit-vector is packed into a 32-bit word table
``T[R, 128]`` that stays VMEM-resident across the whole sweep (128 KB per
1M actors).  Mosaic supports per-vreg dynamic lane shuffles
(``take_along_axis`` within an (8, 128) register) but nothing across
vregs, so each grid step walks 8-row table chunks.  Two layout invariants
make the walk cheap:

1. *Slot row = source row mod 8.*  An edge whose source bit lives at table
   position (row_e, lane_e) is parked at slot ``(row_e % 8, col)``, so
   when the walk reaches the edge's chunk a single lane-gather
   ``take_along_axis(chunk, lane, axis=1)`` lands the right word at the
   edge's own slot — no cross-sublane shuffle, no slot/lane binding table.
   Uniqueness (one edge per (chunk-row-class, col) pair) is guaranteed by
   the host packer, which ranks edges within each (dst supertile,
   row-class) group and assigns col = rank mod 128.

2. *Per-block chunk ranges.*  Within each (dst supertile, row-class)
   group the packer sorts edges by source row, so the 128-edge runs that
   land in one block cover a narrow, contiguous band of the table.  The
   block's ``[c_lo, c_lo + span)`` range is scalar-prefetched and the
   kernel's chunk loop walks only that band — total chunk-iterations per
   sweep are O(n_super · n_chunks + n_blocks), not O(n_blocks · n_chunks),
   which is what lets the kernel scale to 10M+ actors.

**Scatter side.**  Edges are pre-sorted by destination supertile
(``SUPER = S_ROWS * 128`` nodes = one (S_ROWS, 128) f32 output block).
The block's 8x128 gathered bits become a segment-sum via one fused one-hot
contraction on the MXU:

    A[s, r*128+c] = vals[r, c] * (dst_sub[r, c] == s)     (S_ROWS, 1024)
    B[r*128+c, l] = (dst_lane[r, c] == l)                 (1024, 128)
    contrib      += A @ B                                 (S_ROWS, 128)

A and B are 0/1 so bf16 inputs with f32 accumulation are exact, doubling
MXU rate.  The output BlockSpec revisits one supertile block per run of
grid steps via a scalar-prefetched supertile-id, so accumulation happens
in VMEM and each block hits HBM exactly once per sweep.  Empty supertiles
get a dummy all-padding group so every output block is initialized.

Per-edge metadata is packed into two int32 arrays (source row; and
lane|bit|dst_lane|dst_sub) to halve HBM streaming per sweep.

Semantics are identical to ``trace_marks_np`` (the oracle for the
reference's ShadowGraph.java:205-289): supervisor pointers are folded in
as ordinary propagation pairs, sources gate on ``mark & ~halted``, and
only positive-weight edges propagate.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from . import trace as trace_ops
from ..utils import events
from ..utils.validation import require

LANE = 128  # lanes per vreg row
ROWS = 8  # sublane rows per edge-slot sub-block (slot row = src row mod 8)
#: default slot sub-blocks merged into one grid step on a real chip.
#: Each grid step streams a (ROWS * sub, LANE) slot block and runs ONE
#: (s_rows, ROWS*sub*LANE) @ (ROWS*sub*LANE, LANE) one-hot contraction —
#: sub-fold fewer grid steps (and their fixed stream/dispatch cost) for
#: the same total edges.
SUB_TPU = 4
#: default 8-row table chunks walked per gather-loop iteration on a real
#: chip.  The chunk walk was the measured bottleneck at graph scale
#: (~250ns/iteration of serial loop overhead for ~30ns of VPU work);
#: walking `group` chunks per iteration cuts iterations ~group-fold and
#: amortizes the overhead over `group` statically-unrolled sub-gathers.
GROUP_TPU = 8
#: interpret-mode defaults.  The wide geometry statically unrolls
#: sub*group gather stages per chunk iteration — on the CPU test tier
#: that inflates XLA compile time by minutes per trace geometry (enough
#: to stall a collector thread mid-protocol), while buying nothing
#: (interpret mode has no per-step hardware overhead to amortize).
SUB_CPU = 1
GROUP_CPU = 1
WORD_BITS = 32
#: default output sublane rows per block (s_rows * 128 dst nodes per
#: supertile).  32 is the packing limit (dst_sub is 5 bits) and measured
#: ~1.7x faster than 8 at the 10M-actor graph: the one-hot contraction
#: grows from (8, 1024) @ (1024, 128) to (32, 1024) @ (1024, 128), 4x the
#: MXU utilization per block for the same streamed bytes.
S_ROWS = 32
# Sentinel row for empty slots: beyond any table chunk, so they never hit.
_PAD_ROW = np.int32(1 << 28)
_SPAN_BITS = 12  # chunk index / span fit in 12 bits up to ~134M actors
#: quantum for large-layout block padding (see _pad_blocks_target)
_BLOCK_QUANTUM = 8192
#: bump when prepare_pairs' output format changes (layout caches key on
#: it; tools/sweep_profile.py persists packed layouts across runs)
PACK_FORMAT_VERSION = 2

# --------------------------------------------------------------------- #
# Trace propagation modes (uigc.crgc.trace-mode)
# --------------------------------------------------------------------- #
#: plain source-push sweeps over the dirty-chunk frontier (the pre-mode
#: behavior; every other mode is a strict superset of its propagation).
MODE_PUSH = "push"
#: push walks + destination-pull saturation gates every sweep: blocks
#: whose output supertile has no unmarked in-use node left are skipped
#: outright (GraphACT's push-vs-pull density asymmetry, PAPERS.md).
MODE_PULL = "pull"
#: push walks + pointer-jumping: marks additionally jump through a
#: min-source parent array that is squared each sweep, so convergence
#: needs O(log diameter) sweeps instead of O(diameter) ("Adaptive
#: Work-Efficient Connected Components on the GPU", PAPERS.md).
MODE_JUMP = "jump"
#: jump acceleration always on, pull gates switched per sweep when the
#: dirty-chunk density crosses ``pull_density`` — the default.
MODE_AUTO = "auto"
TRACE_MODES = (MODE_AUTO, MODE_PUSH, MODE_PULL, MODE_JUMP)
#: dirty-chunk density (fraction of walk chunks dirty) above which AUTO
#: turns the pull gates on for a sweep.  Below it the source frontier is
#: sparse enough that dirty-chunk pruning already bounds the sweep, and
#: the per-tile saturation pass would only add latency.
DEFAULT_PULL_DENSITY = 0.25
#: pointer doublings applied per sweep.  One doubling gives the classic
#: 2^k reach-per-sweep schedule; two squares the relation twice per
#: sweep (4^k), which at the 10M-actor benchmark geometry converges in
#: ~4 sweeps instead of ~12 (tools/sweep_profile.py --simulate).
JUMP_STEPS = 2
#: per-sweep stat ring length for with_stats builds (sweeps beyond this
#: fold into the last slot; fixpoints run ~4-12 sweeps)
MAX_SWEEP_STATS = 32

#: per-tile gate values consumed by dst_gate kernels
GATE_PUSH = 0  # walk the dirty chunks inside the block's span (default)
GATE_FULL = 1  # walk the FULL span (decremental repair re-derivation)
GATE_SKIP = 2  # skip the block outright (saturated destination tile)


def jump_parents(psrc, pdst, n: int) -> np.ndarray:
    """Min-source jump-parent array: J[d] = the smallest source with a
    live propagation pair into ``d``, sentinel ``n`` when none.

    Minimum (not first/last) is the load-bearing choice: low slots are
    the oldest, shallowest actors (roots intern first; preferential
    attachment biases hub targets low), so the parent forest points
    toward the seed-rich end of the graph — the min-label hooking of the
    GPU connected-components literature.  Shaped (n + 1,) with J[n] = n
    so pointer doubling can gather through the sentinel."""
    j = np.full(n + 1, n, dtype=np.int32)
    pdst = np.asarray(pdst, dtype=np.int64)
    psrc = np.asarray(psrc, dtype=np.int64)
    ok = (pdst < n) & (psrc < n)
    np.minimum.at(j, pdst[ok], psrc[ok].astype(np.int32))
    j[n] = n
    return j


def fold_jump_log(jump_parent, log, n: int, writes=None) -> None:
    """Vectorized jump-parent maintenance for one pair-transition batch
    ``[(insert?, src, dst, kind), ...]`` — the batched form of the
    min-fold-on-insert / invalidate-on-remove rules (``jump_parents``),
    shared by the single-device and mesh layout planes.

    Order-insensitive and conservative: pointers built from any pair
    removed in the batch are invalidated (even when an insert earlier
    in the same batch created them), and inserts whose (src, dst) pair
    is ALSO removed anywhere in the batch are not folded (their order
    against the remove is lost once the batch is vectorized).  A
    spurious invalidation or a skipped fold costs acceleration only;
    a pointer surviving its pair's removal would let the jump sweep
    cross a dead edge, which this can never produce.  Ids >= ``n``
    (node spaces that grew past the layout) are ignored.

    Mutates ``jump_parent`` in place; when ``writes`` is a dict the
    changed entries are recorded there too (the device-mirror scatter
    queue), O(changed) not O(batch)."""
    if not log:
        return
    arr = np.asarray(log, dtype=np.int64).reshape(len(log), -1)
    ins = arr[:, 0] != 0
    src, dst = arr[:, 1], arr[:, 2]
    ok = (src >= 0) & (src < n) & (dst >= 0) & (dst < n)
    rs, rd = src[~ins & ok], dst[~ins & ok]
    if rd.size:
        hit = jump_parent[rd] == rs
        hrd = rd[hit]
        if hrd.size:
            jump_parent[hrd] = n
            if writes is not None:
                for d in hrd.tolist():
                    writes[d] = n
    isrc, idst = src[ins & ok], dst[ins & ok]
    if isrc.size and rd.size:
        removed = set(zip(rs.tolist(), rd.tolist()))
        keep = np.fromiter(
            ((s, d) not in removed
             for s, d in zip(isrc.tolist(), idst.tolist())),
            bool, isrc.size,
        )
        isrc, idst = isrc[keep], idst[keep]
    if isrc.size:
        before = jump_parent[idst].copy()
        np.minimum.at(
            jump_parent, idst, isrc.astype(jump_parent.dtype)
        )
        if writes is not None:
            after = jump_parent[idst]
            changed = after < before
            for d, v in zip(idst[changed].tolist(),
                            after[changed].tolist()):
                writes[d] = v


def jump_parents_from_graph(
    edge_src, edge_dst, edge_weight, supervisor, n: int
) -> np.ndarray:
    """jump_parents over a graph's live propagation pairs (edges with
    positive weight + supervisor pointers)."""
    live = edge_weight > 0
    psrc = edge_src[live].astype(np.int64)
    pdst = edge_dst[live].astype(np.int64)
    sup_src = np.nonzero(supervisor >= 0)[0].astype(np.int64)
    if sup_src.size:
        psrc = np.concatenate([psrc, sup_src])
        pdst = np.concatenate([pdst, supervisor[sup_src].astype(np.int64)])
    return jump_parents(psrc, pdst, n)


# --------------------------------------------------------------------- #
# Marking parents (why-live provenance; telemetry/inspect.py)
#
# The observability analogue of the jump-parent forest above: where
# jump_parents is an ACCELERATION structure (min-source over raw pairs,
# squared each sweep, free to over-shortcut), the marking-parent array is
# an EXPLANATION structure — parent[i] is the node whose propagation
# first marked i in a plain BFS fixpoint, so following parents from any
# live actor walks a concrete pseudoroot→actor retaining path in which
# every hop is a real positive-weight edge or supervisor pointer.  It is
# computed by a separate scatter-min XLA fixpoint over the same flat
# node/edge arrays the mark kernels consume, NOT inside the Pallas mark
# kernel: the mark kernel's one-hot MXU contraction reduces sources to a
# single OR bit per destination and cannot say *which* source fired, and
# threading an argmin through it would double the streamed bytes of
# every plain wake.  Keeping provenance in its own dispatch means the
# no-capture wake path is untouched (stats-variant gating discipline)
# and a capture costs exactly one extra device fixpoint.
# --------------------------------------------------------------------- #

_parents_fn_cache: Dict[str, object] = {}


def _build_parents_fn():
    import jax
    import jax.numpy as jnp

    F = trace_ops

    def parents_fn(flags, recv_count, supervisor, edge_src, edge_dst,
                   edge_weight):
        n = flags.shape[0]
        in_use = (flags & F.FLAG_IN_USE) != 0
        halted = (flags & F.FLAG_HALTED) != 0
        seed = (
            ((flags & F.FLAG_ROOT) != 0)
            | ((flags & F.FLAG_BUSY) != 0)
            | (recv_count != 0)
            | ((flags & F.FLAG_INTERNED) == 0)
        )
        mark0 = in_use & (~halted) & seed
        parent0 = jnp.full(n, -1, dtype=jnp.int32)

        live_edge = edge_weight > 0
        edst = jnp.where(live_edge, edge_dst, n)
        esrc = jnp.where(live_edge, edge_src, n).astype(jnp.int32)
        sup_dst = jnp.where(supervisor >= 0, supervisor, n)
        sup_src = jnp.arange(n, dtype=jnp.int32)

        def cond(carry):
            return carry[2]

        def body(carry):
            mark, parent, _ = carry
            active = mark & (~halted)
            active_pad = jnp.concatenate([active, jnp.zeros((1,), bool)])
            # Scatter-min of the active source's own index per
            # destination; slot n is the sink for dead edges/no-sup.
            cand = jnp.full((n + 1,), n, dtype=jnp.int32)
            cand = cand.at[edst].min(
                jnp.where(active_pad[esrc], esrc, n)
            )
            cand = cand.at[sup_dst].min(
                jnp.where(active, sup_src, n)
            )
            cand = cand[:n]
            newly = (cand < n) & (~mark) & in_use
            parent = jnp.where(newly, cand, parent)
            return mark | newly, parent, jnp.any(newly)

        mark, parent, _ = jax.lax.while_loop(
            cond, body, (mark0, parent0, jnp.array(True))
        )
        return mark, parent

    return jax.jit(parents_fn)


def marking_parents_jax(flags, recv_count, supervisor, edge_src, edge_dst,
                        edge_weight):
    """Device (XLA) mark fixpoint with marking-parent capture.  Same
    mark contract as ``trace_ops.trace_marks_jax``; additionally returns
    ``parent`` (int32[n], -1 = pseudoroot seed or unmarked, else the
    minimum source whose propagation first marked the slot) — matching
    ``trace_ops.trace_marks_np_parents`` exactly, which is the parity
    oracle.  Shapes are static; the jitted fn is cached process-wide."""
    if "fn" not in _parents_fn_cache:
        _parents_fn_cache["fn"] = _build_parents_fn()
        if events.recorder.enabled:
            events.recorder.commit(
                events.COMPILE, tag="parents_fn", geom="static", hit=False
            )
    fn = _parents_fn_cache["fn"]
    mark, parent = fn(
        flags, recv_count, supervisor, edge_src, edge_dst, edge_weight
    )
    return np.asarray(mark), np.asarray(parent)  # readback: host boundary: device marks/parents -> np result contract


def bits_at(table, ids, n, jnp):
    """Gather per-node bits from a packed word table for an int32 id
    vector; ids >= n (the sentinel and any padding) read as 0."""
    flat = table.reshape(-1)
    word = jnp.minimum(ids >> 5, flat.shape[0] - 1)
    return (((flat[word] >> (ids & 31)) & 1) > 0) & (ids < n)


def jump_sweep(table, jump_j, trans_w, n, jnp, steps: int = JUMP_STEPS):
    """One pointer-jump propagation step + ``steps`` pointer doublings.

    Returns (hits, new_jump_j): ``hits`` is the (n,) bool plane of nodes
    whose current jump parent is active in ``table`` (mark & ~halted —
    the same source gate as edge propagation), and the parent array is
    then advanced by squaring, extending each pointer through
    ``trans_w``-transparent (in-use, non-halted) intermediates only.

    Soundness: by construction J[v] always reaches v through a path of
    live pairs whose intermediate nodes are all transparent, so
    mark[J[v]] & ~halted[J[v]] implies the plain fixpoint would
    eventually mark v — the jump only collapses the sweeps in between.
    Parents never extend through an opaque node, and the host layer
    invalidates J[d] whenever the pair it was built from is removed, so
    a jump can never cross a deleted edge or a halted relay."""
    hits = bits_at(table, jump_j[:n], n, jnp)
    for _ in range(steps):
        j2 = jump_j[jump_j]
        can = bits_at(trans_w, jump_j, n, jnp) & (j2 < n)
        jump_j = jnp.where(can, j2, jump_j)
    return hits, jump_j


def saturated_tiles(mark_w, iu_w, n_super, sup_words, jnp):
    """Per-supertile saturation bits (int32, 1 = no unmarked in-use node
    left): the destination-pull summary.  A saturated tile's blocks can
    be skipped outright — every contribution they could make would land
    on an already-marked or never-markable bit."""
    un = (iu_w & ~mark_w).reshape(-1)[: n_super * sup_words]
    return (
        ~(un.reshape(n_super, sup_words).any(axis=1))
    ).astype(jnp.int32)


def hier_dirty_lists(table, table_prev, n_chunks, group_rows, n_super,
                     sup_words, jnp):
    """The hierarchical frontier: per-supertile summary bits above the
    walk-chunk dirty lists.

    Level 1 (coarse, destination space): one summary bit per supertile —
    did any of its words change this sweep.  Feeds the pull gates (a
    tile's saturation can only flip where its summary fired, so the
    per-sweep saturation update is masked to the changed tiles and the
    rest carry over) and the frontier-density stats.
    Level 2 (fine, source space): the existing compacted dirty-chunk
    prefix/list the kernels walk (``dirty_group_lists``) — the word
    diff is shared between both levels (XLA CSEs the duplicate
    comparison inside one trace).

    Returns (d, l, changed, super_changed) with d/l/changed exactly as
    ``dirty_group_lists`` produces them."""
    d, l, changed = dirty_group_lists(table, table_prev, n_chunks,
                                      group_rows, jnp)
    flat = (table != table_prev).reshape(-1)[: n_super * sup_words]
    super_changed = flat.reshape(n_super, sup_words).any(axis=1).astype(
        jnp.int32
    )
    return d, l, changed, super_changed


def _int8_mxu() -> bool:
    """UIGC_KERNEL_INT8=1 runs the one-hot contraction in int8 with
    int32 accumulation (A and B are 0/1, so it is exact) — on chips
    whose MXU doubles int8 rate vs bf16 this is a candidate 2x when the
    sweep is contraction-bound.  Read at kernel BUILD time and part of
    every kernel-cache key, so one process can A/B by flipping the env
    var between runs — no restart needed."""
    import os

    return os.environ.get("UIGC_KERNEL_INT8", "") not in ("", "0")


def pack_hits_words(hits2d, jnp):
    """Word-pack a (t, LANE) boolean hits plane into flat int32 words.

    The one layout invariant every fixpoint pack shares: lane g*32+b of
    row t is bit b of flat word t*4+g (node id = 32*word + bit), so the
    flat words lay out row-major into the (r_rows, LANE) table at
    position (w >> 7, w & 127).  Callers pad/reshape to their table
    geometry (global table, shard-local words, or a benchmark probe)."""
    t = hits2d.shape[0]
    shifts = jnp.arange(WORD_BITS, dtype=jnp.int32)
    h3 = hits2d.astype(jnp.int32).reshape(t, LANE // WORD_BITS, WORD_BITS)
    w = (h3 << shifts[None, None, :]).sum(axis=2, dtype=jnp.int32)
    return w.reshape(-1)


def pack_bools(active, n, r_rows, jnp):
    """Scatter-pack an (n,) bool vector into the (r_rows, LANE) word
    table (bits >= n stay 0).  O(n) — used once per trace for seed/gate
    vectors; the fixpoint's per-sweep pack is pack_hits_table."""
    shifts = jnp.arange(WORD_BITS, dtype=jnp.int32)
    a = jnp.zeros(r_rows * LANE * WORD_BITS, jnp.int32)
    a = a.at[:n].set(active.astype(jnp.int32))
    w = (a.reshape(-1, WORD_BITS) << shifts[None, :]).sum(
        axis=1, dtype=jnp.int32
    )
    return w.reshape(r_rows, LANE)


def dirty_group_lists(table, table_prev, n_chunks, group_rows, jnp):
    """Prefix D and compacted index list L of the walk groups whose words
    changed — the kernel ABI build_propagate consumes (D sized
    n_chunks+1, L sized n_chunks, plus the any-changed flag)."""
    chunk_ids = jnp.arange(n_chunks, dtype=jnp.int32)
    diff = (
        (table != table_prev).reshape(n_chunks, group_rows * LANE).any(axis=1)
    )
    counts = diff.astype(jnp.int32)
    d = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)])
    pos = jnp.where(diff, d[:-1], n_chunks)
    l = (
        jnp.zeros((n_chunks + 1,), jnp.int32).at[pos].set(chunk_ids)[:n_chunks]
    )
    return d, l, d[n_chunks] > 0


def pack_hits_table(hits2d, r_rows, jnp):
    """pack_hits_words padded and reshaped into the (r_rows, LANE) word
    table — the exact per-sweep pack on the fixpoint path (trace_fn's
    pack2d) and the expression benchmark probes must time."""
    flat = pack_hits_words(hits2d, jnp)
    flat = jnp.concatenate(
        [flat, jnp.zeros((r_rows * LANE - flat.shape[0],), jnp.int32)]
    )
    return flat.reshape(r_rows, LANE)


def unpack_table(words, n, jnp):
    """Unpack the (r_rows, LANE) word table back to an (n,) bool vector
    (inverse of pack_bools/pack_hits_table for bits < n)."""
    shifts = jnp.arange(WORD_BITS, dtype=jnp.int32)
    bits = (words.reshape(-1)[:, None] >> shifts[None, :]) & 1
    return bits.reshape(-1)[:n] > 0


def build_sweep_contribs(specs, propagates, n, n_super, s_rows, jnp):
    """The per-layout propagation sweep shared by the full trace and the
    decremental wake: returns fn(table, d, l, layout_args, gate) -> hits
    plane (t_rows, LANE) bool.

    ``propagates`` holds one kernel per packed spec (None for xla
    tiers).  ``gate`` is the per-global-supertile dst-gate vector for
    dst_gate=True kernels, or None when the kernels were built without a
    gate operand.  Keeping this loop in one place is what guarantees the
    two fixpoints propagate identically per sweep — the parity the
    differential tests rely on."""
    t_rows = n_super * s_rows
    n_pad_nodes = t_rows * LANE
    sub_iota_rows = jnp.arange(s_rows, dtype=jnp.int32)

    def sweep(table, d, l, layout_args, gate=None):
        contrib = jnp.zeros((t_rows, LANE), jnp.float32)
        xla_hits2d = jnp.zeros((t_rows, LANE), bool)
        have_xla = False
        pos = 0
        for spec, propagate in zip(specs, propagates):
            if spec[0] == "xla":
                psrc, pdst = layout_args[pos:pos + 2]
                pos += 2
                # Source-active bits gathered straight from the packed
                # table; sink pads (src = n) masked out.
                word = psrc >> 5
                w = table[word >> 7, word & 127]
                src_active = (((w >> (psrc & 31)) & 1) > 0) & (psrc < n)
                prop = (
                    jnp.zeros((n_pad_nodes + 1,), jnp.int32)
                    .at[pdst]
                    .max(src_active.astype(jnp.int32))
                )
                xla_hits2d = xla_hits2d | (
                    prop[:n_pad_nodes].reshape(t_rows, LANE) > 0
                )
                have_xla = True
                continue
            if spec[0] == "compact":
                bmeta1, bmeta2, row_pos, emeta, super_ids = layout_args[
                    pos:pos + 5
                ]
                pos += 5
                if gate is None:
                    c = propagate(d, l, bmeta1, bmeta2, table, row_pos, emeta)
                else:
                    c = propagate(
                        d, l, gate[super_ids], bmeta1, bmeta2, table,
                        row_pos, emeta,
                    )
                rows = (
                    super_ids[:, None] * s_rows + sub_iota_rows[None, :]
                ).reshape(-1)
                contrib = contrib.at[rows].add(
                    c, mode="drop", unique_indices=False
                )
            else:
                bmeta1, bmeta2, row_pos, emeta = layout_args[pos:pos + 4]
                pos += 4
                if gate is None:
                    c = propagate(d, l, bmeta1, bmeta2, table, row_pos, emeta)
                else:
                    c = propagate(
                        d, l, gate, bmeta1, bmeta2, table, row_pos, emeta
                    )
                contrib = contrib + c
        hits2d = contrib > 0
        if have_xla:
            hits2d = hits2d | xla_hits2d
        return hits2d

    return sweep


def default_geometry(interpret: bool | None = None) -> tuple:
    """(sub, group) for new layouts: wide on a real chip, minimal in
    interpret mode (see SUB_CPU note)."""
    if interpret is None:
        interpret = default_interpret()
    return (SUB_CPU, GROUP_CPU) if interpret else (SUB_TPU, GROUP_TPU)


def _parallel_argsort(keys: np.ndarray) -> np.ndarray:
    """argsort through torch's multi-threaded sort when available —
    numpy's is single-threaded and dominates the 50M-pair pack (~9s vs
    ~2s).  Equal keys may land in either order; the packer's placement
    is valid under any tie-break (the composite key carries every field
    the placement reads)."""
    if keys.size < (1 << 20):
        return np.argsort(keys)
    try:
        import torch

        return torch.from_numpy(keys).argsort().numpy()
    except Exception:
        return np.argsort(keys)


def _pad_blocks_target(n_blocks: int) -> int:
    """Padded block count for a mutable layout: power of two while small
    (maximum kernel-cache reuse), then multiples of ``_BLOCK_QUANTUM``.
    Block metadata is scalar-prefetched into SMEM (1 MB): pow2 padding of
    a ~90k-block layout would waste ~350 KB of it and OOM the 10M-actor
    graph, while quantum padding stays within budget up to ~60M actors."""
    if n_blocks <= _BLOCK_QUANTUM:
        return 1 << max(0, int(n_blocks - 1).bit_length())
    return ((n_blocks + _BLOCK_QUANTUM - 1) // _BLOCK_QUANTUM) * _BLOCK_QUANTUM


def prepare_chunks(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_weight: np.ndarray,
    supervisor: np.ndarray,
    n: int,
    s_rows: int = S_ROWS,
    pad_blocks_pow2: bool = False,
    sub: int = None,
    group: int = None,
) -> Dict[str, np.ndarray]:
    """Host-side packer: place propagation pairs into kernel blocks.

    Rebuild whenever the edge set or supervisor pointers change (one
    lexsort of the live pairs, amortized across the trace's fixpoint
    iterations and across traces between graph mutations; a live,
    churning graph should use ops/pallas_incremental.py instead, which
    keeps this full pack off the per-wake path).

    ``pad_blocks_pow2`` rounds the block count up to a power of two with
    inert padding blocks (they re-accumulate zeros into the last
    supertile), so a live, mutating graph triggers at most log-many
    kernel recompiles instead of one per edge-set change.
    """
    live = edge_weight > 0
    psrc = edge_src[live].astype(np.int64)
    pdst = edge_dst[live].astype(np.int64)
    sup_src = np.nonzero(supervisor >= 0)[0].astype(np.int64)
    if sup_src.size:
        psrc = np.concatenate([psrc, sup_src])
        pdst = np.concatenate([pdst, supervisor[sup_src].astype(np.int64)])
    return prepare_pairs(
        psrc, pdst, n, s_rows=s_rows, pad_blocks_pow2=pad_blocks_pow2,
        sub=sub, group=group,
    )


def prepare_pairs(
    psrc: np.ndarray,
    pdst: np.ndarray,
    n: int,
    s_rows: int = S_ROWS,
    pad_blocks_pow2: bool = False,
    want_slots: bool = False,
    compact_supers: bool = False,
    n_src: int = None,
    sub: int = None,
    group: int = None,
) -> Dict[str, np.ndarray]:
    """Pack explicit propagation pairs (already filtered to live ones)
    into kernel blocks.

    With ``want_slots`` the result also carries ``slot_ri``/``slot_col``
    — each input pair's (row, column) in ``row_pos``/``emeta``, aligned
    with the *input* pair order — so a caller can later mask individual
    pairs in place (the deletion path of the incremental layout).

    With ``compact_supers`` the layout covers only the destination
    supertiles this pair set actually touches: the kernel's output is
    (k_touched * s_rows, LANE) and ``super_ids`` maps each compact tile
    back to its global supertile.  Without it, a tiny delta layout over
    a 10M-node space would still pay one (mostly dummy) grid step per
    global supertile; with it the cost scales with the delta.

    ``n_src`` decouples the source space from the destination space: the
    bit-table geometry (r_rows) covers ``n_src`` nodes while supertiles
    cover ``n`` destinations.  The mesh path uses this — sources are
    global ids gathered from the all-gathered table, destinations are
    shard-local (parallel/sharded_trace)."""
    assert 1 <= s_rows <= 32, "dst_sub is packed in 5 bits"
    if sub is None or group is None:
        d_sub, d_group = default_geometry()
        sub = d_sub if sub is None else sub
        group = d_group if group is None else group
    block_rows = ROWS * sub
    group_rows = ROWS * group
    super_sz = s_rows * LANE
    psrc = np.asarray(psrc, dtype=np.int64)
    pdst = np.asarray(pdst, dtype=np.int64)

    n_super = max(1, -(-n // super_sz))
    n_pad = n_super * super_sz
    # Bit table geometry: R rows of 128 lanes of 32-bit words, padded to
    # whole walk groups.
    n_words = -(-(n_src if n_src is not None else n_pad) // WORD_BITS)
    r_rows = -(-n_words // LANE)
    r_rows = ((r_rows + group_rows - 1) // group_rows) * group_rows
    assert r_rows // group_rows < (1 << _SPAN_BITS), (
        "graph too large for span packing"
    )

    m = psrc.size
    word = psrc >> 5
    w_row = word >> 7
    if super_sz & (super_sz - 1) == 0:
        # pow2 supertile (any pow2 s_rows): shifts instead of int64
        # division, which costs whole seconds at 50M pairs
        ss = super_sz.bit_length() - 1
        d_super = pdst >> ss
        d_local = pdst & (super_sz - 1)
    else:
        d_super = pdst // super_sz
        d_local = pdst % super_sz
    # per-pair emeta value, computed pre-sort so the sort permutation
    # needs only two gathers (composite + this) instead of six
    eval32 = (
        (word & 127)
        | ((psrc & 31) << 7)
        | ((d_local & 127) << 12)
        | ((d_local >> 7) << 19)
    ).astype(np.int32)

    if compact_supers:
        touched = np.unique(d_super)
        if touched.size == 0:
            touched = np.zeros(1, dtype=np.int64)
        d_super = np.searchsorted(touched, d_super)
        n_tiles = int(touched.size)
    else:
        touched = None
        n_tiles = n_super

    # --- placement -----------------------------------------------------
    # Sort by (dst supertile, row%8 class, source row); rank within each
    # class gives (block-in-supertile, column) such that each column holds
    # at most one edge per class — the slot row can then be the class
    # itself — and each block's 128-edge runs are source-sorted, keeping
    # its table-chunk span narrow.  One composite-key argsort instead of
    # a 3-key lexsort: a third of the sorting passes on the 50M-pair
    # packs, and equal keys are interchangeable so stability is not
    # needed (w_row fits 31 bits for any graph the span packing admits).
    # The key also CARRIES d_super/r8/w_row, so the sorted values are
    # recovered by bit ops on one gathered array instead of per-field
    # gathers.
    composite = (d_super << 34) | ((w_row & 7) << 31) | w_row
    order = _parallel_argsort(composite)
    comp_s = composite[order]
    eval32 = eval32[order]
    w_row = (comp_s & ((1 << 31) - 1)).astype(np.int32)
    r8 = (comp_s >> 31) & 7
    d_super = comp_s >> 34

    # rank of each edge within its (d_super, r8) class
    if m:
        key_change = np.ones(m, dtype=bool)
        cls = comp_s >> 31  # (d_super, r8) in one compare
        key_change[1:] = cls[1:] != cls[:-1]
        start_idx = np.nonzero(key_change)[0]
        starts = np.repeat(start_idx, np.diff(np.append(start_idx, m)))
        rank = np.arange(m, dtype=np.int64) - starts
    else:
        rank = np.zeros(0, dtype=np.int64)

    # blocks needed per (compact) supertile = max over classes of
    # ceil(ceil(class/128)/sub)
    sub_shift = sub.bit_length() - 1 if sub & (sub - 1) == 0 else None
    blocks_needed = np.zeros(n_tiles, dtype=np.int64)
    if m:
        sub_seq = (
            (rank >> 7) >> sub_shift if sub_shift is not None
            else (rank >> 7) // sub
        )
        np.maximum.at(blocks_needed, d_super, sub_seq + 1)
    blocks_needed = np.maximum(blocks_needed, 1)  # dummy for empty supertiles

    n_blocks = int(blocks_needed.sum())
    block_base = np.zeros(n_tiles, dtype=np.int64)
    block_base[1:] = np.cumsum(blocks_needed)[:-1]

    # --- fill kernel arrays -------------------------------------------
    shape = (n_blocks * block_rows, LANE)
    row_pos = np.full(shape, _PAD_ROW, dtype=np.int32)
    emeta = np.zeros(shape, dtype=np.int32)

    slot_ri = slot_col = None
    if m:
        sub_idx = rank >> 7  # sub-block sequence within the class
        g_block = block_base[d_super] + (
            sub_idx >> sub_shift if sub_shift is not None else sub_idx // sub
        )
        col = rank & 127
        # slot row = (sub-block within grid block, source row mod 8)
        sub_in = (
            sub_idx & (sub - 1) if sub_shift is not None else sub_idx % sub
        )
        ri = g_block * block_rows + sub_in * ROWS + r8
        if want_slots:
            # Undo the placement sort: slot of the i-th *input* pair.
            slot_ri = np.empty(m, dtype=np.int64)
            slot_col = np.empty(m, dtype=np.int64)
            slot_ri[order] = ri
            slot_col[order] = col
        row_pos[ri, col] = w_row
        emeta[ri, col] = eval32
        # per-block table walk-group range
        if group_rows & (group_rows - 1) == 0:
            chunk = (w_row >> (group_rows.bit_length() - 1)).astype(np.int64)
        else:
            chunk = (w_row // group_rows).astype(np.int64)
        c_lo = np.full(n_blocks, 1 << 30, dtype=np.int64)
        c_hi = np.zeros(n_blocks, dtype=np.int64)
        np.minimum.at(c_lo, g_block, chunk)
        np.maximum.at(c_hi, g_block, chunk + 1)
        empty = c_lo > c_hi
        c_lo[empty] = 0
        c_hi[empty] = 0
    else:
        c_lo = np.zeros(n_blocks, dtype=np.int64)
        c_hi = np.zeros(n_blocks, dtype=np.int64)

    span = c_hi - c_lo
    assert span.max(initial=0) < (1 << _SPAN_BITS)

    block_super = np.repeat(np.arange(n_tiles, dtype=np.int64), blocks_needed)
    block_first = np.zeros(n_blocks, dtype=np.int64)
    block_first[block_base] = 1

    if compact_supers and pad_blocks_pow2:
        # Pad the compact tile count to a power of two so repeated delta
        # packs reuse cached kernels.  Each pad tile gets one inert
        # first-visit block (initializes its output to zero); the
        # host-side scatter maps pad tiles to global supertile 0 with a
        # zero contribution, which is a no-op add.
        k_pad = 1 << max(0, int(n_tiles - 1).bit_length())
        if k_pad > n_tiles:
            extra_t = k_pad - n_tiles
            block_super = np.concatenate(
                [block_super, np.arange(n_tiles, k_pad, dtype=np.int64)]
            )
            block_first = np.concatenate(
                [block_first, np.ones(extra_t, dtype=np.int64)]
            )
            c_lo = np.concatenate([c_lo, np.zeros(extra_t, dtype=np.int64)])
            span = np.concatenate([span, np.zeros(extra_t, dtype=np.int64)])
            row_pos = np.concatenate(
                [row_pos, np.full((extra_t * block_rows, LANE), _PAD_ROW, np.int32)]
            )
            emeta = np.concatenate(
                [emeta, np.zeros((extra_t * block_rows, LANE), np.int32)]
            )
            n_blocks += extra_t
            n_tiles = k_pad

    if pad_blocks_pow2:
        padded = _pad_blocks_target(n_blocks)
        if padded > n_blocks:
            extra = padded - n_blocks
            # Inert blocks: span 0 (no gather), accumulate zeros into the
            # last (compact) supertile (keeps output revisits consecutive).
            block_super = np.concatenate(
                [block_super, np.full(extra, n_tiles - 1, dtype=np.int64)]
            )
            block_first = np.concatenate(
                [block_first, np.zeros(extra, dtype=np.int64)]
            )
            c_lo = np.concatenate([c_lo, np.zeros(extra, dtype=np.int64)])
            span = np.concatenate([span, np.zeros(extra, dtype=np.int64)])
            row_pos = np.concatenate(
                [row_pos, np.full((extra * block_rows, LANE), _PAD_ROW, np.int32)]
            )
            emeta = np.concatenate(
                [emeta, np.zeros((extra * block_rows, LANE), np.int32)]
            )
            n_blocks = padded

    # meta1 = supertile id | first-visit bit; meta2 = chunk range
    bmeta1 = (block_super << 1 | block_first).astype(np.int32)
    bmeta2 = (c_lo << _SPAN_BITS | span).astype(np.int32)

    prep = {
        "row_pos": row_pos,
        "emeta": emeta,
        "bmeta1": bmeta1,
        "bmeta2": bmeta2,
        "n_super": n_super,
        "n_blocks": n_blocks,
        "r_rows": r_rows,
        "n_pad": n_pad,
        "n": n,
        "s_rows": s_rows,
        "sub": sub,
        "group": group,
        "n_pairs": int(m),
    }
    if compact_supers:
        k = int(touched.size)
        super_ids = np.zeros(n_tiles, dtype=np.int32)
        super_ids[:k] = touched.astype(np.int32)
        prep["super_ids"] = super_ids
        prep["out_supers"] = n_tiles
    if want_slots:
        prep["slot_ri"] = (
            slot_ri if slot_ri is not None else np.zeros(0, dtype=np.int64)
        )
        prep["slot_col"] = (
            slot_col if slot_col is not None else np.zeros(0, dtype=np.int64)
        )
    return prep


def pad_layout_blocks(prep: Dict[str, np.ndarray], target: int) -> None:
    """Pad a packed layout with inert blocks (span 0, not first-visit,
    accumulating nothing into the last supertile) up to ``target`` blocks,
    in place.  The mesh path uses this to equalize per-shard block counts
    so one SPMD program covers every shard."""
    extra = target - prep["n_blocks"]
    if extra <= 0:
        return
    block_rows = ROWS * prep["sub"]
    n_tiles = prep.get("out_supers", prep["n_super"])
    bmeta1_pad = np.full(extra, (n_tiles - 1) << 1, dtype=np.int32)
    prep["bmeta1"] = np.concatenate([prep["bmeta1"], bmeta1_pad])
    prep["bmeta2"] = np.concatenate(
        [prep["bmeta2"], np.zeros(extra, dtype=np.int32)]
    )
    prep["row_pos"] = np.concatenate(
        [prep["row_pos"], np.full((extra * block_rows, LANE), _PAD_ROW, np.int32)]
    )
    prep["emeta"] = np.concatenate(
        [prep["emeta"], np.zeros((extra * block_rows, LANE), np.int32)]
    )
    prep["n_blocks"] = target


def device_args(prep: Dict[str, np.ndarray]) -> tuple:
    """The kernel operands (after flags/recv) in call order."""
    if "xla_src" in prep:
        return (prep["xla_src"], prep["xla_dst"])
    args = (prep["bmeta1"], prep["bmeta2"], prep["row_pos"], prep["emeta"])
    if "out_supers" in prep:
        args = args + (prep["super_ids"],)
    return args


def xla_tier(psrc, pdst, n: int, capacity: int) -> Dict[str, np.ndarray]:
    """A propagation tier held as raw pair arrays, padded to a static
    ``capacity`` with inert sink pairs (src=dst=n).  Propagated by an
    XLA scatter-max instead of the Pallas kernel: O(capacity) per
    fixpoint iteration, but zero pack cost and zero recompiles while
    the capacity is stable — the landing pad for the newest churn."""
    m = len(psrc)
    assert m <= capacity
    src = np.full(capacity, n, dtype=np.int32)
    dst = np.full(capacity, n, dtype=np.int32)
    src[:m] = psrc
    dst[:m] = pdst
    return {"xla_src": src, "xla_dst": dst, "capacity": capacity, "n": n}


_fn_cache: Dict[tuple, object] = {}


def layout_spec(prep: Dict[str, np.ndarray]) -> tuple:
    """The static shape signature of a packed layout (kernel cache key
    component)."""
    if "xla_src" in prep:
        return ("xla", prep["capacity"])
    if "out_supers" in prep:
        return (
            "compact",
            prep["n_blocks"],
            prep["out_supers"],
            prep["sub"],
            prep["group"],
        )
    return ("dense", prep["n_blocks"], prep["sub"], prep["group"])


def build_layout_propagates(
    specs, n_super, r_rows, s_rows, interpret, dst_gate=False
):
    """One propagation kernel per packed layout spec (None for xla
    tiers) — the builder loop shared by the full trace and the
    decremental wake."""
    out = []
    for spec in specs:
        if spec[0] == "dense":
            out.append(
                build_propagate(
                    spec[1], n_super, r_rows, s_rows, interpret,
                    sub=spec[2], group=spec[3], dst_gate=dst_gate,
                )
            )
        elif spec[0] == "compact":
            out.append(
                build_propagate(
                    spec[1], spec[2], r_rows, s_rows, interpret,
                    sub=spec[3], group=spec[4], dst_gate=dst_gate,
                )
            )
        else:
            out.append(None)
    return out


def build_propagate(
    n_blocks: int,
    out_tiles: int,
    r_rows: int,
    s_rows: int,
    interpret: bool,
    sub: int = None,
    group: int = None,
    dst_gate: bool = False,
):
    """One propagation sweep as a pallas_call: gather source bits from the
    packed table, one-hot segment-sum into per-supertile contributions.

    Operands (after the scalar-prefetch ones): the (r_rows, LANE) bit
    table, then row_pos and emeta.  Scalar-prefetch operands are the
    dirty-chunk prefix D (size n_chunks + 1, D[c] = number of dirty
    chunks below c), the compacted dirty-chunk index list L, and bmeta1,
    bmeta2: each block walks only the *dirty* chunks inside its span, and
    a block with none skips its gather and matmul entirely.  Correct
    under the trace's monotone OR-accumulation: a clean chunk's words are
    unchanged since the sweep that last walked them, so the skipped
    contribution is already in the mark vector.

    With ``dst_gate`` a fifth scalar-prefetch operand S (one int per
    output tile) selects the walk per block from the destination side:
    ``GATE_FULL`` (1) forces blocks whose output tile is flagged to walk
    their FULL chunk span regardless of the dirty lists.  The decremental
    wake's repair pass needs this: after unmarking a suspect region, the
    region's supertiles must re-derive their contributions from ALL their
    in-edges — including sources whose table groups did not change —
    which the source-side dirty machinery cannot express
    (ops/pallas_decremental.py).  ``GATE_SKIP`` (2) skips the block
    outright — the pull side of direction-optimizing propagation: a
    saturated destination tile (no unmarked in-use node left) cannot
    gain a bit from any contribution, so its blocks need not walk even a
    dirty span.  ``GATE_PUSH`` (0) is the default dirty-chunk walk.
    Skip wins over full: a tile both saturated and repair-gated has
    nothing left to re-derive (contributions are not carried across
    sweeps, only marks are).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if sub is None or group is None:
        d_sub, d_group = default_geometry(interpret)
        sub = d_sub if sub is None else sub
        group = d_group if group is None else group
    block_rows = ROWS * sub
    group_rows = ROWS * group
    use_int8 = _int8_mxu()

    def kernel(*refs):
        if dst_gate:
            d_ref, l_ref, s_ref, meta1_ref, meta2_ref = refs[:5]
            table_ref, row_ref, emeta_ref, out_ref = refs[5:]
        else:
            d_ref, l_ref, meta1_ref, meta2_ref = refs[:4]
            table_ref, row_ref, emeta_ref, out_ref = refs[4:]
        i = pl.program_id(0)
        m2 = meta2_ref[i]
        c_lo = jax.lax.shift_right_logical(m2, _SPAN_BITS)
        span = m2 & ((1 << _SPAN_BITS) - 1)
        first = (meta1_ref[i] & 1) == 1

        j_lo = d_ref[c_lo]
        j_hi = d_ref[c_lo + span]
        if dst_gate:
            g = s_ref[meta1_ref[i] >> 1]
            gated = g == GATE_FULL
            n_iter = jnp.where(
                g == GATE_SKIP,
                0,
                jnp.where(gated, span, j_hi - j_lo),
            )
            l_cap = l_ref.shape[0] - 1
        else:
            gated = None
            n_iter = j_hi - j_lo

        row_iota = jax.lax.broadcasted_iota(jnp.int32, (block_rows, LANE), 0)
        r8_iota = row_iota & 7  # slot row class = src row mod 8
        sub_iota = jax.lax.broadcasted_iota(jnp.int32, (s_rows, LANE), 0)
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 1)

        @pl.when(n_iter > 0)
        def _():
            row_pos = row_ref[:]
            emeta = emeta_ref[:]
            lane_idx = emeta & 127
            bit_pos = (emeta >> 7) & 31
            dst_lane = (emeta >> 12) & 127
            dst_sub = (emeta >> 19) & 31

            def chunk_body(j, acc):
                # One iteration walks a group_rows-row table group:
                # `group` statically-unrolled sub-gathers, each matching
                # slots whose source row falls in that 8-row sub-chunk.
                if dst_gate:
                    # Gated blocks walk the plain span; ungated blocks
                    # the compacted dirty list (clamped load: the list
                    # value is unused when gated).
                    lc = l_ref[jnp.minimum(j_lo + j, l_cap)]
                    c = jnp.where(gated, c_lo + j, lc)
                else:
                    c = l_ref[j_lo + j]
                tab_g = table_ref[pl.ds(c * group_rows, group_rows), :]
                base = c * group_rows
                for s in range(group):
                    sub_c = tab_g[s * ROWS : (s + 1) * ROWS, :]
                    # Stack the 8-row sub-chunk `sub` times so slot row
                    # (sb * 8 + r8) gathers from table row (base+8s+r8).
                    tiled = (
                        jnp.concatenate([sub_c] * sub, axis=0)
                        if sub > 1
                        else sub_c
                    )
                    g = jnp.take_along_axis(tiled, lane_idx, axis=1)
                    hit = (row_pos - (base + s * ROWS)) == r8_iota
                    acc = jnp.where(hit, g, acc)
                return acc

            words = jax.lax.fori_loop(
                0,
                n_iter,
                chunk_body,
                jnp.zeros((block_rows, LANE), jnp.int32),
            )
            bits = jax.lax.shift_right_logical(words, bit_pos) & 1
            mm_dt = jnp.int8 if use_int8 else jnp.bfloat16
            acc_dt = jnp.int32 if use_int8 else jnp.float32
            vals = bits.astype(mm_dt)

            # Fused one-hot segment-sum on the MXU: one
            # (s_rows, block_rows*128) @ (block_rows*128, 128)
            # contraction per block.
            a_parts = []
            b_parts = []
            for r in range(block_rows):
                # Mask-multiply instead of jnp.where: a where() whose
                # selected operand is a sublane-broadcast bf16 vector does
                # not lower through Mosaic on the current TPU toolchain.
                # vals is 0/1 bits, so the product is bit-identical to the
                # select.
                a_parts.append(
                    (sub_iota == dst_sub[r, :][None, :]).astype(mm_dt)
                    * vals[r, :][None, :]
                )
                b_parts.append(
                    (lane_iota == dst_lane[r, :][:, None]).astype(mm_dt)
                )
            a = jnp.concatenate(a_parts, axis=1)  # (s_rows, block_rows*LANE)
            b = jnp.concatenate(b_parts, axis=0)  # (block_rows*LANE, LANE)
            acc = jnp.dot(a, b, preferred_element_type=acc_dt)
            if use_int8:
                acc = acc.astype(jnp.float32)

            @pl.when(first)
            def _():
                out_ref[:] = acc

            @pl.when(jnp.logical_not(first))
            def _():
                out_ref[:] = out_ref[:] + acc

        @pl.when(jnp.logical_not(n_iter > 0) & first)
        def _():
            out_ref[:] = jnp.zeros((s_rows, LANE), jnp.float32)

    def imap_block(i, *_meta):
        return (i, 0)

    def imap_table(i, *_meta):
        return (0, 0)

    if dst_gate:

        def imap_out(i, d, l, sg, m1, m2):
            return (m1[i] >> 1, 0)

    else:

        def imap_out(i, d, l, m1, m2):
            return (m1[i] >> 1, 0)

    blockmap = pl.BlockSpec((block_rows, LANE), imap_block)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5 if dst_gate else 4,
        grid=(n_blocks,),
        in_specs=[
            # bit table: whole array, VMEM-resident across all steps
            pl.BlockSpec((r_rows, LANE), imap_table),
            blockmap,  # row_pos
            blockmap,  # emeta
        ],
        out_specs=pl.BlockSpec((s_rows, LANE), imap_out),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((out_tiles * s_rows, LANE), jnp.float32),
        interpret=interpret,
    )


def _build_trace_fn_multi(
    n: int,
    specs: tuple,
    n_super: int,
    r_rows: int,
    s_rows: int,
    interpret: bool,
    mode: str = MODE_PUSH,
    pull_density: float = DEFAULT_PULL_DENSITY,
    with_stats: bool = False,
):
    """Trace fn over one or more pair layouts sharing a node space.

    ``specs`` holds one static shape signature per layout:
      ("dense", n_blocks, sub, group)   — full layout, every supertile
      ("compact", n_blocks, out_tiles, sub, group) — only touched
        supertiles; the kernel output is scattered into the global
        contribution by the layout's ``super_ids`` operand
      ("xla", capacity)                 — raw pair arrays propagated by
        an XLA scatter-max; O(capacity) per iteration but zero pack and
        zero recompile cost, the landing tier for the newest churn
    Packed layouts sharing a trace must share (sub, group): the walk
    geometry fixes the dirty-list granularity.

    Each layout contributes per fixpoint iteration; contributions are
    combined *before* thresholding, so the result is identical to a
    single layout holding the union of the pairs.  This is what lets a
    churning graph keep a big, static "base" layout plus small delta
    tiers (ops/pallas_incremental) instead of re-packing everything.

    ``mode`` selects the propagation strategy (module MODE_* docs); jump
    and auto modes take a jump-parent operand right after flags/recv.
    ``with_stats`` returns (marks, stats) where stats carries the sweep
    count and per-sweep frontier decomposition (dirty chunks, changed
    supertiles, tiles skipped, pull-gate decision) for the profiler."""
    import jax
    import jax.numpy as jnp

    F = trace_ops
    require(
        mode in TRACE_MODES, "config.trace_mode",
        "bad trace mode", mode=mode, valid=TRACE_MODES,
    )
    use_jump = mode in (MODE_JUMP, MODE_AUTO)
    use_pull = mode in (MODE_PULL, MODE_AUTO)

    geoms = {spec[-2:] for spec in specs if spec[0] != "xla"}
    assert len(geoms) == 1, "packed layouts must share (sub, group)"
    ((_, group),) = geoms
    group_rows = ROWS * group

    propagates = build_layout_propagates(
        specs, n_super, r_rows, s_rows, interpret, dst_gate=use_pull
    )

    n_words_pad = r_rows * LANE
    n_chunks = r_rows // group_rows  # dirty granularity = one walk group
    n_pad_nodes = n_super * s_rows * LANE  # contrib coverage, >= n
    t_rows = n_super * s_rows  # contrib rows (128 nodes each)
    sup_words = s_rows * (LANE // WORD_BITS)  # words per supertile
    # AUTO's per-sweep pull decision, in dirty-chunk counts
    pull_cut = max(1, int(round(pull_density * n_chunks)))

    def trace_fn(flags, recv_count, *rest):
        if use_jump:
            jump_j0, *layout_args = rest
        else:
            jump_j0, layout_args = None, rest
        in_use = (flags & F.FLAG_IN_USE) != 0
        halted = (flags & F.FLAG_HALTED) != 0
        seed = (
            ((flags & F.FLAG_ROOT) != 0)
            | ((flags & F.FLAG_BUSY) != 0)
            | (recv_count != 0)
            | ((flags & F.FLAG_INTERNED) == 0)
        )
        mark0 = in_use & (~halted) & seed

        def pack(active):
            return pack_bools(active, n, r_rows, jnp)

        def pack2d(hits2d):
            """Per-sweep word-space pack of the (t_rows, LANE) hits
            plane: O(n/32) output instead of the O(n) scatter+shift
            repack of the bool-space pack."""
            return pack_hits_table(hits2d, r_rows, jnp)

        def unpack(words):
            return unpack_table(words, n, jnp)

        def dirty_chunks(table, table_prev):
            return hier_dirty_lists(
                table, table_prev, n_chunks, group_rows, n_super,
                sup_words, jnp,
            )

        def cond(carry):
            return carry["changed"]

        sweep = build_sweep_contribs(specs, propagates, n, n_super, s_rows, jnp)

        # Gate tables: in_use bits (mark gating) and ~halted bits
        # (propagation gating).  pack() only sets bits < n, so padding
        # bits stay 0 in both.
        iu_w = pack(in_use)
        nh_w = pack(~halted)
        trans_w = iu_w & nh_w  # jump-transparent intermediates

        # The level-1 summary is carried only when something consumes
        # it: the pull gates (masked saturation update) or the stats.
        track_super = use_pull or with_stats

        def body(carry):
            mark_w, table = carry["mark"], carry["table"]
            d, l = carry["d"], carry["l"]
            n_dirty = d[n_chunks]
            if use_pull:
                # Destination-side pull gates: marks grow monotonically
                # within one fixpoint so saturation only latches on,
                # and a tile can only flip where the level-1 summary
                # fired last sweep — the update is masked to those
                # tiles, the rest carry over.
                sat = jnp.where(
                    carry["sup_ch"] > 0,
                    saturated_tiles(mark_w, iu_w, n_super, sup_words,
                                    jnp),
                    carry["sat"],
                )
                if mode == MODE_AUTO:
                    pull_on = n_dirty >= pull_cut
                else:
                    pull_on = jnp.array(True)
                gate = jnp.where(pull_on, sat * GATE_SKIP,
                                 jnp.zeros_like(sat))
            else:
                sat = None
                pull_on = jnp.array(False)
                gate = None
            hits2d = sweep(table, d, l, layout_args, gate=gate)
            hit_w = pack2d(hits2d)
            new_mark_w = mark_w | (hit_w & iu_w)
            if use_jump:
                jh, jump_j = jump_sweep(
                    table, carry["jump"], trans_w, n, jnp
                )
                new_mark_w = new_mark_w | (pack(jh) & iu_w)
            new_table = new_mark_w & nh_w
            d2, l2, changed, sup_ch2 = dirty_chunks(new_table, table)
            out = dict(carry, mark=new_mark_w, table=new_table, d=d2,
                       l=l2, changed=changed)
            if track_super:
                out["sup_ch"] = sup_ch2
            if use_pull:
                out["sat"] = sat
            if use_jump:
                out["jump"] = jump_j
            if with_stats:
                i = jnp.minimum(carry["sweep_i"], MAX_SWEEP_STATS - 1)
                out["sweep_i"] = carry["sweep_i"] + 1
                out["st_dirty"] = carry["st_dirty"].at[i].set(n_dirty)
                out["st_super"] = carry["st_super"].at[i].set(
                    carry["sup_ch"].sum()
                )
                if use_pull:
                    out["st_skip"] = carry["st_skip"].at[i].set(
                        jnp.where(pull_on, sat.sum(), 0)
                    )
                    out["st_pull"] = carry["st_pull"].at[i].set(
                        pull_on.astype(jnp.int32)
                    )
            return out

        mark_w0 = pack(mark0)
        table0 = mark_w0 & nh_w
        d0, l0, changed0, sup_ch0 = dirty_chunks(
            table0, jnp.zeros_like(table0)
        )
        carry0 = {"mark": mark_w0, "table": table0, "d": d0, "l": l0,
                  "changed": changed0}
        if track_super:
            carry0["sup_ch"] = sup_ch0
        if use_pull:
            carry0["sat"] = saturated_tiles(
                mark_w0, iu_w, n_super, sup_words, jnp
            )
        if use_jump:
            carry0["jump"] = jump_j0.astype(jnp.int32)
        if with_stats:
            zero_stats = jnp.zeros((MAX_SWEEP_STATS,), jnp.int32)
            carry0.update(
                sweep_i=jnp.zeros((), jnp.int32), st_dirty=zero_stats,
                st_super=zero_stats, st_skip=zero_stats,
                st_pull=zero_stats,
            )
        out = jax.lax.while_loop(cond, body, carry0)
        if not with_stats:
            return unpack(out["mark"])
        stats = {
            "n_sweeps": out["sweep_i"],
            "dirty_chunks": out["st_dirty"],
            "changed_supers": out["st_super"],
            "tiles_skipped": out["st_skip"],
            "pull_on": out["st_pull"],
        }
        return unpack(out["mark"]), stats

    return jax.jit(trace_fn)


def default_interpret() -> bool:
    """Interpret mode defaults to True off-TPU (Mosaic can't compile
    there); on a real chip (incl. the "axon" tunnel plugin) it compiles
    for real."""
    import jax

    from ..utils.platform import is_tpu_platform

    return not is_tpu_platform(jax.devices()[0].platform)


def get_trace_fn(
    prep: Dict[str, np.ndarray],
    interpret: bool | None = None,
    mode: str = MODE_PUSH,
    pull_density: float = DEFAULT_PULL_DENSITY,
    with_stats: bool = False,
):
    """Cached jitted trace fn for a prepared pair-array layout."""
    return get_trace_fn_multi(
        prep["n"],
        (layout_spec(prep),),
        prep["n_super"],
        prep["r_rows"],
        prep["s_rows"],
        interpret,
        mode=mode,
        pull_density=pull_density,
        with_stats=with_stats,
    )


def get_trace_fn_multi(
    n: int,
    specs: tuple,
    n_super: int,
    r_rows: int,
    s_rows: int,
    interpret: bool | None = None,
    mode: str = MODE_PUSH,
    pull_density: float = DEFAULT_PULL_DENSITY,
    with_stats: bool = False,
):
    """Cached jitted trace fn over one or more pair layouts (operand
    arrays per layout in ``device_args`` order, appended after
    flags/recv — and, for jump/auto modes, after the jump-parent
    operand)."""
    if interpret is None:
        interpret = default_interpret()
    key = (
        n, tuple(specs), n_super, r_rows, s_rows, interpret, _int8_mxu(),
        mode, pull_density, with_stats,
    )
    fn = _fn_cache.get(key)
    if fn is None:
        import time as _time

        t0 = _time.perf_counter()
        fn = _build_trace_fn_multi(
            n, tuple(specs), n_super, r_rows, s_rows, interpret,
            mode=mode, pull_density=pull_density, with_stats=with_stats,
        )
        _fn_cache[key] = fn
        if events.recorder.enabled:
            # Compile-cache plane (telemetry/device.py): per-wake misses
            # of one (tag, geom) stream are the recompile_storm input.
            events.recorder.commit(
                events.COMPILE, duration_s=_time.perf_counter() - t0,
                tag="trace_fn", geom=events.compile_geom(key), hit=False,
            )
    elif events.recorder.enabled:
        events.recorder.commit(
            events.COMPILE, tag="trace_fn",
            geom=events.compile_geom(key), hit=True,
        )
    return fn


def trace_marks_prepared(flags, recv_count, prep: Dict[str, np.ndarray]) -> np.ndarray:
    """Run the Pallas-backed trace against pre-packed pair arrays."""
    return trace_marks_layouts(flags, recv_count, [prep])


def trace_marks_layouts(
    flags,
    recv_count,
    preps,
    interpret: bool | None = None,
    mode: str = MODE_PUSH,
    pull_density: float = DEFAULT_PULL_DENSITY,
    jump_parent: np.ndarray | None = None,
    with_stats: bool = False,
):
    """Run the Pallas-backed trace against one or more pair layouts that
    share a node space (their per-node contributions are combined before
    thresholding, so the union of the layouts' pairs propagates).  The
    first layout must be a packed (non-xla) one; it pins the geometry.

    ``mode`` jump/auto requires ``jump_parent`` — the (n + 1,) min-source
    parent array over the SAME live pair set the layouts hold
    (jump_parents / IncrementalPallasLayout.jump_parent); a stale parent
    crossing a deleted pair would propagate marks along a dead edge."""
    first = preps[0]
    n = first["n"]
    assert "xla_src" not in first, "first layout pins the packed geometry"
    for p in preps[1:]:
        assert p["n"] == n, "layouts must share the node space"
        if "xla_src" not in p:
            assert (
                p["n_super"] == first["n_super"]
                and p["r_rows"] == first["r_rows"]
                and p["s_rows"] == first["s_rows"]
                and p["sub"] == first["sub"]
                and p["group"] == first["group"]
            ), "layouts must share node-space geometry"
    fn = get_trace_fn_multi(
        n,
        tuple(layout_spec(p) for p in preps),
        first["n_super"],
        first["r_rows"],
        first["s_rows"],
        interpret,
        mode=mode,
        pull_density=pull_density,
        with_stats=with_stats,
    )
    args = []
    if mode in (MODE_JUMP, MODE_AUTO):
        require(
            jump_parent is not None, "trace.jump_parent",
            "jump modes need the parent array", mode=mode,
        )
        args.append(jump_parent)
    for p in preps:
        args.extend(device_args(p))
    out = fn(flags[:n], recv_count[:n], *args)
    if with_stats:
        marks, stats = out
        return np.asarray(marks), {  # readback: host boundary: device marks -> np result contract
            k: np.asarray(v) for k, v in stats.items()  # readback: host boundary: device stats -> np result contract
        }
    return np.asarray(out)  # readback: host boundary: device marks -> np result contract


def trace_marks_pallas(
    flags, recv_count, supervisor, edge_src, edge_dst, edge_weight,
    mode: str = MODE_PUSH,
) -> np.ndarray:
    """Same contract as trace_marks_np/_jax, Pallas propagation inside."""
    n = flags.shape[0]
    prep = prepare_chunks(
        np.asarray(edge_src),  # readback: host-side graph layout prep (inputs are host arrays)
        np.asarray(edge_dst),  # readback: host-side graph layout prep (inputs are host arrays)
        np.asarray(edge_weight),  # readback: host-side graph layout prep (inputs are host arrays)
        np.asarray(supervisor),  # readback: host-side graph layout prep (inputs are host arrays)
        n,
    )
    jp = None
    if mode in (MODE_JUMP, MODE_AUTO):
        jp = jump_parents_from_graph(
            np.asarray(edge_src),  # readback: host-side jump-parent prep (inputs are host arrays)
            np.asarray(edge_dst),  # readback: host-side jump-parent prep (inputs are host arrays)
            np.asarray(edge_weight),  # readback: host-side jump-parent prep (inputs are host arrays)
            np.asarray(supervisor),  # readback: host-side jump-parent prep (inputs are host arrays)
            n,
        )
    return trace_marks_layouts(
        np.asarray(flags), np.asarray(recv_count), [prep], mode=mode,  # readback: host-side layout prep (inputs are host arrays)
        jump_parent=jp,
    )
