"""Pallas TPU kernel for the liveness-trace propagation step.

The trace (ops/trace.py) is an iterative frontier expansion whose inner op
is, per propagation pair (src, dst): OR the source's active bit into the
destination's mark.  XLA lowers both the gather of source bits and the
scatter into destinations to serialized per-element loops (~7 ns/edge
measured) — the bottleneck at graph scale.  This kernel vectorizes both
sides with the primitives the TPU VPU/MXU actually has:

**Gather side.**  The active bit-vector is packed into a 32-bit word table
``T[R, 128]`` that stays VMEM-resident across the whole sweep (128 KB per
1M actors).  Mosaic supports per-vreg dynamic shuffles
(``take_along_axis`` within an (8, 128) register: axis=1 lane-gather and
axis=0 sublane-gather) but nothing across vregs, so the kernel loops over
8-row table chunks with a two-step shuffle:

    g1[i, j] = chunk[i, lane_idx[i, j]]        (lane-gather)
    g2[i, j] = g1[row_sel[i, j], j]            (sublane-gather)
    word     = select(chunk hit, g2)

which yields, for the edge parked at slot (i, j), the word at
``(row_e, lane_e)`` provided the host placed it so that
``lane_idx[row_e % 8, j] == lane_e``.  The host-side packer (prepare_chunks)
bins each destination supertile's edges into columns with at most one edge
per (row_e mod 8) class per column, which makes that binding conflict-free
by construction; slots left empty get an out-of-range row so they read 0.

**Scatter side.**  Edges are pre-sorted by destination supertile (1024
nodes = one (8, 128) f32 output block).  Each block-row of 128 edge values
becomes a segment-sum via two in-register one-hot factors contracted on
the MXU:

    A_r[s, c] = vals[r, c] * (dst_sub[r, c] == s)       (8, 128)
    B_r[c, l] = (dst_lane[r, c] == l)                   (128, 128)
    contrib  += A_r @ B_r                               (8, 128)

The output BlockSpec revisits one supertile block per run of grid steps
via a scalar-prefetched supertile-id array, so accumulation happens in
VMEM and each block hits HBM exactly once per sweep.  Empty supertiles get
a dummy all-padding group so every output block is initialized.

Semantics are identical to ``trace_marks_np`` (the oracle for the
reference's ShadowGraph.java:205-289): supervisor pointers are folded in
as ordinary propagation pairs, sources gate on ``mark & ~halted``, and
only positive-weight edges propagate.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from . import trace as trace_ops

LANE = 128  # lanes per vreg row
ROWS = 8  # sublane rows per block
SUPER = ROWS * LANE  # destination nodes per output block / edges per group
WORD_BITS = 32
# Sentinel row for empty slots: beyond any table chunk, so they read 0.
_PAD_ROW = np.int32(1 << 28)


def prepare_chunks(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_weight: np.ndarray,
    supervisor: np.ndarray,
    n: int,
) -> Dict[str, np.ndarray]:
    """Host-side packer: place propagation pairs into kernel blocks.

    Rebuild whenever the edge set or supervisor pointers change (one
    lexsort of the live pairs, amortized across the trace's fixpoint
    iterations and across traces between graph mutations).
    """
    live = edge_weight > 0
    psrc = edge_src[live].astype(np.int64)
    pdst = edge_dst[live].astype(np.int64)
    sup_src = np.nonzero(supervisor >= 0)[0].astype(np.int64)
    if sup_src.size:
        psrc = np.concatenate([psrc, sup_src])
        pdst = np.concatenate([pdst, supervisor[sup_src].astype(np.int64)])

    n_super = max(1, -(-n // SUPER))
    n_pad = n_super * SUPER
    # Bit table geometry: R rows of 128 lanes of 32-bit words.
    n_words = -(-n_pad // WORD_BITS)
    r_rows = -(-n_words // LANE)
    r_rows = ((r_rows + ROWS - 1) // ROWS) * ROWS  # multiple of 8

    m = psrc.size
    word = psrc >> 5
    w_row = (word >> 7).astype(np.int32)
    w_lane = (word & 127).astype(np.int32)
    w_bit = (psrc & 31).astype(np.int32)
    d_super = (pdst // SUPER).astype(np.int64)
    d_local = (pdst % SUPER).astype(np.int64)
    r8 = (w_row & 7).astype(np.int64)

    # --- placement -----------------------------------------------------
    # Sort by (dst supertile, row%8 class); rank within each class gives
    # a (block-in-supertile, column) slot such that each column holds at
    # most one edge per class — the lane-binding is then conflict-free.
    order = np.lexsort((r8, d_super))
    psrc, w_row, w_lane, w_bit = (
        psrc[order],
        w_row[order],
        w_lane[order],
        w_bit[order],
    )
    d_super, d_local, r8 = d_super[order], d_local[order], r8[order]

    # rank of each edge within its (d_super, r8) class
    if m:
        key_change = np.ones(m, dtype=bool)
        key_change[1:] = (d_super[1:] != d_super[:-1]) | (r8[1:] != r8[:-1])
        start_idx = np.nonzero(key_change)[0]
        starts = np.repeat(start_idx, np.diff(np.append(start_idx, m)))
        rank = np.arange(m, dtype=np.int64) - starts
    else:
        rank = np.zeros(0, dtype=np.int64)

    # blocks needed per supertile = max over classes of ceil(class/128)
    blocks_needed = np.zeros(n_super, dtype=np.int64)
    if m:
        per_class_blocks = rank // LANE + 1
        np.maximum.at(
            blocks_needed, d_super, per_class_blocks
        )
    blocks_needed = np.maximum(blocks_needed, 1)  # dummy for empty supertiles

    n_blocks = int(blocks_needed.sum())
    block_base = np.zeros(n_super, dtype=np.int64)
    block_base[1:] = np.cumsum(blocks_needed)[:-1]

    if m:
        g_block = block_base[d_super] + rank // LANE
        col = rank % LANE
        # slot within (block, col): edges there have distinct r8; order by
        # r8 via a second pass
        slot_key = g_block * LANE + col
        order2 = np.lexsort((r8, slot_key))
        inv = np.empty(m, dtype=np.int64)
        sk_sorted = slot_key[order2]
        change2 = np.ones(m, dtype=bool)
        change2[1:] = sk_sorted[1:] != sk_sorted[:-1]
        start2 = np.nonzero(change2)[0]
        starts2 = np.repeat(start2, np.diff(np.append(start2, m)))
        slot_sorted = np.arange(m, dtype=np.int64) - starts2
        inv[order2] = slot_sorted
        slot = inv  # per-edge sublane slot in its (block, col)
    else:
        g_block = np.zeros(0, dtype=np.int64)
        col = np.zeros(0, dtype=np.int64)
        slot = np.zeros(0, dtype=np.int64)

    assert not m or slot.max() < ROWS, "placement overflow: >8 classes per column"

    # --- fill kernel arrays -------------------------------------------
    shape = (n_blocks * ROWS, LANE)
    row_pos = np.full(shape, _PAD_ROW, dtype=np.int32)
    lane_idx = np.zeros(shape, dtype=np.int32)
    bit_pos = np.zeros(shape, dtype=np.int32)
    dst_sub = np.zeros(shape, dtype=np.int32)
    dst_lane = np.zeros(shape, dtype=np.int32)

    if m:
        ri = g_block * ROWS + slot
        row_pos[ri, col] = w_row
        bit_pos[ri, col] = w_bit
        dst_sub[ri, col] = (d_local >> 7).astype(np.int32)
        dst_lane[ri, col] = (d_local & 127).astype(np.int32)
        # lane binding: consulted at (row_e % 8, col)
        li = g_block * ROWS + r8
        lane_idx[li, col] = w_lane

    block_super = np.repeat(
        np.arange(n_super, dtype=np.int32), blocks_needed
    )
    block_first = np.zeros(n_blocks, dtype=np.int32)
    block_first[block_base] = 1

    return {
        "row_pos": row_pos,
        "lane_idx": lane_idx,
        "bit_pos": bit_pos,
        "dst_sub": dst_sub,
        "dst_lane": dst_lane,
        "super": block_super,
        "first": block_first,
        "n_super": n_super,
        "n_blocks": n_blocks,
        "r_rows": r_rows,
        "n_pad": n_pad,
        "n": n,
    }


_fn_cache: Dict[tuple, object] = {}


def _build_trace_fn(
    n: int, n_blocks: int, n_super: int, r_rows: int, interpret: bool
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F = trace_ops
    n_chunks = r_rows // ROWS

    def kernel(
        sup_ref,
        first_ref,
        table_ref,
        row_ref,
        laneidx_ref,
        bit_ref,
        dsub_ref,
        dlane_ref,
        out_ref,
    ):
        i = pl.program_id(0)
        row_pos = row_ref[:]
        lane_idx = laneidx_ref[:]

        def chunk_body(c, acc):
            tab_c = table_ref[pl.ds(c * ROWS, ROWS), :]
            g1 = jnp.take_along_axis(tab_c, lane_idx, axis=1)
            row_rel = row_pos - c * ROWS
            row_sel = jnp.clip(row_rel, 0, ROWS - 1)
            g2 = jnp.take_along_axis(g1, row_sel, axis=0)
            hit = (row_rel >= 0) & (row_rel < ROWS)
            return jnp.where(hit, g2, acc)

        words = jax.lax.fori_loop(
            0, n_chunks, chunk_body, jnp.zeros((ROWS, LANE), jnp.int32)
        )
        bits = jax.lax.shift_right_logical(words, bit_ref[:]) & 1
        vals = bits.astype(jnp.float32)

        sub_iota = jax.lax.broadcasted_iota(jnp.int32, (ROWS, LANE), 0)
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 1)
        acc = jnp.zeros((ROWS, LANE), jnp.float32)
        for r in range(ROWS):
            vals_r = vals[r, :]
            a = jnp.where(sub_iota == dsub_ref[r, :][None, :], vals_r[None, :], 0.0)
            b = jnp.where(lane_iota == dlane_ref[r, :][:, None], 1.0, 0.0)
            acc = acc + jnp.dot(a, b, preferred_element_type=jnp.float32)

        @pl.when(first_ref[i] == 1)
        def _():
            out_ref[:] = acc

        @pl.when(first_ref[i] == 0)
        def _():
            out_ref[:] = out_ref[:] + acc

    blockmap = pl.BlockSpec((ROWS, LANE), lambda i, sup, first: (i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_blocks,),
        in_specs=[
            # bit table: whole array, VMEM-resident across all steps
            pl.BlockSpec((r_rows, LANE), lambda i, sup, first: (0, 0)),
            blockmap,  # row_pos
            blockmap,  # lane_idx
            blockmap,  # bit_pos
            blockmap,  # dst_sub
            blockmap,  # dst_lane
        ],
        out_specs=pl.BlockSpec((ROWS, LANE), lambda i, sup, first: (sup[i], 0)),
    )
    propagate = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_super * ROWS, LANE), jnp.float32),
        interpret=interpret,
    )

    n_pad = n_super * SUPER
    n_words_pad = r_rows * LANE

    def trace_fn(
        flags, recv_count, block_super, block_first, row_pos, lane_idx,
        bit_pos, dst_sub, dst_lane,
    ):
        in_use = (flags & F.FLAG_IN_USE) != 0
        halted = (flags & F.FLAG_HALTED) != 0
        seed = (
            ((flags & F.FLAG_ROOT) != 0)
            | ((flags & F.FLAG_BUSY) != 0)
            | (recv_count != 0)
            | ((flags & F.FLAG_INTERNED) == 0)
        )
        mark0 = in_use & (~halted) & seed

        shifts = jnp.arange(WORD_BITS, dtype=jnp.int32)

        def pack(active):
            a = jnp.zeros(n_words_pad * WORD_BITS, jnp.int32)
            a = a.at[:n].set(active.astype(jnp.int32))
            w = (a.reshape(-1, WORD_BITS) << shifts[None, :]).sum(
                axis=1, dtype=jnp.int32
            )
            return w.reshape(r_rows, LANE)

        def cond(carry):
            _, changed = carry
            return changed

        def body(carry):
            mark, _ = carry
            table = pack(mark & (~halted))
            contrib = propagate(
                block_super, block_first, table, row_pos, lane_idx,
                bit_pos, dst_sub, dst_lane,
            )
            hits = contrib.reshape(-1)[:n] > 0
            new_mark = mark | (hits & in_use)
            changed = jnp.any(new_mark != mark)
            return new_mark, changed

        mark, _ = jax.lax.while_loop(cond, body, (mark0, jnp.array(True)))
        return mark

    return jax.jit(trace_fn)


def get_trace_fn(prep: Dict[str, np.ndarray], interpret: bool | None = None):
    """Cached jitted trace fn for a prepared pair-array layout.

    ``interpret`` defaults to True off-TPU (Mosaic can't compile there)."""
    import jax

    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    key = (prep["n"], prep["n_blocks"], prep["n_super"], prep["r_rows"], interpret)
    fn = _fn_cache.get(key)
    if fn is None:
        fn = _build_trace_fn(
            prep["n"], prep["n_blocks"], prep["n_super"], prep["r_rows"], interpret
        )
        _fn_cache[key] = fn
    return fn


def trace_marks_prepared(flags, recv_count, prep: Dict[str, np.ndarray]) -> np.ndarray:
    """Run the Pallas-backed trace against pre-packed pair arrays."""
    n = prep["n"]
    fn = get_trace_fn(prep)
    out = fn(
        flags[:n],
        recv_count[:n],
        prep["super"],
        prep["first"],
        prep["row_pos"],
        prep["lane_idx"],
        prep["bit_pos"],
        prep["dst_sub"],
        prep["dst_lane"],
    )
    return np.asarray(out)


def trace_marks_pallas(
    flags, recv_count, supervisor, edge_src, edge_dst, edge_weight
) -> np.ndarray:
    """Same contract as trace_marks_np/_jax, Pallas propagation inside."""
    n = flags.shape[0]
    prep = prepare_chunks(
        np.asarray(edge_src),
        np.asarray(edge_dst),
        np.asarray(edge_weight),
        np.asarray(supervisor),
        n,
    )
    return trace_marks_prepared(np.asarray(flags), np.asarray(recv_count), prep)
