"""Pallas TPU kernel for the liveness-trace propagation step.

The trace (ops/trace.py) is an iterative frontier expansion whose inner op
is, per propagation pair (src, dst): OR the source's active bit into the
destination's mark.  XLA lowers both the gather of source bits and the
scatter into destinations to serialized per-element loops (~7 ns/edge
measured) — the bottleneck at graph scale.  This kernel vectorizes both
sides with the primitives the TPU VPU/MXU actually has:

**Gather side.**  The active bit-vector is packed into a 32-bit word table
``T[R, 128]`` that stays VMEM-resident across the whole sweep (128 KB per
1M actors).  Mosaic supports per-vreg dynamic lane shuffles
(``take_along_axis`` within an (8, 128) register) but nothing across
vregs, so each grid step walks 8-row table chunks.  Two layout invariants
make the walk cheap:

1. *Slot row = source row mod 8.*  An edge whose source bit lives at table
   position (row_e, lane_e) is parked at slot ``(row_e % 8, col)``, so
   when the walk reaches the edge's chunk a single lane-gather
   ``take_along_axis(chunk, lane, axis=1)`` lands the right word at the
   edge's own slot — no cross-sublane shuffle, no slot/lane binding table.
   Uniqueness (one edge per (chunk-row-class, col) pair) is guaranteed by
   the host packer, which ranks edges within each (dst supertile,
   row-class) group and assigns col = rank mod 128.

2. *Per-block chunk ranges.*  Within each (dst supertile, row-class)
   group the packer sorts edges by source row, so the 128-edge runs that
   land in one block cover a narrow, contiguous band of the table.  The
   block's ``[c_lo, c_lo + span)`` range is scalar-prefetched and the
   kernel's chunk loop walks only that band — total chunk-iterations per
   sweep are O(n_super · n_chunks + n_blocks), not O(n_blocks · n_chunks),
   which is what lets the kernel scale to 10M+ actors.

**Scatter side.**  Edges are pre-sorted by destination supertile
(``SUPER = S_ROWS * 128`` nodes = one (S_ROWS, 128) f32 output block).
The block's 8x128 gathered bits become a segment-sum via one fused one-hot
contraction on the MXU:

    A[s, r*128+c] = vals[r, c] * (dst_sub[r, c] == s)     (S_ROWS, 1024)
    B[r*128+c, l] = (dst_lane[r, c] == l)                 (1024, 128)
    contrib      += A @ B                                 (S_ROWS, 128)

A and B are 0/1 so bf16 inputs with f32 accumulation are exact, doubling
MXU rate.  The output BlockSpec revisits one supertile block per run of
grid steps via a scalar-prefetched supertile-id, so accumulation happens
in VMEM and each block hits HBM exactly once per sweep.  Empty supertiles
get a dummy all-padding group so every output block is initialized.

Per-edge metadata is packed into two int32 arrays (source row; and
lane|bit|dst_lane|dst_sub) to halve HBM streaming per sweep.

Semantics are identical to ``trace_marks_np`` (the oracle for the
reference's ShadowGraph.java:205-289): supervisor pointers are folded in
as ordinary propagation pairs, sources gate on ``mark & ~halted``, and
only positive-weight edges propagate.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from . import trace as trace_ops

LANE = 128  # lanes per vreg row
ROWS = 8  # sublane rows per edge-slot block (8 * 128 edge slots per step)
WORD_BITS = 32
S_ROWS = 8  # default output sublane rows per block (s_rows * 128 dst nodes)
# Sentinel row for empty slots: beyond any table chunk, so they never hit.
_PAD_ROW = np.int32(1 << 28)
_SPAN_BITS = 12  # chunk index / span fit in 12 bits up to ~134M actors


def prepare_chunks(
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_weight: np.ndarray,
    supervisor: np.ndarray,
    n: int,
    s_rows: int = S_ROWS,
    pad_blocks_pow2: bool = False,
) -> Dict[str, np.ndarray]:
    """Host-side packer: place propagation pairs into kernel blocks.

    Rebuild whenever the edge set or supervisor pointers change (one
    lexsort of the live pairs, amortized across the trace's fixpoint
    iterations and across traces between graph mutations).

    ``pad_blocks_pow2`` rounds the block count up to a power of two with
    inert padding blocks (they re-accumulate zeros into the last
    supertile), so a live, mutating graph triggers at most log-many
    kernel recompiles instead of one per edge-set change.
    """
    assert 1 <= s_rows <= 32, "dst_sub is packed in 5 bits"
    super_sz = s_rows * LANE
    live = edge_weight > 0
    psrc = edge_src[live].astype(np.int64)
    pdst = edge_dst[live].astype(np.int64)
    sup_src = np.nonzero(supervisor >= 0)[0].astype(np.int64)
    if sup_src.size:
        psrc = np.concatenate([psrc, sup_src])
        pdst = np.concatenate([pdst, supervisor[sup_src].astype(np.int64)])

    n_super = max(1, -(-n // super_sz))
    n_pad = n_super * super_sz
    # Bit table geometry: R rows of 128 lanes of 32-bit words.
    n_words = -(-n_pad // WORD_BITS)
    r_rows = -(-n_words // LANE)
    r_rows = ((r_rows + ROWS - 1) // ROWS) * ROWS  # multiple of 8
    assert r_rows // ROWS < (1 << _SPAN_BITS), "graph too large for span packing"

    m = psrc.size
    word = psrc >> 5
    w_row = (word >> 7).astype(np.int32)
    w_lane = (word & 127).astype(np.int32)
    w_bit = (psrc & 31).astype(np.int32)
    d_super = (pdst // super_sz).astype(np.int64)
    d_local = (pdst % super_sz).astype(np.int64)
    r8 = (w_row & 7).astype(np.int64)

    # --- placement -----------------------------------------------------
    # Sort by (dst supertile, row%8 class, source row); rank within each
    # class gives (block-in-supertile, column) such that each column holds
    # at most one edge per class — the slot row can then be the class
    # itself — and each block's 128-edge runs are source-sorted, keeping
    # its table-chunk span narrow.
    order = np.lexsort((w_row, r8, d_super))
    w_row, w_lane, w_bit = w_row[order], w_lane[order], w_bit[order]
    d_super, d_local, r8 = d_super[order], d_local[order], r8[order]

    # rank of each edge within its (d_super, r8) class
    if m:
        key_change = np.ones(m, dtype=bool)
        key_change[1:] = (d_super[1:] != d_super[:-1]) | (r8[1:] != r8[:-1])
        start_idx = np.nonzero(key_change)[0]
        starts = np.repeat(start_idx, np.diff(np.append(start_idx, m)))
        rank = np.arange(m, dtype=np.int64) - starts
    else:
        rank = np.zeros(0, dtype=np.int64)

    # blocks needed per supertile = max over classes of ceil(class/128)
    blocks_needed = np.zeros(n_super, dtype=np.int64)
    if m:
        np.maximum.at(blocks_needed, d_super, rank // LANE + 1)
    blocks_needed = np.maximum(blocks_needed, 1)  # dummy for empty supertiles

    n_blocks = int(blocks_needed.sum())
    block_base = np.zeros(n_super, dtype=np.int64)
    block_base[1:] = np.cumsum(blocks_needed)[:-1]

    # --- fill kernel arrays -------------------------------------------
    shape = (n_blocks * ROWS, LANE)
    row_pos = np.full(shape, _PAD_ROW, dtype=np.int32)
    emeta = np.zeros(shape, dtype=np.int32)

    if m:
        g_block = block_base[d_super] + rank // LANE
        col = rank % LANE
        ri = g_block * ROWS + r8  # slot row = source row mod 8
        row_pos[ri, col] = w_row
        emeta[ri, col] = (
            w_lane
            | (w_bit << 7)
            | ((d_local & 127).astype(np.int32) << 12)
            | ((d_local >> 7).astype(np.int32) << 19)
        )
        # per-block table-chunk range
        chunk = (w_row >> 3).astype(np.int64)
        c_lo = np.full(n_blocks, 1 << 30, dtype=np.int64)
        c_hi = np.zeros(n_blocks, dtype=np.int64)
        np.minimum.at(c_lo, g_block, chunk)
        np.maximum.at(c_hi, g_block, chunk + 1)
        empty = c_lo > c_hi
        c_lo[empty] = 0
        c_hi[empty] = 0
    else:
        c_lo = np.zeros(n_blocks, dtype=np.int64)
        c_hi = np.zeros(n_blocks, dtype=np.int64)

    span = c_hi - c_lo
    assert span.max(initial=0) < (1 << _SPAN_BITS)

    block_super = np.repeat(np.arange(n_super, dtype=np.int64), blocks_needed)
    block_first = np.zeros(n_blocks, dtype=np.int64)
    block_first[block_base] = 1

    if pad_blocks_pow2:
        padded = 1 << max(0, int(n_blocks - 1).bit_length())
        if padded > n_blocks:
            extra = padded - n_blocks
            # Inert blocks: span 0 (no gather), accumulate zeros into the
            # last supertile (keeps output revisits consecutive).
            block_super = np.concatenate(
                [block_super, np.full(extra, n_super - 1, dtype=np.int64)]
            )
            block_first = np.concatenate(
                [block_first, np.zeros(extra, dtype=np.int64)]
            )
            c_lo = np.concatenate([c_lo, np.zeros(extra, dtype=np.int64)])
            span = np.concatenate([span, np.zeros(extra, dtype=np.int64)])
            row_pos = np.concatenate(
                [row_pos, np.full((extra * ROWS, LANE), _PAD_ROW, np.int32)]
            )
            emeta = np.concatenate(
                [emeta, np.zeros((extra * ROWS, LANE), np.int32)]
            )
            n_blocks = padded

    # meta1 = supertile id | first-visit bit; meta2 = chunk range
    bmeta1 = (block_super << 1 | block_first).astype(np.int32)
    bmeta2 = (c_lo << _SPAN_BITS | span).astype(np.int32)

    return {
        "row_pos": row_pos,
        "emeta": emeta,
        "bmeta1": bmeta1,
        "bmeta2": bmeta2,
        "n_super": n_super,
        "n_blocks": n_blocks,
        "r_rows": r_rows,
        "n_pad": n_pad,
        "n": n,
        "s_rows": s_rows,
    }


def device_args(prep: Dict[str, np.ndarray]) -> tuple:
    """The kernel operands (after flags/recv) in call order."""
    return (prep["bmeta1"], prep["bmeta2"], prep["row_pos"], prep["emeta"])


_fn_cache: Dict[tuple, object] = {}


def _build_trace_fn(
    n: int, n_blocks: int, n_super: int, r_rows: int, s_rows: int, interpret: bool
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F = trace_ops

    def kernel(meta1_ref, meta2_ref, table_ref, row_ref, emeta_ref, out_ref):
        i = pl.program_id(0)
        m2 = meta2_ref[i]
        c_lo = jax.lax.shift_right_logical(m2, _SPAN_BITS)
        span = m2 & ((1 << _SPAN_BITS) - 1)

        row_pos = row_ref[:]
        emeta = emeta_ref[:]
        lane_idx = emeta & 127
        bit_pos = (emeta >> 7) & 31
        dst_lane = (emeta >> 12) & 127
        dst_sub = (emeta >> 19) & 31
        row_iota = jax.lax.broadcasted_iota(jnp.int32, (ROWS, LANE), 0)

        def chunk_body(k, acc):
            c = c_lo + k
            tab_c = table_ref[pl.ds(c * ROWS, ROWS), :]
            g = jnp.take_along_axis(tab_c, lane_idx, axis=1)
            hit = (row_pos - c * ROWS) == row_iota
            return jnp.where(hit, g, acc)

        words = jax.lax.fori_loop(
            0, span, chunk_body, jnp.zeros((ROWS, LANE), jnp.int32)
        )
        bits = jax.lax.shift_right_logical(words, bit_pos) & 1
        vals = bits.astype(jnp.bfloat16)

        # Fused one-hot segment-sum on the MXU: one (s_rows, 1024) @
        # (1024, 128) contraction per block.
        sub_iota = jax.lax.broadcasted_iota(jnp.int32, (s_rows, LANE), 0)
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 1)
        zero_a = jnp.zeros((s_rows, LANE), jnp.bfloat16)
        a_parts = []
        b_parts = []
        for r in range(ROWS):
            a_parts.append(
                jnp.where(sub_iota == dst_sub[r, :][None, :], vals[r, :][None, :], zero_a)
            )
            b_parts.append(
                (lane_iota == dst_lane[r, :][:, None]).astype(jnp.bfloat16)
            )
        a = jnp.concatenate(a_parts, axis=1)  # (s_rows, ROWS*LANE)
        b = jnp.concatenate(b_parts, axis=0)  # (ROWS*LANE, LANE)
        acc = jnp.dot(a, b, preferred_element_type=jnp.float32)

        @pl.when((meta1_ref[i] & 1) == 1)
        def _():
            out_ref[:] = acc

        @pl.when((meta1_ref[i] & 1) == 0)
        def _():
            out_ref[:] = out_ref[:] + acc

    blockmap = pl.BlockSpec((ROWS, LANE), lambda i, m1, m2: (i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_blocks,),
        in_specs=[
            # bit table: whole array, VMEM-resident across all steps
            pl.BlockSpec((r_rows, LANE), lambda i, m1, m2: (0, 0)),
            blockmap,  # row_pos
            blockmap,  # emeta
        ],
        out_specs=pl.BlockSpec(
            (s_rows, LANE), lambda i, m1, m2: (m1[i] >> 1, 0)
        ),
    )
    propagate = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_super * s_rows, LANE), jnp.float32),
        interpret=interpret,
    )

    n_words_pad = r_rows * LANE

    def trace_fn(flags, recv_count, bmeta1, bmeta2, row_pos, emeta):
        in_use = (flags & F.FLAG_IN_USE) != 0
        halted = (flags & F.FLAG_HALTED) != 0
        seed = (
            ((flags & F.FLAG_ROOT) != 0)
            | ((flags & F.FLAG_BUSY) != 0)
            | (recv_count != 0)
            | ((flags & F.FLAG_INTERNED) == 0)
        )
        mark0 = in_use & (~halted) & seed

        shifts = jnp.arange(WORD_BITS, dtype=jnp.int32)

        def pack(active):
            a = jnp.zeros(n_words_pad * WORD_BITS, jnp.int32)
            a = a.at[:n].set(active.astype(jnp.int32))
            w = (a.reshape(-1, WORD_BITS) << shifts[None, :]).sum(
                axis=1, dtype=jnp.int32
            )
            return w.reshape(r_rows, LANE)

        def cond(carry):
            _, changed = carry
            return changed

        def body(carry):
            mark, _ = carry
            table = pack(mark & (~halted))
            contrib = propagate(bmeta1, bmeta2, table, row_pos, emeta)
            hits = contrib.reshape(-1)[:n] > 0
            new_mark = mark | (hits & in_use)
            changed = jnp.any(new_mark != mark)
            return new_mark, changed

        mark, _ = jax.lax.while_loop(cond, body, (mark0, jnp.array(True)))
        return mark

    return jax.jit(trace_fn)


def get_trace_fn(prep: Dict[str, np.ndarray], interpret: bool | None = None):
    """Cached jitted trace fn for a prepared pair-array layout.

    ``interpret`` defaults to True off-TPU (Mosaic can't compile there)."""
    import jax

    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    key = (
        prep["n"],
        prep["n_blocks"],
        prep["n_super"],
        prep["r_rows"],
        prep["s_rows"],
        interpret,
    )
    fn = _fn_cache.get(key)
    if fn is None:
        fn = _build_trace_fn(
            prep["n"],
            prep["n_blocks"],
            prep["n_super"],
            prep["r_rows"],
            prep["s_rows"],
            interpret,
        )
        _fn_cache[key] = fn
    return fn


def trace_marks_prepared(flags, recv_count, prep: Dict[str, np.ndarray]) -> np.ndarray:
    """Run the Pallas-backed trace against pre-packed pair arrays."""
    n = prep["n"]
    fn = get_trace_fn(prep)
    out = fn(flags[:n], recv_count[:n], *device_args(prep))
    return np.asarray(out)


def trace_marks_pallas(
    flags, recv_count, supervisor, edge_src, edge_dst, edge_weight
) -> np.ndarray:
    """Same contract as trace_marks_np/_jax, Pallas propagation inside."""
    n = flags.shape[0]
    prep = prepare_chunks(
        np.asarray(edge_src),
        np.asarray(edge_dst),
        np.asarray(edge_weight),
        np.asarray(supervisor),
        n,
    )
    return trace_marks_prepared(np.asarray(flags), np.asarray(recv_count), prep)
