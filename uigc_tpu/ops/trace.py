"""The liveness trace as array kernels: masked label propagation to fixpoint.

This is the TPU-native re-design of the reference's pointer-chasing BFS
(reference: ShadowGraph.java:205-289).  The shadow graph lives as dense
node-feature arrays plus a COO edge list; one trace is an iterative
frontier expansion:

    mark    <- pseudoroot(flags, recv_count)
    repeat: mark |= scatter_or(mark[src] & ~halted[src] & (w > 0) -> dst)
            mark |= scatter_or(mark & ~halted -> supervisor)
    until fixpoint

Semantics must match the oracle exactly:
- pseudoroot = (root | busy | recv_count != 0 | ~interned) & ~halted
  (reference: ShadowGraph.java:201-203)
- only edges with positive net count propagate
  (reference: ShadowGraph.java:231-241)
- halted actors neither seed nor propagate, but may be marked
  (reference: ShadowGraph.java:226-229)
- supervisors of marked, non-halted actors are marked
  (reference: ShadowGraph.java:242-267)

Two implementations with identical semantics: numpy (host fallback and
oracle for differential tests) and JAX (jit-compiled; static shapes, so
buffers are padded to capacity and recompiles happen only on capacity
doubling).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# Node flag bits (shared by host and device code).
FLAG_ROOT = np.uint8(1)
FLAG_BUSY = np.uint8(2)
FLAG_INTERNED = np.uint8(4)
FLAG_LOCAL = np.uint8(8)
FLAG_HALTED = np.uint8(16)
FLAG_IN_USE = np.uint8(32)


def pseudoroots_np(flags: np.ndarray, recv_count: np.ndarray) -> np.ndarray:
    """(reference: ShadowGraph.java:201-203)"""
    in_use = (flags & FLAG_IN_USE) != 0
    not_halted = (flags & FLAG_HALTED) == 0
    seed = (
        ((flags & FLAG_ROOT) != 0)
        | ((flags & FLAG_BUSY) != 0)
        | (recv_count != 0)
        | ((flags & FLAG_INTERNED) == 0)
    )
    return in_use & not_halted & seed


def trace_marks_np(
    flags: np.ndarray,
    recv_count: np.ndarray,
    supervisor: np.ndarray,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_weight: np.ndarray,
) -> np.ndarray:
    """Host (numpy) mark fixpoint.  Returns a bool[N] mark vector."""
    n = flags.shape[0]
    in_use = (flags & FLAG_IN_USE) != 0
    halted = (flags & FLAG_HALTED) != 0
    mark = pseudoroots_np(flags, recv_count)

    live_edge = edge_weight > 0
    esrc = edge_src[live_edge]
    edst = edge_dst[live_edge]

    has_sup = supervisor >= 0
    sup_src = np.nonzero(has_sup)[0]
    sup_dst = supervisor[sup_src]

    while True:
        active = mark & ~halted
        new_mark = mark.copy()
        # Edge propagation: dst gets marked if any active src points at it.
        if esrc.size:
            hits = edst[active[esrc]]
            new_mark[hits] = True
        # Supervisor marking.
        if sup_src.size:
            sup_hits = sup_dst[active[sup_src]]
            new_mark[sup_hits] = True
        new_mark &= in_use  # never mark free slots
        if np.array_equal(new_mark, mark):
            return mark
        mark = new_mark


def trace_marks_np_parents(
    flags: np.ndarray,
    recv_count: np.ndarray,
    supervisor: np.ndarray,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    edge_weight: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Host mark fixpoint that additionally records the marking-parent
    array: ``parent[i]`` is the slot whose propagation first marked
    ``i`` (the minimum such source within the marking sweep, matching
    the device variant's scatter-min), or ``-1`` for pseudoroot seeds
    and unmarked slots.  Marks are bit-identical to
    :func:`trace_marks_np`; parents form an acyclic forest rooted at
    the seeds — the raw material of a why-live retaining path
    (telemetry/inspect.py).  A separate entry point, not a flag on the
    plain trace, so the no-capture wake path pays nothing."""
    n = flags.shape[0]
    in_use = (flags & FLAG_IN_USE) != 0
    halted = (flags & FLAG_HALTED) != 0
    mark = pseudoroots_np(flags, recv_count)
    parent = np.full(n, -1, dtype=np.int64)

    live_edge = edge_weight > 0
    esrc = edge_src[live_edge].astype(np.int64)
    edst = edge_dst[live_edge].astype(np.int64)

    has_sup = supervisor >= 0
    sup_src = np.nonzero(has_sup)[0]
    sup_dst = supervisor[sup_src].astype(np.int64)

    while True:
        active = mark & ~halted
        cand = np.full(n, n, dtype=np.int64)
        if esrc.size:
            hit = active[esrc]
            np.minimum.at(cand, edst[hit], esrc[hit])
        if sup_src.size:
            hit = active[sup_src]
            np.minimum.at(cand, sup_dst[hit], sup_src[hit])
        newly = (cand < n) & ~mark & in_use
        if not newly.any():
            return mark, parent
        parent[newly] = cand[newly]
        mark = mark | newly


# --------------------------------------------------------------------- #
# JAX implementation
# --------------------------------------------------------------------- #

_jax_trace_cache = {}


def _build_jax_trace():
    import jax
    import jax.numpy as jnp

    def trace_marks(flags, recv_count, supervisor, edge_src, edge_dst, edge_weight):
        n = flags.shape[0]
        in_use = (flags & FLAG_IN_USE) != 0
        halted = (flags & FLAG_HALTED) != 0
        seed = (
            ((flags & FLAG_ROOT) != 0)
            | ((flags & FLAG_BUSY) != 0)
            | (recv_count != 0)
            | ((flags & FLAG_INTERNED) == 0)
        )
        mark0 = in_use & (~halted) & seed

        live_edge = edge_weight > 0
        # Free/dead edges scatter into a sink slot (index n).
        edst = jnp.where(live_edge, edge_dst, n)
        esrc = jnp.where(live_edge, edge_src, n)
        sup_dst = jnp.where(supervisor >= 0, supervisor, n)

        def cond(carry):
            mark, changed = carry
            return changed

        def body(carry):
            mark, _ = carry
            active = mark & (~halted)
            active_pad = jnp.concatenate([active, jnp.zeros((1,), bool)])
            # Edge propagation via scatter-max of the source's active bit.
            src_active = active_pad[esrc]
            prop = (
                jnp.zeros((n + 1,), dtype=jnp.int32)
                .at[edst]
                .max(src_active.astype(jnp.int32))
            )
            # Supervisor marking.
            prop = prop.at[sup_dst].max(active.astype(jnp.int32))
            new_mark = mark | (prop[:n] > 0)
            new_mark = new_mark & in_use
            changed = jnp.any(new_mark != mark)
            return new_mark, changed

        mark, _ = jax.lax.while_loop(cond, body, (mark0, jnp.array(True)))
        return mark

    return jax.jit(trace_marks)


def trace_marks_jax(
    flags, recv_count, supervisor, edge_src, edge_dst, edge_weight
):
    """Device (JAX) mark fixpoint.  Same contract as :func:`trace_marks_np`.
    Shapes are static; pad buffers to capacity and keep capacity stable to
    avoid recompiles."""
    if "fn" not in _jax_trace_cache:
        _jax_trace_cache["fn"] = _build_jax_trace()
    fn = _jax_trace_cache["fn"]
    import numpy as _np

    out = fn(flags, recv_count, supervisor, edge_src, edge_dst, edge_weight)
    return _np.asarray(out)  # readback: host boundary: device marks -> np result contract


def garbage_and_kills_np(
    flags: np.ndarray, supervisor: np.ndarray, mark: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Post-trace sweep decisions (reference: ShadowGraph.java:273-284).

    Returns (garbage, kill): ``garbage`` = in-use and unmarked;
    ``kill`` = garbage that is local, not halted, and whose supervisor is
    marked — the oldest unmarked ancestors; the runtime's stop cascade
    takes down their subtrees."""
    in_use = (flags & FLAG_IN_USE) != 0
    garbage = in_use & ~mark
    local = (flags & FLAG_LOCAL) != 0
    not_halted = (flags & FLAG_HALTED) == 0
    sup_ok = supervisor >= 0
    sup_idx = np.where(sup_ok, supervisor, 0)
    sup_marked = mark[sup_idx] & sup_ok
    kill = garbage & local & not_halted & sup_marked
    return garbage, kill
