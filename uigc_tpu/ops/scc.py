"""Strongly-connected components as array kernels (device SCC).

The MAC engine's cycle detector needs the SCCs of the blocked-actor
reference graph.  The reference ships only a stub detector
(reference: src/main/resources/reference.conf:48, mac/CycleDetector.scala:42-97);
ours completes it with host-side Tarjan (engines/mac/detector.py), and
this module provides the TPU-scalable alternative the build plan calls
for: SCC by iterative forward-backward label propagation ("coloring"
SCC), which is nothing but the trace kernel's propagation pattern run in
both directions — static shapes, ``lax.while_loop`` fixpoints, scatter-max
inner ops that XLA maps onto the same machinery as the liveness trace.

Algorithm (FB-MAX coloring):

1. color[v] := max over nodes u that can reach v (forward max-propagation
   to fixpoint, restricted to unassigned nodes).
2. pivots are nodes with color[v] == v; each color class has exactly one.
3. backward-propagate reachability from each pivot within its own color
   class; every node reached belongs to the pivot's SCC.
4. assign those nodes, repeat on the rest.  Each round assigns at least
   one whole SCC, so the outer loop terminates in <= #SCC rounds.

Labels are the pivot node ids.  ``scc_labels_np`` is the Tarjan oracle
with identically-normalized labels for differential testing.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def scc_labels_np(
    n: int,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    active: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Tarjan oracle.  Returns int32[n] labels; nodes in the same SCC get
    the same label (the max member id, matching the device kernel);
    inactive nodes get their own id."""
    labels = np.arange(n, dtype=np.int32)
    if active is None:
        active = np.ones(n, dtype=bool)
    adj: Dict[int, list] = {}
    for s, d in zip(edge_src.tolist(), edge_dst.tolist()):
        if 0 <= s < n and 0 <= d < n and active[s] and active[d]:
            adj.setdefault(s, []).append(d)

    index_of: Dict[int, int] = {}
    lowlink: Dict[int, int] = {}
    on_stack = set()
    stack: list = []
    counter = [0]

    for root in range(n):
        if not active[root] or root in index_of:
            continue
        work = [(root, iter(adj.get(root, ())))]
        index_of[root] = lowlink[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in index_of:
                    index_of[succ] = lowlink[succ] = counter[0]
                    counter[0] += 1
                    stack.append(succ)
                    on_stack.add(succ)
                    work.append((succ, iter(adj.get(succ, ()))))
                    advanced = True
                    break
                elif succ in on_stack:
                    lowlink[node] = min(lowlink[node], index_of[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index_of[node]:
                members = []
                while True:
                    member = stack.pop()
                    on_stack.discard(member)
                    members.append(member)
                    if member is node or member == node:
                        break
                rep = max(members)
                for member in members:
                    labels[member] = rep
    return labels


_fn_cache: Dict[tuple, object] = {}


def _build_scc_fn(n: int, m: int):
    import jax
    import jax.numpy as jnp

    sink = n  # scatter target for masked-out edges

    def scc(edge_src, edge_dst, active):
        iota = jnp.arange(n, dtype=jnp.int32)
        # Edge endpoint validity is fixed for the whole run.
        evalid = (
            (edge_src >= 0)
            & (edge_src < n)
            & (edge_dst >= 0)
            & (edge_dst < n)
            & active[jnp.clip(edge_src, 0, n - 1)]
            & active[jnp.clip(edge_dst, 0, n - 1)]
        )
        esrc = jnp.where(evalid, edge_src, 0)
        edst = jnp.where(evalid, edge_dst, 0)

        labels0 = iota  # inactive nodes keep their own id
        assigned0 = ~active

        def any_unassigned(carry):
            _, assigned = carry
            return jnp.any(~assigned)

        def round_body(carry):
            labels, assigned = carry
            live_edge = evalid & (~assigned[esrc]) & (~assigned[edst])
            dst_or_sink = jnp.where(live_edge, edst, sink)
            src_or_sink = jnp.where(live_edge, esrc, sink)

            # 1. forward max-propagation of node ids.
            color0 = jnp.where(assigned, -1, iota)

            def fwd_cond(c):
                _, changed = c
                return changed

            def fwd_body(c):
                color, _ = c
                color_pad = jnp.concatenate([color, jnp.full((1,), -1, jnp.int32)])
                prop = (
                    jnp.full((n + 1,), -1, jnp.int32)
                    .at[dst_or_sink]
                    .max(color_pad[src_or_sink])
                )[:n]
                new = jnp.where(assigned, color, jnp.maximum(color, prop))
                return new, jnp.any(new != color)

            color, _ = jax.lax.while_loop(
                fwd_cond, fwd_body, (color0, jnp.array(True))
            )

            # 2-3. backward reach from pivots within each color class.
            reach0 = (color == iota) & (~assigned)

            def bwd_cond(c):
                _, changed = c
                return changed

            def bwd_body(c):
                reach, _ = c
                reach_pad = jnp.concatenate([reach, jnp.zeros((1,), bool)])
                same_color = color[esrc] == color[edst]
                hit = reach_pad[dst_or_sink] & same_color
                prop = (
                    jnp.zeros((n + 1,), jnp.int32)
                    .at[src_or_sink]
                    .max(hit.astype(jnp.int32))
                )[:n]
                new = reach | ((prop > 0) & (~assigned))
                return new, jnp.any(new != reach)

            reach, _ = jax.lax.while_loop(
                bwd_cond, bwd_body, (reach0, jnp.array(True))
            )

            labels = jnp.where(reach, color, labels)
            assigned = assigned | reach
            return labels, assigned

        labels, _ = jax.lax.while_loop(
            any_unassigned, round_body, (labels0, assigned0)
        )
        return labels

    return jax.jit(scc)


def scc_labels_jax(
    n: int,
    edge_src: np.ndarray,
    edge_dst: np.ndarray,
    active: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Device SCC labels; same contract as :func:`scc_labels_np`.  Shapes
    are static per (n, m); pad the edge list and keep capacities stable to
    avoid recompiles (invalid endpoints, e.g. -1 padding, are ignored)."""
    if active is None:
        active = np.ones(n, dtype=bool)
    m = int(edge_src.shape[0])
    key = (n, m)
    fn = _fn_cache.get(key)
    if fn is None:
        fn = _fn_cache[key] = _build_scc_fn(n, m)
    out = fn(
        np.asarray(edge_src, dtype=np.int32),
        np.asarray(edge_dst, dtype=np.int32),
        np.asarray(active, dtype=bool),
    )
    return np.asarray(out)  # readback: host boundary: device SCC labels -> np result contract
