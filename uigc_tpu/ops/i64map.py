"""Vectorized open-addressing int64 -> int64 hash map.

The shadow graph's edge map (``owner << 32 | target`` -> edge id) is the
last Python dict on the collector's fold path: a drained batch can carry
hundreds of thousands of unique edge keys, and ``dict.get`` per key costs
more than the entire vectorized scatter-apply it feeds
(profile: ~70% of `_apply_edge_deltas` time).  This map keeps keys and
values in flat numpy arrays and probes a whole batch per step, so a
600k-key lookup is a handful of gathers instead of 600k interpreter
round-trips.

Linear probing over a power-of-two table with a multiplicative
(splitmix-style) hash.  Batch inserts use scatter-and-verify: colliding
keys that lose a claimed slot simply continue probing — the standard
GPU-hash-building technique, which maps exactly onto numpy scatters.

Keys must be non-negative (bit 63 clear); -1 marks an empty slot and -2
a tombstone.  Scalar dict-compatible operations (`get`/`pop`/`[]`/`in`/
`items`) are provided for the non-batch paths and the tests.
"""

from __future__ import annotations

import ctypes
from typing import Iterator, Optional, Tuple

import numpy as np

_C_I64_P = ctypes.POINTER(ctypes.c_int64)

EMPTY = -1
TOMBSTONE = -2

_MULT = np.uint64(0x9E3779B97F4A7C15)
_SHIFT = np.uint64(29)

#: native probe kernels (uigc_tpu/native/crgc_shadow.cpp): serial C
#: loops beat the numpy scatter-and-verify rounds once batches are big
#: enough to amortize the call.  None = not probed yet, False = no
#: toolchain (pure-numpy fallback).  The C side uses the identical hash
#: and probe order, so both sides can operate on the same table.
_native = None
_NATIVE_MIN_BATCH = 64


def _native_lib():
    global _native
    if _native is None:
        try:
            from ..native import load

            _native = load()
        except Exception:
            _native = False
    return _native or None


def _native_lib_checked():
    """Load + one-time hash-equivalence check: the C probes MUST agree
    with _h_batch/_h_scalar on every slot choice (both sides operate on
    the same table), so a retuned _MULT/_SHIFT here must refuse the
    native path rather than silently mis-probe."""
    lib = _native_lib()
    if lib is None:
        return None
    global _native
    if not getattr(_native_lib_checked, "_verified", False):
        probe = np.array([0, 1, 0x7FFF_FFFF_FFFF_FFFF, 12345678901], np.int64)
        mask = np.int64(1023)
        expect = ((probe.astype(np.uint64) * _MULT) >> _SHIFT).astype(
            np.int64
        ) & mask
        tab = np.full(1024, EMPTY, dtype=np.int64)
        vals = np.arange(1024, dtype=np.int64)
        # the four probe keys hash to distinct slots at mask 1023, so a
        # correct C hash fills exactly the expected slot set
        lib.uigc_map_put_batch_new(
            _ptr(tab), _ptr(vals), mask, _ptr(probe), _ptr(probe), probe.size
        )
        if not np.array_equal(np.sort(np.nonzero(tab >= 0)[0]), np.sort(expect)):
            _native = False
            return None
        _native_lib_checked._verified = True
    return lib


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(_C_I64_P)


class I64Map:
    """int64 key -> int64 value open-addressing table."""

    __slots__ = ("keys", "vals", "cap", "mask", "size", "tombs")

    def __init__(self, cap: int = 1024):
        cap = max(16, cap)
        if cap & (cap - 1):
            cap = 1 << (cap - 1).bit_length()
        self.keys = np.full(cap, EMPTY, dtype=np.int64)
        self.vals = np.empty(cap, dtype=np.int64)
        self.cap = cap
        self.mask = cap - 1
        self.size = 0
        self.tombs = 0

    @classmethod
    def build(cls, keys: np.ndarray, vals: np.ndarray) -> "I64Map":
        """Bulk-construct from unique keys."""
        m = cls(cap=max(16, int(keys.size * 2)))
        if keys.size:
            m.put_batch_new(
                np.asarray(keys, dtype=np.int64),
                np.asarray(vals, dtype=np.int64),
            )
        return m

    # -- hashing ---------------------------------------------------- #

    def _h_batch(self, karr: np.ndarray) -> np.ndarray:
        return (
            ((karr.astype(np.uint64) * _MULT) >> _SHIFT).astype(np.int64)
            & self.mask
        )

    def _h_scalar(self, k: int) -> int:
        # Python-int modular arithmetic: no numpy scalar overflow
        # warnings, and faster than boxing to uint64.
        return ((k * 0x9E3779B97F4A7C15 & 0xFFFFFFFFFFFFFFFF) >> 29) & self.mask

    # -- batch operations ------------------------------------------- #

    def get_batch(self, karr: np.ndarray) -> np.ndarray:
        """Values for ``karr`` (-1 where absent).  Keys need not be
        unique."""
        karr = np.ascontiguousarray(karr, dtype=np.int64)
        n = karr.size
        out = np.full(n, -1, dtype=np.int64)
        if n == 0 or self.size == 0:
            return out
        if n >= _NATIVE_MIN_BATCH:
            lib = _native_lib_checked()
            if lib is not None:
                lib.uigc_map_get_batch(
                    _ptr(self.keys), _ptr(self.vals), self.mask,
                    _ptr(karr), n, _ptr(out),
                )
                return out
        idx = self._h_batch(karr)
        pending = np.arange(n)
        keys = self.keys
        mask = self.mask
        while pending.size:
            ia = idx[pending]
            tk = keys[ia]
            hit = tk == karr[pending]
            if hit.any():
                out[pending[hit]] = self.vals[ia[hit]]
            done = hit | (tk == EMPTY)
            pending = pending[~done]
            idx[pending] = (idx[pending] + 1) & mask
        return out

    def put_batch_new(self, karr: np.ndarray, varr: np.ndarray) -> None:
        """Insert keys known to be UNIQUE and ABSENT (the fold path
        learns absence from get_batch first).  Scatter-and-verify:
        losers of a slot race keep probing."""
        karr = np.ascontiguousarray(karr, dtype=np.int64)
        varr = np.ascontiguousarray(varr, dtype=np.int64)
        n = karr.size
        if n == 0:
            return
        self._maybe_grow(n)
        if n >= _NATIVE_MIN_BATCH:
            lib = _native_lib_checked()
            if lib is not None:
                freed = lib.uigc_map_put_batch_new(
                    _ptr(self.keys), _ptr(self.vals), self.mask,
                    _ptr(karr), _ptr(varr), n,
                )
                self.size += n
                self.tombs -= int(freed)
                return
        keys = self.keys
        mask = self.mask
        idx = self._h_batch(karr)
        pending = np.arange(n)
        claimed = 0
        freed_tombs = 0
        while pending.size:
            ia = idx[pending]
            tk = keys[ia]
            free = tk < 0
            if free.any():
                cand = pending[free]
                slots = ia[free]
                prev = tk[free]
                keys[slots] = karr[cand]
                won = keys[slots] == karr[cand]
                ws = slots[won]
                wi = cand[won]
                self.vals[ws] = varr[wi]
                claimed += int(won.sum())
                freed_tombs += int((prev[won] == TOMBSTONE).sum())
                done = np.zeros(pending.size, dtype=bool)
                free_idx = np.nonzero(free)[0]
                done[free_idx[won]] = True
                pending = pending[~done]
            idx[pending] = (idx[pending] + 1) & mask
        self.size += claimed
        self.tombs -= freed_tombs

    def pop_batch(self, karr: np.ndarray) -> np.ndarray:
        """Remove ``karr`` (unique); returns their values (-1 where
        absent)."""
        karr = np.ascontiguousarray(karr, dtype=np.int64)
        n = karr.size
        out = np.full(n, -1, dtype=np.int64)
        if n == 0 or self.size == 0:
            return out
        if n >= _NATIVE_MIN_BATCH:
            lib = _native_lib_checked()
            if lib is not None:
                removed = lib.uigc_map_pop_batch(
                    _ptr(self.keys), _ptr(self.vals), self.mask,
                    _ptr(karr), n, _ptr(out),
                )
                self.size -= int(removed)
                self.tombs += int(removed)
                return out
        keys = self.keys
        mask = self.mask
        idx = self._h_batch(karr)
        pending = np.arange(n)
        removed = 0
        while pending.size:
            ia = idx[pending]
            tk = keys[ia]
            hit = tk == karr[pending]
            if hit.any():
                slots = ia[hit]
                out[pending[hit]] = self.vals[slots]
                keys[slots] = TOMBSTONE
                removed += int(hit.sum())
            done = hit | (tk == EMPTY)
            pending = pending[~done]
            idx[pending] = (idx[pending] + 1) & mask
        self.size -= removed
        self.tombs += removed
        return out

    # -- scalar dict-compatible operations -------------------------- #

    def get(self, k: int, default=None):
        keys = self.keys
        mask = self.mask
        i = self._h_scalar(k)
        while True:
            tk = int(keys[i])
            if tk == k:
                return int(self.vals[i])
            if tk == EMPTY:
                return default
            i = (i + 1) & mask

    def __getitem__(self, k: int) -> int:
        v = self.get(k)
        if v is None:
            raise KeyError(k)
        return v

    def __setitem__(self, k: int, v: int) -> None:
        """Scalar upsert: scan the chain for the key, remembering the
        first free slot to claim if the key is absent."""
        self._maybe_grow(1)
        keys = self.keys
        mask = self.mask
        i = self._h_scalar(k)
        first_free = -1
        while True:
            tk = int(keys[i])
            if tk == k:
                self.vals[i] = v
                return
            if tk == EMPTY:
                j = first_free if first_free >= 0 else i
                was_tomb = int(keys[j]) == TOMBSTONE
                keys[j] = k
                self.vals[j] = v
                self.size += 1
                if was_tomb:
                    self.tombs -= 1
                return
            if tk == TOMBSTONE and first_free < 0:
                first_free = i
            i = (i + 1) & mask

    def pop(self, k: int, default=None):
        keys = self.keys
        mask = self.mask
        i = self._h_scalar(k)
        while True:
            tk = int(keys[i])
            if tk == k:
                keys[i] = TOMBSTONE
                self.size -= 1
                self.tombs += 1
                return int(self.vals[i])
            if tk == EMPTY:
                return default
            i = (i + 1) & mask

    def __contains__(self, k: int) -> bool:
        return self.get(k) is not None

    def __len__(self) -> int:
        return self.size

    def items(self) -> Iterator[Tuple[int, int]]:
        live = np.nonzero(self.keys >= 0)[0]
        for i in live.tolist():
            yield int(self.keys[i]), int(self.vals[i])

    def keys_live(self) -> np.ndarray:
        """All live keys (unordered)."""
        return self.keys[self.keys >= 0].copy()

    def key_set(self) -> set:
        return set(self.keys_live().tolist())

    # -- growth ----------------------------------------------------- #

    def _maybe_grow(self, incoming: int) -> None:
        # keep load (live + tombstones + incoming) under 2/3
        if (self.size + self.tombs + incoming) * 3 <= self.cap * 2:
            return
        live = self.keys >= 0
        old_keys = self.keys[live]
        old_vals = self.vals[live]
        newcap = self.cap
        while (self.size + incoming) * 3 > newcap * 2:
            newcap *= 2
        self.keys = np.full(newcap, EMPTY, dtype=np.int64)
        self.vals = np.empty(newcap, dtype=np.int64)
        self.cap = newcap
        self.mask = newcap - 1
        self.size = 0
        self.tombs = 0
        if old_keys.size:
            self.put_batch_new(old_keys, old_vals)


class IntStack:
    """LIFO free-list backed by a flat int64 array: batch push/pop are
    slice copies instead of list extend/del (the sweep frees hundreds of
    thousands of ids per batch)."""

    __slots__ = ("buf", "n")

    def __init__(self, init: Optional[np.ndarray] = None, cap: int = 64):
        if init is not None:
            init = np.asarray(init, dtype=np.int64)
            cap = max(cap, init.size)
        self.buf = np.empty(cap, dtype=np.int64)
        self.n = 0
        if init is not None and init.size:
            self.buf[: init.size] = init
            self.n = init.size

    @classmethod
    def from_range(cls, lo: int, hi: int) -> "IntStack":
        """Stack holding hi-1 .. lo (so pops come lowest-first, matching
        ``list(range(hi-1, lo-1, -1)).pop()`` order)."""
        return cls(np.arange(hi - 1, lo - 1, -1, dtype=np.int64))

    def _ensure(self, extra: int) -> None:
        need = self.n + extra
        if need > self.buf.shape[0]:
            newcap = max(need, self.buf.shape[0] * 2)
            nb = np.empty(newcap, dtype=np.int64)
            nb[: self.n] = self.buf[: self.n]
            self.buf = nb

    def push(self, v: int) -> None:
        self._ensure(1)
        self.buf[self.n] = v
        self.n += 1

    def push_batch(self, arr: np.ndarray) -> None:
        arr = np.asarray(arr, dtype=np.int64)
        self._ensure(arr.size)
        self.buf[self.n : self.n + arr.size] = arr
        self.n += arr.size

    def push_range(self, lo: int, hi: int) -> None:
        """Push hi-1 .. lo (list(range(hi-1, lo-1, -1)) order)."""
        self.push_batch(np.arange(hi - 1, lo - 1, -1, dtype=np.int64))

    def pop(self) -> int:
        self.n -= 1
        return int(self.buf[self.n])

    def pop_batch(self, k: int) -> np.ndarray:
        self.n -= k
        return self.buf[self.n : self.n + k].copy()

    def __len__(self) -> int:
        return self.n

    def __bool__(self) -> bool:
        return self.n > 0
