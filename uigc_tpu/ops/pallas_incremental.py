"""Incremental pair layout for the Pallas trace: base + frozen + live.

The full packer (pallas_trace.prepare_chunks) lexsorts every live
propagation pair — O(E log E) host work.  Fine for a static benchmark
graph; on the live collector path it used to run before nearly every
wake, because any positive edge insertion invalidated the cached layout
(VERDICT r1, weak item 3).  At 10M actors / 30M edges that sort dwarfs
the kernel it feeds.

This module keeps the full pack off the per-wake path with three tiers:

- **Base.**  A dense packed layout built from the whole graph, rebuilt
  only when accumulated churn crosses ``repack_fraction`` of its size.
  Deletions mask the pair's slot in place with the inert ``_PAD_ROW``
  sentinel (the packer's ``want_slots`` map locates it in O(1)); the
  layout, spans and block count never change, so no recompile.
- **Frozen deltas.**  When the live tier overflows, its pairs are packed
  into a *compact* layout (only the supertiles they touch, so a small
  delta over a 10M-node space stays small) and appended to a chain.
  Frozen pairs are slot-mapped, so later deletions mask them the same
  way.  When the chain exceeds ``max_frozen`` it is consolidated into
  one compact layout — O(d log d) in the total delta, amortized.
- **Live tier.**  The newest insertions sit in an ordered dict and ride
  along as raw pair arrays propagated by an XLA scatter-max
  (pallas_trace.xla_tier): zero pack cost, zero recompiles (static
  pow2 capacity), O(capacity) device work per fixpoint iteration —
  cheap while the tier is small, which freezing guarantees.

Per-wake maintenance is therefore O(changes since last wake), plus an
amortized freeze/consolidate.  The trace launches the propagation
kernel once per packed tier and combines all contributions before
thresholding (pallas_trace.trace_marks_layouts), which is equivalent to
one layout holding the union of the pairs.

Pairs are keyed (src, dst, kind) where kind distinguishes refob edges
from supervisor pointers — the same (src, dst) node pair can legally
carry both (reference: ShadowGraph.java:224-268 treats them as separate
propagation reasons).

Semantics are covered by differential tests against trace_marks_np
(tests/test_pallas_incremental.py) at every mutation step.
"""

from __future__ import annotations

from time import perf_counter
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import pallas_trace as pt
from ..utils.validation import require
from .slotmap import PackedSlotMap, fold_log, pack_key, pack_keys, unpack_keys

#: pair kinds
EDGE = 0
SUP = 1

Key = Tuple[int, int, int]


class IncrementalPallasLayout:
    """Mutable pair layout with O(changes) per-wake maintenance."""

    def __init__(
        self,
        n: int,
        s_rows: int = pt.S_ROWS,
        repack_fraction: float = 0.25,
        min_repack: int = 1 << 18,
        freeze_threshold: int = 1 << 14,
        max_frozen: int = 4,
        interpret: Optional[bool] = None,
        sub: Optional[int] = None,
        group: Optional[int] = None,
        mode: str = pt.MODE_AUTO,
        pull_density: float = pt.DEFAULT_PULL_DENSITY,
    ):
        self.n = n
        self.s_rows = s_rows
        #: propagation strategy (pallas_trace MODE_*, uigc.crgc.trace-mode)
        require(
            mode in pt.TRACE_MODES, "config.trace_mode",
            "bad trace mode", mode=mode, valid=pt.TRACE_MODES,
        )
        self.mode = mode
        self.pull_density = pull_density
        self.use_jump = mode in (pt.MODE_JUMP, pt.MODE_AUTO)
        # Pin the kernel walk geometry once: base and delta tiers must
        # agree (they share one trace), and a mid-life platform change
        # must not silently mix geometries.  Explicit sub/group override
        # the platform default (tests cover the wide geometry in
        # interpret mode this way).
        d_sub, d_group = pt.default_geometry(interpret)
        self.sub = d_sub if sub is None else sub
        self.group = d_group if group is None else group
        self.repack_fraction = repack_fraction
        self.min_repack = min_repack
        self.freeze_threshold = freeze_threshold
        self.max_frozen = max_frozen
        self.interpret = interpret
        self.base: Optional[Dict[str, np.ndarray]] = None
        #: packed (src, dst, kind) key -> packed (row << 8 | col) into the
        #: base row_pos/emeta.  Sorted numpy bulk + churn overlays, so a
        #: rebuild stays vectorized and O(E) ints, not O(E) Python objects.
        self.base_slot = PackedSlotMap()
        #: frozen compact delta layouts
        self.frozen: List[Dict[str, np.ndarray]] = []
        #: packed key -> (frozen index, row, col); churn-bounded, plain dict
        self.frozen_slot: Dict[int, Tuple[int, int, int]] = {}
        #: newest insertions, not yet packed (ordered set of packed keys)
        self.pending: Dict[int, None] = {}
        #: masked (deleted-in-place) slots, tracked per home so frozen
        #: masks can be forgiven when consolidation rebuilds the chain
        self.masked_base = 0
        self.masked_frozen = 0
        self._xla_cap = 1 << 10
        #: min-source jump-parent array (n + 1,) for the jump/auto trace
        #: modes.  Invariant: jump_parent[d] is always a CURRENT live
        #: pair's source (or the sentinel n) — a stale pointer would let
        #: the jump sweep propagate marks across a deleted edge.
        #: Maintained O(1) per mutation: inserts fold in by minimum,
        #: removing the pair a pointer was built from invalidates it
        #: (best-effort: the next insert or rebuild re-derives).
        self.jump_parent = np.full(n + 1, n, dtype=np.int32)
        #: queued jump-parent device writes (dst -> final host value;
        #: last-wins dedup keeps the device scatter order-independent)
        self._jump_writes: Dict[int, int] = {}
        self._jump_dev = None
        self.stats = {
            "rebuilds": 0,
            "freezes": 0,
            "consolidations": 0,
            "pack_s": 0.0,
            "anomalies": 0,
        }
        #: device-resident mirrors (trace_device): mirror token -> dict of
        #: device arrays; plus per-prep masked-slot write queues so the
        #: mirror syncs in O(churn) instead of re-uploading the layout.
        #: Tokens are monotonically assigned and stamped into the prep
        #: dict — keying by id(prep) would serve a stale mirror when the
        #: allocator recycles a freed dict's address.
        self._dev_mirror: Dict[int, dict] = {}
        self._dev_writes: Dict[int, List[int]] = {}
        self._dev_scatter = None
        self._mirror_next = 0

    # ----------------------------------------------------------------- #
    # Building
    # ----------------------------------------------------------------- #

    @staticmethod
    def pairs_from_graph(edge_src, edge_dst, edge_weight, supervisor):
        """(psrc, pdst, kinds) for all live propagation pairs."""
        live = edge_weight > 0
        psrc = edge_src[live].astype(np.int64)
        pdst = edge_dst[live].astype(np.int64)
        kinds = np.zeros(psrc.size, dtype=np.int64)
        sup_src = np.nonzero(supervisor >= 0)[0].astype(np.int64)
        if sup_src.size:
            psrc = np.concatenate([psrc, sup_src])
            pdst = np.concatenate([pdst, supervisor[sup_src].astype(np.int64)])
            kinds = np.concatenate([kinds, np.ones(sup_src.size, np.int64)])
        return psrc, pdst, kinds

    def rebuild(self, edge_src, edge_dst, edge_weight, supervisor) -> None:
        """Full repack from the graph arrays (the only O(E log E) step)."""
        t0 = perf_counter()
        psrc, pdst, kinds = self.pairs_from_graph(
            edge_src, edge_dst, edge_weight, supervisor
        )
        self.base = pt.prepare_pairs(
            psrc,
            pdst,
            self.n,
            s_rows=self.s_rows,
            pad_blocks_pow2=True,
            want_slots=True,
            sub=self.sub,
            group=self.group,
        )
        if self.use_jump:
            self.jump_parent = pt.jump_parents(psrc, pdst, self.n)
        self._jump_writes.clear()
        self._jump_dev = None
        slot_ri = self.base.pop("slot_ri")
        slot_col = self.base.pop("slot_col")
        self.base_slot = PackedSlotMap(
            pack_keys(psrc, pdst, kinds), (slot_ri << 8) | slot_col
        )
        self.frozen = []
        self.frozen_slot = {}
        self.pending.clear()
        self.masked_base = 0
        self.masked_frozen = 0
        self.stats["rebuilds"] += 1
        self.stats["pack_s"] += perf_counter() - t0

    def _freeze_pending(self) -> None:
        """Pack the live tier into a compact frozen layout."""
        t0 = perf_counter()
        keys = list(self.pending)
        m = len(keys)
        psrc, pdst = unpack_keys(np.fromiter(keys, np.int64, m))
        prep = pt.prepare_pairs(
            psrc,
            pdst,
            self.n,
            s_rows=self.s_rows,
            pad_blocks_pow2=True,
            want_slots=True,
            compact_supers=True,
            sub=self.sub,
            group=self.group,
        )
        slot_ri = prep.pop("slot_ri")
        slot_col = prep.pop("slot_col")
        fidx = len(self.frozen)
        self.frozen.append(prep)
        for key, ri, co in zip(keys, slot_ri, slot_col):
            self.frozen_slot[key] = (fidx, int(ri), int(co))
        self.pending.clear()
        self.stats["freezes"] += 1
        self.stats["pack_s"] += perf_counter() - t0

    def _consolidate_frozen(self) -> None:
        """Merge the frozen chain into one compact layout."""
        t0 = perf_counter()
        keys = list(self.frozen_slot)
        m = len(keys)
        if m == 0:
            self.frozen = []
            self.masked_frozen = 0
            self.stats["consolidations"] += 1
            return
        psrc, pdst = unpack_keys(np.fromiter(keys, np.int64, m))
        prep = pt.prepare_pairs(
            psrc,
            pdst,
            self.n,
            s_rows=self.s_rows,
            pad_blocks_pow2=True,
            want_slots=True,
            compact_supers=True,
            sub=self.sub,
            group=self.group,
        )
        slot_ri = prep.pop("slot_ri")
        slot_col = prep.pop("slot_col")
        self.frozen = [prep]
        self.frozen_slot = {
            key: (0, int(ri), int(co))
            for key, ri, co in zip(keys, slot_ri, slot_col)
        }
        # consolidation dropped every masked frozen slot
        self.masked_frozen = 0
        self.stats["consolidations"] += 1
        self.stats["pack_s"] += perf_counter() - t0

    # ----------------------------------------------------------------- #
    # Mutation (O(1) per changed pair)
    # ----------------------------------------------------------------- #

    def _jump_insert(self, src: int, dst: int) -> None:
        """Fold a new live pair into the jump-parent array (minimum
        wins, see jump_parents); O(1), queued for the device mirror."""
        if not self.use_jump or dst >= self.n or src >= self.n:
            return
        if src < self.jump_parent[dst]:
            self.jump_parent[dst] = src
            if self._jump_dev is not None:
                self._jump_writes[dst] = src

    def _jump_remove(self, src: int, dst: int) -> None:
        """Invalidate the jump parent if it was built from this pair.
        Conservative: another live pair with the same (src, dst) node
        ids (the other kind) may remain, but a spurious invalidation
        only costs acceleration, never soundness."""
        if not self.use_jump or dst >= self.n:
            return
        if self.jump_parent[dst] == src:
            self.jump_parent[dst] = self.n
            if self._jump_dev is not None:
                self._jump_writes[dst] = self.n

    def insert(self, src: int, dst: int, kind: int) -> None:
        key = pack_key(src, dst, kind)
        self._jump_insert(src, dst)
        if key in self.pending or key in self.frozen_slot or key in self.base_slot:
            # The graph layer only reports dead->live transitions, so a
            # duplicate means caller-side accounting drift; the pair is
            # already live here, which keeps the trace correct.
            self.stats["anomalies"] += 1
            return
        self.pending[key] = None

    def _queue_dev_write(self, prep, ri, col) -> None:
        """Record a masked slot for the device mirror (packed ri<<8|col)."""
        tok = prep.get("_mirror_token")
        if tok is None:
            return
        writes = self._dev_writes.get(tok)
        if writes is not None:
            writes.append((int(ri) << 8) | int(col))

    def remove(self, src: int, dst: int, kind: int) -> None:
        key = pack_key(src, dst, kind)
        self._jump_remove(src, dst)
        if key in self.pending:
            del self.pending[key]
            return
        slot = self.frozen_slot.pop(key, None)
        if slot is not None:
            fidx, ri, col = slot
            prep = self.frozen[fidx]
            prep["row_pos"][ri, col] = pt._PAD_ROW
            prep["emeta"][ri, col] = 0
            self._queue_dev_write(prep, ri, col)
            self.masked_frozen += 1
            return
        packed = self.base_slot.pop(key)
        if packed is None:
            self.stats["anomalies"] += 1
            return
        ri, col = packed >> 8, packed & 0xFF
        self.base["row_pos"][ri, col] = pt._PAD_ROW
        self.base["emeta"][ri, col] = 0
        self._queue_dev_write(self.base, ri, col)
        self.masked_base += 1

    def _mask_base_slots(self, vals: np.ndarray) -> int:
        """Mask base slots from packed (row << 8 | col) values (-1 =
        absent); returns how many were found."""
        found = vals >= 0
        ri = vals[found] >> 8
        col = vals[found] & 0xFF
        self.base["row_pos"][ri, col] = pt._PAD_ROW
        self.base["emeta"][ri, col] = 0
        tok = self.base.get("_mirror_token")
        writes = self._dev_writes.get(tok) if tok is not None else None
        if writes is not None:
            writes.extend(vals[found].tolist())
        n = int(found.sum())
        self.masked_base += n
        return n

    def _remove_key(self, k: int, base_rem: List[int]) -> bool:
        """Remove ``k`` from pending/frozen, or defer it to the batched
        base lookup; returns False only when deferred."""
        if k in self.pending:
            del self.pending[k]
            return True
        slot = self.frozen_slot.pop(k, None)
        if slot is not None:
            fidx, ri, col = slot
            prep = self.frozen[fidx]
            prep["row_pos"][ri, col] = pt._PAD_ROW
            prep["emeta"][ri, col] = 0
            self._queue_dev_write(prep, ri, col)
            self.masked_frozen += 1
            return True
        base_rem.append(k)
        return False

    def apply_log(self, log) -> None:
        """Batched replay of a pair-transition log [(insert?, src, dst,
        kind), ...].  Equivalent to calling insert/remove in order
        (including anomaly accounting for caller-side drift), but
        base-slot lookups are one vectorized binary search for the whole
        batch instead of a scalar search per pair (slotmap.fold_log
        documents the net-effect argument)."""
        if self.use_jump:
            # Batched jump-parent maintenance (pt.fold_jump_log):
            # conservative about insert-and-remove-in-one-batch pairs,
            # so an insert-then-remove of the pair a pointer came from
            # always leaves it invalidated, exactly as sequential
            # insert()/remove() calls would.
            pt.fold_jump_log(
                self.jump_parent, log, self.n,
                self._jump_writes if self._jump_dev is not None else None,
            )
        removes, cond_removes, inserts = fold_log(log)

        base_rem: List[int] = []
        for k in removes:
            self._remove_key(k, base_rem)
        if base_rem:
            vals = self.base_slot.pop_batch(
                np.fromiter(base_rem, np.int64, len(base_rem))
            )
            n_found = self._mask_base_slots(vals)
            self.stats["anomalies"] += len(base_rem) - n_found

        # Insert-first/remove-last keys: net no-op unless the key was
        # already live (anomalous duplicate insert followed by a real
        # remove) — then remove it, like the sequential replay would.
        cond_base: List[int] = []
        for k in cond_removes:
            if k in self.pending or k in self.frozen_slot:
                self.stats["anomalies"] += 1
                self._remove_key(k, cond_base)
            else:
                cond_base.append(k)
        if cond_base:
            vals = self.base_slot.pop_batch(
                np.fromiter(cond_base, np.int64, len(cond_base))
            )
            self.stats["anomalies"] += self._mask_base_slots(vals)

        if inserts:
            fresh: List[int] = []
            for k in inserts:
                if k in self.pending or k in self.frozen_slot:
                    self.stats["anomalies"] += 1
                    continue
                self.pending[k] = None
                fresh.append(k)
            if fresh:
                # Anomalous duplicate-with-base inserts are harmless for
                # liveness (contributions are OR'd) but tracked for
                # diagnostics, batched.
                karr = np.fromiter(fresh, np.int64, len(fresh))
                present = self.base_slot.get_batch(karr) >= 0
                n_dup = int(present.sum())
                if n_dup:
                    self.stats["anomalies"] += n_dup
                    for k in karr[present].tolist():
                        del self.pending[k]

    @property
    def churn(self) -> int:
        return (
            len(self.frozen_slot)
            + len(self.pending)
            + self.masked_base
            + self.masked_frozen
        )

    @property
    def needs_repack(self) -> bool:
        base_pairs = self.base["n_pairs"] if self.base is not None else 0
        return self.churn > max(
            self.min_repack, int(self.repack_fraction * base_pairs)
        )

    # ----------------------------------------------------------------- #
    # Trace
    # ----------------------------------------------------------------- #

    def prepare_wake(self) -> list:
        """The per-wake layout maintenance: freeze an overflowing live
        tier, consolidate an overlong frozen chain, and materialize the
        tier list for this trace.  Split out from :meth:`trace` so its
        host cost can be measured without launching the kernel
        (tools/pack_bench.py)."""
        assert self.base is not None, "rebuild() before trace()"
        if len(self.pending) > self.freeze_threshold:
            self._freeze_pending()
        if len(self.frozen) > self.max_frozen:
            self._consolidate_frozen()
        preps = [self.base] + self.frozen
        if self.pending:
            m = len(self.pending)
            while self._xla_cap < m:
                self._xla_cap *= 2
            psrc, pdst = unpack_keys(np.fromiter(self.pending, np.int64, m))
            preps.append(pt.xla_tier(psrc, pdst, self.n, self._xla_cap))
        return preps

    def trace(self, flags, recv_count, with_stats: bool = False):
        preps = self.prepare_wake()
        return pt.trace_marks_layouts(
            flags, recv_count, preps, interpret=self.interpret,
            mode=self.mode, pull_density=self.pull_density,
            jump_parent=self.jump_parent if self.use_jump else None,
            with_stats=with_stats,
        )

    # ----------------------------------------------------------------- #
    # Device-resident trace (steady-state wake path on real hardware)
    # ----------------------------------------------------------------- #

    def _device_args(self, prep) -> list:
        """Device operands for one layout, from a mirror that lives on
        the device across wakes and syncs only the slots masked since the
        last sync (an O(churn) scatter, not an O(layout) re-upload)."""
        import jax

        if "xla_src" in prep:
            # the live tier is small and fully rebuilt per wake; let the
            # call transfer it
            return list(pt.device_args(prep))
        pid = prep.get("_mirror_token")
        if pid is None:
            pid = prep["_mirror_token"] = self._mirror_next
            self._mirror_next += 1
        mirror = self._dev_mirror.get(pid)
        if mirror is None:
            mirror = {
                k: jax.device_put(prep[k])
                for k in ("bmeta1", "bmeta2", "row_pos", "emeta")
            }
            if "super_ids" in prep:
                mirror["super_ids"] = jax.device_put(prep["super_ids"])
            self._dev_mirror[pid] = mirror
            self._dev_writes[pid] = []
        else:
            writes = self._dev_writes[pid]
            if writes:
                import jax.numpy as jnp
                from functools import partial

                if self._dev_scatter is None:

                    @partial(jax.jit, donate_argnums=(0, 1))
                    def _scatter(row_pos, emeta, rows, cols):
                        row_pos = row_pos.at[rows, cols].set(
                            pt._PAD_ROW, mode="drop"
                        )
                        emeta = emeta.at[rows, cols].set(0, mode="drop")
                        return row_pos, emeta

                    self._dev_scatter = _scatter
                k = len(writes)
                kp = 1 << max(6, int(k - 1).bit_length())
                packed = np.fromiter(writes, np.int64, k)
                rows = np.full(kp, prep["row_pos"].shape[0], dtype=np.int32)
                cols = np.zeros(kp, dtype=np.int32)
                rows[:k] = packed >> 8
                cols[:k] = packed & 0xFF
                mirror["row_pos"], mirror["emeta"] = self._dev_scatter(
                    mirror["row_pos"], mirror["emeta"], rows, cols
                )
                writes.clear()
        out = [
            mirror["bmeta1"],
            mirror["bmeta2"],
            mirror["row_pos"],
            mirror["emeta"],
        ]
        if "super_ids" in prep:
            out.append(mirror["super_ids"])
        return out

    def jump_device(self):
        """The device-resident jump-parent mirror, synced with the
        queued host writes (an O(churn) scatter, like the masked-slot
        mirrors — the parent array never re-uploads per wake)."""
        import jax

        if self._jump_dev is None:
            self._jump_dev = jax.device_put(self.jump_parent)
            self._jump_writes.clear()
        elif self._jump_writes:
            import jax.numpy as jnp
            from functools import partial

            if getattr(self, "_jump_scatter", None) is None:

                @partial(jax.jit, donate_argnums=(0,))
                def _jscatter(jp, idx, vals):
                    return jp.at[idx].set(vals, mode="drop")

                self._jump_scatter = _jscatter
            k = len(self._jump_writes)
            kp = 1 << max(6, int(k - 1).bit_length())
            idx = np.full(kp, self.n + 1, dtype=np.int32)  # pad = dropped
            vals = np.zeros(kp, dtype=np.int32)
            idx[:k] = np.fromiter(self._jump_writes.keys(), np.int64, k)
            vals[:k] = np.fromiter(self._jump_writes.values(), np.int64, k)
            self._jump_dev = self._jump_scatter(self._jump_dev, idx, vals)
            self._jump_writes.clear()
        return self._jump_dev

    def prepare_device_wake(self):
        """prepare_wake + device-operand assembly + mirror GC: the
        device-resident wake entry shared by :meth:`trace_device` and the
        decremental tracer (ops/pallas_decremental.py).  Returns
        (preps, args) with the jump-parent mirror leading ``args`` for
        jump/auto-mode layouts."""
        preps = self.prepare_wake()
        args = []
        if self.use_jump:
            args.append(self.jump_device())
        for p in preps:
            args.extend(self._device_args(p))
        live_tokens = {
            p["_mirror_token"] for p in preps if "_mirror_token" in p
        }
        for pid in list(self._dev_mirror):
            if pid not in live_tokens:
                del self._dev_mirror[pid]
                self._dev_writes.pop(pid, None)
        return preps, args

    def trace_device(self, flags_dev, recv_dev):
        """Like :meth:`trace`, but every packed layout's operand arrays
        stay device-resident between wakes (the reference's steady state:
        LocalGC.scala:144-186 never re-ships its graph per wake) and the
        mark vector is returned as a device array, so callers can reduce
        garbage counts/ids on device instead of pulling 10M bools."""
        preps, args = self.prepare_device_wake()
        fn = pt.get_trace_fn_multi(
            self.n,
            tuple(pt.layout_spec(p) for p in preps),
            preps[0]["n_super"],
            preps[0]["r_rows"],
            preps[0]["s_rows"],
            self.interpret,
            mode=self.mode,
            pull_density=self.pull_density,
        )
        return fn(flags_dev, recv_dev, *args)
