"""Packed-int64 slot maps: O(E) numpy storage for pair -> slot lookups.

The incremental layouts (ops/pallas_incremental.py, engines/crgc/mesh.py)
need a map from a live propagation pair to the slot holding it, so that a
later deletion can mask the slot in place.  A Python dict keyed by
(src, dst, kind) tuples costs hundreds of bytes per pair — multiple GB of
host objects at the 10M-actor/30M-pair target, and most of the rebuild
stall measured in BENCH_PACK_r02 was that dict's construction.

This map instead stores the bulk mapping as two sorted int64 numpy arrays
(16 bytes per pair) built vectorized at rebuild time; point lookups are a
binary search.  Mutations after the rebuild go through small Python
overlays (an insert dict and a tombstone set) whose size is bounded by
churn since the rebuild, which the layouts already bound by repacking.

Keys pack (src, dst, kind) into one int64: src in bits 32..62, dst in
bits 1..31, kind in bit 0 — node ids must stay below 2^31, which the
graph's int32 slot arrays already guarantee.  Values are whatever the
caller packs into an int64.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def pack_keys(src, dst, kind) -> np.ndarray:
    """Vectorized (src, dst, kind) -> int64 key."""
    return (
        (np.asarray(src, dtype=np.int64) << 32)
        | (np.asarray(dst, dtype=np.int64) << 1)
        | np.asarray(kind, dtype=np.int64)
    )


def pack_key(src: int, dst: int, kind: int) -> int:
    return (src << 32) | (dst << 1) | kind


def unpack_keys(karr: np.ndarray):
    """Vectorized int64 key -> (src, dst) arrays (kind = karr & 1)."""
    karr = np.asarray(karr, dtype=np.int64)
    return karr >> 32, (karr >> 1) & 0x7FFFFFFF


def fold_log(log):
    """Fold an alternating pair-transition log [(insert?, src, dst, kind),
    ...] into its net effect per packed key.

    A pair's transitions strictly alternate (graph layers only log
    dead<->live flips), so the net effect is determined by the first and
    last op.  Returns ``(removes, cond_removes, inserts)``:

    - ``removes``: first op is a remove — remove from the current home
      (absence is caller drift: count an anomaly);
    - ``cond_removes``: insert-first but remove-last — a net no-op for a
      fresh pair, but if the key was *already live* the insert was
      anomalous drift and the remove is real: remove and count an
      anomaly, matching the sequential scalar replay;
    - ``inserts``: last op is an insert — insert after the removals.
    """
    first: dict = {}
    last: dict = {}
    for ins, src, dst, kind in log:
        k = pack_key(src, dst, kind)
        if k not in first:
            first[k] = ins
        last[k] = ins
    removes = [k for k, ins in first.items() if not ins]
    cond_removes = [k for k, ins in first.items() if ins and not last[k]]
    inserts = [k for k, ins in last.items() if ins]
    return removes, cond_removes, inserts


class PackedSlotMap:
    """int64 key -> int64 value map: sorted bulk arrays + churn overlays."""

    __slots__ = ("_keys", "_vals", "_removed", "_extra")

    def __init__(
        self,
        keys: Optional[np.ndarray] = None,
        vals: Optional[np.ndarray] = None,
    ):
        if keys is None or keys.size == 0:
            self._keys = np.zeros(0, dtype=np.int64)
            self._vals = np.zeros(0, dtype=np.int64)
        else:
            order = np.argsort(keys)
            self._keys = np.ascontiguousarray(keys[order])
            self._vals = np.ascontiguousarray(vals[order])
        self._removed: set = set()  # tombstoned bulk keys
        self._extra: dict = {}  # post-rebuild inserts

    def __len__(self) -> int:
        return self._keys.size - len(self._removed) + len(self._extra)

    def _bulk_find(self, key: int) -> int:
        """Index of ``key`` in the sorted bulk arrays, or -1."""
        keys = self._keys
        i = int(np.searchsorted(keys, key))
        if i < keys.size and keys[i] == key:
            return i
        return -1

    def __contains__(self, key: int) -> bool:
        if key in self._extra:
            return True
        if key in self._removed:
            return False
        return self._bulk_find(key) >= 0

    def get(self, key: int) -> Optional[int]:
        val = self._extra.get(key)
        if val is not None:
            return val
        if key in self._removed:
            return None
        i = self._bulk_find(key)
        if i < 0:
            return None
        return int(self._vals[i])

    def add(self, key: int, val: int) -> None:
        """Insert; the key must not be present (callers check first).
        A tombstoned bulk key may be re-added — the overlay wins on
        lookup, and the tombstone keeps the stale bulk slot hidden."""
        self._extra[key] = val

    def pop(self, key: int) -> Optional[int]:
        val = self._extra.pop(key, None)
        if val is not None:
            return val
        if key in self._removed:
            return None
        i = self._bulk_find(key)
        if i < 0:
            return None
        self._removed.add(key)
        return int(self._vals[i])

    # --------------------------------------------------------------- #
    # Batched point ops: one vectorized binary search for a whole churn
    # batch instead of a ~1us scalar searchsorted per key.
    # --------------------------------------------------------------- #

    def _lookup_batch(self, karr: np.ndarray, remove: bool) -> np.ndarray:
        # Precondition: keys within one batch are unique (callers dedup
        # via fold_log).  A duplicated bulk key would otherwise be
        # tombstoned once but resolved for every occurrence — e.g. a
        # double-free of the same column downstream.
        assert np.unique(karr).size == karr.size, "batch keys must be unique"
        out = np.full(karr.size, -1, dtype=np.int64)
        extra = self._extra
        removed = self._removed
        bulk_idx = []
        for i, k in enumerate(karr.tolist()):
            if k in extra:
                out[i] = extra.pop(k) if remove else extra[k]
            elif k not in removed:
                bulk_idx.append(i)
        if bulk_idx and self._keys.size:
            bi = np.asarray(bulk_idx, dtype=np.int64)
            kq = karr[bi]
            pos = np.minimum(
                np.searchsorted(self._keys, kq), self._keys.size - 1
            )
            found = self._keys[pos] == kq
            out[bi[found]] = self._vals[pos[found]]
            if remove:
                removed.update(kq[found].tolist())
        return out

    def pop_batch(self, karr: np.ndarray) -> np.ndarray:
        """Pop every key in ``karr``; returns int64 values, -1 = absent."""
        return self._lookup_batch(np.asarray(karr, dtype=np.int64), remove=True)

    def get_batch(self, karr: np.ndarray) -> np.ndarray:
        """Look up every key in ``karr``; returns int64 values, -1 = absent."""
        return self._lookup_batch(np.asarray(karr, dtype=np.int64), remove=False)
