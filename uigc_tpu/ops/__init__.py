from .trace import (
    FLAG_BUSY,
    FLAG_HALTED,
    FLAG_IN_USE,
    FLAG_INTERNED,
    FLAG_LOCAL,
    FLAG_ROOT,
    garbage_and_kills_np,
    pseudoroots_np,
    trace_marks_jax,
    trace_marks_np,
)

__all__ = [
    "FLAG_BUSY",
    "FLAG_HALTED",
    "FLAG_IN_USE",
    "FLAG_INTERNED",
    "FLAG_LOCAL",
    "FLAG_ROOT",
    "garbage_and_kills_np",
    "pseudoroots_np",
    "trace_marks_jax",
    "trace_marks_np",
]
