"""Decremental per-wake garbage detection: suspect closure + region repair.

The full trace re-derives reachability from the seeds every wake — ~12
propagation sweeps over a 10M-actor graph even when the wake's churn
touched a few thousand nodes.  The reference never faces this regime (its
collector traces ~10^4-10^5 node-local shadows per 50ms wake,
LocalGC.scala:144-186); at BASELINE.md's 10M-actor scale the <=10ms p50
detection target is unreachable by full re-trace (PERF_WAKE.md).  Marks
do not shrink monotonically under churn — releasing a ref can turn live
actors into garbage — so a sound incremental wake must re-derive exactly
the region whose old derivation might have depended on what changed.

Per wake, relative to the previous fixpoint:

1. **Suspect seeds** ``S``: nodes whose mark derivation inputs may have
   shrunk — destinations of deleted propagation pairs, previously-seed
   nodes that stopped seeding (busy cleared, recv drained, root dropped),
   and newly-halted nodes (their out-edges stop propagating) — all
   intersected with the previous marks (an unmarked node has nothing to
   invalidate).
2. **Closure**: the forward closure of ``S`` through the current layout,
   restricted to previously-marked nodes — every mark that transitively
   depended on a suspect.  A monotone fixpoint, so the source-side
   dirty-group machinery bounds its cost by the region size.
3. **Repair**: clear the closure's marks, reseed from the current seed
   vector, and run the propagation fixpoint where the FIRST sweep forces
   blocks whose output supertile intersects the closure to walk their
   full chunk span (``build_propagate(dst_gate=True)``) — those
   supertiles must re-derive contributions from ALL in-edges, including
   sources whose table groups never changed.  Later sweeps are monotone
   growth and fall back to the ordinary dirty-group walk.

Soundness: a previously-marked node outside the closure retains a support
path untouched by any deletion, de-seeding, or halt (otherwise some node
on the path would have entered ``S`` and pushed the rest into the
closure), so its mark stays valid; closure members are re-derived from
scratch against that stable boundary.  Additions (new pairs, new seeds)
ride the same repair fixpoint through the ordinary monotone machinery.
A cold start degenerates gracefully: with zero previous state the suspect
set is empty and the repair fixpoint IS the full trace from seeds.

Differential coverage: tests/test_pallas_decremental.py drives random
mutation/flag-change schedules and compares every wake against the numpy
oracle re-run from scratch (trace_marks_np, the reference semantics of
ShadowGraph.java:205-289).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

import numpy as np

from . import pallas_trace as pt
from . import trace as trace_ops
from ..utils import events
from ..utils.validation import require
from .pallas_incremental import IncrementalPallasLayout

_fn_cache: Dict[tuple, object] = {}


def _build_wake_fn(
    n: int,
    specs: tuple,
    n_super: int,
    r_rows: int,
    s_rows: int,
    interpret: bool,
    mode: str = pt.MODE_PUSH,
    pull_density: float = pt.DEFAULT_PULL_DENSITY,
    with_stats: bool = False,
):
    """The jitted wake: (flags, recv, del_words, fresh_words, prev
    state, [jump parents,] *layout args) -> (mark_w, seed_w, halted_w,
    iu_w, table) with all word tables (r_rows, LANE) int32 device
    arrays.

    ``mode`` applies to the REPAIR fixpoint only (pallas_trace MODE_*
    docs): on a cold start the repair IS the full derivation, which is
    where the O(diameter) sweep wall lives.  The closure phase stays a
    plain push fixpoint: it is bounded by the churn's region (usually
    shallow), and jump hits there would only over-approximate the
    closure — sound but more re-derivation for nothing.  ``with_stats``
    appends a per-wake stats dict (repair sweep count + per-sweep
    frontier decomposition) to the returned tuple."""
    import jax
    import jax.numpy as jnp

    F = trace_ops
    require(
        mode in pt.TRACE_MODES, "config.trace_mode",
        "bad trace mode", mode=mode, valid=pt.TRACE_MODES,
    )
    use_jump = mode in (pt.MODE_JUMP, pt.MODE_AUTO)
    use_pull = mode in (pt.MODE_PULL, pt.MODE_AUTO)

    geoms = {spec[-2:] for spec in specs if spec[0] != "xla"}
    assert len(geoms) == 1, "packed layouts must share (sub, group)"
    ((_, group),) = geoms
    group_rows = pt.ROWS * group

    # One dst-gated kernel per packed layout serves both phases: a zero
    # gate vector makes it behave exactly like the plain kernel.
    gated = pt.build_layout_propagates(
        specs, n_super, r_rows, s_rows, interpret, dst_gate=True
    )

    n_chunks = r_rows // group_rows
    n_pad_nodes = n_super * s_rows * pt.LANE
    t_rows = n_super * s_rows
    sup_words = s_rows * (pt.LANE // pt.WORD_BITS)  # words per supertile
    pull_cut = max(1, int(round(pull_density * n_chunks)))

    def wake_fn(flags, recv_count, del_w, fresh_w, prev_mark_w,
                prev_seed_w, prev_halted_w, prev_iu_w, prev_table,
                *rest):
        if use_jump:
            jump_j0, *layout_args = rest
        else:
            jump_j0, layout_args = None, rest
        in_use = (flags & F.FLAG_IN_USE) != 0
        halted = (flags & F.FLAG_HALTED) != 0
        seed = (
            ((flags & F.FLAG_ROOT) != 0)
            | ((flags & F.FLAG_BUSY) != 0)
            | (recv_count != 0)
            | ((flags & F.FLAG_INTERNED) == 0)
        )

        def pack(active):
            return pt.pack_bools(active, n, r_rows, jnp)

        def dirty_chunks(table, table_prev):
            return pt.dirty_group_lists(
                table, table_prev, n_chunks, group_rows, jnp
            )

        gated_sweep = pt.build_sweep_contribs(
            specs, gated, n, n_super, s_rows, jnp
        )

        def contribs(table, d, l, gate):
            """One propagation sweep over every layout (shared loop:
            pallas_trace.build_sweep_contribs); a zero gate vector makes
            the dst-gated kernels behave exactly like the plain ones."""
            return gated_sweep(table, d, l, layout_args, gate=gate)

        iu_w = pack(in_use)
        nh_w = pack(~halted)
        halted_w = pack(halted)
        seed_w = pack(in_use & (~halted) & seed)

        # --- 1. suspect seeds --------------------------------------- #
        # A previously-marked node is suspect when any input of its old
        # derivation may have shrunk: it was freed (in_use dropped — the
        # oracle gates marks on in_use, so the mark itself must go), it
        # newly halted (stops propagating), it stopped seeding, or an
        # in-edge was deleted.
        s_w = (
            (~iu_w)
            | (halted_w & ~prev_halted_w)
            | (prev_seed_w & ~seed_w)
            | del_w
        ) & prev_mark_w

        # --- 2. closure: marks that depended on a suspect ----------- #
        def c_cond(carry):
            return carry[-1]

        zero_gate = jnp.zeros((n_super,), jnp.int32)

        def c_body(carry):
            closure_w, d, l, _ = carry
            hits2d = contribs(closure_w, d, l, zero_gate)
            hit_w = pt.pack_hits_table(hits2d, r_rows, jnp)
            new_closure = closure_w | (hit_w & prev_mark_w)
            d2, l2, changed = dirty_chunks(new_closure, closure_w)
            return new_closure, d2, l2, changed

        d0, l0, changed0 = dirty_chunks(s_w, jnp.zeros_like(s_w))
        closure_w, _, _, _ = jax.lax.while_loop(
            c_cond, c_body, (s_w, d0, l0, changed0)
        )

        # per-supertile gate: closure members must re-derive; fresh
        # insert destinations must see their new pairs' contributions at
        # least once (a new edge changes no node word, so the dirty
        # machinery alone would never walk it — and a pair frozen into a
        # packed tier before its first propagation would otherwise be
        # skipped forever).  Gating only ADDS contributions, so it is
        # monotone-safe.
        def per_super(words):
            return (
                words.reshape(-1)[: n_super * sup_words]
                .reshape(n_super, sup_words)
                .any(axis=1)
                .astype(jnp.int32)
            )

        # Newly-in-use nodes (slot reuse) are the additive mirror of the
        # fresh-insert case: reachable but with no word change anywhere,
        # so their supertile must re-derive once to pick the mark up.
        suspect_g = (
            per_super(closure_w)
            | per_super(fresh_w)
            | per_super(iu_w & ~prev_iu_w)
        )

        # --- 3. repair fixpoint ------------------------------------- #
        mark_w0 = (prev_mark_w & ~closure_w) | seed_w
        table0 = mark_w0 & nh_w
        rd0, rl0, rchanged0 = dirty_chunks(table0, prev_table)
        trans_w = iu_w & nh_w  # jump-transparent intermediates

        def r_cond(carry):
            return carry["changed"]

        def r_body(carry):
            mark_w, table = carry["mark"], carry["table"]
            d, l = carry["d"], carry["l"]
            n_dirty = d[n_chunks]
            # Gate composition: the repair forcing (GATE_FULL on suspect
            # tiles, first sweep only) under the pull skip (GATE_SKIP on
            # saturated tiles — a saturated tile has nothing left to
            # re-derive, contributions are not carried across sweeps).
            base_gate = jnp.where(carry["use_gate"], suspect_g, zero_gate)
            if use_pull:
                sat = pt.saturated_tiles(
                    mark_w, iu_w, n_super, sup_words, jnp
                )
                if mode == pt.MODE_AUTO:
                    pull_on = n_dirty >= pull_cut
                else:
                    pull_on = jnp.array(True)
                gate = jnp.where(pull_on & (sat > 0), pt.GATE_SKIP,
                                 base_gate)
            else:
                sat = None
                pull_on = jnp.array(False)
                gate = base_gate
            hits2d = contribs(table, d, l, gate)
            hit_w = pt.pack_hits_table(hits2d, r_rows, jnp)
            new_mark_w = mark_w | (hit_w & iu_w)
            if use_jump:
                jh, jump_j = pt.jump_sweep(
                    table, carry["jump"], trans_w, n, jnp
                )
                new_mark_w = new_mark_w | (pack(jh) & iu_w)
            new_table = new_mark_w & nh_w
            d2, l2, changed = dirty_chunks(new_table, table)
            # The gated sweep fully re-derives suspect supertiles; the
            # monotone dirty machinery is sufficient (and cheaper) after.
            out = dict(carry, mark=new_mark_w, table=new_table, d=d2,
                       l=l2, use_gate=jnp.array(False), changed=changed)
            if use_jump:
                out["jump"] = jump_j
            if with_stats:
                i = jnp.minimum(carry["sweep_i"], pt.MAX_SWEEP_STATS - 1)
                out["sweep_i"] = carry["sweep_i"] + 1
                out["st_dirty"] = carry["st_dirty"].at[i].set(n_dirty)
                if use_pull:
                    out["st_skip"] = carry["st_skip"].at[i].set(
                        jnp.where(pull_on, sat.sum(), 0)
                    )
                    out["st_pull"] = carry["st_pull"].at[i].set(
                        pull_on.astype(jnp.int32)
                    )
            return out

        # Run at least one gated sweep whenever anything is suspect,
        # even if the table diff alone is empty.
        run0 = rchanged0 | (suspect_g.sum() > 0)
        carry0 = {"mark": mark_w0, "table": table0, "d": rd0, "l": rl0,
                  "use_gate": jnp.array(True), "changed": run0}
        if use_jump:
            carry0["jump"] = jump_j0.astype(jnp.int32)
        if with_stats:
            zero_stats = jnp.zeros((pt.MAX_SWEEP_STATS,), jnp.int32)
            carry0.update(
                sweep_i=jnp.zeros((), jnp.int32), st_dirty=zero_stats,
                st_skip=zero_stats, st_pull=zero_stats,
            )
        out = jax.lax.while_loop(r_cond, r_body, carry0)
        mark_w, table = out["mark"], out["table"]
        if with_stats:
            stats = {
                "n_sweeps": out["sweep_i"],
                "dirty_chunks": out["st_dirty"],
                "tiles_skipped": out["st_skip"],
                "pull_on": out["st_pull"],
            }
            return mark_w, seed_w, halted_w, iu_w, table, stats
        return mark_w, seed_w, halted_w, iu_w, table

    jitted = jax.jit(wake_fn)
    jitted.raw = wake_fn  # unjitted body, for callers composing it
    return jitted


def get_wake_fn(n, specs, n_super, r_rows, s_rows, interpret=None,
                mode=pt.MODE_PUSH, pull_density=pt.DEFAULT_PULL_DENSITY,
                with_stats=False):
    """Cached jitted wake fn; its ``raw`` attribute is the unjitted body
    for callers that compose wakes inside a larger program (the chained
    wake benchmark scans K of them in one jit)."""
    if interpret is None:
        interpret = pt.default_interpret()
    # _int8_mxu in the key: the flag is read at kernel build time, so
    # flipping UIGC_KERNEL_INT8 between runs A/Bs both datapaths in one
    # process instead of requiring a restart per arm.
    key = (
        n, tuple(specs), n_super, r_rows, s_rows, interpret,
        pt._int8_mxu(), mode, pull_density, with_stats,
    )
    fn = _fn_cache.get(key)
    if fn is None:
        import time as _time

        t0 = _time.perf_counter()
        fn = _fn_cache[key] = _build_wake_fn(
            n, tuple(specs), n_super, r_rows, s_rows, interpret,
            mode=mode, pull_density=pull_density, with_stats=with_stats,
        )
        if events.recorder.enabled:
            # Compile-cache plane (telemetry/device.py): one miss per
            # geometry is healthy; a per-wake miss stream for one
            # (tag, geom) is a shape-key bug (recompile_storm).
            events.recorder.commit(
                events.COMPILE, duration_s=_time.perf_counter() - t0,
                tag="dec_wake", geom=events.compile_geom(key), hit=False,
            )
    elif events.recorder.enabled:
        events.recorder.commit(
            events.COMPILE, tag="dec_wake",
            geom=events.compile_geom(key), hit=True,
        )
    return fn


class DecrementalTracer:
    """Per-wake detection state on top of IncrementalPallasLayout.

    Owns the device-resident previous-fixpoint words (marks, seeds,
    halted/in-use bits, active table) and the deleted-destination set gathered
    from the mutation log, and runs the closure+repair wake.  The first
    wake (or any wake after the previous state was invalidated) runs the
    full derivation through the same code path.
    """

    def __init__(self, n: int, interpret: Optional[bool] = None, **kwargs):
        self.layout = IncrementalPallasLayout(n, interpret=interpret, **kwargs)
        self.n = n
        self.interpret = interpret
        #: when set, each wake runs the with_stats variant of the wake
        #: fn and leaves the repair fixpoint's per-sweep frontier
        #: decomposition (device arrays, read back lazily) in
        #: ``last_stats`` for the wake profiler
        self.collect_stats = False
        self.last_stats: Optional[dict] = None
        self._mark_w = None
        self._seed_w = None
        self._halted_w = None
        self._iu_w = None
        self._table = None
        self._pending_del_dst: Set[int] = set()
        self._pending_fresh_dst: Set[int] = set()
        self._unpack = None
        self._zeros = None

    # -- building / mutation (layout pass-throughs that watch removals) --

    def rebuild(self, edge_src, edge_dst, edge_weight, supervisor) -> None:
        """Full repack from graph arrays.  The previous fixpoint is
        invalidated: a rebuild may drop pairs that never went through
        remove()/apply_log(), so the next wake re-derives everything (the
        zero prev-state path)."""
        self.layout.rebuild(edge_src, edge_dst, edge_weight, supervisor)
        self._mark_w = self._seed_w = self._halted_w = None
        self._iu_w = self._table = None
        self._pending_del_dst.clear()
        self._pending_fresh_dst.clear()

    def insert(self, src: int, dst: int, kind: int) -> None:
        if dst < self.n:
            self._pending_fresh_dst.add(int(dst))
        self.layout.insert(src, dst, kind)

    def remove(self, src: int, dst: int, kind: int) -> None:
        if dst < self.n:
            self._pending_del_dst.add(int(dst))
        self.layout.remove(src, dst, kind)

    def apply_log(self, log: List[tuple]) -> None:
        for ins, _src, dst, _kind in log:
            # Over-approximation is sound: a removal that nets out (or
            # hits a never-propagated pending pair) adds a suspect whose
            # repair is a no-op; an insert dst only forces one full
            # re-derivation of its supertile.
            if dst < self.n:
                (self._pending_fresh_dst if ins else self._pending_del_dst).add(
                    int(dst)
                )
        self.layout.apply_log(log)

    # -- the wake ------------------------------------------------------ #

    def _id_words(self, id_set: Set[int], r_rows: int):
        # Scatter an id set into a packed word table (device).  The set
        # is NOT drained here: a wake whose dispatch raises (compile
        # error, immediate transport error) keeps its suspects for the
        # retry; wake_device clears them only after dispatch succeeds.
        # An async-poisoned result (error surfacing at readback) loses
        # the device state itself — the caller recovers via
        # invalidate(), after which suspects are irrelevant.
        import jax

        if not id_set:
            if self._zeros is None or self._zeros.shape[0] != r_rows:
                self._zeros = jax.device_put(
                    np.zeros((r_rows, pt.LANE), np.int32)
                )
            return self._zeros
        ids = np.fromiter(id_set, np.int64, len(id_set))
        words = np.zeros(r_rows * pt.LANE, dtype=np.uint32)
        np.bitwise_or.at(
            words, ids >> 5, np.uint32(1) << (ids & 31).astype(np.uint32)
        )
        return jax.device_put(words.view(np.int32).reshape(r_rows, pt.LANE))

    def wake_device(self, flags_dev, recv_dev):
        """Run one wake; returns the packed mark words (device).  Use
        :meth:`marks` for the boolean vector."""
        import jax

        preps, args = self.layout.prepare_device_wake()
        first = preps[0]
        r_rows = first["r_rows"]
        fn = get_wake_fn(
            self.n,
            tuple(pt.layout_spec(p) for p in preps),
            first["n_super"],
            r_rows,
            first["s_rows"],
            self.interpret,
            mode=self.layout.mode,
            pull_density=self.layout.pull_density,
            with_stats=self.collect_stats,
        )
        if self._mark_w is None or self._mark_w.shape[0] != r_rows:
            z = jax.device_put(np.zeros((r_rows, pt.LANE), np.int32))
            self._mark_w = self._seed_w = self._halted_w = z
            self._iu_w = self._table = z
            # every previous mark is gone: everything must re-derive,
            # which the zero prev-state does for free (empty suspects,
            # full seed-diff dirty set)
        del_w = self._id_words(self._pending_del_dst, r_rows)
        fresh_w = self._id_words(self._pending_fresh_dst, r_rows)
        out = fn(
            flags_dev,
            recv_dev,
            del_w,
            fresh_w,
            self._mark_w,
            self._seed_w,
            self._halted_w,
            self._iu_w,
            self._table,
            *args,
        )
        # State + suspects commit when dispatch succeeds.  Under async
        # dispatch a transport death can still poison the returned
        # arrays at first readback — after any such failure the caller
        # must invalidate() (the previous fixpoint is lost with the
        # device state anyway), which makes the next wake a full
        # re-derivation and the drained suspects irrelevant.
        if self.collect_stats:
            *out, self.last_stats = out
        self._mark_w, self._seed_w, self._halted_w, self._iu_w, self._table = out
        self._pending_del_dst.clear()
        self._pending_fresh_dst.clear()
        return self._mark_w

    def invalidate(self) -> None:
        """Drop the previous-fixpoint device state (after a failed or
        poisoned wake, or any external doubt about it): the next wake
        re-derives everything from the current seeds."""
        self._mark_w = self._seed_w = self._halted_w = None
        self._iu_w = self._table = None
        self._pending_del_dst.clear()
        self._pending_fresh_dst.clear()

    def unpack_marks(self, mark_w) -> np.ndarray:
        """Packed mark words -> the oracle's (n,) bool mark vector.

        This is the readback point where an async-poisoned wake (the
        dispatch succeeded, the transport died before the result
        landed) first surfaces.  The tracer auto-invalidates before
        re-raising, so a caller that catches and retries without its
        own invalidate() still gets a clean full re-derivation instead
        of tracing from corrupt committed state."""
        import jax
        import jax.numpy as jnp

        if self._unpack is None:

            @jax.jit
            def unpack(words):
                return pt.unpack_table(words, self.n, jnp)

            self._unpack = unpack
        try:
            return np.asarray(self._unpack(mark_w))  # readback: host boundary: packed wake marks -> np for the caller
        except Exception:
            self.invalidate()
            raise

    def marks(self, flags, recv_count) -> np.ndarray:
        """Wake + unpack to the oracle's (n,) bool mark vector."""
        import jax

        return self.unpack_marks(
            self.wake_device(jax.device_put(flags), jax.device_put(recv_count))
        )
