"""uigc-tpu: a TPU-native actor garbage-collection framework.

A from-scratch re-design of the capability of ``dplyukhin/uigc-akka``
(automatic detection and termination of quiescent actors) with a
JAX/XLA/Pallas execution backend: per-actor snapshots are batched onto a
device-resident shadow graph (CSR adjacency + node features) and the
liveness trace runs as a sparse label-propagation-to-fixpoint kernel.

Public API mirrors the reference's ``edu.illinois.osl.uigc`` surface:
``ActorSystem``, ``ActorContext``, ``Behaviors``, ``AbstractBehavior``,
``Message``/``NoRefs``, pluggable engines behind the ``uigc.engine``
config key.
"""

from .cluster import ClusterSharding, Entity, EntityRef
from .config import Config
from .interfaces import GCMessage, Message, NoRefs, Refob, SpawnInfo, State
from .runtime.behaviors import AbstractBehavior, ActorFactory, Behaviors, RawBehavior
from .runtime.context import ActorContext
from .runtime.signals import PostStop, Signal, Terminated
from .runtime.system import ActorSystem, RawRef
from .runtime.testkit import ActorTestKit, TestProbe

#: The reference calls managed refs ``ActorRef[T] = Refob[T]``
#: (reference: package.scala:7-9).
ActorRef = Refob

__version__ = "0.1.0"

__all__ = [
    "AbstractBehavior",
    "ActorContext",
    "ActorFactory",
    "ActorRef",
    "ActorSystem",
    "ActorTestKit",
    "Behaviors",
    "ClusterSharding",
    "Config",
    "Entity",
    "EntityRef",
    "GCMessage",
    "Message",
    "NoRefs",
    "PostStop",
    "RawBehavior",
    "RawRef",
    "Refob",
    "Signal",
    "SpawnInfo",
    "State",
    "TestProbe",
    "__version__",
]
